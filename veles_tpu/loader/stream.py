"""Streaming loaders: samples arrive at run time, not load time.

Equivalents of the reference's runtime-fed loaders (SURVEY.md §2.3):
- InteractiveLoader (veles/loader/interactive.py:57) — feed samples from
  the owning process;
- RestfulLoader (veles/loader/restful.py:52) — fed by the RESTful serving
  unit, one (ticket, sample) per HTTP request;
- ZeroMQLoader (veles/zmq_loader.py:74) — receive work items over a
  ZeroMQ ROUTER socket from external producers.

All are one StreamLoader mechanism: a thread-safe queue of samples pulled
by ``run()``; ``close()`` stops the owning workflow. Streamed serving is
inherently minibatch-1-ish and host-bound — it exists for the serve path
(forward workflow), not the fused training loop.
"""

from __future__ import annotations

import pickle
import queue as queue_mod
import threading
from typing import Any, Optional, Tuple

import numpy

from ..error import VelesError
from .base import Loader, TEST


class LoaderClosed(VelesError):
    """Feed after close(): service is shutting down — a SERVER state,
    distinct from client-fault rejections (REST maps it to 503)."""


class StreamLoader(Loader):
    """Queue-fed loader. ``feed(sample[, label])`` from any thread;
    ``run()`` blocks until a sample (or close) arrives."""

    MAPPING = "interactive_loader"

    def __init__(self, workflow, sample_shape: Tuple[int, ...] = (),
                 timeout: float = 60.0, **kwargs) -> None:
        kwargs.setdefault("minibatch_size", 1)
        super().__init__(workflow, **kwargs)
        self.sample_shape = tuple(sample_shape)
        self.timeout = timeout
        self._queue: "queue_mod.Queue" = queue_mod.Queue()
        self._closed = threading.Event()
        #: per-row tickets of the samples currently in minibatch_data
        #: (REST routing). minibatch_size > 1 enables DYNAMIC BATCHING:
        #: one dispatch serves every request queued at that moment —
        #: the TPU-first serving shape (one compiled program, batch
        #: dimension amortizes the dispatch; the reference served one
        #: request per run)
        self.current_tickets: list = []

    # -- producer side (any thread) ------------------------------------------
    def feed(self, sample, label: Optional[int] = None,
             ticket: Any = None) -> None:
        if self._closed.is_set():
            raise LoaderClosed("%s is closed" % self.name)
        sample = numpy.asarray(sample)
        # validate on the PRODUCER side: a bad sample must fail the one
        # request that sent it, not raise later inside run() on the
        # workflow thread and kill the serving loop for every client
        if self.sample_shape and sample.shape != self.sample_shape:
            raise VelesError("sample shape %s != declared %s"
                             % (sample.shape, self.sample_shape))
        self._queue.put((sample, label, ticket))

    def parse_request(self, body: dict) -> numpy.ndarray:
        """REST request body → sample array. The base loader reads the
        numeric ``input`` field; subclasses specialize (the image
        variant decodes an ``image`` payload) — the RESTfulAPI unit
        delegates here so the loader owns its wire format, mirroring
        the reference's loader-specific derive/feed split
        (veles/loader/restful.py:133)."""
        return numpy.asarray(body["input"], dtype=numpy.float32)

    def close(self) -> None:
        self._closed.set()
        self._queue.put(None)   # wake a blocked run()

    # -- loader contract ------------------------------------------------------
    def load_data(self) -> None:
        if not self.sample_shape:
            raise VelesError("%s needs sample_shape" % self.name)
        # stream length is unknown; geometry is per-sample
        self.class_lengths = [1, 0, 0]   # serving = TEST class

    def create_minibatch_data(self) -> None:
        from ..config import root
        dtype = root.common.engine.precision_type
        self.minibatch_data.reset(numpy.zeros(
            (self.max_minibatch_size,) + self.sample_shape, dtype=dtype))
        self.minibatch_labels.reset(numpy.zeros(
            self.max_minibatch_size, dtype=numpy.int32))

    def fill_minibatch(self) -> None:  # pragma: no cover - not used
        pass

    def run(self) -> None:
        try:
            item = self._queue.get(timeout=self.timeout)
        except queue_mod.Empty:
            raise VelesError("%s: no sample within %.0fs"
                             % (self.name, self.timeout))
        if item is None or self._closed.is_set():
            self.workflow.stop()
            return
        # dynamic batching: block for the FIRST sample, then drain
        # whatever else is already queued (up to capacity) into the
        # same dispatch — concurrent clients share one program run
        batch = [item]
        while len(batch) < self.max_minibatch_size:
            try:
                nxt = self._queue.get_nowait()
            except queue_mod.Empty:
                break
            if nxt is None:
                # close() landed mid-drain: serve this batch, stop on
                # the NEXT run
                self._queue.put(None)
                break
            batch.append(nxt)
        data = self.minibatch_data.map_invalidate()
        labels_arr = self.minibatch_labels.map_invalidate()
        self.current_tickets = []
        # shape validation lives in feed() (producer side — failures
        # belong to the request that sent them, never to this loop)
        for row, (sample, label, ticket) in enumerate(batch):
            data[row] = sample
            # unlabeled rows must not inherit a previous dispatch's
            # label parked at the same row
            labels_arr[row] = 0 if label is None else label
            self.current_tickets.append(ticket)
        self.minibatch_class = TEST
        self.minibatch_size = len(batch)
        self.samples_served += len(batch)


class InteractiveLoader(StreamLoader):
    """Reference naming (veles/loader/interactive.py:57)."""


class RestfulLoader(StreamLoader):
    """Fed by the RESTfulAPI service unit with per-request tickets
    (reference: veles/loader/restful.py:52)."""

    MAPPING = "restful_loader"


class _ImageStreamMixin:
    """Decode-before-enqueue for image serving: accepts raw encoded
    image bytes (feed) or a base64 ``image`` JSON field (REST), decoded
    with the SAME size/color policy the training loader used — the
    geometry contract the reference carried via derive_from
    (veles/loader/restful.py:137-152)."""

    def __init__(self, workflow, size=None, color: str = "RGB",
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        # default geometry comes from the declared sample shape: with
        # size=None a decodable image of any other dimensions would
        # pass feed() and blow up downstream instead of being resized
        if size is None and len(self.sample_shape) >= 2:
            size = self.sample_shape[:2]
        self.size = size
        self.color = color

    def decode_sample(self, data: bytes) -> numpy.ndarray:
        from .image import decode_image
        return decode_image(bytes(data), self.size, self.color)

    def feed(self, sample, label: Optional[int] = None,
             ticket: Any = None) -> None:
        if isinstance(sample, (bytes, bytearray)):
            sample = self.decode_sample(sample)
        super().feed(sample, label, ticket)

    def parse_request(self, body: dict) -> numpy.ndarray:
        if "image" in body:
            import base64
            return self.decode_sample(base64.b64decode(body["image"]))
        return super().parse_request(body)


class InteractiveImageLoader(_ImageStreamMixin, InteractiveLoader):
    """Reference: InteractiveImageLoader (veles/loader/interactive.py)."""

    MAPPING = "interactive_image_loader"


class RestfulImageLoader(_ImageStreamMixin, RestfulLoader):
    """Reference: RestfulImageLoader (veles/loader/restful.py:133)."""

    MAPPING = "restful_image_loader"


class ZeroMQLoader(StreamLoader):
    """Receives pickled (sample, label) work items over a ZeroMQ ROUTER
    socket (reference: veles/zmq_loader.py:74). A background thread drains
    the socket into the stream queue; producers use DEALER sockets and get
    a b"ok" ack per item; an empty payload closes the stream."""

    MAPPING = "zeromq_loader"

    def __init__(self, workflow, endpoint: str = "tcp://*:0",
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.endpoint = endpoint
        #: actual endpoint after bind (port resolved)
        self.bound_endpoint: Optional[str] = None
        self._thread: Optional[threading.Thread] = None
        self._ctx = None

    def initialize(self, **kwargs):
        res = super().initialize(**kwargs)
        if res:
            return res
        import zmq
        self._ctx = zmq.Context.instance()
        sock = self._ctx.socket(zmq.ROUTER)
        if self.endpoint.endswith(":0"):
            port = sock.bind_to_random_port(self.endpoint[:-2])
            self.bound_endpoint = "%s:%d" % (
                self.endpoint[:-2].replace("*", "127.0.0.1"), port)
        else:
            sock.bind(self.endpoint)
            self.bound_endpoint = self.endpoint.replace("*", "127.0.0.1")
        self._thread = threading.Thread(
            target=self._drain, args=(sock,), daemon=True,
            name=self.name + ".zmq")
        self._thread.start()
        self.info("%s: listening on %s", self.name, self.bound_endpoint)
        return None

    def _drain(self, sock) -> None:
        import zmq
        poller = zmq.Poller()
        poller.register(sock, zmq.POLLIN)
        # poll with timeout so stop()/close() can end the thread (and
        # release the bound port) without cross-thread socket access
        while not self._closed.is_set():
            if not poller.poll(200):
                continue
            try:
                ident, payload = sock.recv_multipart()
            except Exception:
                break
            if not payload:
                sock.send_multipart([ident, b"bye"])
                self.close()
                break
            sample, label = pickle.loads(payload)
            self.feed(sample, label, ticket=ident)
            sock.send_multipart([ident, b"ok"])
        sock.close(0)

    def stop(self) -> None:
        super().stop()          # Loader.stop closes any prefetcher
        self._closed.set()
