"""Data loading: the minibatch-serving unit family.

Equivalent of the reference's veles/loader/ package (SURVEY.md §2.3): a
Loader walks three sample sets (TEST/VALIDATION/TRAIN) in epochs, shuffles
the train set, pads tail minibatches to a static size (mask-valid), and
hands minibatches to the compute graph. TPU-first: datasets that fit in HBM
live there as jax Arrays and minibatch gather happens inside the jitted
step (the fullbatch_loader.cl equivalent); bigger datasets stream from host
with double-buffered device transfer.
"""

from .base import (Loader, LoaderMSE, TEST, VALID, TRAIN,
                   CLASS_NAMES)                        # noqa: F401
from .fullbatch import FullBatchLoader, FullBatchLoaderMSE  # noqa: F401
from .file_loader import (FileFilter, FileListScanner,      # noqa: F401
                          auto_label)
from .image import (ImageLoader, ClassImageLoader, decode_image,  # noqa
                    augment, deterministic_split,
                    FileListImageLoader, ImageLoaderMSE)
from .pickles import PicklesLoader                     # noqa: F401
from .hdf5 import HDF5Loader                           # noqa: F401
from .saver import MinibatchesSaver, MinibatchesLoader  # noqa: F401
from .stream import (StreamLoader, InteractiveLoader,  # noqa: F401
                     RestfulLoader, ZeroMQLoader,
                     InteractiveImageLoader, RestfulImageLoader)
from .ensemble import EnsembleLoader                   # noqa: F401
from .sound import SoundFileLoader, decode_audio       # noqa: F401
from .kv_store import LMDBLoader, HDFSTextLoader       # noqa: F401
from .text import TextFileLoader                       # noqa: F401
