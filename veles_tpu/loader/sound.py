"""Audio ingest: decode sound files into fixed-length float windows.

Equivalent of the reference's libsndfile ctypes binding
(veles/loader/libsndfile.py:91) + the sound loaders exercised by
veles/tests/test_snd_file_loader.py (sawyer.flac fixture). Decode order:
the ``soundfile`` package if installed, else a ctypes ``libsndfile``
binding (the reference's approach), else the stdlib ``wave`` module
(.wav only). FLAC/OGG therefore work wherever libsndfile exists; the
framework itself only needs PCM arrays.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import os
import wave
from typing import List, Optional, Sequence, Tuple

import numpy

from ..error import VelesError
from .fullbatch import FullBatchLoader


# ---------------------------------------------------------------------------
# decoders
# ---------------------------------------------------------------------------

def _decode_soundfile(path):
    import soundfile                    # optional dependency
    data, rate = soundfile.read(path, dtype="float32", always_2d=True)
    return data, int(rate)


class _SndfileInfo(ctypes.Structure):
    _fields_ = [("frames", ctypes.c_int64), ("samplerate", ctypes.c_int),
                ("channels", ctypes.c_int), ("format", ctypes.c_int),
                ("sections", ctypes.c_int), ("seekable", ctypes.c_int)]


_sndfile_lib = None


def _load_sndfile():
    global _sndfile_lib
    if _sndfile_lib is None:
        name = ctypes.util.find_library("sndfile")
        if not name:
            raise ImportError("libsndfile not found")
        lib = ctypes.CDLL(name)
        lib.sf_open.restype = ctypes.c_void_p
        lib.sf_open.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                ctypes.POINTER(_SndfileInfo)]
        lib.sf_readf_float.restype = ctypes.c_int64
        lib.sf_readf_float.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(ctypes.c_float),
                                       ctypes.c_int64]
        lib.sf_close.argtypes = [ctypes.c_void_p]
        _sndfile_lib = lib
    return _sndfile_lib


def _decode_libsndfile(path):
    """ctypes FFI, the reference's own approach
    (veles/loader/libsndfile.py:91)."""
    lib = _load_sndfile()
    info = _SndfileInfo()
    handle = lib.sf_open(path.encode(), 0x10, ctypes.byref(info))  # READ
    if not handle:
        raise VelesError("libsndfile cannot open %s" % path)
    try:
        frames, channels = int(info.frames), int(info.channels)
        buf = numpy.zeros(frames * channels, dtype=numpy.float32)
        got = lib.sf_readf_float(
            handle, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            frames)
        return buf[:got * channels].reshape(-1, channels), \
            int(info.samplerate)
    finally:
        lib.sf_close(handle)


def _decode_wave(path):
    with wave.open(path, "rb") as wav:
        n = wav.getnframes()
        width = wav.getsampwidth()
        channels = wav.getnchannels()
        raw = wav.readframes(n)
        rate = wav.getframerate()
    if width == 2:
        data = numpy.frombuffer(raw, dtype="<i2").astype(
            numpy.float32) / 32768.0
    elif width == 1:
        data = (numpy.frombuffer(raw, dtype=numpy.uint8).astype(
            numpy.float32) - 128.0) / 128.0
    elif width == 4:
        data = numpy.frombuffer(raw, dtype="<i4").astype(
            numpy.float32) / 2147483648.0
    else:
        raise VelesError("%s: unsupported sample width %d" % (path, width))
    return data.reshape(-1, channels), rate


def decode_audio(path: str) -> Tuple[numpy.ndarray, int]:
    """→ (float32 samples (frames, channels) in [-1, 1], sample rate)."""
    errors = []
    for dec in (_decode_soundfile, _decode_libsndfile):
        try:
            return dec(path)
        except ImportError as e:
            errors.append(str(e))
        except VelesError:
            raise
    if path.lower().endswith(".wav"):
        return _decode_wave(path)
    raise VelesError("cannot decode %s (no soundfile/libsndfile: %s)" %
                     (path, "; ".join(errors)))


# ---------------------------------------------------------------------------
# loader
# ---------------------------------------------------------------------------

class SoundFileLoader(FullBatchLoader):
    """Full-batch loader over audio files, windowed to fixed length.

    Each file is mono-mixed, split into ``window`` -sample frames with
    ``stride`` hop; every frame becomes one sample labelled by the file's
    position in ``label_names`` (or its directory name). This is the shape
    the genre-recognition LSTM workflow (BASELINE config #5 genre) eats.
    """

    MAPPING = "sound_file_loader"
    hide_from_registry = False

    def __init__(self, workflow, files: Sequence[str] = (),
                 labels: Optional[Sequence[int]] = None,
                 window: int = 1024, stride: Optional[int] = None,
                 validation_ratio: float = 0.15, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.files: List[str] = list(files)
        self.file_labels = None if labels is None else list(labels)
        self.window = int(window)
        self.stride = int(stride or window)
        self.validation_ratio = float(validation_ratio)
        self.sample_rate: Optional[int] = None

    def windows_of(self, path: str) -> numpy.ndarray:
        data, rate = decode_audio(path)
        if self.sample_rate is None:
            self.sample_rate = rate
        elif rate != self.sample_rate:
            raise VelesError(
                "%s: sample rate %d differs from the dataset's %d — "
                "resample before loading" % (path, rate,
                                             self.sample_rate))
        mono = data.mean(axis=1)
        n = (len(mono) - self.window) // self.stride + 1
        if n <= 0:
            raise VelesError("%s shorter than window %d" %
                             (path, self.window))
        idx = (numpy.arange(self.window)[None, :] +
               self.stride * numpy.arange(n)[:, None])
        return mono[idx].astype(numpy.float32)

    def load_data(self) -> None:
        if not self.files:
            raise VelesError("%s: no files" % self.name)
        chunks, labels = [], []
        for i, path in enumerate(self.files):
            frames = self.windows_of(path)
            label = (self.file_labels[i] if self.file_labels is not None
                     else i)
            chunks.append(frames)
            labels.append(numpy.full(len(frames), label,
                                     dtype=numpy.int32))
        data = numpy.concatenate(chunks)
        lbls = numpy.concatenate(labels)
        # deterministic shuffle before the validation split
        order = numpy.random.RandomState(1).permutation(len(data))
        data, lbls = data[order], lbls[order]
        n_valid = int(len(data) * self.validation_ratio)
        self.create_originals(data, lbls)
        self.class_lengths = [0, n_valid, len(data) - n_valid]
