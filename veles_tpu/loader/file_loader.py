"""File-scanning loaders: build datasets from directory trees.

Equivalent of the reference's veles/loader/file_loader.py:54-277
(FileFilter / FileLoaderBase / AutoLabelFileLoader): glob include/exclude
filters, per-class path lists (test/validation/train), and automatic
labelling from the containing directory name.
"""

from __future__ import annotations

import fnmatch
import os
from typing import List, Optional, Sequence

from ..error import VelesError
from .base import TEST, VALID, TRAIN


class FileFilter:
    """Include/exclude glob patterns over file names
    (reference: FileFilter, veles/loader/file_loader.py:54)."""

    def __init__(self, include: Sequence[str] = ("*",),
                 exclude: Sequence[str] = ()) -> None:
        self.include = list(include)
        self.exclude = list(exclude)

    def matches(self, name: str) -> bool:
        base = os.path.basename(name)
        if not any(fnmatch.fnmatch(base, p) for p in self.include):
            return False
        return not any(fnmatch.fnmatch(base, p) for p in self.exclude)

    def scan(self, path: str) -> List[str]:
        """All matching files under path (recursive, sorted for
        deterministic sample order)."""
        found = []
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames.sort()
            for name in sorted(filenames):
                full = os.path.join(dirpath, name)
                if self.matches(full):
                    found.append(full)
        return found


class FileListScanner:
    """Resolves the reference's (test_paths, validation_paths, train_paths)
    contract into per-class file lists (FileLoaderBase,
    veles/loader/file_loader.py:~120)."""

    def __init__(self, train_paths: Sequence[str],
                 validation_paths: Sequence[str] = (),
                 test_paths: Sequence[str] = (),
                 file_filter: Optional[FileFilter] = None) -> None:
        self.paths = {TEST: list(test_paths), VALID: list(validation_paths),
                      TRAIN: list(train_paths)}
        self.filter = file_filter or FileFilter()

    def scan(self) -> List[List[str]]:
        """[test_files, validation_files, train_files]."""
        out: List[List[str]] = [[], [], []]
        for cls in (TEST, VALID, TRAIN):
            for path in self.paths[cls]:
                if not os.path.exists(path):
                    raise VelesError("path %r does not exist" % path)
                if os.path.isfile(path):
                    out[cls].append(path)
                else:
                    out[cls].extend(self.filter.scan(path))
        if not out[TRAIN] and not out[TEST]:
            raise VelesError("no files matched in %s" % self.paths)
        return out


def auto_label(path: str) -> str:
    """Label = name of the containing directory (reference:
    AutoLabelFileLoader, veles/loader/file_loader.py:241-277)."""
    return os.path.basename(os.path.dirname(path))
