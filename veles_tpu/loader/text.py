"""Character-level text loader for language-model workflows.

New capability vs the reference (2015 VELES had no text pipeline at
all; the closest was the per-format family of SURVEY.md §2.3):
``TextFileLoader`` reads plain text files, builds (or accepts) a
character vocabulary, and serves fixed-length windows of token ids
with shifted next-token targets — exactly the contract
``loss_function="softmax_seq"`` + ``Embedding``/``LMHead`` consume
(models/char_lm.py trains on it unchanged by passing
``loader_unit=TextFileLoader(...)``).

Windows are non-overlapping by default (``stride = seq_len``); a
smaller stride oversamples long documents. The validation split is
carved from the TAIL of the corpus so train/valid never share text.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy

from ..error import VelesError
from .fullbatch import FullBatchLoaderMSE


class TextFileLoader(FullBatchLoaderMSE):
    """``files``: text file paths (concatenated in order). ``vocab``:
    optional explicit string of characters (index = id); by default the
    vocabulary is every distinct character in the corpus, sorted.
    Characters outside the vocabulary map to the reserved unk id
    (``len(vocab)``, one past the last real character — included in
    ``vocab_size``); ``decode`` renders it as ``UNK_CHAR``."""

    MAPPING = "text_loader"

    #: what decode() renders for the reserved unknown id
    UNK_CHAR = "�"

    def __init__(self, workflow, files: Sequence[str] = (),
                 seq_len: int = 128, stride: Optional[int] = None,
                 vocab: Optional[str] = None,
                 validation_ratio: float = 0.1, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        if not files:
            raise VelesError("TextFileLoader needs at least one file")
        self.files = list(files)
        self.seq_len = int(seq_len)
        self.stride = int(stride) if stride else self.seq_len
        self.vocab: Optional[str] = vocab
        self.char_to_id: Dict[str, int] = {}
        self.text_validation_ratio = float(validation_ratio)

    # -- vocabulary ----------------------------------------------------------
    @property
    def unk_id(self) -> int:
        """Dedicated id for out-of-vocabulary characters — one past the
        vocabulary, NEVER a real character's id: aliasing OOV onto id 0
        (a real char) silently skewed training targets and decode
        output toward that character (ADVICE r2)."""
        return len(self.vocab or "")

    def encode(self, text: str) -> numpy.ndarray:
        table, unk = self.char_to_id, self.unk_id
        return numpy.fromiter((table.get(c, unk) for c in text),
                              dtype=numpy.int32, count=len(text))

    def decode(self, ids) -> str:
        if not self.vocab:
            raise VelesError("decode before load_data: no vocabulary yet")
        return "".join(self.vocab[i] if 0 <= i < len(self.vocab)
                       else self.UNK_CHAR
                       for i in numpy.asarray(ids).ravel())

    @property
    def vocab_size(self) -> int:
        """len(vocab) + 1: the unk slot is part of the id space, so
        embedding tables / LM heads sized from here stay in range for
        every id encode() can produce."""
        return len(self.vocab or "") + 1

    # -- loader contract -----------------------------------------------------
    def load_data(self) -> None:
        corpus_parts: List[str] = []
        for path in self.files:
            if not os.path.exists(path):
                raise VelesError("text file missing: %s" % path)
            with open(path, "r", encoding="utf-8", errors="replace") as f:
                corpus_parts.append(f.read())
        corpus = "".join(corpus_parts)
        if len(corpus) < self.seq_len + 1:
            raise VelesError(
                "corpus of %d chars cannot fill one %d-char window"
                % (len(corpus), self.seq_len))
        if self.vocab is None:
            self.vocab = "".join(sorted(set(corpus)))
        self.char_to_id = {c: i for i, c in enumerate(self.vocab)}
        ids = self.encode(corpus)
        n_oov = int((ids == self.unk_id).sum())
        if n_oov:
            # only possible with a user-restricted vocab; loud because
            # every such position trains the model on the unk token
            self.warning(
                "%d of %d corpus characters are outside the supplied "
                "%d-char vocabulary; they map to the reserved unk id "
                "%d (decoded as %r)", n_oov, len(ids),
                len(self.vocab), self.unk_id, self.UNK_CHAR)

        # a window at s consumes ids[s : s+seq_len+1] (input + shifted
        # target), so the last valid start is len - seq_len - 1 —
        # arange's stop is exclusive, hence - seq_len
        starts = numpy.arange(0, len(ids) - self.seq_len, self.stride)
        n = len(starts)
        n_valid = int(round(n * self.text_validation_ratio))
        n_train = n - n_valid
        if n_valid and self.stride < self.seq_len + 1:
            # overlapping windows share text across the split boundary
            # (a window at s covers ids[s : s+seq_len+1] including the
            # shifted target): drop the straddling VALID-side windows
            # until first_valid_start >= last_train_end, so
            # 'train/valid never share text' stays true in
            # oversampling mode
            gap = max(0, -(-(self.seq_len + 1 - self.stride)
                           // self.stride))
            gap = min(gap, n_valid)
            keep = numpy.ones(n, dtype=bool)
            keep[n_train:n_train + gap] = False
            starts = starts[keep]
            n = len(starts)
            n_valid = n - n_train
        if n_train <= 0:
            raise VelesError("validation_ratio %.2f leaves no training "
                             "windows (%d total)"
                             % (self.text_validation_ratio, n))
        x = numpy.stack([ids[s:s + self.seq_len] for s in starts])
        y = numpy.stack([ids[s + 1:s + self.seq_len + 1]
                         for s in starts])
        # validation = the corpus TAIL: no shared text with train
        order = numpy.concatenate([numpy.arange(n_train, n),
                                   numpy.arange(n_train)])
        self.create_originals(x[order], None, targets=y[order])
        self.class_lengths = [0, n_valid, n_train]
        self.info("%s: %d chars, vocab %d, %d windows of %d "
                  "(%d train / %d valid)", self.name, len(corpus),
                  self.vocab_size, n, self.seq_len, n_train, n_valid)
