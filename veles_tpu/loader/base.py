"""Loader base: the minibatch-serving contract.

Equivalent of the reference's veles/loader/base.py:72-1181 (``Loader``):
three sample sets served per epoch in the fixed order TEST → VALIDATION →
TRAIN, per-epoch train shuffling, label statistics, epoch/end flags, and
static-size minibatches (the reference zero-padded short tails,
veles/loader/base.py:749-753 — here padding comes with a validity mask so
jitted steps keep static shapes and padded samples are inert).

The reference's distributed index-serving plane (master sends indices,
slave fills data locally, :631-663) is superseded by SPMD: every host runs
the same loader with the same seed and takes its shard of each minibatch
(see parallel/). ``failed_minibatches`` re-serving maps to checkpoint
restart."""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy

from ..config import root
from ..error import NoMoreJobs
from ..memory import Array
from ..mutable import Bool
from ..units import Unit
from .. import prng

TEST, VALID, TRAIN = 0, 1, 2
CLASS_NAMES = ("test", "validation", "train")


class Loader(Unit):
    """Minibatch server (reference: veles/loader/base.py:120)."""

    hide_from_registry = True

    def __init__(self, workflow, minibatch_size=100, shuffle_limit=None,
                 shard_dataset=False, prefetch_depth=None, **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "LOADER"
        self.max_minibatch_size = int(minibatch_size)
        #: data-plane prefetch (overlap engine, veles_tpu/overlap/
        #: prefetch.py): with depth N > 0 the pure per-batch gather
        #: (``fetch_batch``) for up to N upcoming minibatches runs on a
        #: background thread while the current step computes. The
        #: serving state machine — offsets, epoch flags, PRNG shuffles
        #: — stays on the main thread, so results are bit-identical
        #: with prefetch on or off (the producer walks a frozen copy of
        #: this epoch's index order and never crosses an epoch
        #: boundary). Host-fill path only; fused/plan modes already
        #: overlap via async dispatch (their host work is index
        #: bookkeeping, not sample gathering).
        if prefetch_depth is None:
            prefetch_depth = root.common.overlap.get(
                "prefetch_depth", 0) or 0
        self.prefetch_depth = int(prefetch_depth)
        self._prefetcher = None
        #: None = not probed yet; False = this loader has no pure
        #: fetch_batch (custom fill) — prefetch silently falls back
        self._prefetch_supported: Optional[bool] = None
        #: shard the device-resident dataset over the mesh 'data' axis
        #: instead of replicating it on every chip: HBM per chip scales
        #: 1/n with the axis (GSPMD turns the in-step gather into the
        #: needed collectives). Keep False for small datasets — the
        #: replicated gather is collective-free.
        self.shard_dataset = bool(shard_dataset)
        #: samples per class: [test, validation, train]
        self.class_lengths: List[int] = [0, 0, 0]
        self.epoch_number = 0
        #: unlimited shuffles by default (reference shuffle_limit)
        self.shuffle_limit = (numpy.inf if shuffle_limit is None
                              else shuffle_limit)
        #: train on a random subset of the train class (ensemble members,
        #: reference --ensemble-train N:r, veles/ensemble/base_workflow.py:59)
        self.train_ratio = 1.0
        # flags (reference :862-878)
        self.epoch_ended = Bool(False)
        self.last_minibatch = Bool(False)
        self.train_ended = Bool(False)
        self.test_ended = Bool(False)
        # per-minibatch outputs
        self.minibatch_data = Array(name=self.name + ".minibatch_data")
        self.minibatch_labels = Array(name=self.name + ".minibatch_labels")
        self.minibatch_indices = Array(name=self.name + ".minibatch_indices")
        self.minibatch_mask = Array(name=self.name + ".minibatch_mask")
        self.minibatch_class = TRAIN
        self.minibatch_size = 0          # valid samples in this minibatch
        self.minibatch_offset = 0
        #: serve N minibatches per run() as a (N, mb) index plan — the
        #: fused TrainStep scans over them in ONE device dispatch (kills
        #: per-step dispatch latency; crucial over a tunnelled TPU)
        self.plan_steps = 1
        #: number of valid rows in the current plan
        self.plan_length = 1
        #: when True, a fused step consumes indices on device and the host
        #: minibatch_data fill is skipped entirely
        self.fused = False
        #: serve H whole epochs per run() as per-class (H, K_c, mb) index
        #: plans (TrainStep epochs_per_dispatch: ONE device dispatch
        #: covers H epochs of eval+train — the per-epoch host round trip
        #: disappears). Set by TrainStep; fused-only.
        self.block_epochs = 1
        #: {class: (idx Array (H, K_c, mb) int32, mask Array f32)} —
        #: allocated on first serve_epoch_block
        self.block_plans: Dict[int, tuple] = {}
        #: hard epoch cap (Decision.max_epochs, set by StandardWorkflow):
        #: the FINAL block clamps to the epochs remaining under it —
        #: training past max_epochs would desynchronize the reported
        #: trajectory from the actual weights
        self.block_epochs_cap: Optional[int] = None
        #: epochs actually served by the last serve_epoch_block
        self.block_length = 0
        self._global_offset = 0
        self._shuffled_indices: Optional[numpy.ndarray] = None
        self.samples_served = 0
        # label bookkeeping (reference label mapping/stats :120-…)
        self.labels_mapping: Dict[object, int] = {}
        self.prng = prng.get(self.name)

    # -- subclass contract ---------------------------------------------------
    def load_data(self) -> None:
        """Populate class_lengths (+ dataset storage). Called at init."""
        raise NotImplementedError

    def create_minibatch_data(self) -> None:
        """Allocate minibatch_data/labels arrays with static shapes."""
        raise NotImplementedError

    def fill_minibatch(self) -> None:
        """Copy samples minibatch_indices → minibatch_data/labels."""
        raise NotImplementedError

    # -- prefetch seam (overlap engine) --------------------------------------
    def fetch_batch(self, idx, size):
        """PURE gather of one minibatch: given an index row, return
        {name → ndarray} for the ``minibatch_<name>`` arrays — or None
        when this loader cannot gather outside its own state (custom
        fill/augmentation). Must be thread-safe (runs on the prefetch
        producer thread) and must not touch serving state or PRNG.
        Subclasses with a pure fill implement it (FullBatchLoader)."""
        return None

    def apply_batch(self, batch) -> None:
        """Install a :meth:`fetch_batch` result into the minibatch
        arrays (main thread — the one place prefetch writes shared
        state)."""
        for name, arr in batch.items():
            getattr(self, "minibatch_" + name).map_invalidate()[...] = arr

    # -- derived geometry ----------------------------------------------------
    @property
    def total_samples(self) -> int:
        return int(sum(self.class_lengths))

    @property
    def class_end_offsets(self) -> List[int]:
        ends, acc = [], 0
        for n in self.class_lengths:
            acc += n
            ends.append(acc)
        return ends

    def class_of_offset(self, offset: int) -> int:
        for idx, end in enumerate(self.class_end_offsets):
            if offset < end:
                return idx
        raise NoMoreJobs("offset %d beyond %d samples" %
                         (offset, self.total_samples))

    # -- lifecycle -----------------------------------------------------------
    def initialize(self, **kwargs):
        res = super().initialize(**kwargs)
        if res:
            return res
        self.load_data()
        if self.total_samples == 0:
            raise NoMoreJobs("loader %s has no samples" % self.name)
        # BEFORE train_ratio subsetting: the check must see the labels the
        # class_lengths geometry still describes
        self.check_label_diversity()
        self._shuffled_indices = numpy.arange(self.total_samples,
                                              dtype=numpy.int32)
        if self.train_ratio < 1.0 and self.class_lengths[TRAIN]:
            # random train subset: keep head (test+valid) intact, replace
            # the train tail with a sampled subset of itself
            start = self.class_end_offsets[VALID]
            train = self._shuffled_indices[start:]
            keep = max(1, int(round(len(train) * self.train_ratio)))
            subset = self.prng.permutation(len(train))[:keep] + start
            self._shuffled_indices = numpy.concatenate(
                [self._shuffled_indices[:start],
                 subset.astype(numpy.int32)])
            self.class_lengths[TRAIN] = keep
        self.shuffle()
        self.create_minibatch_data()
        n = self.max_minibatch_size
        if self.plan_steps > 1:
            # the plan height is STATIC: the fused consumer scans every
            # row, and rows past a class/epoch boundary are mask-zero
            # DEAD COMPUTE. Clamp to the tallest per-class height so a
            # large minibatch cannot silently burn most of the dispatch
            # on masked rows (measured: the mb=256 conv-AE at the
            # default 16-step plan spent 12/16 rows masked — 4x the
            # work per served sample of the mb=64 config)
            tallest = max((self.plan_rows_for(c) for c in range(3)
                           if self.class_lengths[c]), default=1)
            if tallest < self.plan_steps:
                # say so: a silently overridden steps_per_dispatch is a
                # mystery to whoever configured it (ADVICE)
                self.info("%s: plan_steps clamped %d -> %d (tallest "
                          "class plan)", self.name, self.plan_steps,
                          tallest)
                self.plan_steps = tallest
        k = self.plan_steps
        if k > 1 and not self.fused:
            from ..error import Bug
            raise Bug("plan_steps>1 requires a fused consumer (host "
                      "fill_minibatch cannot batch plans)")
        shape = (k, n) if k > 1 else (n,)
        self.minibatch_indices.reset(numpy.zeros(shape, dtype=numpy.int32))
        self.minibatch_mask.reset(numpy.zeros(shape, dtype=numpy.float32))
        self.info(
            "%s: %d samples (test=%d validation=%d train=%d), mb=%d",
            self.name, self.total_samples, *self.class_lengths, n)
        return None

    def check_label_diversity(self) -> Optional[float]:
        """χ² homogeneity check of VALIDATION vs TRAIN label distributions
        (reference: veles/loader/base.py:1007): a skewed split usually
        means a broken loader. Warns; returns the p-value (None when not
        applicable)."""
        labels = getattr(self, "original_labels", None)
        if labels is None:
            return None
        if hasattr(labels, "mem"):      # veles_tpu Array
            labels = labels.mem
        if labels is None:              # Array allocated but empty
            return None
        labels = numpy.asarray(labels).ravel()
        if labels.size == 0:
            return None
        try:        # optional dep, like lmdb/h5py: diagnostic only —
            # probe before doing any counting work
            from scipy.stats import chi2 as chi2_dist
        except ImportError:
            return None
        offs = self.class_end_offsets
        valid = labels[offs[TEST]:offs[VALID]]
        train = labels[offs[VALID]:offs[TRAIN]]
        if len(valid) == 0 or len(train) == 0:
            return None
        classes = numpy.union1d(numpy.unique(valid), numpy.unique(train))
        if len(classes) < 2:
            return None
        cv = numpy.array([(valid == c).sum() for c in classes], float)
        ct = numpy.array([(train == c).sum() for c in classes], float)
        # χ² two-sample homogeneity statistic
        n1, n2 = cv.sum(), ct.sum()
        pooled = (cv + ct) / (n1 + n2)
        expected_v, expected_t = pooled * n1, pooled * n2
        with numpy.errstate(divide="ignore", invalid="ignore"):
            chi2 = numpy.nansum((cv - expected_v) ** 2 / expected_v +
                                (ct - expected_t) ** 2 / expected_t)
        p = float(chi2_dist.sf(chi2, df=len(classes) - 1))
        if p < 0.01:
            self.warning(
                "%s: validation/train label distributions differ "
                "(χ²=%.1f, p=%.2g) — check the dataset split",
                self.name, chi2, p)
        return p

    def shuffle(self) -> None:
        """Shuffle ONLY the train tail (reference: veles/loader/base.py
        shuffles train indices each epoch)."""
        if self.class_lengths[TRAIN] == 0:
            return
        if self.epoch_number > self.shuffle_limit:
            return
        start = self.class_end_offsets[VALID]
        train = self._shuffled_indices[start:]
        self.prng.shuffle(train)

    # -- the serving loop ----------------------------------------------------
    def run(self) -> None:
        from ..resilience.faults import fire as fire_fault
        fire_fault("loader.batch")
        if self.block_epochs > 1:
            self.serve_epoch_block()
        elif self.plan_steps > 1:
            self.serve_plan()
        else:
            self.serve_next_minibatch()

    def _begin_serving(self) -> None:
        if bool(self.epoch_ended):
            # previous run ended the epoch: start a new one
            self.epoch_number += 1
            self._global_offset = 0
            self.shuffle()
        self.epoch_ended <<= False
        self.last_minibatch <<= False
        self.train_ended <<= False
        self.test_ended <<= False

    def _geometry_for(self, offset):
        """(class, valid size) of the minibatch at ``offset`` — pure
        read of the epoch geometry, shared by the serial server and
        the prefetch producer (ONE copy of the walk rule: the two
        paths must never disagree on what batch lives at an offset)."""
        cls = self.class_of_offset(offset)
        return cls, min(self.max_minibatch_size,
                        self.class_end_offsets[cls] - offset)

    def _next_geometry(self):
        """(offset, class, valid_size) of the next minibatch."""
        offset = self._global_offset
        cls, size = self._geometry_for(offset)
        return offset, cls, size

    def _fill_row(self, idx_row, mask_row, offset, size,
                  indices=None) -> None:
        """Write one index row (tail-padded with the last valid index)
        and optionally its validity mask. ``indices`` defaults to the
        live shuffle order; the prefetch producer passes its frozen
        per-epoch copy — same pad rule, one implementation."""
        src = self._shuffled_indices if indices is None else indices
        idx_row[:size] = src[offset:offset + size]
        idx_row[size:] = idx_row[size - 1] if size else 0
        if mask_row is not None:
            mask_row[:size] = 1.0
            mask_row[size:] = 0.0

    def _advance(self, cls, size) -> None:
        """Move the global offset and update flags
        (reference: veles/loader/base.py:862-878)."""
        self.samples_served += size
        self._global_offset += size
        if self._global_offset >= self.class_end_offsets[cls]:
            if cls == TEST:
                self.test_ended <<= True
            if cls == TRAIN:
                self.train_ended <<= True
        if self._global_offset >= self.total_samples:
            self.last_minibatch <<= True
            self.epoch_ended <<= True
            self.event("epoch", "single", number=self.epoch_number)

    def serve_next_minibatch(self) -> None:
        """(reference: veles/loader/base.py:726)"""
        self._begin_serving()
        offset, cls, size = self._next_geometry()
        self.minibatch_offset = offset
        self.minibatch_class = cls
        self.minibatch_size = size
        self._fill_row(self.minibatch_indices.map_invalidate(),
                       self.minibatch_mask.map_invalidate(), offset, size)
        if not self.fused:
            if self.prefetch_depth > 0:
                self._fill_prefetched(offset)
            else:
                self.fill_minibatch()
        self._advance(cls, size)

    # -- prefetch machinery (overlap engine, docs/overlap.md) ----------------
    def _epoch_batches(self, start, indices, total):
        """Generator the prefetch producer runs: walk THIS epoch's
        remaining geometry over a frozen index copy, gathering each
        batch with the pure :meth:`fetch_batch`. Geometry and pad rule
        come from the same ``_geometry_for``/``_fill_row`` the serial
        server uses (class_lengths are stable within an epoch). No
        serving state, no PRNG — the main thread replays the identical
        geometry, so prefetch changes when the gather happens, never
        its content."""
        offset = start
        while offset < total:
            cls, size = self._geometry_for(offset)
            idx = numpy.empty(self.max_minibatch_size, numpy.int32)
            self._fill_row(idx, None, offset, size, indices=indices)
            yield {"offset": offset,
                   "batch": self.fetch_batch(idx, size),
                   "last": offset + size >= total}
            offset += size

    def _arm_prefetcher(self):
        """Start a producer for the CURRENT epoch from the CURRENT
        offset (re-armed each epoch — the producer must see the
        post-shuffle order, and must never shuffle itself)."""
        if self._prefetch_supported is False:
            return None
        from ..overlap.prefetch import Prefetcher
        self._prefetcher = Prefetcher(
            self._epoch_batches(
                self._global_offset,
                numpy.array(self._shuffled_indices),
                self.total_samples),
            depth=self.prefetch_depth,
            name="%s.epoch%d" % (self.name, self.epoch_number))
        return self._prefetcher

    def _fill_prefetched(self, offset) -> None:
        """The prefetching variant of ``fill_minibatch()``: install the
        staged batch, or fall back inline when the loader has no pure
        gather or the stream desynced (e.g. mid-epoch resume)."""
        pf = self._prefetcher or self._arm_prefetcher()
        if pf is None:
            self.fill_minibatch()
            return
        try:
            rec = pf.get()
        except StopIteration:
            rec = None
        if rec is not None and rec["batch"] is None:
            # probed unsupported: this loader customizes its fill —
            # permanent inline fallback, said once
            self._prefetch_supported = False
            self._close_prefetcher()
            self.info("%s: fetch_batch not supported — prefetch_depth="
                      "%d falls back to inline fill", self.name,
                      self.prefetch_depth)
            self.fill_minibatch()
            return
        if rec is None or rec["offset"] != offset:
            self._close_prefetcher()
            self.fill_minibatch()
            return
        self._prefetch_supported = True
        self.apply_batch(rec["batch"])
        if rec["last"]:
            # epoch exhausted: the next epoch re-arms AFTER the main
            # thread's shuffle (in _begin_serving order)
            self._close_prefetcher()

    def _close_prefetcher(self) -> None:
        if self._prefetcher is not None:
            self._prefetcher.close()
            self._prefetcher = None

    def stop(self) -> None:
        self._close_prefetcher()

    def serve_plan(self) -> None:
        """Serve up to plan_steps minibatches of ONE sample class as a
        (plan_steps, mb) index/mask plan; unused rows are mask-zero.
        Stops early at class or epoch boundaries so Decision/flag semantics
        stay exact."""
        self._begin_serving()
        idx = self.minibatch_indices.map_invalidate()
        mask = self.minibatch_mask.map_invalidate()
        first_cls = None
        k = 0
        while k < self.plan_steps:
            if self._global_offset >= self.total_samples:
                break
            offset, cls, size = self._next_geometry()
            if first_cls is None:
                first_cls = cls
                self.minibatch_offset = offset
            elif cls != first_cls:
                break
            self._fill_row(idx[k], mask[k], offset, size)
            self._advance(cls, size)
            k += 1
        mask[k:] = 0.0
        idx[k:] = 0
        self.minibatch_class = first_cls if first_cls is not None else TRAIN
        self.plan_length = k
        self.minibatch_size = int(mask.sum())
        # no host fill: plan mode is fused-only (enforced at initialize)

    def plan_rows_for(self, cls: int) -> int:
        """Static plan height for one sample class: ceil(len / mb)."""
        n = self.class_lengths[cls]
        mb = self.max_minibatch_size
        return -(-n // mb) if n else 0

    def serve_epoch_block(self) -> None:
        """Serve ``block_epochs`` WHOLE epochs as per-class stacked index
        plans: for each class c with samples, (H, K_c, mb) indices+mask.
        The epoch walk order inside each epoch is the offset order
        (test → validation → train), exactly the classic loop's order;
        flags/counters advance as if the epochs were served one by one,
        so Decision/Snapshotter semantics are unchanged (they just see H
        epochs per drain)."""
        from ..error import Bug
        if not self.fused:
            raise Bug("serve_epoch_block requires a fused consumer")
        h = self.block_epochs
        if self.block_epochs_cap is not None:
            completed = self.epoch_number + (1 if bool(self.epoch_ended)
                                             else 0)
            h = max(1, min(h, self.block_epochs_cap - completed))
        mb = self.max_minibatch_size
        if not self.block_plans:
            for cls in (TEST, VALID, TRAIN):
                rows = self.plan_rows_for(cls)
                if not rows:
                    continue
                shape = (h, rows, mb)
                self.block_plans[cls] = (
                    Array(numpy.zeros(shape, numpy.int32),
                          name="%s.block_idx%d" % (self.name, cls)),
                    Array(numpy.zeros(shape, numpy.float32),
                          name="%s.block_mask%d" % (self.name, cls)))
        self.block_length = h
        views = {cls: (idx.map_invalidate(), mask.map_invalidate())
                 for cls, (idx, mask) in self.block_plans.items()}
        for e in range(h):
            self._begin_serving()
            rows_done = {cls: 0 for cls in views}
            while self._global_offset < self.total_samples:
                offset, cls, size = self._next_geometry()
                idx, mask = views[cls]
                k = rows_done[cls]
                self._fill_row(idx[e, k], mask[e, k], offset, size)
                rows_done[cls] = k + 1
                self._advance(cls, size)
            # epoch_ended is now True; the next e re-enters a new epoch
        self.minibatch_class = TRAIN
        self.plan_length = self.plan_rows_for(TRAIN)
        self.minibatch_size = mb

    # -- checkpoint protocol -------------------------------------------------
    def state_dict(self):
        return {
            "epoch_number": self.epoch_number,
            "global_offset": self._global_offset,
            # train_ratio subsetting rewrites geometry at initialize;
            # a resume in a fresh process (default ratio 1.0) must see
            # the subset geometry the indices were built for
            "class_lengths": list(self.class_lengths),
            "shuffled_indices": (None if self._shuffled_indices is None
                                 else numpy.array(self._shuffled_indices)),
            "samples_served": self.samples_served,
            "flags": {"epoch_ended": bool(self.epoch_ended),
                      "last_minibatch": bool(self.last_minibatch),
                      "train_ended": bool(self.train_ended),
                      "test_ended": bool(self.test_ended)},
        }

    def load_state_dict(self, sd) -> None:
        # a restored position invalidates anything staged ahead; the
        # desync guard in _fill_prefetched would catch it, but closing
        # now avoids serving a whole stale epoch into the fallback path
        self._close_prefetcher()
        self.epoch_number = sd["epoch_number"]
        self._global_offset = sd["global_offset"]
        if "class_lengths" in sd:
            self.class_lengths = list(sd["class_lengths"])
        if sd["shuffled_indices"] is not None:
            self._shuffled_indices = numpy.array(sd["shuffled_indices"])
        self.samples_served = sd["samples_served"]
        flags = sd["flags"]
        self.epoch_ended <<= flags["epoch_ended"]
        self.last_minibatch <<= flags["last_minibatch"]
        self.train_ended <<= flags["train_ended"]
        self.test_ended <<= flags["test_ended"]

    # -- introspection -------------------------------------------------------
    def get_metric_values(self) -> Dict[str, object]:
        return {"epochs_served": self.epoch_number,
                "samples_served": self.samples_served}


class LoaderMSE(Loader):
    """Loader with regression targets instead of integer labels
    (reference: veles/loader/base.py:1149)."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.minibatch_targets = Array(name=self.name + ".minibatch_targets")
