"""HDF5Loader: datasets stored in HDF5 files.

Equivalent of the reference's veles/loader/loader_hdf5.py:94 (HDF5Loader):
per-class HDF5 files each with "data" and (optionally) "labels" datasets,
or one file with per-class groups.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy

from ..error import VelesError
from .base import TEST, VALID, TRAIN
from .fullbatch import FullBatchLoader


class HDF5Loader(FullBatchLoader):
    """``files``: 3-sequence (test, validation, train) of .h5/.hdf5 paths,
    None for absent classes; ``data_key``/``labels_key`` name the datasets
    inside each file."""

    MAPPING = "hdf5_loader"

    def __init__(self, workflow, files: Sequence[Optional[str]] = (),
                 data_key: str = "data", labels_key: str = "labels",
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        if len(files) != 3:
            raise VelesError(
                "files must be (test, validation, train) paths")
        self.files = list(files)
        self.data_key = data_key
        self.labels_key = labels_key

    def load_data(self) -> None:
        try:
            import h5py
        except ImportError as exc:  # pragma: no cover - present in image
            raise VelesError("HDF5Loader needs h5py: %s" % exc)
        datas, labelss, lengths = [], [], [0, 0, 0]
        have_labels = None
        for cls in (TEST, VALID, TRAIN):
            path = self.files[cls]
            if not path:
                continue
            with h5py.File(path, "r") as fin:
                if self.data_key not in fin:
                    raise VelesError("%s has no %r dataset"
                                     % (path, self.data_key))
                data = numpy.asarray(fin[self.data_key])
                labels = (numpy.asarray(fin[self.labels_key])
                          if self.labels_key in fin else None)
            if have_labels is None:
                have_labels = labels is not None
            elif have_labels != (labels is not None):
                raise VelesError(
                    "inconsistent %r presence across class files"
                    % self.labels_key)
            if labels is not None:
                if len(labels) != len(data):
                    raise VelesError("%s: %d labels for %d samples"
                                     % (path, len(labels), len(data)))
                labelss.append(labels)
            datas.append(data)
            lengths[cls] = len(data)
        self.create_originals(
            numpy.concatenate(datas),
            numpy.concatenate(labelss) if labelss else None)
        self.class_lengths = lengths
        if self.validation_ratio and not lengths[VALID]:
            self.resize_validation(self.validation_ratio)
