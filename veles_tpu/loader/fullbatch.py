"""Full-batch loaders: whole dataset in memory (and HBM when it fits).

Equivalent of the reference's veles/loader/fullbatch.py:79-566
(FullBatchLoader + FullBatchLoaderMSE with the GPU ``fill_minibatch``
kernel, ocl/fullbatch_loader.cl). TPU-native: the dataset is placed once as
a device jax.Array and minibatch gather (``jnp.take``) happens on device —
inside the fused train step when one is attached (zero host↔device traffic
per step), or standalone in ``fill_minibatch``. Falls back to host storage
when the dataset exceeds the HBM budget (reference OOM fallback,
veles/loader/fullbatch.py:170-187)."""

from __future__ import annotations

from typing import Optional

import numpy

from ..config import root
from ..memory import Array
from .base import Loader, LoaderMSE, TEST, VALID, TRAIN


def _storage_dtype(arr: numpy.ndarray):
    """Storage dtype policy shared by dataset and MSE-target arrays:
    integer arrays (token ids) keep their dtype — casting ids through a
    float policy dtype would silently corrupt large values; float
    arrays take engine.dataset_dtype when set (bf16 halves device
    residency and host->device staging), else the param policy dtype."""
    if numpy.issubdtype(arr.dtype, numpy.integer):
        return arr.dtype
    return (root.common.engine.get("dataset_dtype", None)
            or root.common.engine.precision_type)


class FullBatchLoader(Loader):
    """Subclasses fill ``original_data``/``original_labels`` in load_data
    (reference: create_originals, veles/loader/fullbatch.py:278)."""

    hide_from_registry = True

    def __init__(self, workflow, on_device=True, validation_ratio=None,
                 **kwargs):
        super().__init__(workflow, **kwargs)
        self.original_data = Array(name=self.name + ".original_data")
        self.original_labels = Array(name=self.name + ".original_labels")
        self.on_device = on_device
        self.validation_ratio = validation_ratio

    # -- helpers for subclasses ---------------------------------------------
    def create_originals(self, data: numpy.ndarray,
                         labels: Optional[numpy.ndarray] = None) -> None:
        data = numpy.asarray(data)
        self.original_data.reset(numpy.ascontiguousarray(
            data, dtype=_storage_dtype(data)))
        if labels is not None:
            self.original_labels.reset(
                numpy.ascontiguousarray(labels, dtype=numpy.int32))

    def resize_validation(self, ratio: float) -> None:
        """Carve a RANDOM validation subset out of the train region
        (reference: _resize_validation, veles/loader/fullbatch.py:349).
        The train rows are permuted first — datasets usually arrive
        class-sorted, and a head-slice split would be 100% one class."""
        n_train = self.class_lengths[TRAIN]
        n_valid = int(n_train * ratio)
        start = self.class_lengths[0] + self.class_lengths[VALID]
        perm = start + self.prng.permutation(n_train)
        self.original_data.mem[start:] = self.original_data.mem[perm]
        to_permute = [self.original_labels]
        # a label-indexed target TABLE is row-order independent — it
        # must never be permuted like row-aligned targets
        if not getattr(self, "targets_by_label", False):
            to_permute.append(getattr(self, "original_targets", None))
        for arr in to_permute:
            if arr is not None and arr:
                arr.mem[start:] = arr.mem[perm]
        paths = getattr(self, "row_paths", None)
        if paths:
            # provenance must follow the row permutation or downstream
            # path-keyed matching (ImageLoaderMSE basenames) misaligns
            self.row_paths = paths[:start] + [paths[i] for i in perm]
        self.class_lengths[VALID] += n_valid
        self.class_lengths[TRAIN] -= n_valid

    # -- loader contract -----------------------------------------------------
    def create_minibatch_data(self) -> None:
        n = self.max_minibatch_size
        # on-device augmentation may change the sample shape (e.g. random
        # crop): downstream units must see the post-augment shape
        shape_for = getattr(self, "sample_shape_after_augment", None)
        sample = (shape_for() if callable(shape_for)
                  else self.original_data.shape[1:])
        self.minibatch_data.reset(
            numpy.zeros((n,) + tuple(sample),
                        dtype=self.original_data.dtype))
        if self.original_labels:
            self.minibatch_labels.reset(numpy.zeros(n, dtype=numpy.int32))

    def fill_minibatch(self) -> None:
        idx = self.minibatch_indices.mem
        data = self.minibatch_data.map_invalidate()
        data[...] = self.original_data.mem[idx]
        if self.original_labels:
            labels = self.minibatch_labels.map_invalidate()
            labels[...] = self.original_labels.mem[idx]

    def fetch_batch(self, idx, size):
        """Pure mirror of :meth:`fill_minibatch` for the overlap
        prefetcher: fancy indexing copies, so the producer thread never
        aliases shared arrays. A subclass that customizes the fill
        (augmentation) opts out automatically — the mirror would
        silently skip its work."""
        if type(self).fill_minibatch not in (
                FullBatchLoader.fill_minibatch,
                FullBatchLoaderMSE.fill_minibatch):
            return None
        out = {"data": self.original_data.mem[idx]}
        if self.original_labels:
            out["labels"] = self.original_labels.mem[idx]
        return out

    # -- device-resident dataset for fused steps ----------------------------
    def dataset_device_views(self):
        """(data, labels) device arrays for in-step gather (the
        fullbatch_loader.cl equivalent)."""
        data = self.original_data.device_view()
        labels = (self.original_labels.device_view()
                  if self.original_labels else None)
        return data, labels


class FullBatchLoaderMSE(FullBatchLoader, LoaderMSE):
    """Full-batch loader with regression targets
    (reference: veles/loader/fullbatch.py:563).

    ``targets_by_label = True`` switches ``original_targets`` from a
    row-aligned array to a per-LABEL table indexed by the row's label
    (the channels scheme: one template per class stored ONCE, not
    copied per row — per-row materialization would double the dominant
    HBM buffer). The fused step and the host minibatch fill both
    compose the gather through ``original_labels``."""

    hide_from_registry = True

    #: when True, original_targets rows are LABEL ids, not dataset rows
    targets_by_label = False

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.original_targets = Array(name=self.name + ".original_targets")

    def create_originals(self, data, labels=None, targets=None):
        super().create_originals(data, labels)
        if targets is not None:
            targets = numpy.asarray(targets)
            # targets are pixel-volume arrays in the AE/kanji cases —
            # the same storage policy as the data applies
            self.original_targets.reset(numpy.ascontiguousarray(
                targets, dtype=_storage_dtype(targets)))

    def create_minibatch_data(self) -> None:
        super().create_minibatch_data()
        if self.original_targets:
            n = self.max_minibatch_size
            shape = (n,) + self.original_targets.shape[1:]
            self.minibatch_targets.reset(
                numpy.zeros(shape, dtype=self.original_targets.dtype))

    def fill_minibatch(self) -> None:
        super().fill_minibatch()
        if self.original_targets:
            idx = self.minibatch_indices.mem
            t = self.minibatch_targets.map_invalidate()
            if self.targets_by_label:
                t[...] = self.original_targets.mem[
                    self.original_labels.mem[idx]]
            else:
                t[...] = self.original_targets.mem[idx]

    def fetch_batch(self, idx, size):
        out = super().fetch_batch(idx, size)
        if out is not None and self.original_targets:
            if self.targets_by_label:
                out["targets"] = self.original_targets.mem[
                    self.original_labels.mem[idx]]
            else:
                out["targets"] = self.original_targets.mem[idx]
        return out
