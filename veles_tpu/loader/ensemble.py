"""EnsembleLoader: member predictions as a dataset (stacking input).

Equivalent of the reference's veles/loader/ensemble.py:46-143
(EnsembleLoader*): reads the per-model outputs recorded by an ensemble
test run and serves them as minibatch input — the training set for a
stacking combiner (or any analysis over member votes). Member outputs are
.npy files referenced from the outputs manifest written by
``EnsembleTester(save_outputs=dir)``."""

from __future__ import annotations

import json

import numpy

from ..error import VelesError
from .base import TRAIN
from .fullbatch import FullBatchLoader


class EnsembleLoader(FullBatchLoader):
    """``manifest``: path of the outputs JSON ({"outputs": [npy, ...],
    "labels": npy}); features = member probabilities concatenated along
    the class axis."""

    MAPPING = "ensemble_loader"

    def __init__(self, workflow, manifest: str = "", **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.manifest = manifest

    def load_data(self) -> None:
        with open(self.manifest) as fin:
            man = json.load(fin)
        outputs = man.get("outputs", [])
        if not outputs:
            raise VelesError("%s lists no member outputs" % self.manifest)
        probs = [numpy.load(p) for p in outputs]
        shapes = {p.shape for p in probs}
        if len(shapes) != 1:
            raise VelesError("member output shapes differ: %s"
                             % sorted(shapes))
        data = numpy.concatenate(probs, axis=1)
        labels = (numpy.load(man["labels"])
                  if man.get("labels") else None)
        self.create_originals(data, labels)
        self.class_lengths = [0, 0, len(data)]
        if self.validation_ratio:
            self.resize_validation(self.validation_ratio)
