"""Minibatch record / replay: checkpoint the data pipeline itself.

Equivalent of the reference's veles/loader/saver.py:69-383
(MinibatchesSaver / MinibatchesLoader): a Saver unit linked after any
loader records every served minibatch (data, labels, class, size) into one
compressed container; MinibatchesLoader later replays that file as a
drop-in Loader — reproducing a preprocessed pipeline without the original
dataset or augmentation cost. The reference used snappy-framed binary;
here it is a single compressed .npz-style pickle stream (gzip), written
incrementally.
"""

from __future__ import annotations

import gzip
import pickle
from typing import Optional

import numpy

from ..error import VelesError
from ..units import Unit
from .base import Loader
from .fullbatch import FullBatchLoader

MAGIC = b"VTMB1\n"


class MinibatchesSaver(Unit):
    """Link after a loader: records each minibatch as it is served.

    ``python -m veles_tpu model.py`` + a saver in the graph → file;
    MinibatchesLoader replays it (reference: veles/loader/saver.py:69).
    """

    MAPPING = "minibatches_saver"

    def __init__(self, workflow, file_name: str = "minibatches.vtmb",
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.file_name = file_name
        self.loader: Optional[Loader] = None
        self._fout = None
        self._count = 0

    def initialize(self, **kwargs):
        res = super().initialize(**kwargs)
        if res:
            return res
        if self.loader is None:
            raise VelesError("%s needs .loader set" % self.name)
        self._fout = gzip.open(self.file_name, "wb")
        self._fout.write(MAGIC)
        self._count = 0
        return None

    def run(self) -> None:
        ld = self.loader
        if ld.fused:
            # fused loaders never fill minibatch_data on host; gather the
            # served rows from the originals via the index plan
            idx = ld.minibatch_indices.mem
            mask = ld.minibatch_mask.mem
            rows = idx.reshape(1, -1) if idx.ndim == 1 else idx
            mrows = mask.reshape(1, -1) if mask.ndim == 1 else mask
            for k in range(getattr(ld, "plan_length", 1) or 1):
                size = int(mrows[k].sum())
                if not size:
                    continue
                sel = rows[k][:size]
                self._dump({
                    "class": ld.minibatch_class, "size": size,
                    "data": numpy.array(ld.original_data.mem[sel]),
                    "labels": (numpy.array(ld.original_labels.mem[sel])
                               if ld.original_labels else None)})
            return
        self._dump({
            "class": ld.minibatch_class,
            "size": ld.minibatch_size,
            "data": numpy.array(ld.minibatch_data.mem[:ld.minibatch_size]),
            "labels": (numpy.array(
                ld.minibatch_labels.mem[:ld.minibatch_size])
                if ld.minibatch_labels else None),
        })

    def _dump(self, rec) -> None:
        pickle.dump(rec, self._fout, protocol=pickle.HIGHEST_PROTOCOL)
        self._count += 1

    def stop(self) -> None:
        if self._fout is not None:
            self._fout.close()
            self._fout = None
            self.info("saved %d minibatches → %s", self._count,
                      self.file_name)


class MinibatchesLoader(FullBatchLoader):
    """Replays a MinibatchesSaver file as a drop-in Loader
    (reference: veles/loader/saver.py:182). Reconstructs a full-batch
    dataset from the records so the fused TPU step gathers on device like
    any other loader."""

    MAPPING = "minibatches_loader"

    def __init__(self, workflow, file_name: str = "minibatches.vtmb",
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.file_name = file_name

    def load_data(self) -> None:
        per_class = {0: ([], []), 1: ([], []), 2: ([], [])}
        with gzip.open(self.file_name, "rb") as fin:
            if fin.read(len(MAGIC)) != MAGIC:
                raise VelesError("%s is not a minibatches file"
                                 % self.file_name)
            while True:
                try:
                    rec = pickle.load(fin)
                except EOFError:
                    break
                datas, labels = per_class[rec["class"]]
                datas.append(rec["data"])
                if rec["labels"] is not None:
                    labels.append(rec["labels"])
        datas, labelss, lengths = [], [], [0, 0, 0]
        for cls in (0, 1, 2):
            d, l = per_class[cls]
            if not d:
                continue
            data = numpy.concatenate(d)
            datas.append(data)
            if l:
                labelss.append(numpy.concatenate(l))
            lengths[cls] = len(data)
        if not datas:
            raise VelesError("%s holds no minibatches" % self.file_name)
        self.create_originals(
            numpy.concatenate(datas),
            numpy.concatenate(labelss) if labelss else None)
        self.class_lengths = lengths
