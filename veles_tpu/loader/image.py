"""Image loaders: directory scanning + decode + augmentation.

Equivalent of the reference's veles/loader/image.py /
veles/loader/file_image.py / veles/loader/fullbatch_image.py surface
(ImageLoader with scale/crop/mirror/rotation augmentation, channel
handling, auto-labelling): decode via PIL, normalize to NHWC float32,
materialize the whole (augmented) dataset as a full-batch array — the
TPU-native shape: the dataset lives in HBM and minibatch gather happens
inside the fused step, so augmentation multiplicity is paid once at load
time, not per epoch.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy

from ..error import VelesError
from .base import TEST, VALID, TRAIN
from .file_loader import FileFilter, FileListScanner, auto_label
from .fullbatch import FullBatchLoader

IMAGE_PATTERNS = ("*.png", "*.jpg", "*.jpeg", "*.bmp", "*.gif", "*.tiff",
                  "*.webp")


def decode_image(path: str, size: Optional[Tuple[int, int]] = None,
                 color: str = "RGB") -> numpy.ndarray:
    """File → HWC float32 in [0, 1] (reference decode path used PIL or
    jpeg4py, veles/loader/image.py:106+)."""
    from PIL import Image
    with Image.open(path) as img:
        img = img.convert(color)
        if size is not None:
            img = img.resize((size[1], size[0]), Image.BILINEAR)
        arr = numpy.asarray(img, dtype=numpy.float32) / 255.0
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return arr


def augment(arr: numpy.ndarray, mirror: bool = False,
            rotations: Sequence[int] = (0,),
            crop: Optional[Tuple[int, int]] = None,
            crop_number: int = 1, rand=None) -> list:
    """All augmented variants of one HWC image (reference knobs: scale,
    crop, rotation, mirror — veles/loader/image.py augmentation)."""
    variants = []
    for rot in rotations:
        v = numpy.rot90(arr, rot // 90) if rot else arr
        variants.append(v)
        if mirror:
            variants.append(v[:, ::-1])
    if crop is not None:
        ch, cw = crop
        cropped = []
        for v in variants:
            h, w = v.shape[:2]
            if h < ch or w < cw:
                raise VelesError("crop %s larger than image %s"
                                 % (crop, v.shape))
            for _ in range(crop_number):
                y = rand.randint(0, h - ch + 1) if rand else (h - ch) // 2
                x = rand.randint(0, w - cw + 1) if rand else (w - cw) // 2
                cropped.append(v[y:y + ch, x:x + cw])
        variants = cropped
    return [numpy.ascontiguousarray(v) for v in variants]


class ImageLoader(FullBatchLoader):
    """Scan directories of images per class, decode, augment, label.

    - ``train_paths``/``validation_paths``/``test_paths``: directories or
      files (reference FileImageLoader contract).
    - labels come from the containing directory name unless the subclass
      overrides ``get_label`` (reference AutoLabelFileLoader).
    - augmentation (train class only): mirror, rotations, random crops.
    """

    MAPPING = "image_loader"

    def __init__(self, workflow, train_paths: Sequence[str] = (),
                 validation_paths: Sequence[str] = (),
                 test_paths: Sequence[str] = (),
                 size: Optional[Tuple[int, int]] = None,
                 color: str = "RGB", mirror: bool = False,
                 rotations: Sequence[int] = (0,),
                 crop: Optional[Tuple[int, int]] = None,
                 crop_number: int = 1, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.scanner = FileListScanner(
            train_paths, validation_paths, test_paths,
            FileFilter(include=IMAGE_PATTERNS))
        self.size = size
        self.color = color
        self.mirror = mirror
        self.rotations = tuple(rotations)
        self.crop = crop
        self.crop_number = crop_number
        #: label string → index (reference labels_mapping)
        self.label_names: Dict[int, str] = {}

    def get_label(self, path: str) -> str:
        return auto_label(path)

    def load_data(self) -> None:
        per_class = self.scanner.scan()
        # deterministic label mapping over ALL classes
        names = sorted({self.get_label(p)
                        for files in per_class for p in files})
        self.labels_mapping = {n: i for i, n in enumerate(names)}
        self.label_names = {i: n for n, i in self.labels_mapping.items()}
        data, labels = [], []
        lengths = [0, 0, 0]
        for cls in (TEST, VALID, TRAIN):
            for path in per_class[cls]:
                arr = decode_image(path, self.size, self.color)
                if cls == TRAIN:
                    variants = augment(
                        arr, self.mirror, self.rotations, self.crop,
                        self.crop_number, self.prng)
                elif self.crop is not None:
                    # eval classes: deterministic center crop only
                    variants = augment(arr, crop=self.crop)
                else:
                    variants = [arr]
                label = self.labels_mapping[self.get_label(path)]
                data.extend(variants)
                labels.extend([label] * len(variants))
                lengths[cls] += len(variants)
        shapes = {v.shape for v in data}
        if len(shapes) != 1:
            raise VelesError(
                "images have differing shapes %s — pass size=(H, W) or "
                "crop=(H, W)" % sorted(shapes))
        self.create_originals(numpy.stack(data),
                              numpy.asarray(labels, dtype=numpy.int32))
        self.class_lengths = lengths
        if self.validation_ratio and not lengths[VALID]:
            self.resize_validation(self.validation_ratio)
