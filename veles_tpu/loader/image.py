"""Image loaders: directory scanning + decode + augmentation.

Equivalent of the reference's veles/loader/image.py /
veles/loader/file_image.py / veles/loader/fullbatch_image.py surface
(ImageLoader with scale/crop/mirror/rotation augmentation, channel
handling, auto-labelling): decode via PIL, normalize to NHWC float32,
materialize the whole (augmented) dataset as a full-batch array — the
TPU-native shape: the dataset lives in HBM and minibatch gather happens
inside the fused step, so augmentation multiplicity is paid once at load
time, not per epoch.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy

from ..error import VelesError
from .base import TEST, VALID, TRAIN
from .file_loader import FileFilter, FileListScanner, auto_label
from .fullbatch import FullBatchLoader, FullBatchLoaderMSE

IMAGE_PATTERNS = ("*.png", "*.jpg", "*.jpeg", "*.bmp", "*.gif", "*.tiff",
                  "*.webp")


def decode_image(path, size: Optional[Tuple[int, int]] = None,
                 color: str = "RGB") -> numpy.ndarray:
    """File (or raw encoded ``bytes`` — the serving path posts image
    payloads, not paths) → HWC float32 in [0, 1] with a codec-fallback
    chain (reference used jpeg4py with a PIL fallback,
    veles/loader/image.py:106+): PIL → imageio → matplotlib; .npy/.npz
    arrays load directly."""
    if isinstance(path, (bytes, bytearray)):
        import io
        path = io.BytesIO(bytes(path))
    if isinstance(path, str) and path.endswith((".npy", ".npz")):
        arr = numpy.load(path)
        if hasattr(arr, "files"):          # npz: first member
            arr = arr[arr.files[0]]
        arr = numpy.asarray(arr, dtype=numpy.float32)
        if arr.max() > 1.5:
            arr /= 255.0
    else:
        arr = None
        errors = []
        try:
            from PIL import Image
            if hasattr(path, "seek"):
                path.seek(0)      # fallback chain may retry the stream
            with Image.open(path) as img:
                img = img.convert(color)
                if size is not None:
                    img = img.resize((size[1], size[0]), Image.BILINEAR)
                arr = numpy.asarray(img, dtype=numpy.float32) / 255.0
        except Exception as e:        # PIL missing codec / truncated file
            errors.append("PIL: %s" % e)
        if arr is None:
            for mod, fn in (("imageio", "imread"),
                            ("matplotlib.image", "imread")):
                try:
                    import importlib
                    m = importlib.import_module(mod)
                    if hasattr(path, "seek"):
                        path.seek(0)
                    arr = numpy.asarray(getattr(m, fn)(path),
                                        dtype=numpy.float32)
                    if arr.max() > 1.5:
                        arr /= 255.0
                    arr = _convert_channels(arr, color)
                    break
                except Exception as e:
                    errors.append("%s: %s" % (mod, e))
        if arr is None:
            raise VelesError("cannot decode %s (%s)" %
                             (path, "; ".join(errors)))
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if size is not None and arr.shape[:2] != tuple(size):
        # fallback decoders have no resize: nearest-neighbour index map
        h, w = arr.shape[:2]
        yi = (numpy.arange(size[0]) * h // size[0]).clip(0, h - 1)
        xi = (numpy.arange(size[1]) * w // size[1]).clip(0, w - 1)
        arr = arr[yi][:, xi]
    return arr


def _convert_channels(arr: numpy.ndarray, color: str) -> numpy.ndarray:
    """Normalize fallback-decoder output to the requested color mode —
    the PIL path does this via Image.convert; imageio/matplotlib return
    whatever the file holds (RGBA pngs, grayscale…), which would mix
    channel counts inside one dataset."""
    if arr.ndim == 2:
        arr = arr[:, :, None]
    c = arr.shape[-1]
    if color in ("L", "I") :
        if c >= 3:       # ITU-R 601 luma, like PIL convert('L')
            arr = (arr[..., 0] * 0.299 + arr[..., 1] * 0.587
                   + arr[..., 2] * 0.114)[..., None]
        return arr[..., :1]
    # RGB-like targets
    if c == 1:
        return numpy.repeat(arr, 3, axis=-1)
    if c >= 4:
        return numpy.ascontiguousarray(arr[..., :3])
    return arr


def deterministic_split(paths: Sequence[str], valid_ratio: float = 0.0,
                        test_ratio: float = 0.0,
                        key: str = "split") -> Tuple[list, list, list]:
    """Stable (machine/run/order independent) train/valid/test split by
    hashing each file's basename — re-scanning a grown dataset keeps
    every previously-assigned file in its old set (the property the
    reference's shuffled-index splits lacked)."""
    import hashlib
    train, valid, test = [], [], []
    for p in sorted(paths):
        h = int.from_bytes(hashlib.sha1(
            (key + "/" + os.path.basename(p)).encode()).digest()[:8],
            "little") / 2.0 ** 64
        if h < test_ratio:
            test.append(p)
        elif h < test_ratio + valid_ratio:
            valid.append(p)
        else:
            train.append(p)
    return train, valid, test


def augment(arr: numpy.ndarray, mirror: bool = False,
            rotations: Sequence[int] = (0,),
            crop: Optional[Tuple[int, int]] = None,
            crop_number: int = 1, rand=None) -> list:
    """All augmented variants of one HWC image (reference knobs: scale,
    crop, rotation, mirror — veles/loader/image.py augmentation)."""
    variants = []
    for rot in rotations:
        v = numpy.rot90(arr, rot // 90) if rot else arr
        variants.append(v)
        if mirror:
            variants.append(v[:, ::-1])
    if crop is not None:
        ch, cw = crop
        cropped = []
        for v in variants:
            h, w = v.shape[:2]
            if h < ch or w < cw:
                raise VelesError("crop %s larger than image %s"
                                 % (crop, v.shape))
            for _ in range(crop_number):
                y = rand.randint(0, h - ch + 1) if rand else (h - ch) // 2
                x = rand.randint(0, w - cw + 1) if rand else (w - cw) // 2
                cropped.append(v[y:y + ch, x:x + cw])
        variants = cropped
    return [numpy.ascontiguousarray(v) for v in variants]


class ImageLoader(FullBatchLoader):
    """Scan directories of images per class, decode, augment, label.

    - ``train_paths``/``validation_paths``/``test_paths``: directories or
      files (reference FileImageLoader contract).
    - labels come from the containing directory name unless the subclass
      overrides ``get_label`` (reference AutoLabelFileLoader).
    - augmentation (train class only): mirror, rotations, random crops.
    """

    MAPPING = "image_loader"

    def __init__(self, workflow, train_paths: Sequence[str] = (),
                 validation_paths: Sequence[str] = (),
                 test_paths: Sequence[str] = (),
                 size: Optional[Tuple[int, int]] = None,
                 color: str = "RGB", mirror: bool = False,
                 rotations: Sequence[int] = (0,),
                 crop: Optional[Tuple[int, int]] = None,
                 crop_number: int = 1,
                 device_augmentation: bool = False, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.scanner = FileListScanner(
            train_paths, validation_paths, test_paths,
            FileFilter(include=IMAGE_PATTERNS))
        self.size = size
        self.color = color
        self.mirror = mirror
        self.rotations = tuple(rotations)
        self.crop = crop
        self.crop_number = crop_number
        #: TPU-first augmentation: keep ONE copy of each image in the
        #: device-resident dataset and apply random mirror/crop INSIDE
        #: the fused train step (memory multiplicity 1 instead of
        #: mirror x rotations x crop_number — the host-materializing
        #: path pays that multiplicity in RAM/HBM)
        self.device_augmentation = device_augmentation
        #: label string → index (reference labels_mapping)
        self.label_names: Dict[int, str] = {}
        self.device_augment_fn = None
        self.device_eval_fn = None

    def get_label(self, path: str) -> str:
        return auto_label(path)

    def load_data(self) -> None:
        per_class = self.scanner.scan()
        # deterministic label mapping over ALL classes
        names = sorted({self.get_label(p)
                        for files in per_class for p in files})
        self.labels_mapping = {n: i for i, n in enumerate(names)}
        self.label_names = {i: n for n, i in self.labels_mapping.items()}
        data, labels = [], []
        #: source file per dataset ROW (augment variants repeat their
        #: source) — provenance for debugging and the MSE target match
        self.row_paths: List[str] = []
        lengths = [0, 0, 0]
        for cls in (TEST, VALID, TRAIN):
            for path in per_class[cls]:
                arr = decode_image(path, self.size, self.color)
                if self.device_augmentation:
                    variants = [arr]       # multiplicity lives on device
                elif cls == TRAIN:
                    variants = augment(
                        arr, self.mirror, self.rotations, self.crop,
                        self.crop_number, self.prng)
                elif self.crop is not None:
                    # eval classes: deterministic center crop only
                    variants = augment(arr, crop=self.crop)
                else:
                    variants = [arr]
                label = self.labels_mapping[self.get_label(path)]
                data.extend(variants)
                labels.extend([label] * len(variants))
                self.row_paths.extend([path] * len(variants))
                lengths[cls] += len(variants)
        shapes = {v.shape for v in data}
        if len(shapes) != 1:
            raise VelesError(
                "images have differing shapes %s — pass size=(H, W) or "
                "crop=(H, W)" % sorted(shapes))
        self.create_originals(numpy.stack(data),
                              numpy.asarray(labels, dtype=numpy.int32))
        self.class_lengths = lengths
        if self.device_augmentation:
            self._build_device_augment()
        if self.validation_ratio and not lengths[VALID]:
            self.resize_validation(self.validation_ratio)

    def _build_device_augment(self) -> None:
        """Pure-jax per-batch augmentation, applied by TrainStep after
        the on-device gather: random horizontal mirror (when enabled)
        and random crop (train) / center crop (eval). Rotations need
        host multiplicity — use the materializing path for those."""
        if any(r % 360 for r in self.rotations):
            raise VelesError("device_augmentation supports mirror/crop; "
                             "rotations need the host path")
        mirror, crop = self.mirror, self.crop

        def eval_fn(batch):
            if crop is None:
                return batch
            ch, cw = crop
            h, w = batch.shape[1:3]
            y, x = (h - ch) // 2, (w - cw) // 2
            return batch[:, y:y + ch, x:x + cw, :]

        def train_fn(batch, rng):
            import jax
            import jax.numpy as jnp
            if rng is None:
                return eval_fn(batch)
            b = batch.shape[0]
            if mirror:
                flip = jax.random.bernoulli(
                    jax.random.fold_in(rng, 1), 0.5, (b,))
                batch = jnp.where(flip[:, None, None, None],
                                  batch[:, :, ::-1, :], batch)
            if crop is not None:
                ch, cw = crop
                h, w = batch.shape[1:3]
                ys = jax.random.randint(jax.random.fold_in(rng, 2),
                                        (b,), 0, h - ch + 1)
                xs = jax.random.randint(jax.random.fold_in(rng, 3),
                                        (b,), 0, w - cw + 1)

                def one(img, y, x):
                    return jax.lax.dynamic_slice(
                        img, (y, x, 0), (ch, cw, img.shape[-1]))
                batch = jax.vmap(one)(batch, ys, xs)
            return batch

        self.device_augment_fn = train_fn
        self.device_eval_fn = eval_fn

    def sample_shape_after_augment(self) -> Tuple[int, ...]:
        base = tuple(self.original_data.shape[1:])
        if self.device_augmentation and self.crop is not None:
            return tuple(self.crop) + base[2:]
        return base


class ClassImageLoader(ImageLoader):
    """Per-class directory tree loader (reference: FileImageLoader over
    class subdirectories, veles/loader/file_image.py):

        root/daisy/001.png
        root/rose/xyz.jpg …

    Labels come from the first-level subdirectory name; files split
    train/valid/test by the deterministic hash split (stable as the
    dataset grows). Pass explicit ``train``/``validation``/``test``
    subtrees instead by using ImageLoader directly."""

    MAPPING = "class_image_loader"

    def __init__(self, workflow, root_dir: str,
                 valid_ratio: float = 0.15, test_ratio: float = 0.0,
                 **kwargs) -> None:
        import glob as _glob
        train, valid, test = [], [], []
        if not os.path.isdir(root_dir):
            raise VelesError("no such dataset root: %s" % root_dir)
        for cls_dir in sorted(os.listdir(root_dir)):
            full = os.path.join(root_dir, cls_dir)
            if not os.path.isdir(full):
                continue
            files = []
            for pat in IMAGE_PATTERNS + ("*.npy",):
                files += _glob.glob(os.path.join(full, pat))
            tr, va, te = deterministic_split(files, valid_ratio,
                                             test_ratio, key=cls_dir)
            train += tr
            valid += va
            test += te
        super().__init__(workflow, train_paths=train,
                         validation_paths=valid, test_paths=test,
                         **kwargs)

    def get_label(self, path: str) -> str:
        return os.path.basename(os.path.dirname(path))


class FileListImageLoader(ImageLoader):
    """Index-file driven image loader (reference: FileListImageLoader,
    veles/loader/file_image.py:130 — "text file, with each line giving
    an image filename and label"; useful for large datasets where the
    split lives in manifest files, not directory structure).

    ``train_list`` / ``validation_list`` / ``test_list``: text files
    with one ``path[<whitespace>label]`` per line (blank lines and
    ``#`` comments skipped). Relative paths resolve against the list
    file's own directory. Lines without a label fall back to the
    containing-directory convention (auto_label)."""

    MAPPING = "file_list_image_loader"

    def __init__(self, workflow, train_list: Optional[str] = None,
                 validation_list: Optional[str] = None,
                 test_list: Optional[str] = None, **kwargs) -> None:
        self._explicit_labels: Dict[str, str] = {}
        per_class = {}
        for key, list_path in (("train_paths", train_list),
                               ("validation_paths", validation_list),
                               ("test_paths", test_list)):
            per_class[key] = (self._parse_list(list_path)
                              if list_path else ())
        super().__init__(workflow, **per_class, **kwargs)

    def _parse_list(self, list_path: str) -> List[str]:
        if not os.path.exists(list_path):
            raise VelesError("no such list file: %s" % list_path)
        base = os.path.dirname(os.path.abspath(list_path))
        paths = []
        with open(list_path) as fin:
            for line in fin:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split(None, 1)
                path = parts[0]
                if not os.path.isabs(path):
                    path = os.path.join(base, path)
                paths.append(path)
                if len(parts) == 2:
                    self._explicit_labels[path] = parts[1].strip()
        if not paths:
            raise VelesError("list file %s has no entries" % list_path)
        return paths

    def get_label(self, path: str) -> str:
        return self._explicit_labels.get(path) or auto_label(path)


class ImageLoaderMSE(ImageLoader, FullBatchLoaderMSE):
    """Image-target regression loader (reference: ImageLoaderMSE /
    FileImageLoaderMSE, veles/loader/image_mse.py): each input image's
    MSE target is itself an image from ``target_paths``.

    Matching (the reference's two schemes):
    - ``target_by_label=True`` (default): ONE target image per label —
      the target whose auto_label equals the input's label (the classic
      VELES channels setup: per-class ideal template).
    - ``target_by_label=False``: 1:1 by file BASENAME (a denoising /
      reconstruction pair tree); requires augmentation multiplicity 1
      (each row must map to exactly one target).

    Targets are decoded with the same size/color policy as inputs and
    are never augmented (reference behavior)."""

    MAPPING = "image_mse_loader"

    def __init__(self, workflow, target_paths: Sequence[str] = (),
                 target_by_label: bool = True, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        if not target_paths:
            raise VelesError("ImageLoaderMSE needs target_paths")
        self.target_paths = list(target_paths)
        self.target_by_label = bool(target_by_label)
        if not self.target_by_label and (
                self.mirror or self.crop is not None
                or any(r % 360 for r in self.rotations)):
            # not just multiplicity: ANY spatial transform of the input
            # (including a single random crop, host or device path)
            # misaligns a basename-matched reconstruction pair while
            # the target stays untransformed
            raise VelesError(
                "basename-matched targets need geometrically "
                "untransformed inputs (set target_by_label=True for "
                "per-label targets, or drop mirror/rotations/crop)")

    def load_data(self) -> None:
        super().load_data()
        file_filter = FileFilter(include=IMAGE_PATTERNS + ("*.npy",))
        targets = []
        for path in self.target_paths:
            if os.path.isfile(path):
                targets.append(path)
            else:
                targets.extend(file_filter.scan(path))
        if not targets:
            raise VelesError("no target images under %s"
                             % self.target_paths)
        decoded = {p: decode_image(p, self.size, self.color)
                   for p in targets}
        if self.target_by_label:
            by_label = {}
            for p, arr in decoded.items():
                label = auto_label(p)
                if label in by_label:
                    raise VelesError(
                        "duplicate target for label %r" % label)
                by_label[label] = arr
            missing = set(self.labels_mapping) - set(by_label)
            if missing:
                raise VelesError("labels with no target image: %s"
                                 % sorted(missing))
            # a TABLE with one row per label id — stored once, gathered
            # through the row's label by both the host fill and the
            # fused step (per-row materialization would copy each
            # class template n_rows times)
            rows = [by_label[self.label_names[i]]
                    for i in range(len(self.label_names))]
            self.targets_by_label = True
        else:
            by_base: Dict[str, numpy.ndarray] = {}
            for p, arr in decoded.items():
                base = os.path.basename(p)
                if base in by_base:
                    # same ambiguity the label branch rejects: which
                    # target a row trains against must never depend on
                    # directory walk order
                    raise VelesError(
                        "duplicate target basename %r across target "
                        "paths" % base)
                by_base[base] = arr
            missing = [p for p in self.row_paths
                       if os.path.basename(p) not in by_base]
            if missing:
                raise VelesError(
                    "inputs with no basename-matched target: %s"
                    % sorted(os.path.basename(p)
                             for p in missing)[:10])
            rows = [by_base[os.path.basename(p)]
                    for p in self.row_paths]
        shapes = {r.shape for r in rows}
        if len(shapes) != 1:
            raise VelesError("target images have differing shapes %s — "
                             "pass size=(H, W)" % sorted(shapes))
        from .fullbatch import _storage_dtype
        stacked = numpy.stack(rows)
        # same storage policy as every other originals path (e.g. the
        # bf16 dataset_dtype bench config must apply to targets too)
        self.original_targets.reset(numpy.ascontiguousarray(
            stacked, dtype=_storage_dtype(stacked)))
