"""Key-value-store loaders: LMDB and HDFS text.

Equivalents of Znicz ``loader.loader_lmdb`` (``LMDBLoader``, reference
surface: manualrst_veles_workflow_parameters.rst:190) and the core's
``HDFSTextLoader`` (veles/loader/hdfs_loader.py:48). Both back ends are
optional in this environment (``lmdb`` wheel / a reachable HDFS namenode):
the loaders gate cleanly with an actionable error, and the parsing layer
is importable and tested without the backing store.

LMDB records use a data-only format where Caffe used its Datum protobuf:
``value = <i32 label little-endian><.npy sample bytes>`` — decoded with
``numpy.load(allow_pickle=False)``, so reading a database can never
execute code. The reference-era convention ``value = pickle((sample,
label))`` is still readable via ``pickle_records=True``, but that is an
explicit trust statement: **unpickling an LMDB from an untrusted source
executes arbitrary code**; only enable it for databases you created.

HDFS text is served through WebHDFS (stdlib HTTP; the reference used the
``hdfs`` package's InsecureClient) — one sample per line, parsed by a
user ``line_parser``.
"""

from __future__ import annotations

import io
import struct
import urllib.parse
import urllib.request
from typing import Callable, List, Optional, Sequence, Tuple

import numpy

from ..error import VelesError
from .fullbatch import FullBatchLoader


def encode_record(sample: numpy.ndarray, label: int) -> bytes:
    """(sample, label) → the data-only LMDB record format."""
    buf = io.BytesIO()
    numpy.save(buf, numpy.asarray(sample))
    return struct.pack("<i", int(label)) + buf.getvalue()


def decode_record(value: bytes) -> Tuple[numpy.ndarray, int]:
    """Inverse of :func:`encode_record`; never unpickles."""
    (label,) = struct.unpack_from("<i", value)
    sample = numpy.load(io.BytesIO(value[4:]), allow_pickle=False)
    return sample, label


def _load_splits(loader: FullBatchLoader, paths, read_fn) -> None:
    """Shared (test, validation, train) aggregation: read each configured
    split with ``read_fn(path) -> (data, labels)`` and install the
    concatenated dataset on ``loader``."""
    datas, lbls, lengths = [], [], []
    for path in paths:
        if not path:
            lengths.append(0)
            continue
        d, l = read_fn(path)
        datas.append(d)
        lbls.append(l)
        lengths.append(len(d))
    if not datas:
        raise VelesError("%s: no databases/paths configured (all three "
                         "split entries are empty)" % loader.name)
    loader.create_originals(numpy.concatenate(datas),
                            numpy.concatenate(lbls))
    loader.class_lengths = lengths


class LMDBLoader(FullBatchLoader):
    """Full-batch loader over (test, validation, train) LMDB databases
    (Znicz ``LMDBLoader``)."""

    MAPPING = "lmdb_loader"
    hide_from_registry = False

    def __init__(self, workflow, databases: Sequence[Optional[str]] = (),
                 pickle_records: bool = False, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        if len(databases) != 3:
            raise VelesError(
                "databases must be (test, validation, train) paths")
        self.databases = list(databases)
        #: SECURITY: legacy reference-era records are pickled tuples;
        #: enabling this executes whatever the database author pickled.
        self.pickle_records = bool(pickle_records)

    def _read_db(self, path: str) -> Tuple[numpy.ndarray, numpy.ndarray]:
        try:
            import lmdb
        except ImportError:
            raise VelesError(
                "LMDBLoader needs the 'lmdb' package (not installed in "
                "this environment); convert the dataset with "
                "PicklesLoader or FullBatchLoader instead")
        if self.pickle_records:
            import pickle
            decode = pickle.loads
        else:
            decode = decode_record
        samples: List[numpy.ndarray] = []
        labels: List[int] = []
        env = lmdb.open(path, readonly=True, lock=False)
        try:
            with env.begin() as txn:
                for _key, value in txn.cursor():
                    sample, label = decode(value)
                    samples.append(numpy.asarray(sample,
                                                 dtype=numpy.float32))
                    labels.append(int(label))
        finally:
            env.close()
        if not samples:
            raise VelesError("%s: empty LMDB" % path)
        return numpy.stack(samples), numpy.asarray(labels,
                                                   dtype=numpy.int32)

    def load_data(self) -> None:
        _load_splits(self, self.databases, self._read_db)


def parse_tsv_line(line: str) -> Tuple[numpy.ndarray, int]:
    """Default HDFS line parser: tab-separated floats, label last."""
    parts = line.rstrip("\n").split("\t")
    return (numpy.asarray([float(p) for p in parts[:-1]],
                          dtype=numpy.float32), int(parts[-1]))


class HDFSTextLoader(FullBatchLoader):
    """Reads newline-delimited samples from HDFS over WebHDFS
    (reference: veles/loader/hdfs_loader.py:48)."""

    MAPPING = "hdfs_text_loader"
    hide_from_registry = False

    def __init__(self, workflow, namenode: str = "",
                 paths: Sequence[Optional[str]] = (),
                 line_parser: Callable = parse_tsv_line,
                 timeout: float = 30.0, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        if len(paths) != 3:
            raise VelesError("paths must be (test, validation, train)")
        self.namenode = namenode.rstrip("/")
        self.paths = list(paths)
        self.line_parser = line_parser
        self.timeout = timeout

    def _webhdfs_open(self, path: str) -> str:
        if not self.namenode:
            raise VelesError("HDFSTextLoader needs namenode="
                             "http://host:9870")
        url = "%s/webhdfs/v1%s?op=OPEN" % (
            self.namenode, urllib.parse.quote(path))
        with urllib.request.urlopen(url, timeout=self.timeout) as resp:
            return resp.read().decode()

    def parse_text(self, text: str) -> Tuple[numpy.ndarray, numpy.ndarray]:
        samples, labels = [], []
        for line in text.splitlines():
            if not line.strip():
                continue
            sample, label = self.line_parser(line)
            samples.append(sample)
            labels.append(label)
        if not samples:
            raise VelesError("no samples parsed")
        return numpy.stack(samples), numpy.asarray(labels,
                                                   dtype=numpy.int32)

    def load_data(self) -> None:
        _load_splits(self, self.paths,
                     lambda p: self.parse_text(self._webhdfs_open(p)))
