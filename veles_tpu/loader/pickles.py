"""PicklesLoader: datasets stored as pickle files.

Equivalent of the reference's veles/loader/pickles.py:55 (PicklesLoader):
one pickle per class position (test, validation, train), each holding an
array or a (data, labels) pair; missing classes are empty.
"""

from __future__ import annotations

import pickle
from typing import Optional, Sequence

import numpy

from ..error import VelesError
from .base import TEST, VALID, TRAIN
from .fullbatch import FullBatchLoader


def _load_one(path: str):
    with open(path, "rb") as fin:
        obj = pickle.load(fin)
    if isinstance(obj, (tuple, list)) and len(obj) == 2:
        data, labels = obj
        return numpy.asarray(data), numpy.asarray(labels)
    if isinstance(obj, dict):
        return (numpy.asarray(obj["data"]),
                numpy.asarray(obj["labels"]) if "labels" in obj else None)
    return numpy.asarray(obj), None


class PicklesLoader(FullBatchLoader):
    """``files`` is a 3-sequence (test, validation, train) of pickle paths
    (None/"" = class absent), mirroring the reference's per-class file
    list."""

    MAPPING = "pickles_loader"

    def __init__(self, workflow, files: Sequence[Optional[str]] = (),
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        if len(files) != 3:
            raise VelesError(
                "files must be (test, validation, train) paths")
        self.files = list(files)

    def load_data(self) -> None:
        datas, labelss, lengths = [], [], [0, 0, 0]
        have_labels = None
        for cls in (TEST, VALID, TRAIN):
            path = self.files[cls]
            if not path:
                continue
            data, labels = _load_one(path)
            if have_labels is None:
                have_labels = labels is not None
            elif have_labels != (labels is not None):
                raise VelesError("inconsistent labels across classes")
            datas.append(data)
            if labels is not None:
                if len(labels) != len(data):
                    raise VelesError("%s: %d labels for %d samples"
                                     % (path, len(labels), len(data)))
                labelss.append(labels)
            lengths[cls] = len(data)
        self.create_originals(
            numpy.concatenate(datas),
            numpy.concatenate(labelss) if labelss else None)
        self.class_lengths = lengths
        if self.validation_ratio and not lengths[VALID]:
            self.resize_validation(self.validation_ratio)
