"""Plotter base: units that emit plot snapshots.

Equivalent of the reference's veles/plotter.py:48 (Plotter) +
veles/iplotter.py (IPlotter), with one deliberate change: the reference
pickled the *whole unit object* to the graphics client process, which then
called its ``redraw()`` — coupling the renderer to framework code and
executing pickled code cross-process. Here a plotter emits a declarative
**snapshot** (plain dict of scalars/numpy arrays + a ``kind`` tag) and the
renderer (veles_tpu/graphics.py) owns one draw function per kind. Snapshots
are cheap host-side data; nothing device-resident crosses the boundary, so
plotting never synchronizes the TPU stream beyond the values already
fetched by the decision/evaluator units.

Redraw throttling semantics preserved from the reference (Plotter redraw
throttling, veles/plotter.py:48).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from .config import root
from .units import Unit


class PlotSink:
    """Where snapshots go. The default sink just remembers the last snapshot
    per plot (usable by tests and by the Publisher); GraphicsServer extends
    it with ZeroMQ pub-sub fan-out to a renderer process."""

    def __init__(self) -> None:
        self.snapshots: Dict[str, Dict[str, Any]] = {}

    def publish(self, snapshot: Dict[str, Any]) -> None:
        self.snapshots[snapshot["name"]] = snapshot


#: process-wide fallback sink (a Launcher/Workflow normally installs a
#: GraphicsServer as ``workflow.graphics``)
default_sink = PlotSink()


class Plotter(Unit):
    """Base of all plot-emitting units (reference: veles/plotter.py:48).

    Subclasses implement ``fill_snapshot() -> dict`` returning the payload;
    this base adds the ``kind`` tag, throttles redraws and routes the result
    to the graphics sink. ``run()`` is always cheap and host-side.
    """

    hide_from_registry = True
    KIND = "none"
    #: plot emission is pure output (snapshot → sink/renderer); with
    #: the overlap engine on, the scheduler moves it off the step loop
    side_effect_only = True

    def __init__(self, workflow, **kwargs) -> None:
        self.redraw_interval: float = kwargs.pop("redraw_interval", 0.1)
        super().__init__(workflow, **kwargs)
        self.view_group = "PLOTTER"
        self.clear_plot: bool = False
        self.last_snapshot: Optional[Dict[str, Any]] = None
        self._last_redraw = 0.0

    @property
    def sink(self) -> PlotSink:
        wf = self.workflow
        while wf is not None:
            g = getattr(wf, "graphics", None)
            if g is not None:
                return g
            wf = getattr(wf, "workflow", None)
        return default_sink

    def fill_snapshot(self) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def run(self) -> None:
        if root.common.disable.plotting:
            return
        now = time.time()
        if now - self._last_redraw < self.redraw_interval:
            return
        data = self.fill_snapshot()
        if data is None:
            return
        snapshot = {"name": self.name, "kind": self.KIND, "time": now}
        snapshot.update(data)
        self.last_snapshot = snapshot
        self._last_redraw = now
        self.sink.publish(snapshot)

    def finalize(self) -> None:
        """Force one final redraw regardless of throttling (the reference
        flushed pending plots on workflow finish)."""
        self._last_redraw = 0.0
        self.run()
