"""veles_tpu — a TPU-native dataflow deep-learning framework.

A ground-up rebuild of the capabilities of Samsung VELES (the reference at
/root/reference; see SURVEY.md) designed for TPUs: models are Workflows of
linked Units, but the per-minibatch compute compiles to a single jitted XLA
SPMD step over a ``jax.sharding.Mesh`` instead of per-unit kernel dispatch,
and distributed data parallelism is ``psum`` over ICI instead of a ZeroMQ
master–slave parameter server.
"""

__version__ = "0.1.0"

import os as _os

_chips = _os.environ.get("TPU_VISIBLE_CHIPS")
if _chips:
    # mesh_slice_placement contract honored on the host platform too:
    # a trial child placed on a d-chip slice materializes exactly d
    # virtual CPU devices, however the CPU backend ends up selected
    # (env pin here, or --backend cpu later) — so slice-placement
    # correctness is CI-testable without multi-chip hardware
    # (parallel/trials.py). Harmless on a real TPU host, where the
    # runtime consumes TPU_VISIBLE_CHIPS natively and the CPU client
    # is never the training backend. The forced-host-device-count flag
    # would fight the setting — strip it before jax initializes.
    _flags = _os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in _flags:
        # the user set BOTH knobs: dropping their flag silently (and
        # exporting the stripped XLA_FLAGS to every child) would be a
        # mystery device-count change — say so (ADVICE r4)
        import warnings as _warnings
        _warnings.warn(
            "TPU_VISIBLE_CHIPS overrides xla_force_host_platform_"
            "device_count: stripping the flag from XLA_FLAGS (the "
            "slice-placement contract owns the CPU device count; "
            "unset TPU_VISIBLE_CHIPS to keep your flag)",
            RuntimeWarning, stacklevel=2)
    _os.environ["XLA_FLAGS"] = " ".join(
        t for t in _flags.split()
        if "xla_force_host_platform_device_count" not in t)
    import jax as _jax

    _n_chips = len([c for c in _chips.split(",") if c.strip() != ""])
    try:
        _jax.config.update("jax_num_cpu_devices", _n_chips)
    except AttributeError:
        # jax 0.4.x has no jax_num_cpu_devices; the XLA flag is the
        # same knob, and jax reads XLA_FLAGS at first backend init,
        # which cannot have happened yet at package import
        _os.environ["XLA_FLAGS"] = (
            _os.environ["XLA_FLAGS"]
            + " --xla_force_host_platform_device_count=%d" % _n_chips
        ).strip()

if _os.environ.get("JAX_PLATFORMS", "").lower() in ("cpu", "cpu,"):
    # Honor a host-platform pin in EVERY process, including subprocesses
    # the framework spawns (genetics candidates, ensemble members,
    # multihost launcher children). Tunnelled-TPU plugins can override
    # the JAX_PLATFORMS env var at import time, which would make a child
    # ignore the parent's cpu pin and block on hardware the parent never
    # intended it to touch — the config key wins over the plugin.
    # Only the standard 'cpu' name is pinned: plugin platform names
    # (e.g. 'axon') must resolve through the plugin's own env-var path —
    # pinning them via jax.config breaks backend discovery entirely.
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

from .config import root                              # noqa: F401
from .error import (VelesError, Bug, NoMoreJobs)      # noqa: F401
from .mutable import Bool, LinkableAttribute, link    # noqa: F401
from .units import Unit, UnitRegistry, TrivialUnit    # noqa: F401
from .workflow import Workflow                        # noqa: F401
from .plumbing import (StartPoint, EndPoint, Repeater,
                       FireStarter)                   # noqa: F401
from .memory import Array, Watcher                    # noqa: F401
from .backends import (Device_for, XLADevice, NumpyDevice,
                       make_mesh)                     # noqa: F401
from .accelerated import (AcceleratedUnit,
                          AcceleratedWorkflow)        # noqa: F401
from .snapshotter import (Snapshotter, SnapshotterToDB, load_snapshot,
                          resume, collect_state,
                          apply_state)                # noqa: F401
from .mean_disp_normalizer import MeanDispNormalizer  # noqa: F401
from .input_joiner import InputJoiner                 # noqa: F401
from .avatar import Avatar                            # noqa: F401
from . import normalization                           # noqa: F401
from . import prng                                    # noqa: F401
from .plotter import Plotter, PlotSink                # noqa: F401
from .plotting_units import (AccumulatingPlotter, MatrixPlotter,
                             ImagePlotter, Histogram, MultiHistogram,
                             TableMaxMin, StepStats)  # noqa: F401
from .restful_api import GenerationAPI, RESTfulAPI    # noqa: F401
from . import overlap                                 # noqa: F401
from .overlap import Prefetcher, SidePlane            # noqa: F401
from . import resilience                              # noqa: F401
from .resilience import (RetryPolicy, FaultInjected,
                         SnapshotCorruptError)        # noqa: F401
from .publishing import Publisher                     # noqa: F401
from .interaction import Shell                        # noqa: F401
from .json_encoders import NumpyJSONEncoder           # noqa: F401
