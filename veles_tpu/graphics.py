"""Graphics pipeline: snapshot pub-sub + matplotlib renderer.

Equivalent of the reference's veles/graphics_server.py:73 (ZeroMQ PUB of
plot snapshots; client subprocess launch) and veles/graphics_client.py:84
(SUB socket → matplotlib). Differences, deliberate:

- payloads are the declarative snapshots of veles_tpu/plotter.py, not
  pickled Plotter units — the renderer holds one draw function per ``kind``
  and no framework state;
- endpoints are tcp://127.0.0.1 or ipc:// only (the reference's epgm
  multicast served cluster-wide spectators; the SPMD build has exactly one
  program to watch, veles/graphics_server.py:100-136);
- the Agg backend writes ``<out>/<plot name>.png`` continuously; these files
  double as the Publisher's figures.

``render_snapshot`` is also importable directly (no zmq, no subprocess) —
that in-process path is what tests and the Publisher use.
"""

from __future__ import annotations

import io
import json
import os
import struct
import subprocess
import sys
import tempfile
import threading
from typing import Any, Dict, List, Optional

import numpy

from .config import root
from .logger import Logger
from .plotter import PlotSink


# ---------------------------------------------------------------------------
# Wire codec: JSON header + npz payload. Snapshots are declarative data
# (scalars, strings, numpy arrays — see plotter.py), so the frame format is
# data-only by construction: the renderer subprocess never unpickles, which
# closes the deserialization surface the reference's pickled-Plotter protocol
# had (veles/graphics_client.py:84 executed pickled framework objects).
# ---------------------------------------------------------------------------

def pack_snapshot(snapshot: Dict[str, Any]) -> bytes:
    """Encode a snapshot as ``<u32 header len><JSON header><npz arrays>``.
    Arrays (including arrays nested in lists) become npz entries referenced
    from the header; everything else must be JSON-serializable."""
    arrays: List[numpy.ndarray] = []

    def enc(v):
        if isinstance(v, numpy.ndarray):
            arrays.append(v)
            return {"__npy__": len(arrays) - 1}
        if isinstance(v, (list, tuple)):
            return {"__seq__": [enc(x) for x in v]}
        if isinstance(v, dict):
            return {k: enc(x) for k, x in v.items()}
        if isinstance(v, numpy.integer):
            return int(v)
        if isinstance(v, (numpy.floating, numpy.bool_)):
            return v.item()
        return v

    header = json.dumps({k: enc(v) for k, v in snapshot.items()}).encode()
    buf = io.BytesIO()
    numpy.savez(buf, **{"a%d" % i: a for i, a in enumerate(arrays)})
    return struct.pack("<I", len(header)) + header + buf.getvalue()


def unpack_snapshot(frame: bytes) -> Dict[str, Any]:
    """Inverse of :func:`pack_snapshot`; never unpickles
    (``allow_pickle=False``)."""
    (hlen,) = struct.unpack_from("<I", frame)
    meta = json.loads(frame[4:4 + hlen].decode())
    npz = numpy.load(io.BytesIO(frame[4 + hlen:]), allow_pickle=False)

    def dec(v):
        if isinstance(v, dict):
            if "__npy__" in v:
                return npz["a%d" % v["__npy__"]]
            if "__seq__" in v:
                return [dec(x) for x in v["__seq__"]]
            return {k: dec(x) for k, x in v.items()}
        return v

    return {k: dec(v) for k, v in meta.items()}


def safe_name(name: str) -> str:
    """Plot name → file-system-safe stem (one rule shared by the renderer
    subprocess and the Publisher, so both write the same file names)."""
    return "".join(c if c.isalnum() or c in "-_" else "_" for c in name)


class GraphicsServer(PlotSink, Logger):
    """Publishes plot snapshots over ZeroMQ PUB and optionally owns a
    renderer subprocess (reference: veles/graphics_server.py:73,174-220)."""

    def __init__(self, endpoint: Optional[str] = None) -> None:
        PlotSink.__init__(self)
        Logger.__init__(self)
        self._zmq_socket = None
        # plotters may publish from concurrent side-plane lanes
        # (overlap engine); zmq sockets are not thread-safe
        self._pub_lock = threading.Lock()
        self._client: Optional[subprocess.Popen] = None
        self.endpoint: Optional[str] = None
        if root.common.disable.plotting:
            return
        try:
            import zmq
        except ImportError:             # pragma: no cover
            self.warning("pyzmq unavailable; plots collected in-process "
                         "only")
            return
        ctx = zmq.Context.instance()
        # XPUB, not PUB: the server can observe subscription handshakes and
        # hold the first snapshots until the renderer is actually listening
        # (plain PUB silently drops everything sent before the SUB connects)
        sock = ctx.socket(zmq.XPUB)
        if endpoint:
            sock.bind(endpoint)
            self.endpoint = endpoint
        else:
            # same-host tiering as the reference (ipc preferred, tcp
            # fallback), veles/server.py:721-732
            try:
                path = os.path.join(tempfile.gettempdir(),
                                    "veles-graphics-%d.ipc" % os.getpid())
                self.endpoint = "ipc://" + path
                sock.bind(self.endpoint)
            except zmq.ZMQError:
                port = sock.bind_to_random_port("tcp://127.0.0.1")
                self.endpoint = "tcp://127.0.0.1:%d" % port
        self._zmq_socket = sock
        self.info("graphics PUB on %s", self.endpoint)

    def publish(self, snapshot: Dict[str, Any]) -> None:
        super().publish(snapshot)
        if self._zmq_socket is not None:
            try:
                with self._pub_lock:
                    self._zmq_socket.send(
                        pack_snapshot(snapshot),
                        flags=getattr(__import__("zmq"), "NOBLOCK", 1))
            except Exception as e:      # PUB drops are fine; never stall
                self.debug("snapshot drop: %s", e)

    def wait_subscriber(self, timeout: float = 10.0) -> bool:
        """Block until at least one SUB completes its handshake (XPUB
        delivers subscription frames to the server side)."""
        if self._zmq_socket is None:
            return False
        import zmq
        poller = zmq.Poller()
        poller.register(self._zmq_socket, zmq.POLLIN)
        if poller.poll(int(timeout * 1000)):
            frame = self._zmq_socket.recv()
            return bool(frame) and frame[0] == 1
        return False

    def launch_client(self, backend: str = "Agg",
                      out_dir: Optional[str] = None) -> Optional[int]:
        """Spawn the renderer subprocess and wait for it to subscribe
        (reference: veles/graphics_server.py:174-220)."""
        if self._zmq_socket is None:
            return None
        out_dir = out_dir or os.path.join(
            root.common.dirs.cache, "plots")
        os.makedirs(out_dir, exist_ok=True)
        #: resolved plot directory — the launcher's status beacon reads
        #: it to inline the latest renders into the drill-down gallery
        self.out_dir = out_dir
        log = open(os.path.join(out_dir, "client.log"), "ab")
        # run from the package's parent so `-m veles_tpu.graphics` resolves
        # regardless of the caller's cwd/sys.path setup
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        self._client = subprocess.Popen(
            [sys.executable, "-m", "veles_tpu.graphics", self.endpoint,
             "--backend", backend, "--out", out_dir],
            stdout=log, stderr=log, cwd=pkg_parent)
        log.close()
        if not self.wait_subscriber(30.0):
            self.warning("graphics client did not subscribe within "
                         "timeout; see %s", os.path.join(out_dir,
                                                         "client.log"))
        self.info("graphics client pid %d → %s", self._client.pid, out_dir)
        return self._client.pid

    def shutdown(self) -> None:
        if self._zmq_socket is not None:
            try:
                self._zmq_socket.send(
                    pack_snapshot({"kind": "__stop__", "name": "__stop__"}))
                self._zmq_socket.close(linger=200)
            except Exception:
                pass
            self._zmq_socket = None
        if self._client is not None:
            try:
                self._client.wait(timeout=5)
            except Exception:
                self._client.kill()
            self._client = None
        if self.endpoint and self.endpoint.startswith("ipc://"):
            try:
                os.unlink(self.endpoint[len("ipc://"):])
            except OSError:
                pass


# ---------------------------------------------------------------------------
# Renderers: one draw function per snapshot kind.
# ---------------------------------------------------------------------------

def _draw_lines(ax, snap):
    ax.plot(snap["values"], snap.get("style", "-"))
    ax.set_ylabel(snap.get("label", "value"))
    ax.set_xlabel("updates")
    if snap.get("ylim"):
        ax.set_ylim(*snap["ylim"])
    ax.grid(True, alpha=0.3)


def _draw_matrix(ax, snap):
    m = snap["matrix"]
    im = ax.imshow(m, interpolation="nearest", cmap="viridis")
    ax.figure.colorbar(im, ax=ax)
    ax.set_xticks(range(m.shape[1]))
    ax.set_xticklabels(snap["column_labels"], rotation=90, fontsize=7)
    ax.set_yticks(range(m.shape[0]))
    ax.set_yticklabels(snap["row_labels"], fontsize=7)
    if m.size <= 400:                   # annotate small matrices only
        thresh = (m.max() + m.min()) / 2.0
        for i in range(m.shape[0]):
            for j in range(m.shape[1]):
                ax.text(j, i, "%g" % m[i, j], ha="center", va="center",
                        fontsize=6,
                        color="white" if m[i, j] < thresh else "black")


def _draw_image_grid(ax, snap):
    import numpy
    imgs = snap["images"]
    n = len(imgs)
    cols = max(1, int(numpy.ceil(numpy.sqrt(n))))
    rows = (n + cols - 1) // cols
    h, w = imgs.shape[1], imgs.shape[2]
    canvas = numpy.ones((rows * (h + 2), cols * (w + 2)) + imgs.shape[3:],
                        dtype=imgs.dtype)
    for k, img in enumerate(imgs):
        r, c = divmod(k, cols)
        canvas[r * (h + 2):r * (h + 2) + h,
               c * (w + 2):c * (w + 2) + w] = img
    ax.imshow(canvas, cmap=None if canvas.ndim == 3 else "gray")
    ax.axis("off")


def _draw_histogram(ax, snap):
    edges, counts = snap["edges"], snap["counts"]
    ax.bar(edges[:-1], counts, width=(edges[1:] - edges[:-1]),
           align="edge")
    ax.grid(True, alpha=0.3)


def _draw_multi_histogram(ax, snap):
    import numpy
    fig = ax.figure
    ax.axis("off")
    counts, edges = snap["counts"], snap["edges"]
    n = len(counts)
    cols = max(1, int(numpy.ceil(numpy.sqrt(n))))
    rows = (n + cols - 1) // cols
    for k in range(n):
        sub = fig.add_subplot(rows, cols, k + 1)
        sub.bar(edges[k][:-1], counts[k],
                width=(edges[k][1:] - edges[k][:-1]), align="edge")
        sub.set_xticks(())
        sub.set_yticks(())


def _draw_table(ax, snap):
    ax.axis("off")
    table = ax.table(cellText=snap["rows"], colLabels=snap["header"],
                     loc="center")
    table.auto_set_font_size(False)
    table.set_fontsize(8)


RENDERERS = {
    "lines": _draw_lines,
    "matrix": _draw_matrix,
    "image_grid": _draw_image_grid,
    "histogram": _draw_histogram,
    "multi_histogram": _draw_multi_histogram,
    "table": _draw_table,
}


def render_snapshot(snapshot: Dict[str, Any], path: str) -> str:
    """Draw one snapshot to an image file; returns the path. Usable without
    zmq or a subprocess (tests, Publisher)."""
    import matplotlib
    matplotlib.use("Agg", force=False)
    from matplotlib import pyplot
    fig = pyplot.figure(figsize=(6, 4.5), dpi=100)
    ax = fig.add_subplot(111)
    renderer = RENDERERS.get(snapshot["kind"])
    if renderer is None:
        raise KeyError("no renderer for snapshot kind %r" %
                       snapshot["kind"])
    renderer(ax, snapshot)
    ax.set_title(snapshot["name"])
    fig.tight_layout()
    fig.savefig(path)
    pyplot.close(fig)
    return path


def client_main(argv: Optional[List[str]] = None) -> int:
    """``python -m veles_tpu.graphics ENDPOINT`` — the renderer process
    (reference: veles/graphics_client.py:84)."""
    import argparse
    parser = argparse.ArgumentParser(description=client_main.__doc__)
    parser.add_argument("endpoint")
    parser.add_argument("--backend", default="Agg",
                        help="matplotlib backend (Agg renders PNG files)")
    parser.add_argument("--out", default=".", help="output directory")
    args = parser.parse_args(argv)
    import zmq
    import matplotlib
    matplotlib.use(args.backend)
    os.makedirs(args.out, exist_ok=True)
    ctx = zmq.Context.instance()
    sock = ctx.socket(zmq.SUB)
    sock.connect(args.endpoint)
    sock.setsockopt(zmq.SUBSCRIBE, b"")
    while True:
        snap = unpack_snapshot(sock.recv())
        if snap.get("kind") == "__stop__":
            break
        name = safe_name(snap["name"])
        try:
            render_snapshot(snap, os.path.join(args.out, name + ".png"))
        except Exception as e:          # keep rendering subsequent plots
            print("render error for %s: %s" % (snap.get("name"), e),
                  file=sys.stderr)
    return 0


if __name__ == "__main__":             # pragma: no cover
    sys.exit(client_main())
