"""CLI entry: ``python -m veles_tpu MODEL.py [CONFIG] [overrides] [flags]``.

Equivalent of the reference's veles/__main__.py:136-867 (Main): argv →
config → model import → Launcher boot → run → results. Model contract
(both reference styles supported):
- ``build_workflow(**kwargs) -> Workflow``  (preferred, simple), or
- ``run(load, main)``: the reference's canonical protocol
  (veles/__main__.py:591-627) — the module calls ``load(WorkflowClass,
  **kw)`` to construct/resume and ``main(**kw)`` to initialize+run.
"""

from __future__ import annotations

import logging
import sys

from .cmdline import (apply_config_overrides, make_parser, parse_args,
                      parse_mesh)
from .config import root
from .error import VelesError
from .import_file import import_file_as_module
from .launcher import Launcher
from .logger import setup_logging


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "trace":
        # telemetry subcommand family (no model/workflow involved):
        #   veles-tpu trace export RUN.jsonl TRACE.json
        return _trace_cli(argv[1:])
    if argv and argv[0] == "metrics":
        # fleet observability subcommand family (telemetry/fleet.py):
        #   veles-tpu metrics aggregate URL [URL ...]
        from .telemetry import fleet
        return fleet.main(argv[1:])
    if argv and argv[0] == "watch":
        # watchtower live dashboard (telemetry/timeseries.py):
        #   veles-tpu watch URL [URL ...] [--endpoints-file ROSTER]
        return _watch_cli(argv[1:])
    if argv and argv[0] == "alerts":
        # watchtower rule states (telemetry/alerts.py):
        #   veles-tpu alerts URL
        return _alerts_cli(argv[1:])
    if argv and argv[0] == "route":
        # serving-fleet front (serving/router.py):
        #   veles-tpu route URL [URL ...] [--port P] [...]
        return _route_cli(argv[1:])
    if argv and argv[0] == "faults":
        # resilience subcommand family:
        #   veles-tpu faults list
        return _faults_cli(argv[1:])
    if argv and argv[0] == "loadgen":
        # load/chaos harness (veles_tpu/loadgen/):
        #   veles-tpu loadgen URL [--requests N] [--rate R] [...]
        return _loadgen_cli(argv[1:])
    if argv and argv[0] == "blackbox":
        # flight-recorder subcommand family (telemetry/recorder.py):
        #   veles-tpu blackbox dump [--out PATH]
        #   veles-tpu blackbox inspect BLACKBOX.jsonl
        return _blackbox_cli(argv[1:])
    if argv and argv[0] == "quantize":
        # quantization subcommand family (veles_tpu/quant/):
        #   veles-tpu quantize SNAPSHOT [--out PATH] [--granularity G]
        return _quantize_cli(argv[1:])
    if argv and argv[0] == "export":
        # export subcommand family (veles_tpu/export/):
        #   veles-tpu export serve-artifact MODEL.py --out DIR [...]
        return _export_cli(argv[1:])
    if argv and argv[0] == "linalg":
        # distributed linear-algebra family (veles_tpu/linalg/):
        #   veles-tpu linalg bench [--m M --k K --n N] [--grid PRxPC]
        #   veles-tpu linalg solve [--n N] [--precondition]
        return _linalg_cli(argv[1:])
    parser = make_parser()
    # intermixed parsing: config overrides (positionals) may appear
    # between/after flags — see cmdline.parse_args
    args = parse_args(parser, argv)
    if args.serve_draft_snapshot and not args.serve_draft:
        # argv-detectable misuse fails BEFORE any (possibly minutes-
        # long) initialize/restore — and regardless of --serve-generate
        parser.error("--serve-draft-snapshot needs --serve-draft")
    if args.serve_draft and args.serve_generate is None:
        parser.error("--serve-draft needs --serve-generate")
    # serving knobs land in the config tree; GenerationAPI (and any
    # programmatic ContinuousEngine) reads root.common.serving.*
    from .config import root as _root
    if args.serve_engine:
        _root.common.serving.engine = args.serve_engine
    if args.serve_slots is not None:
        _root.common.serving.max_slots = args.serve_slots
    if args.serve_buckets is not None:
        _root.common.serving.buckets = args.serve_buckets
    if args.serve_max_context is not None:
        _root.common.serving.max_context = args.serve_max_context
    if args.serve_page_size is not None:
        _root.common.serving.page_size = args.serve_page_size
    if args.serve_pages is not None:
        _root.common.serving.pages = args.serve_pages
    if args.serve_spec_gamma is not None:
        _root.common.serving.spec_gamma = args.serve_spec_gamma
    if args.serve_beam_width is not None:
        _root.common.serving.beam_width = args.serve_beam_width
    if args.serve_artifact:
        _root.common.serving.artifact = args.serve_artifact
    if args.serve_prefix_cache is not None:
        _root.common.serving.prefix_cache = \
            args.serve_prefix_cache == "on"
    if args.serve_prefill_chunk is not None:
        _root.common.serving.prefill_chunk = args.serve_prefill_chunk
    if args.serve_tp is not None:
        _root.common.serving.tp = args.serve_tp
    if args.serve_state_cache is not None:
        _root.common.serving.state_cache = \
            args.serve_state_cache == "on"
    if args.serve_stream is not None:
        _root.common.serving.stream = args.serve_stream == "on"
    if args.serve_drain_grace is not None:
        _root.common.serving.drain_grace = args.serve_drain_grace
    if args.serve_drain_handoff is not None:
        _root.common.serving.drain_handoff = \
            args.serve_drain_handoff == "on"
    if args.serve_qos is not None:
        _root.common.serving.qos = args.serve_qos == "on"
    if args.router_qos is not None:
        _root.common.router.qos = args.router_qos == "on"
    if args.router_slo_ttft_ms is not None:
        _root.common.router.slo_ttft_ms = args.router_slo_ttft_ms
    # quantization policy (veles_tpu/quant/): the flags arm the config
    # tree; the serving engine (and any programmatic consumer) reads
    # root.common.quant.*
    if args.quant_weights:
        _root.common.quant.weights = True
    if args.quant_kv:
        _root.common.quant.kv = True
    level = (logging.WARNING, logging.INFO,
             logging.DEBUG)[min(args.verbose, 2)]
    setup_logging(level=level, tracefile=args.trace_file)
    if args.trace_file:
        # telemetry spans stream into the same JSONL file as the logger
        # events (span records carry name+ts+dur, events name+time —
        # `trace export` picks out the spans); one --trace-file, one
        # observability stream
        from .telemetry.spans import recorder
        recorder.set_sink(args.trace_file)
    if args.debug:
        from .logger import enable_debug
        enable_debug(args.debug)

    # config layering: file, then inline overrides; a bare root.x=y in the
    # config position is an override, not a file
    if args.config and "=" in args.config:
        args.config_list.insert(0, args.config)
        args.config = None
    if args.config:
        root.update_from_file(args.config)
    if args.config_list:
        apply_config_overrides(root, args.config_list)
    if args.force_numpy:
        root.common.engine.force_numpy = True
    if args.mixed_precision:
        root.common.engine.mixed_precision = True
    if args.backend in ("cpu", "numpy"):
        # keep jax away from the (exclusive, possibly busy) TPU tunnel
        # when the user explicitly asked for a host backend
        import jax
        jax.config.update("jax_platforms", "cpu")
    if args.slave_death_probability:
        root.common.slave_death_probability = args.slave_death_probability
    if args.elastic:
        # elastic generation controller (resilience/elastic.py): host
        # loss ends a generation, not the run
        root.common.resilience.elastic.enabled = True
    if args.job_timeout:
        root.common.job_timeout = args.job_timeout
    if args.snapshot_dir:
        root.common.dirs.snapshots = args.snapshot_dir
    if args.tensormon or args.nan_policy:
        # model-health taps (telemetry/tensormon.py): --nan-policy
        # implies monitoring — a sentinel with no taps would be inert
        root.common.telemetry.tensormon.enabled = True
        if args.nan_policy:
            root.common.telemetry.tensormon.nan_policy = args.nan_policy
    if args.blackbox:
        root.common.telemetry.recorder.autodump = True
    if args.overlap:
        # the overlap engine (veles_tpu/overlap/): async side-plane +
        # non-blocking checkpoints; prefetch depth rides its own flag
        root.common.overlap.enabled = True
        root.common.overlap.async_snapshots = True
    if args.prefetch_depth is not None:
        root.common.overlap.prefetch_depth = int(args.prefetch_depth)
    if args.timings:
        root.common.trace.timings = True
    if args.dump_config:
        root.print_()
        return 0

    launcher = Launcher(
        backend=args.backend,
        mesh=parse_mesh(args.mesh) if args.mesh else None,
        coordinator=args.coordinator, num_processes=args.num_processes,
        process_id=args.process_id, random_seed=args.random_seed,
        test_mode=args.test,
        graphics=args.graphics, plots_dir=args.plots_dir,
        status_url=args.status_url,
        notification_interval=args.status_interval,
        profile_dir=args.profile_dir)

    module = import_file_as_module(args.model)
    # a model module may (re)set config keys at import time (including
    # Range markers); the user's config FILE and inline overrides must
    # win — re-apply both, in layering order
    if args.config:
        root.update_from_file(args.config)
    if args.config_list:
        apply_config_overrides(root, args.config_list)

    if args.optimize or args.ensemble_train or args.ensemble_test:
        return _run_meta(launcher, module, args)

    _materialize(args)

    if hasattr(module, "run"):
        # reference-style protocol
        state = {}

        def load(workflow_cls, **kwargs):
            state["workflow"] = workflow_cls(**kwargs)
            return state["workflow"], bool(args.snapshot)

        def main_(**kwargs):
            return _drive(launcher, state["workflow"], args)
        module.run(load, main_)
        return 0
    if hasattr(module, "build_workflow"):
        workflow = module.build_workflow()
        _drive(launcher, workflow, args)
        return 0
    raise VelesError(
        "%s defines neither build_workflow() nor run(load, main)"
        % args.model)


def _trace_cli(argv) -> int:
    """``veles-tpu trace export RUN.jsonl TRACE.json`` — convert a
    span JSONL stream (--trace-file output, or a
    telemetry.spans.recorder.to_jsonl dump) into Chrome trace_event
    JSON viewable in Perfetto / chrome://tracing.

    ``veles-tpu trace self-time TRACE.json[.gz]`` — summarize a
    captured profiler trace (or a ``jax.profiler`` log DIRECTORY)
    into per-stream device self-time, and — with ``--spans
    RUN.jsonl`` — per-telemetry-span device self-time: the
    operator-facing view of the numbers ``bench.py gate``'s
    device-time sections consume (telemetry/devtime.py)."""
    import argparse
    parser = argparse.ArgumentParser(
        prog="veles_tpu trace",
        description="telemetry trace tools (veles_tpu/telemetry/)")
    sub = parser.add_subparsers(dest="cmd", required=True)
    exp = sub.add_parser(
        "export", help="span JSONL -> Chrome trace_event JSON")
    exp.add_argument("jsonl", help="span JSONL (from --trace-file)")
    exp.add_argument("out", help="trace_event JSON to write")
    exp.add_argument("--request", default=None, metavar="ID",
                     help="export only spans tagged with this "
                          "request_id (one serving request's "
                          "timeline — no hand-grepping the JSONL)")
    fl = sub.add_parser(
        "fleet",
        help="pull span rings from a router + its replicas "
             "(GET /trace/spans), align clocks, merge into ONE "
             "Chrome trace — one lane per process "
             "(docs/observability.md 'Fleet tracing')")
    fl.add_argument("urls", nargs="*", metavar="URL",
                    help="endpoint serving /trace/spans (the router "
                         "and/or replicas; bare host:port accepted)")
    fl.add_argument("--endpoints-file", default=None, metavar="FILE",
                    help="replica roster file — same format as "
                         "`route`/`metrics aggregate` (plain lines, "
                         "or a saved GET /roster page); the router's "
                         "own URL still goes in positionally")
    fl.add_argument("--out", required=True, metavar="TRACE.json",
                    help="merged Chrome trace to write (open in "
                         "Perfetto)")
    fl.add_argument("--request", default=None, metavar="ID",
                    help="keep one request's story only: a "
                         "request_id or trace_id — the whole fleet "
                         "trace of that request (queue, attempts, "
                         "backoff, resume) across every process")
    fl.add_argument("--timeout", type=float, default=5.0,
                    help="per-endpoint pull timeout, seconds")
    st = sub.add_parser(
        "self-time",
        help="device self-time summary of a captured trace "
             "(docs/perf.md 'Device-time measurement plane')")
    st.add_argument("trace",
                    help="Chrome trace-event JSON[.gz], or a "
                         "jax.profiler log directory")
    st.add_argument("--spans", default=None, metavar="RUN.jsonl",
                    help="telemetry span JSONL to attribute device "
                         "time onto (per-span-name table)")
    st.add_argument("--top", type=int, default=12, metavar="N",
                    help="print at most N rows per table")
    args = parser.parse_args(argv)
    if args.cmd == "fleet":
        return _trace_fleet(args)
    if args.cmd == "self-time":
        return _trace_self_time(args)
    from .telemetry import chrome_trace
    try:
        n = chrome_trace.export(args.jsonl, args.out,
                                request_id=args.request)
    except (OSError, ValueError) as e:
        print("trace export failed: %s" % e, file=sys.stderr)
        return 1
    print("exported %d spans%s -> %s (open in Perfetto: "
          "https://ui.perfetto.dev)"
          % (n, " for request %s" % args.request if args.request
             else "", args.out))
    return 0


def _trace_fleet(args) -> int:
    """``veles-tpu trace fleet URL... --out trace.json`` — pull the
    span ring of every listed process (router + replicas), estimate
    per-process clock offsets by bracketing alignment
    (``route.attempt`` spans contain the replica ``request`` spans
    they proxied — telemetry/fleet.py), and write ONE merged Chrome
    trace with one lane per process. With ``--request ID`` the trace
    is a single request's full cross-fleet story."""
    import json as _json
    from .telemetry import fleet as _fleet
    urls = list(args.urls)
    if args.endpoints_file:
        from .telemetry.fleet import read_endpoints
        try:
            urls += read_endpoints(args.endpoints_file)
        except (OSError, ValueError) as e:
            print("trace fleet: bad --endpoints-file: %s" % e,
                  file=sys.stderr)
            return 1
    if not urls:
        print("trace fleet: no endpoints (positional URLs and/or "
              "--endpoints-file)", file=sys.stderr)
        return 1
    try:
        doc, summary = _fleet.trace_fleet(
            urls, request=args.request, timeout=args.timeout)
    except ValueError as e:
        print("trace fleet failed: %s" % e, file=sys.stderr)
        return 1
    with open(args.out, "w") as fout:
        _json.dump(doc, fout)
    down = [s for s in summary.get("endpoints", ())
            if not s["up"]]
    print("fleet trace: %d span(s) over %d process lane(s)%s -> %s "
          "(open in Perfetto: https://ui.perfetto.dev)"
          % (summary["spans"], summary["processes"],
             " for %s" % "/".join(summary.get("trace_ids", ()))
             if args.request else "", args.out))
    for key, info in sorted(summary["offsets"].items(),
                            key=lambda kv: str(kv[0])):
        pid = info.get("pid", key)
        if info.get("reference"):
            print("  pid %-8s reference clock (the router's)" % pid)
        elif info["pairs"]:
            print("  pid %-8s offset %+0.6fs over %d bracketing "
                  "pair(s), +/-%.6fs" % (pid, info["offset"],
                                         info["pairs"],
                                         info["bound"] or 0.0))
        else:
            print("  pid %-8s no bracketing pair — own clock "
                  "(offset unknown)" % pid)
    for s in down:
        print("  down: %s (%s)" % (s["url"], s["error"]),
              file=sys.stderr)
    return 0


def _trace_self_time(args) -> int:
    """Parse the trace-event stream (torn/truncated files are
    salvaged with a counted warning, like ``spans.read_jsonl``) and
    print per-stream — and optionally per-span — device self-time."""
    import os as _os
    from .telemetry import devtime
    try:
        if _os.path.isdir(args.trace):
            events = devtime.load_profile_dir(args.trace)
        else:
            events = devtime.load_trace_events(args.trace)
    except (OSError, ValueError) as e:
        print("trace self-time failed: %s" % e, file=sys.stderr)
        return 1
    st = devtime.device_self_time(events)
    print("device self-time: %.6f s over %d device-stream event(s)"
          % (st["device_time_s"], st["n_events"]))
    if not st["n_events"]:
        print("  (no device streams — a host-only capture; bench "
              "falls back to host-sync timing here)")
    rows = sorted(st["by_stream"].items(), key=lambda kv: -kv[1])
    for label, secs in rows[:max(0, args.top)]:
        print("  %-40s %.6f s" % (label, secs))
    if args.spans:
        from .telemetry.spans import read_jsonl
        try:
            span_records = read_jsonl(args.spans)
        except OSError as e:
            print("trace self-time failed: %s" % e, file=sys.stderr)
            return 1
        per = devtime.attribute_spans(events, span_records)
        print("per-span device self-time (%d span name(s)):"
              % len(per))
        rows = sorted(per.items(),
                      key=lambda kv: -kv[1]["device_time_s"])
        for name, row in rows[:max(0, args.top)]:
            print("  %-40s %.6f s over %d span(s)"
                  % (name, row["device_time_s"], row["spans"]))
    return 0


def _faults_cli(argv) -> int:
    """``veles-tpu faults list`` — print the registered fault-injection
    points of the resilience plane (veles_tpu/resilience/faults.py) and
    the spec that is currently armed, if any."""
    import argparse
    parser = argparse.ArgumentParser(
        prog="veles_tpu faults",
        description="deterministic fault-injection plane "
                    "(docs/resilience.md)")
    sub = parser.add_subparsers(dest="cmd", required=True)
    sub.add_parser("list", help="print registered injection points")
    parser.parse_args(argv)
    from .resilience import faults
    print("registered injection points (arm via VELES_FAULTS or "
          "root.common.resilience.faults):")
    for name, desc in sorted(faults.list_points().items()):
        print("  %-17s %s" % (name, desc))
    print("clause grammar: point:action[:p=P,after=N,times=N,"
          "delay=S,window=T0:T1]")
    print("  window=T0:T1 arms the action only between the T0-th and "
          "T1-th trigger\n  of the point (then it heals) — the timed "
          "chaos-storm form `veles-tpu\n  loadgen --storm` requires")
    spec = faults.plane.current_spec()
    print("active spec: %s" % (spec or "(none)"))
    return 0


def _linalg_cli(argv) -> int:
    """``veles-tpu linalg bench|solve`` — the distributed
    linear-algebra workload family (veles_tpu/linalg/,
    docs/workloads.md) from the command line.

    ``bench`` runs the blocked kernels (block-cyclic SUMMA matmul,
    right-looking Cholesky solve) over the device mesh, checks each
    against the dense ``numpy.linalg`` reference within the stated
    dtype tolerance, and prints one JSON line with the relative
    errors, the achieved MFU graded against the dtype-correct peak
    table and the stated SUMMA step-time prediction.

    ``solve`` runs conjugate gradient on the 5-point Poisson model
    problem as a Workflow graph (``--precondition`` arms the 2-level
    multigrid V-cycle) and prints the per-iteration residual story."""
    import argparse
    import json as _json
    import time as _time
    parser = argparse.ArgumentParser(
        prog="veles_tpu linalg",
        description="distributed linear-algebra workloads "
                    "(docs/workloads.md)")
    sub = parser.add_subparsers(dest="cmd", required=True)
    bench = sub.add_parser(
        "bench", help="blocked kernels vs the dense reference + MFU")
    bench.add_argument("--m", type=int, default=512)
    bench.add_argument("--k", type=int, default=512)
    bench.add_argument("--n", type=int, default=512)
    bench.add_argument("--cholesky", type=int, default=256,
                       metavar="N",
                       help="SPD factor/solve size (0 skips it)")
    bench.add_argument("--block", type=int, default=None,
                       help="block size (default linalg.DEFAULT_BLOCK)")
    bench.add_argument("--grid", default=None, metavar="PRxPC",
                       help="device grid, e.g. 2x4 (default: squarest "
                            "factorization of the visible devices)")
    bench.add_argument("--dtype", default="float32",
                       choices=("float32", "float64"),
                       help="computation dtype (grades MFU against "
                            "the matching peak table)")
    bench.add_argument("--seed", type=int, default=0)
    solve = sub.add_parser(
        "solve", help="CG on the Poisson problem as a Workflow graph")
    solve.add_argument("--n", type=int, default=64, metavar="N",
                       help="interior grid side (N^2 unknowns)")
    solve.add_argument("--tol", type=float, default=1e-6)
    solve.add_argument("--max-iters", type=int, default=500)
    solve.add_argument("--precondition", action="store_true",
                       help="2-level multigrid V-cycle preconditioner "
                            "(needs even --n)")
    solve.add_argument("--grid", default=None, metavar="PRxPC")
    solve.add_argument("--block", type=int, default=None)
    solve.add_argument("--seed", type=int, default=0)
    solve.add_argument("--json", default=None, metavar="PATH",
                       help="write {iterations, residual, history} "
                            "as JSON")
    args = parser.parse_args(argv)
    import numpy
    from .linalg import (DEFAULT_BLOCK, LinalgError, TwoLevelPoisson,
                         blocked_matmul, build_cg_workflow,
                         cholesky_solve, default_tolerance,
                         linalg_mesh, poisson2d_matvec,
                         predict_summa_time)
    grid = None
    if args.grid:
        try:
            pr, _, pc = args.grid.lower().partition("x")
            grid = (int(pr), int(pc))
        except ValueError:
            parser.error("--grid wants PRxPC, e.g. 2x4")
    block = args.block or DEFAULT_BLOCK
    mesh = linalg_mesh(grid)
    rng = numpy.random.RandomState(args.seed)
    if args.cmd == "solve":
        rhs = rng.standard_normal(args.n * args.n).astype(numpy.float32)
        precond = None
        if args.precondition:
            precond = TwoLevelPoisson(args.n, block=block, mesh=mesh)
        wf = build_cg_workflow(poisson2d_matvec(args.n), rhs,
                               tol=args.tol, max_iters=args.max_iters,
                               preconditioner=precond)
        wf.initialize()
        try:
            wf.run()
        except LinalgError as e:
            print("linalg solve FAILED verification: %s" % e,
                  file=sys.stderr)
            return 1
        res = wf.cg_decision.get_metric_values()
        print("poisson %dx%d (%d unknowns)%s: %s in %d iteration(s), "
              "recurrence residual %.3e, verified true residual %s"
              % (args.n, args.n, args.n * args.n,
                 " + multigrid V-cycle" if precond else "",
                 "converged" if res["converged"] else
                 "DID NOT CONVERGE", res["iterations"],
                 res["residual"],
                 "%.3e" % res["true_residual"]
                 if res["true_residual"] is not None else "(skipped)"))
        history = res["residual_history"]
        for i in range(0, len(history),
                       max(1, len(history) // 10) or 1):
            print("  iter %-4d residual %.3e" % (i, history[i]))
        if args.json:
            with open(args.json, "w") as fh:
                _json.dump(res, fh, indent=2, sort_keys=True)
            print("report written: %s" % args.json)
        return 0 if res["converged"] else 1
    # bench
    from .telemetry.cost import peak_flops_entry
    dtype = numpy.dtype(args.dtype)
    tol = default_tolerance(dtype)
    a = rng.standard_normal((args.m, args.k)).astype(dtype)
    b = rng.standard_normal((args.k, args.n)).astype(dtype)
    c = numpy.asarray(blocked_matmul(a, b, block=block, mesh=mesh))
    ref = a.astype(numpy.float64) @ b.astype(numpy.float64)
    mm_err = float(numpy.linalg.norm(c - ref) / numpy.linalg.norm(ref))
    t0 = _time.perf_counter()
    blocked_matmul(a, b, block=block, mesh=mesh)
    step_s = max(_time.perf_counter() - t0, 1e-9)
    peak_source, peak = peak_flops_entry(dtype)
    pgrid = tuple(mesh.devices.shape)
    report = {
        "grid": "%dx%d" % pgrid,
        "dtype": args.dtype,
        "block": block,
        "matmul": {"m": args.m, "k": args.k, "n": args.n,
                   "rel_err": mm_err, "tolerance": tol,
                   "step_s": step_s,
                   "mfu": (2.0 * args.m * args.n * args.k)
                   / (step_s * peak * mesh.size)},
        "peak_flops_used": peak,
        "peak_source": peak_source,
        "predicted": predict_summa_time(args.m, args.k, args.n, pgrid,
                                        t1_step_s=step_s, dtype=dtype),
    }
    failed = not mm_err < tol
    if args.cholesky:
        g = rng.standard_normal((args.cholesky,
                                 args.cholesky)).astype(dtype)
        spd = g @ g.T + args.cholesky * numpy.eye(args.cholesky,
                                                  dtype=dtype)
        rhs = rng.standard_normal((args.cholesky, 1)).astype(dtype)
        try:
            x = numpy.asarray(cholesky_solve(spd, rhs, block=block,
                                             mesh=mesh, check=True))
            xref = numpy.linalg.solve(spd.astype(numpy.float64),
                                      rhs.astype(numpy.float64))
            ch_err = float(numpy.linalg.norm(x - xref)
                           / numpy.linalg.norm(xref))
            report["cholesky"] = {"n": args.cholesky,
                                  "rel_err": ch_err, "tolerance": tol}
            failed = failed or not ch_err < tol
        except LinalgError as e:
            report["cholesky"] = {"n": args.cholesky, "error": str(e)}
            failed = True
    print(_json.dumps(report))
    if failed:
        print("linalg bench FAILED the dense-reference tolerance",
              file=sys.stderr)
    return 1 if failed else 0


def _alerts_url(url: str) -> str:
    url = url.strip()
    if "://" not in url:
        url = "http://" + url
    url = url.rstrip("/")
    if url.endswith("/metrics"):
        url = url[:-len("/metrics")]
    return url + "/alerts"


def _fetch_alerts(urls, timeout: float = 5.0):
    """First answering ``GET /alerts`` page across ``urls`` →
    (payload, url) — or (None, None) when nobody answered."""
    import json as _json
    import urllib.request
    for url in urls:
        try:
            with urllib.request.urlopen(_alerts_url(url),
                                        timeout=timeout) as r:
                return _json.loads(r.read() or b"{}"), url
        except Exception:        # noqa: BLE001 — a down endpoint is data
            continue
    return None, None


def _watch_frame(rep, agg, alerts) -> str:
    """One dashboard frame (``veles-tpu watch``): fleet rates +
    windowed quantiles from the client-side SeriesStore, roster
    health, and the firing-alert block."""
    def fmt(v, unit="", nd=None):
        if v is None:
            return "-"
        if nd is not None:
            v = round(v, nd)
        return "%g%s" % (v, unit)
    lines = ["veles-tpu watch  %s/%s endpoint(s) up"
             % (fmt(rep["up"]), fmt(rep["endpoints"]))]
    lines.append("  qps %-8s tok/s %-8s shed/s %s"
                 % (fmt(rep["qps"]), fmt(rep["tok_s"]),
                    fmt(rep["shed_s"])))
    lines.append("  ttft p50/p99 %s/%s   tpot p50/p99 %s/%s   "
                 "e2e p99 %s"
                 % (fmt(rep["ttft_p50"], "s"), fmt(rep["ttft_p99"], "s"),
                    fmt(rep["tpot_p50"], "s"), fmt(rep["tpot_p99"], "s"),
                    fmt(rep["e2e_p99"], "s")))
    lines.append("  slots busy %s/%s   queue %s   brownout L%s   "
                 "admit %s"
                 % (fmt(rep["slots_busy"]), fmt(rep["slots"]),
                    fmt(rep["queue_depth"]), fmt(rep["brownout"]),
                    fmt(rep["admit_rate"], nd=3)))
    for ep in agg["endpoints"]:
        lines.append("  %-4s %s%s"
                     % ("up" if ep["up"] else "DOWN", ep["url"],
                        "" if ep["up"] else "  (%s)" % ep["error"]))
    if alerts is None:
        lines.append("  alerts: no /alerts endpoint answered")
    elif not alerts.get("enabled"):
        lines.append("  alerts: watchtower off "
                     "(root.common.telemetry.watch.enabled)")
    else:
        firing = [r for r in alerts.get("rules", ())
                  if r.get("state") == "firing"]
        if not firing:
            lines.append("  alerts: %d rule(s), none firing"
                         % len(alerts.get("rules", ())))
        for r in firing:
            lines.append("  alerts: FIRING %s (%s) value=%s since=%s"
                         % (r.get("rule"), r.get("severity"),
                            r.get("value"), r.get("since")))
    return "\n".join(lines)


def _watch_cli(argv) -> int:
    """``veles-tpu watch URL [URL ...]`` — live terminal dashboard
    over a serving fleet: scrape every endpoint's ``/metrics`` each
    period into a client-side watchtower SeriesStore
    (telemetry/timeseries.py, ``count_samples=False``), display
    WINDOWED rates and latency quantiles (bucket deltas between
    samples — not the cumulative-since-start ``_p99`` gauges), the
    roster's up/down state, and the firing alerts from the fleet's
    ``GET /alerts``."""
    import argparse
    import json as _json
    import time as _time
    parser = argparse.ArgumentParser(
        prog="veles_tpu watch",
        description="live fleet watch dashboard "
                    "(docs/observability.md 'Watchtower')")
    parser.add_argument("urls", nargs="*", metavar="URL",
                        help="endpoint serving /metrics (router "
                             "and/or replicas; bare host:port "
                             "accepted)")
    parser.add_argument("--endpoints-file", default=None,
                        metavar="FILE",
                        help="replica roster file — same format as "
                             "`route`/`metrics aggregate` (plain "
                             "lines, or a saved GET /roster page)")
    parser.add_argument("--period", type=float, default=1.0,
                        metavar="SEC",
                        help="seconds between scrapes (default 1)")
    parser.add_argument("--window", type=float, default=30.0,
                        metavar="SEC",
                        help="trailing window for rates/quantiles "
                             "(default 30)")
    parser.add_argument("--iterations", type=int, default=0,
                        metavar="N",
                        help="stop after N frames (0 = run until "
                             "interrupted)")
    parser.add_argument("--once", action="store_true",
                        help="two samples one period apart, one "
                             "frame, exit (scriptable snapshot)")
    parser.add_argument("--no-clear", action="store_true",
                        help="append frames instead of redrawing "
                             "(logs, tests)")
    parser.add_argument("--json", action="store_true",
                        help="print one JSON line per frame instead "
                             "of the dashboard (implies --no-clear)")
    parser.add_argument("--timeout", type=float, default=5.0,
                        help="per-endpoint scrape timeout, seconds")
    args = parser.parse_args(argv)
    from .telemetry import fleet as _fleet
    from .telemetry.timeseries import SeriesStore
    urls = list(args.urls)
    if args.endpoints_file:
        try:
            urls += _fleet.read_endpoints(args.endpoints_file)
        except (OSError, ValueError) as e:
            parser.error("bad --endpoints-file: %s" % e)
    if not urls:
        parser.error("no endpoints (positional URLs and/or "
                     "--endpoints-file)")
    if args.period <= 0:
        parser.error("--period must be > 0")
    store = SeriesStore(period=args.period,
                        retention=max(600.0, args.period * 600),
                        count_samples=False)
    iterations = 2 if args.once else args.iterations
    n = 0
    last_up = 0
    try:
        while True:
            agg = _fleet.aggregate(urls, timeout=args.timeout)
            _fleet.ingest_aggregate(store, agg)
            last_up = sum(1 for ep in agg["endpoints"] if ep["up"])
            n += 1
            final = iterations and n >= iterations
            # --once stays quiet until its second sample: the first
            # frame of a fresh store has no deltas to show
            if not args.once or final:
                rep = _fleet.interval_report(store, window=args.window)
                alerts, _ = _fetch_alerts(urls, timeout=args.timeout)
                if args.json:
                    rep["alerts"] = alerts
                    print(_json.dumps(rep, sort_keys=True))
                else:
                    if not args.no_clear:
                        print("\x1b[2J\x1b[H", end="")
                    print(_watch_frame(rep, agg, alerts), flush=True)
            if final:
                break
            _time.sleep(args.period)
    except KeyboardInterrupt:
        pass
    return 0 if last_up else 2


def _alerts_cli(argv) -> int:
    """``veles-tpu alerts URL`` — list the fleet watchtower's alert
    rule states (``GET /alerts``). Exit 0 with nothing firing, 1
    with at least one firing rule (scriptable: a deploy gate can
    refuse to proceed into a burning fleet), 2 when no endpoint
    answered."""
    import argparse
    import json as _json
    parser = argparse.ArgumentParser(
        prog="veles_tpu alerts",
        description="watchtower alert rule states "
                    "(docs/observability.md 'Watchtower')")
    parser.add_argument("urls", nargs="+", metavar="URL",
                        help="endpoint serving /alerts (first "
                             "answering one is reported)")
    parser.add_argument("--timeout", type=float, default=5.0)
    parser.add_argument("--json", action="store_true",
                        help="print the raw /alerts payload")
    args = parser.parse_args(argv)
    payload, url = _fetch_alerts(args.urls, timeout=args.timeout)
    if payload is None:
        print("alerts: no endpoint answered /alerts", file=sys.stderr)
        return 2
    if args.json:
        print(_json.dumps(payload, indent=2, sort_keys=True))
        return 1 if payload.get("firing") else 0
    if not payload.get("enabled"):
        print("%s: watchtower off "
              "(set root.common.telemetry.watch.enabled)" % url)
        return 0
    rules = payload.get("rules", [])
    print("%s: %d rule(s), %d firing"
          % (url, len(rules), len(payload.get("firing", []))))
    for r in rules:
        print("  %-8s %-24s %-9s value=%-12s since=%s"
              % (r.get("severity"), r.get("rule"),
                 r.get("state") or "pending",
                 r.get("value"), r.get("since")))
    return 1 if payload.get("firing") else 0


def _loadgen_cli(argv) -> int:
    """``veles-tpu loadgen URL`` — drive a serving endpoint (replica
    or router front) open-loop with a seeded synthetic workload
    (veles_tpu/loadgen/), optionally under timed chaos storms, and
    print the per-class latency aggregates plus the SLO verdict.
    Storms arm the PROCESS-LOCAL fault plane, so they reach
    in-process fleets only — arm a remote replica through its own
    VELES_FAULTS."""
    import argparse
    import json as _json
    parser = argparse.ArgumentParser(
        prog="veles_tpu loadgen",
        description="open-loop fleet load/chaos harness "
                    "(docs/services.md 'Overload & QoS')")
    parser.add_argument("url", metavar="URL",
                        help="endpoint to drive (http://host:port)")
    parser.add_argument("--path", default="/generate",
                        help="POST path (default /generate)")
    parser.add_argument("--requests", type=int, default=100,
                        metavar="N", help="requests to offer")
    parser.add_argument("--rate", type=float, default=20.0,
                        metavar="R", help="offered req/s (base rate)")
    parser.add_argument("--shape", default="steady",
                        choices=("steady", "burst", "diurnal"),
                        help="arrival shape (default steady)")
    parser.add_argument("--n-new", type=int, default=8, metavar="T",
                        help="tokens to decode per request")
    parser.add_argument("--min-prompt", type=int, default=4)
    parser.add_argument("--max-prompt", type=int, default=64)
    parser.add_argument("--vocab", type=int, default=128,
                        help="prompt token id upper bound (match the "
                             "served model's vocabulary)")
    parser.add_argument("--batch-fraction", type=float, default=0.5,
                        metavar="F",
                        help="fraction labeled priority=batch")
    parser.add_argument("--stream-fraction", type=float, default=0.0,
                        metavar="F", help="fraction streaming (SSE)")
    parser.add_argument("--sample-fraction", type=float, default=0.25,
                        metavar="F", help="fraction mode=sample")
    parser.add_argument("--shared-fraction", type=float, default=0.5,
                        metavar="F",
                        help="fraction opening with a shared prefix")
    parser.add_argument("--deadline-ms", type=float, default=None,
                        metavar="MS",
                        help="per-request deadline for interactive "
                             "requests (propagated to the fleet)")
    parser.add_argument("--storm", action="append", default=[],
                        metavar="SPEC",
                        help="timed chaos storm, a fault clause with "
                             "a window= field (repeatable), e.g. "
                             "serve.replica_death:raise:window=50:51")
    parser.add_argument("--timeout", type=float, default=60.0,
                        metavar="SEC", help="per-request client "
                        "patience (default 60)")
    parser.add_argument("--slo-ttft-ms", type=float, default=2000.0,
                        metavar="MS", help="interactive TTFT p99 "
                        "bound for the verdict (default 2000)")
    parser.add_argument("--max-interactive-loss", type=float,
                        default=0.05, metavar="F",
                        help="interactive shed+error fraction bound")
    parser.add_argument("--min-goodput", type=float, default=0.0,
                        metavar="TPS",
                        help="goodput floor (tokens/s) for the "
                             "verdict (default 0 = no floor)")
    parser.add_argument("--abort-on-alert", action="store_true",
                        help="poll the fleet's GET /alerts while "
                             "driving and stop dispatching the "
                             "moment any watchtower rule fires — "
                             "the run FAILS at fire time instead of "
                             "at the end-of-run verdict")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--json", default=None, metavar="PATH",
                        help="write the full report (records "
                             "included) as JSON")
    args = parser.parse_args(argv)
    from .loadgen import LoadGen, Workload, parse_storm, verdict
    workload = Workload(
        n_requests=args.requests, rate=args.rate, shape=args.shape,
        min_prompt=args.min_prompt, max_prompt=args.max_prompt,
        n_new=args.n_new, vocab=args.vocab,
        shared_fraction=args.shared_fraction,
        batch_fraction=args.batch_fraction,
        stream_fraction=args.stream_fraction,
        sample_fraction=args.sample_fraction,
        deadline_ms=args.deadline_ms, seed=args.seed)
    storms = [parse_storm(s) for s in args.storm]
    url = args.url if "://" in args.url else "http://" + args.url
    report = LoadGen(url, workload, storms=storms, path=args.path,
                     timeout=args.timeout,
                     abort_on_alert=args.abort_on_alert).run()
    slo = verdict(report, slo_ttft_ms=args.slo_ttft_ms,
                  max_interactive_loss=args.max_interactive_loss,
                  min_goodput_tokens_per_s=args.min_goodput)
    report["verdict"] = slo
    agg = report["aggregates"]
    print("offered %d, answered %d in %.1fs (goodput %.1f tok/s)"
          % (report["offered"], report["answered"],
             report["wall_seconds"], agg["goodput_tokens_per_s"]))
    aborted = report.get("aborted_on_alert")
    if aborted is not None:
        print("  ABORTED on firing alert(s) %s after %d dispatched"
              % (",".join(aborted["rules"]) or "(unknown)",
                 aborted["after_requests"]))
    for cls in ("interactive", "batch"):
        row = agg[cls]
        print("  %-11s ok=%d shed=%d err=%d ttft_p99=%sms "
              "e2e_p99=%sms" % (cls, row["ok"], row["shed"],
                                row["errors"], row["ttft_p99_ms"],
                                row["e2e_p99_ms"]))
    if agg["server_ttft_p99_ms"] is not None:
        print("  server ttft_p99=%sms queue_wait_p99=%sms"
              % (agg["server_ttft_p99_ms"],
                 agg["server_queue_wait_p99_ms"]))
    for check in slo["checks"]:
        print("  [%s] %s: %s vs %s"
              % ("ok" if check["ok"] else "FAIL", check["name"],
                 check["observed"], check["bound"]))
    if args.json:
        with open(args.json, "w") as fh:
            _json.dump(report, fh, indent=2, sort_keys=True)
        print("report written: %s" % args.json)
    print("verdict: %s" % ("PASS" if slo["pass"] else "FAIL"))
    return 0 if slo["pass"] else 1


def _route_cli(argv) -> int:
    """``veles-tpu route URL [URL ...]`` — run the serving-fleet
    router (serving/router.py): health-gated admission over the
    replica roster, per-replica circuit breakers, idempotent failover
    keyed on request_id, graceful drain on SIGTERM / POST /drain.
    The roster comes from positional URLs and/or ``--endpoints-file``
    (plain lines, or the JSON a saved ``GET /roster`` page is — the
    same file ``veles-tpu metrics aggregate --endpoints-file``
    consumes, so fleet scraping and routing share one roster)."""
    import argparse
    import signal
    import threading
    parser = argparse.ArgumentParser(
        prog="veles_tpu route",
        description="serving fleet router "
                    "(docs/services.md 'Serving fleet')")
    parser.add_argument("endpoints", nargs="*", metavar="URL",
                        help="replica endpoint (http://host:port; "
                             "bare host:port accepted)")
    parser.add_argument("--endpoints-file", default=None,
                        metavar="FILE",
                        help="replica roster file: one endpoint per "
                             "line (# comments), or JSON "
                             "({\"endpoints\": [...]} / a bare list)")
    parser.add_argument("--port", type=int, default=0,
                        help="router port (0 = ephemeral, printed)")
    parser.add_argument("--path", default="/generate",
                        help="proxied POST path (default /generate)")
    parser.add_argument("--probe-interval", type=float, default=None,
                        metavar="SEC",
                        help="replica /readyz + /metrics probe period "
                             "(root.common.router.probe_interval, "
                             "default 1)")
    parser.add_argument("--failure-threshold", type=int, default=None,
                        metavar="N",
                        help="consecutive attempt failures that open "
                             "a replica's circuit breaker (default 3)")
    parser.add_argument("--retry-budget", type=int, default=None,
                        metavar="N",
                        help="failover retries per request beyond the "
                             "first attempt (default 2)")
    parser.add_argument("--attempt-timeout", type=float, default=None,
                        metavar="SEC",
                        help="patience per replica attempt before "
                             "failing over (default 10)")
    parser.add_argument("--request-timeout", type=float, default=None,
                        metavar="SEC",
                        help="total routing budget per request "
                             "(default 120)")
    parser.add_argument("--drain-grace", type=float, default=None,
                        metavar="SEC",
                        help="graceful-drain budget on SIGTERM / "
                             "POST /drain (default 30)")
    parser.add_argument("--journal", default=None, metavar="DIR",
                        help="durable request journal directory "
                             "(docs/services.md 'Lossless request "
                             "plane'): every accepted request is "
                             "fsync'd to DIR before dispatch and "
                             "marked terminal on answer; a restart "
                             "replays the unanswered tail, so a "
                             "router SIGKILL loses zero accepted "
                             "requests")
    args = parser.parse_args(argv)
    endpoints = list(args.endpoints)
    if args.endpoints_file:
        from .telemetry.fleet import read_endpoints
        try:
            endpoints += read_endpoints(args.endpoints_file)
        except (OSError, ValueError) as e:
            print("route: bad --endpoints-file: %s" % e,
                  file=sys.stderr)
            return 1
    if not endpoints:
        parser.error("no replica endpoints (positional URLs and/or "
                     "--endpoints-file)")
    from .serving.router import FleetRouter
    router = FleetRouter(
        endpoints, port=args.port, path=args.path,
        probe_interval=args.probe_interval,
        failure_threshold=args.failure_threshold,
        retry_budget=args.retry_budget,
        attempt_timeout=args.attempt_timeout,
        request_timeout=args.request_timeout,
        journal_dir=args.journal).start()
    print("ROUTING port=%d replicas=%d" % (router.port,
                                           len(router.replicas)),
          flush=True)                                   # scriptable
    term = threading.Event()
    prev_term = signal.signal(signal.SIGTERM,
                              lambda _s, _f: term.set())
    try:
        while not term.wait(0.2):
            pass
        # SIGTERM: stop admission (/readyz flips to draining), finish
        # in-flight requests, exit 0 — the rolling-restart contract
        router.drain(grace=args.drain_grace)
    except KeyboardInterrupt:
        pass
    finally:
        router.stop()
        signal.signal(signal.SIGTERM, prev_term)
    return 0


def _blackbox_cli(argv) -> int:
    """``veles-tpu blackbox dump|inspect`` — write the current
    process's flight-recorder ring to a black-box file, or summarize
    one written by a crash/watchdog/SIGTERM/NaN-sentinel dump
    (veles_tpu/telemetry/recorder.py)."""
    import argparse
    parser = argparse.ArgumentParser(
        prog="veles_tpu blackbox",
        description="flight-recorder black box "
                    "(docs/observability.md)")
    sub = parser.add_subparsers(dest="cmd", required=True)
    dmp = sub.add_parser("dump", help="dump this process's ring")
    dmp.add_argument("--out", default=None,
                     help="output path (default: blackbox-<ts>.jsonl "
                          "in the snapshot directory)")
    dmp.add_argument("--reason", default="cli dump")
    ins = sub.add_parser(
        "inspect", help="summarize a blackbox-*.jsonl dump")
    ins.add_argument("path")
    ins.add_argument("--tail", type=int, default=10, metavar="N",
                     help="also print the last N events")
    ins.add_argument("--request", default=None, metavar="ID",
                     help="only events tagged with this request_id "
                          "or trace_id — cross-reference a crashed "
                          "replica's black box against a fleet "
                          "trace (`trace fleet --request`)")
    args = parser.parse_args(argv)
    from .telemetry.recorder import (flight, inspect, matches_request,
                                     read_blackbox)
    if args.cmd == "dump":
        try:
            path = flight.dump(args.reason, path=args.out)
        except OSError as e:
            print("blackbox dump failed: %s" % e, file=sys.stderr)
            return 1
        print("black box -> %s (%d events)"
              % (path, flight.stats()["buffered"]))
        return 0
    try:
        summary = inspect(args.path, request=args.request)
    except OSError as e:
        print("blackbox inspect failed: %s" % e, file=sys.stderr)
        return 1
    print("black box %s" % summary["path"])
    print("  reason:  %s" % summary["reason"])
    print("  pid:     %s" % summary["pid"])
    if args.request:
        print("  request: %s (%d of %d events)"
              % (args.request, summary["events"],
                 summary["events_total"]))
    print("  events:  %d over %.3fs"
          % (summary["events"], summary["span_seconds"]))
    for kind, count in sorted(summary["by_kind"].items(),
                              key=lambda kv: -kv[1]):
        print("  %-12s %d" % (kind, count))
    if args.tail > 0:
        _, events = read_blackbox(args.path)
        if args.request:
            events = [e for e in events
                      if matches_request(e, args.request)]
        for rec in events[-args.tail:]:
            label = rec.get("name") or rec.get("counter") or ""
            extra = ""
            if rec.get("request_id"):
                extra = " %s attempt=%s %s" % (
                    rec.get("request_id"), rec.get("attempt", "?"),
                    rec.get("phase") or rec.get("outcome") or "")
            print("  tail: %-10s %s%s" % (rec.get("kind", "?"),
                                          label, extra))
    return 0


def _quantize_cli(argv) -> int:
    """``veles-tpu quantize SNAPSHOT`` — offline int8 weight
    quantization of a snapshot (veles_tpu/quant/): eligible 2-D matmul
    weights become per-channel symmetric int8 with scale sidecars,
    shrinking their bytes ~4x (whole-file ratio depends on the
    float-kept share: embeddings, optimizer state). The output is an
    ordinary snapshot —
    ``load_snapshot`` dequantizes on read, so --snapshot/resume and
    serving work unchanged anywhere."""
    import argparse
    import os
    import pickle
    import time
    parser = argparse.ArgumentParser(
        prog="veles_tpu quantize",
        description="int8 snapshot quantization "
                    "(docs/services.md 'Quantized serving')")
    parser.add_argument("snapshot", help="snapshot file to quantize")
    parser.add_argument("--out", default=None,
                        help="output path (default: insert .int8 "
                             "before the .pickle extension)")
    parser.add_argument("--granularity", default=None,
                        choices=("per_channel", "per_tensor"),
                        help="scale granularity (default: "
                             "root.common.quant.granularity)")
    args = parser.parse_args(argv)
    from .error import VelesError
    from .quant import quantize_state
    from .resilience import checkpoint_chain as chain_mod
    from .snapshotter import CODECS, load_snapshot
    out = args.out
    if out is None:
        base = args.snapshot
        marker = ".pickle"
        if marker not in base:
            parser.error("cannot derive --out from %r (no .pickle "
                         "extension); pass --out" % base)
        idx = base.rindex(marker)
        out = base[:idx] + ".int8" + base[idx:]
    try:
        state = load_snapshot(args.snapshot)
        qstate, report = quantize_state(state,
                                        granularity=args.granularity)
    except (OSError, VelesError) as e:
        print("quantize failed: %s" % e, file=sys.stderr)
        return 1
    opener = open
    for _codec, (op, ext) in CODECS.items():
        if ext and out.endswith(ext):
            opener = op
            break
    tmp = out + ".tmp"
    with opener(tmp, "wb") as fout:
        pickle.dump(qstate, fout, protocol=pickle.HIGHEST_PROTOCOL)
    digest = chain_mod.file_sha256(tmp)
    chain_mod.commit_file(tmp, out)
    chain_mod.write_manifest(
        out, sha256=digest, prefix="quantize", runs=0,
        created=time.time(),
        checksum=qstate.get("__meta__", {}).get("checksum", ""))
    out_size = os.path.getsize(out)
    try:
        in_size = os.path.getsize(args.snapshot)
    except OSError:
        # non-file sources load_snapshot accepts (sqlite://...) have
        # no size to compare; the quantized output is still reported
        in_size = None
    if in_size is None:
        print("quantized %d tensor(s) (%s): %s -> %s (%.1f KiB)"
              % (report["params"],
                 qstate["__meta__"]["quant"]["granularity"],
                 args.snapshot, out, out_size / 1024))
    else:
        print("quantized %d tensor(s) (%s): %s (%.1f KiB) -> %s (%.1f "
              "KiB, %.2fx)"
              % (report["params"],
                 qstate["__meta__"]["quant"]["granularity"],
                 args.snapshot, in_size / 1024, out, out_size / 1024,
                 in_size / max(1, out_size)))
    return 0


def _export_cli(argv) -> int:
    """``veles-tpu export serve-artifact MODEL.py --out DIR`` — build
    the model (optionally restore a snapshot) and serialize the
    continuous engine's per-bucket prefill programs plus its one
    fixed-shape decode step via ``jax.export`` into a package
    directory (export/serve_artifact.py). Serve it with
    ``--serve-artifact DIR``: startup then performs zero jit
    traces/compiles."""
    import argparse
    parser = argparse.ArgumentParser(
        prog="veles_tpu export",
        description="AOT inference-artifact export "
                    "(docs/services.md 'AOT serving artifacts')")
    sub = parser.add_subparsers(dest="cmd", required=True)
    exp = sub.add_parser(
        "serve-artifact",
        help="pre-export the serving engine's decode programs")
    exp.add_argument("model", help="workflow .py (build_workflow())")
    exp.add_argument("--out", required=True,
                     help="artifact package directory to write")
    exp.add_argument("--snapshot", default=None,
                     help="restore this snapshot before exporting")
    exp.add_argument("-b", "--backend", default=None,
                     help="auto | tpu | cpu (the artifact is lowered "
                          "for this platform)")
    exp.add_argument("--serve-slots", type=int, default=None)
    exp.add_argument("--serve-buckets", default=None,
                     metavar="L1,L2,...")
    exp.add_argument("--serve-max-context", type=int, default=None)
    exp.add_argument("--serve-decode-block", type=int, default=None)
    exp.add_argument("--serve-page-size", type=int, default=None)
    exp.add_argument("--serve-pages", type=int, default=None)
    exp.add_argument("--quant-weights", action="store_true")
    exp.add_argument("--quant-kv", action="store_true")
    args = parser.parse_args(argv)
    if args.backend in ("cpu", "numpy"):
        import jax
        jax.config.update("jax_platforms", "cpu")
    if args.quant_weights:
        root.common.quant.weights = True
    if args.quant_kv:
        root.common.quant.kv = True
    from . import Device_for
    from .export.serve_artifact import export_serve_artifact
    module = import_file_as_module(args.model)
    if not hasattr(module, "build_workflow"):
        raise VelesError("%s defines no build_workflow()" % args.model)
    workflow = module.build_workflow()
    workflow.initialize(device=Device_for(args.backend or "auto"))
    if args.snapshot:
        from .snapshotter import resume as snap_resume
        snap_resume(workflow, args.snapshot)
    path = export_serve_artifact(
        workflow, args.out, max_slots=args.serve_slots,
        buckets=args.serve_buckets,
        max_context=args.serve_max_context,
        decode_block=args.serve_decode_block,
        page_size=args.serve_page_size, pages=args.serve_pages)
    import json as _json
    import os as _os
    with open(_os.path.join(path, "contents.json")) as fin:
        serving = _json.load(fin)["serving"]
    print("serve-artifact -> %s (%d programs: %s; serve with "
          "--serve-artifact %s)"
          % (path, len(serving["programs"]),
             ", ".join(sorted(serving["programs"])), path))
    return 0


def _materialize(args) -> None:
    """Collapse Range/Tuneable markers to defaults — any run that is not
    itself the optimizer must still work with an optimize-ready config."""
    from .genetics.config import materialize_defaults
    n = materialize_defaults(root)
    if n:
        logging.getLogger("veles_tpu").info(
            "collapsed %d Range marker(s) to defaults (no --optimize)", n)


def _run_meta(launcher: Launcher, module, args) -> int:
    """--optimize / --ensemble-train / --ensemble-test modes
    (reference: veles/__main__.py:334-361,724-732)."""
    if not hasattr(module, "build_workflow"):
        raise VelesError("meta-learning modes need build_workflow() in %s"
                         % args.model)
    # subprocess candidates need the (exclusive) TPU for themselves —
    # the parent must not initialize a device it will never use
    subprocess_candidates = (
        (args.optimize and (args.optimize_subprocess
                            or args.optimize_workers > 1))
        or (args.ensemble_train and args.ensemble_workers > 1
            and args.ensemble_member is None))
    device = None if subprocess_candidates else launcher.make_device()
    placement = None
    if args.trial_devices:
        # each worker slot trains on its own disjoint chip slice; on a
        # CPU host the package init materializes the slice width as
        # virtual devices, so the same flag is CI-testable
        placement_used = (
            (args.optimize and args.optimize_workers > 1)
            or (args.ensemble_train and args.ensemble_workers > 1
                and args.ensemble_member is None))
        if not placement_used:
            raise VelesError(
                "--trial-devices places WORKER-POOL trials on chip "
                "slices; it needs --optimize-workers or "
                "--ensemble-workers > 1 (serial/inline candidates run "
                "on the parent's device set)")
        from .parallel.trials import mesh_slice_placement
        placement = mesh_slice_placement(
            devices_per_trial=args.trial_devices)
    if args.optimize:
        from .genetics import GeneticsOptimizer
        size, _, gens = args.optimize.partition(":")
        extra = []               # forwarded to subprocess candidates
        if args.config:
            extra.append(args.config)
        extra += args.config_list     # user's inline overrides still apply
        if args.backend:
            extra += ["--backend", args.backend]
        if args.random_seed is not None:
            extra += ["--random-seed", str(args.random_seed)]
        result = GeneticsOptimizer(
            build_workflow=module.build_workflow, model_path=args.model,
            size=int(size), generations=int(gens or 3),
            device=device, subprocess_mode=args.optimize_subprocess,
            n_workers=args.optimize_workers,
            placement=placement,
            crossover=args.optimize_crossover,
            selection=args.optimize_selection,
            extra_argv=extra).run()
    elif args.ensemble_train:
        _materialize(args)
        from .ensemble import EnsembleTrainer
        n, _, ratio = args.ensemble_train.partition(":")
        if args.ensemble_member is not None:
            # parallel-worker child: train exactly one member; the
            # parent assembles the manifest from the entry we emit
            result = EnsembleTrainer(
                module.build_workflow, n_models=int(n),
                train_ratio=float(ratio or 1.0), device=device,
                base_seed=args.random_seed,
                out_file=args.ensemble_file).train_member(
                    args.ensemble_member)
        else:
            extra = []
            if args.config:
                extra.append(args.config)
            extra += args.config_list
            if args.backend:
                extra += ["--backend", args.backend]
            result = EnsembleTrainer(
                module.build_workflow, n_models=int(n),
                train_ratio=float(ratio or 1.0), device=device,
                base_seed=args.random_seed,
                out_file=args.ensemble_file,
                n_workers=args.ensemble_workers, placement=placement,
                model_path=args.model, extra_argv=extra).run()
    else:
        from .ensemble import EnsembleTester
        _materialize(args)
        result = EnsembleTester(module.build_workflow, args.ensemble_test,
                                device=device).run()
    if args.result_file:
        launcher.write_results(result, args.result_file)
    return 0


def _drive(launcher: Launcher, workflow, args):
    launcher.initialize(workflow)
    if args.snapshot:
        launcher.resume(args.snapshot)
    elif args.snapshot_dir:
        # elastic restart: rerunning the same command after a crash or
        # preemption resumes from the newest snapshot automatically
        # (reference disaster-recovery story, SURVEY.md §5.3)
        launcher.try_restore_latest()   # warns if nothing can WRITE
        # snapshots either (no Snapshotter unit linked)
    if args.workflow_graph:
        with open(args.workflow_graph, "w") as fout:
            fout.write(workflow.generate_graph())
        launcher.info("workflow graph → %s", args.workflow_graph)
        return None
    if args.dry_run:
        launcher.info("dry run: initialize OK (%d units)", len(workflow))
        return None
    if args.serve_generate is not None:
        # serve the (optionally snapshot-restored) model instead of
        # training: the CLI face of GenerationAPI. Validate the stack
        # NOW so a non-LM workflow fails with the split_stack reason,
        # not a 500 on the first request.
        from .nn.sampling import split_stack
        from .restful_api import GenerationAPI
        split_stack(list(workflow.forwards))
        draft = None
        if args.serve_draft:
            draft_mod = import_file_as_module(args.serve_draft)
            draft = draft_mod.build_workflow()
            draft.initialize(device=launcher.device)
            if args.serve_draft_snapshot:
                from .snapshotter import resume as snap_resume
                snap_resume(draft, args.serve_draft_snapshot)
            split_stack(list(draft.forwards))
        api = GenerationAPI(workflow, draft=draft,
                            port=args.serve_generate,
                            name="serve_generate")
        api.initialize()
        launcher.info("generation serving on "
                      "http://127.0.0.1:%d/generate — Ctrl-C stops, "
                      "SIGTERM drains gracefully", api.port)
        print("SERVING port=%d" % api.port, flush=True)  # scriptable
        # SIGTERM = the scheduler's eviction notice: stop admission
        # (/readyz flips to draining), finish in-flight tickets within
        # the drain grace, exit 0 — a rolling restart never turns
        # half-served requests into client errors
        import signal
        import threading as _threading
        term = _threading.Event()
        prev_term = signal.signal(signal.SIGTERM,
                                  lambda _s, _f: term.set())
        try:
            while not term.wait(1.0):
                pass
            launcher.info("SIGTERM — draining the serving front")
            api.drain(grace=args.serve_drain_grace)
        except KeyboardInterrupt:
            launcher.info("serving stopped")
        finally:
            api.stop()
            signal.signal(signal.SIGTERM, prev_term)
        return None
    from .resilience import elastic
    results = (launcher.run_elastic() if elastic.enabled()
               else launcher.run())
    if args.timings:
        launcher.print_stats()
    if args.result_file:
        launcher.write_results(results, args.result_file)
    for key, value in sorted(results.items()):
        if not isinstance(value, dict):
            launcher.info("result %s = %s", key, value)
    try:        # peak memory at exit (reference: veles/__main__.py:791-797)
        import resource
        # ru_maxrss units are platform-defined: KiB on Linux, bytes on
        # Darwin
        div = 1024.0 * 1024.0 if sys.platform == "darwin" else 1024.0
        launcher.info("max RSS: %.1f MiB", resource.getrusage(
            resource.RUSAGE_SELF).ru_maxrss / div)
    except Exception:
        pass
    if launcher.interrupted:
        sys.exit(130)   # Ctrl-C must not look like a completed run
    return results


if __name__ == "__main__":
    sys.exit(main())
