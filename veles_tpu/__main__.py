"""CLI entry: ``python -m veles_tpu MODEL.py [CONFIG] [overrides] [flags]``.

Equivalent of the reference's veles/__main__.py:136-867 (Main): argv →
config → model import → Launcher boot → run → results. Model contract
(both reference styles supported):
- ``build_workflow(**kwargs) -> Workflow``  (preferred, simple), or
- ``run(load, main)``: the reference's canonical protocol
  (veles/__main__.py:591-627) — the module calls ``load(WorkflowClass,
  **kw)`` to construct/resume and ``main(**kw)`` to initialize+run.
"""

from __future__ import annotations

import logging
import sys

from .cmdline import apply_config_overrides, make_parser, parse_mesh
from .config import root
from .error import VelesError
from .import_file import import_file_as_module
from .launcher import Launcher
from .logger import setup_logging


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)
    level = (logging.WARNING, logging.INFO,
             logging.DEBUG)[min(args.verbose, 2)]
    setup_logging(level=level, tracefile=args.trace_file)

    # config layering: file, then inline overrides; a bare root.x=y in the
    # config position is an override, not a file
    if args.config and "=" in args.config:
        args.config_list.insert(0, args.config)
        args.config = None
    if args.config:
        root.update_from_file(args.config)
    if args.config_list:
        apply_config_overrides(root, args.config_list)
    if args.force_numpy:
        root.common.engine.force_numpy = True
    if args.backend in ("cpu", "numpy"):
        # keep jax away from the (exclusive, possibly busy) TPU tunnel
        # when the user explicitly asked for a host backend
        import jax
        jax.config.update("jax_platforms", "cpu")
    if args.slave_death_probability:
        root.common.slave_death_probability = args.slave_death_probability
    if args.snapshot_dir:
        root.common.dirs.snapshots = args.snapshot_dir
    if args.timings:
        root.common.trace.timings = True
    if args.dump_config:
        root.print_()
        return 0

    launcher = Launcher(
        backend=args.backend,
        mesh=parse_mesh(args.mesh) if args.mesh else None,
        coordinator=args.coordinator, num_processes=args.num_processes,
        process_id=args.process_id, random_seed=args.random_seed,
        test_mode=args.test)

    module = import_file_as_module(args.model)

    if hasattr(module, "run"):
        # reference-style protocol
        state = {}

        def load(workflow_cls, **kwargs):
            state["workflow"] = workflow_cls(**kwargs)
            return state["workflow"], bool(args.snapshot)

        def main_(**kwargs):
            return _drive(launcher, state["workflow"], args)
        module.run(load, main_)
        return 0
    if hasattr(module, "build_workflow"):
        workflow = module.build_workflow()
        _drive(launcher, workflow, args)
        return 0
    raise VelesError(
        "%s defines neither build_workflow() nor run(load, main)"
        % args.model)


def _drive(launcher: Launcher, workflow, args):
    launcher.initialize(workflow)
    if args.snapshot:
        launcher.resume(args.snapshot)
    if args.workflow_graph:
        with open(args.workflow_graph, "w") as fout:
            fout.write(workflow.generate_graph())
        launcher.info("workflow graph → %s", args.workflow_graph)
        return None
    if args.dry_run:
        launcher.info("dry run: initialize OK (%d units)", len(workflow))
        return None
    results = launcher.run()
    if args.timings:
        launcher.print_stats()
    if args.result_file:
        launcher.write_results(results, args.result_file)
    for key, value in sorted(results.items()):
        if not isinstance(value, dict):
            launcher.info("result %s = %s", key, value)
    if launcher.interrupted:
        sys.exit(130)   # Ctrl-C must not look like a completed run
    return results


if __name__ == "__main__":
    sys.exit(main())
