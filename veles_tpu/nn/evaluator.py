"""Evaluator units: loss + quality metrics.

Equivalent of Znicz ``evaluator`` (EvaluatorSoftmax / EvaluatorMSE; loss
functions "softmax"/"mse", SURVEY.md §2.8 +
docs/manualrst_veles_workflow_parameters.rst:121-166).

The pure ``loss(y_or_logits, labels, mask)`` participates in the fused
train step's jax.grad; ``metrics_fn`` computes n_err/confusion (softmax) or
sum-squared error (MSE) on device. Batch padding (the reference zero-padded
short tail minibatches, veles/loader/base.py:749-753) is handled with a
validity mask so padded samples contribute nothing.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy

from ..memory import Array
from ..units import Unit


class EvaluatorBase(Unit):
    hide_from_registry = True

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "EVALUATOR"
        self.output: Optional[Array] = None      # forward chain output
        self.target: Optional[Array] = None      # labels / target values
        self.batch_metrics: Dict[str, float] = {}

    def loss(self, y, target, mask):
        """Pure scalar loss, mean over valid samples."""
        raise NotImplementedError

    def sum_loss_weight(self, out, mask):
        """Weight turning the mean ``loss`` back into the accumulable
        sum matching ``metrics_fn``'s n_samples unit (samples by
        default; sequence evaluators count tokens)."""
        return mask.sum()

    def metrics_fn(self, y, target, mask):
        """Pure dict of device metrics for the step output."""
        raise NotImplementedError

    def numpy_loss(self, y, target, mask):
        raise NotImplementedError


class EvaluatorSoftmax(EvaluatorBase):
    """Cross-entropy over logits (fused log-softmax — numerically stable,
    unlike composing the reference's separate softmax forward + CE kernel);
    metrics: n_err + confusion matrix (reference EvaluatorSoftmax emitted
    the same for DecisionGD)."""

    MAPPING = "evaluator_softmax"
    hide_from_registry = False

    def __init__(self, workflow, n_classes=None, compute_confusion=False,
                 label_smoothing=0.0, **kwargs):
        super().__init__(workflow, **kwargs)
        self.n_classes = n_classes
        self.compute_confusion = compute_confusion
        #: eps > 0 mixes the one-hot target with the uniform
        #: distribution (Szegedy et al.): CE against
        #: (1-eps)*onehot + eps/V — the classic overconfidence
        #: regularizer
        self.label_smoothing = float(label_smoothing)
        if not 0.0 <= self.label_smoothing < 1.0:
            raise ValueError("label_smoothing must be in [0, 1)")

    def loss(self, logits, labels, mask):
        import jax
        import jax.numpy as jnp
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
        eps = getattr(self, "label_smoothing", 0.0)
        if eps:
            # CE vs (1-eps)·onehot + (eps/V)·uniform
            nll = (1.0 - eps) * nll + eps * (-logp.mean(axis=-1))
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1)

    def metrics_fn(self, logits, labels, mask):
        import jax.numpy as jnp
        pred = jnp.argmax(logits, axis=-1)
        wrong = (pred != labels) & (mask > 0)
        out = {"n_err": jnp.sum(wrong), "n_samples": jnp.sum(mask)}
        if self.compute_confusion and self.n_classes:
            flat = labels * self.n_classes + pred
            cm = jnp.bincount(jnp.where(mask > 0, flat, 0).astype(
                jnp.int32), weights=mask,
                length=self.n_classes * self.n_classes)
            out["confusion"] = cm.reshape(self.n_classes, self.n_classes)
        return out

    def numpy_loss(self, logits, labels, mask):
        z = logits.astype(numpy.float64)
        z = z - z.max(axis=1, keepdims=True)
        logp = z - numpy.log(numpy.exp(z).sum(axis=1, keepdims=True))
        nll = -logp[numpy.arange(len(labels)), labels]
        eps = getattr(self, "label_smoothing", 0.0)
        if eps:
            nll = (1.0 - eps) * nll + eps * (-logp.mean(axis=1))
        return float((nll * mask).sum() / max(mask.sum(), 1))


class EvaluatorSoftmaxSeq(EvaluatorBase):
    """Per-position cross-entropy for sequence models (language
    modeling): logits (B, T, V) vs int targets (B, T). The batch
    validity mask extends over every position of a valid sample;
    metrics count positions, so err = per-token error rate and
    avg loss = mean NLL/token (report perplexity as exp of it).
    New capability vs the reference (no LM anywhere in 2015 VELES)."""

    MAPPING = "evaluator_softmax_seq"
    hide_from_registry = False

    def loss(self, logits, targets, mask):
        import jax
        import jax.numpy as jnp
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(
            logp, targets.astype(jnp.int32)[..., None], axis=-1)[..., 0]
        w = mask[:, None] * jnp.ones(nll.shape[1])[None, :]
        return jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1)

    def metrics_fn(self, logits, targets, mask):
        import jax.numpy as jnp
        pred = jnp.argmax(logits, axis=-1)
        w = mask[:, None] * jnp.ones(pred.shape[1])[None, :]
        wrong = (pred != targets.astype(pred.dtype)) * w
        return {"n_err": jnp.sum(wrong), "n_samples": jnp.sum(w)}

    def sum_loss_weight(self, out, mask):
        # n_samples counts TOKENS: weight the per-token mean loss by
        # token count so sum_loss/n_samples is NLL/token (perplexity =
        # exp of it)
        return mask.sum() * out.shape[1]

    def numpy_loss(self, logits, targets, mask):
        z = logits.astype(numpy.float64)
        z = z - z.max(axis=-1, keepdims=True)
        logp = z - numpy.log(numpy.exp(z).sum(axis=-1, keepdims=True))
        b, t = targets.shape
        nll = -logp[numpy.arange(b)[:, None], numpy.arange(t)[None, :],
                    targets]
        w = numpy.asarray(mask)[:, None] * numpy.ones(t)[None, :]
        return float((nll * w).sum() / max(w.sum(), 1))


class EvaluatorMSE(EvaluatorBase):
    """Mean squared error (reference EvaluatorMSE; used by the autoencoder
    workflows). Reports rmse like the reference's metrics."""

    MAPPING = "evaluator_mse"
    hide_from_registry = False

    def __init__(self, workflow, root_normalize=False, **kwargs):
        super().__init__(workflow, **kwargs)
        self.root_normalize = root_normalize

    def loss(self, y, target, mask):
        """Per-feature mean, like ``metrics_fn``'s rmse: keeps gradient
        scale (and therefore usable learning rates) independent of the
        output dimensionality — a sum-over-features loss made the conv AE
        diverge at any lr that worked for small heads."""
        import jax.numpy as jnp
        y = y.astype(jnp.float32)
        target = target.astype(jnp.float32)
        per_sample = jnp.mean(
            jnp.square(y - target).reshape(y.shape[0], -1), axis=1)
        return jnp.sum(per_sample * mask) / jnp.maximum(jnp.sum(mask), 1)

    def metrics_fn(self, y, target, mask):
        import jax.numpy as jnp
        y = y.astype(jnp.float32)
        target = target.astype(jnp.float32)
        d = jnp.square(y - target).reshape(y.shape[0], -1)
        per_sample = jnp.mean(d, axis=1)
        return {"sum_sq": jnp.sum(per_sample * mask),
                "n_samples": jnp.sum(mask)}

    def numpy_loss(self, y, target, mask):
        d = numpy.square(y.astype(numpy.float64) -
                         target.astype(numpy.float64))
        per_sample = d.reshape(len(y), -1).mean(axis=1)
        return float((per_sample * mask).sum() / max(mask.sum(), 1))
