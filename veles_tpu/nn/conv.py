"""Convolution forward units + matched GD units.

Equivalent of Znicz ``conv`` / ``gd_conv`` (layer type "conv*"; reference
surface SURVEY.md §2.8). TPU-native: NHWC layout (the TPU-preferred
convolution layout), ``jax.lax.conv_general_dilated`` so XLA maps the conv
onto the MXU; bfloat16 compute with float32 accumulation. The Znicz
parameter vocabulary is preserved: ``n_kernels``, ``kx``/``ky``,
``sliding=(sx, sy)``, ``padding=(left, top, right, bottom)``.
"""

from __future__ import annotations

from typing import Dict

import numpy

from ..config import root
from ..memory import Array
from .. import prng
from .nn_units import ForwardBase, GradientDescentBase, matches

#: TPU vector lane width — the minor-most dimension the MXU/VPU tile
#: over. A conv whose channel dim is not a lane multiple pays partial
#: tiles on every spatial position.
LANE = 128

#: pad input channels to the lane multiple only while the extra
#: zero-channel MACs stay under this factor. The CostModel roofline
#: argument (telemetry/cost.py): in the layout-bound regime the conv
#: is NOT FLOP-limited — up to ~1.5× redundant (zero) compute that
#: buys full-lane tiling is free, while beyond it the padding itself
#: becomes the new bottleneck (3→128 would be 42× — never).
PAD_HEADROOM = 1.5


def lane_padded_channels(c: int, lane: int = LANE,
                         headroom: float = PAD_HEADROOM) -> int:
    """Channel-pad target for a conv operand: the next lane multiple
    when the FLOP headroom allows it, else ``c`` unchanged (padding
    not worth it). 96 → 128 (1.33×, pays for itself in full-lane
    tiles); 3, 64, 130 → unchanged."""
    c = int(c)
    if c <= 0:
        return c
    want = -(-c // lane) * lane
    return want if want != c and want <= c * headroom else c


def _lane_pad_channels(xx, ww, in_axis: int):
    """Zero-pad ``xx``'s channel dim (last axis) and ``ww``'s matching
    input-channel dim to the lane multiple when
    ``root.common.engine.conv_lane_pad`` is on. Zero channels
    contribute exact-zero partial products, so the result is
    unchanged while the MXU tiles land full; autodiff slices the pads
    back out (pad's transpose), so weight grads keep their true
    shape. OFF (the default) is byte-for-byte the pre-existing
    path."""
    if not root.common.engine.get("conv_lane_pad", False):
        return xx, ww
    import jax.numpy as jnp
    c = xx.shape[-1]
    cp = lane_padded_channels(c)
    if cp == c:
        return xx, ww
    xpad = [(0, 0)] * xx.ndim
    xpad[-1] = (0, cp - c)
    wpad = [(0, 0)] * ww.ndim
    wpad[in_axis] = (0, cp - c)
    return jnp.pad(xx, xpad), jnp.pad(ww, wpad)


class Conv(ForwardBase):
    """Input (B, H, W, C) → output (B, H', W', n_kernels)."""

    MAPPING = "conv"
    PARAMETERIZED = True
    hide_from_registry = False

    def __init__(self, workflow, n_kernels=16, kx=3, ky=3,
                 sliding=(1, 1), padding=(0, 0, 0, 0), **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.n_kernels = n_kernels
        self.kx, self.ky = kx, ky
        self.sliding = tuple(sliding)
        self.padding = tuple(padding)
        self.weights_stddev = kwargs.get("weights_stddev", None)
        self.include_bias = kwargs.get("include_bias", True)

    def _pad_hw(self):
        left, top, right, bottom = self.padding
        return ((top, bottom), (left, right))

    def output_shape_for(self, input_shape):
        b, h, w, _ = input_shape
        (pt, pb), (pl, pr) = self._pad_hw()
        sx, sy = self.sliding
        oh = (h + pt + pb - self.ky) // sy + 1
        ow = (w + pl + pr - self.kx) // sx + 1
        return (b, oh, ow, self.n_kernels)

    def create_params(self, rng: prng.RandomGenerator) -> Dict[str, Array]:
        c_in = self.input.shape[-1]
        fan_in = self.kx * self.ky * c_in
        stddev = self.weights_stddev or (1.0 / numpy.sqrt(fan_in))
        dtype = root.common.engine.precision_type
        # HWIO layout
        w = numpy.zeros((self.ky, self.kx, c_in, self.n_kernels),
                        dtype=dtype)
        prng.get(self.name).fill_normal(w, stddev)
        params = {"weights": Array(w, name=self.name + ".weights")}
        if self.include_bias:
            params["bias"] = Array(
                numpy.zeros((self.n_kernels,), dtype=dtype),
                name=self.name + ".bias")
        return params

    def _conv(self, params, x):
        import jax
        import jax.numpy as jnp
        from ..ops import matmul_precision
        from ..ops.precision import promote_operands
        sx, sy = self.sliding
        xx, ww, ct = promote_operands(x, params["weights"])
        # NHWC/HWIO layout work (ISSUE 9): optional input-channel
        # padding to the lane width where the roofline says the
        # layout, not the FLOPs, is the bottleneck
        xx, ww = _lane_pad_channels(xx, ww, in_axis=2)
        # f32 result only for f32 operands: for bf16 (AMP) the MXU
        # still accumulates f32 in hardware, and requesting an f32
        # RESULT breaks the conv transpose rule (f32 cotangent meets
        # bf16 operands in the VJP — TypeError at grad time)
        pref = jnp.float32 if ct == jnp.float32 else None
        y = jax.lax.conv_general_dilated(
            xx, ww,
            window_strides=(sy, sx),
            padding=self._pad_hw(),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            precision=matmul_precision(),
            preferred_element_type=pref)
        if "bias" in params:
            y = y + params["bias"]
        return y.astype(ct)

    def activation(self, a):
        return a

    def numpy_activation(self, a):
        return a

    def apply(self, params, x, *, train=False, rng=None):
        return self.activation(self._conv(
            self.merged_params(params), x))

    def numpy_apply(self, params, x):
        """Host oracle: direct im2col convolution."""
        params = self.merged_params(params)
        b, h, w, c = x.shape
        (pt, pb), (pl, pr) = self._pad_hw()
        xp = numpy.pad(x, ((0, 0), (pt, pb), (pl, pr), (0, 0)))
        _, oh, ow, _ = self.output_shape_for(x.shape)
        sx, sy = self.sliding
        cols = numpy.zeros((b, oh, ow, self.ky * self.kx * c),
                           dtype=numpy.float32)
        for i in range(oh):
            for j in range(ow):
                patch = xp[:, i * sy:i * sy + self.ky,
                           j * sx:j * sx + self.kx, :]
                cols[:, i, j, :] = patch.reshape(b, -1)
        wmat = params["weights"].reshape(-1, self.n_kernels)
        y = cols @ wmat
        if "bias" in params:
            y = y + params["bias"]
        return self.numpy_activation(y)


class ConvTanh(Conv):
    MAPPING = "conv_tanh"
    A, B = 1.7159, 0.6666

    def activation(self, a):
        import jax.numpy as jnp
        return self.A * jnp.tanh(self.B * a)

    def numpy_activation(self, a):
        return self.A * numpy.tanh(self.B * a)


class ConvRelu(Conv):
    MAPPING = "conv_relu"

    def activation(self, a):
        import jax.numpy as jnp
        return jnp.maximum(a, 0)

    def numpy_activation(self, a):
        return numpy.maximum(a, 0)


class ConvSigmoid(Conv):
    MAPPING = "conv_sigmoid"

    def activation(self, a):
        import jax
        return jax.nn.sigmoid(a)

    def numpy_activation(self, a):
        return 1.0 / (1.0 + numpy.exp(-a))


@matches(Conv)
class GDConv(GradientDescentBase):
    MAPPING = "gd_conv"
    hide_from_registry = False


@matches(ConvTanh)
class GDConvTanh(GradientDescentBase):
    MAPPING = "gd_conv_tanh"


@matches(ConvRelu)
class GDConvRelu(GradientDescentBase):
    MAPPING = "gd_conv_relu"


@matches(ConvSigmoid)
class GDConvSigmoid(GradientDescentBase):
    MAPPING = "gd_conv_sigmoid"
