"""Mixture-of-Experts feed-forward layer (`expert` mesh axis consumer).

New capability vs the reference (the SURVEY §5.7 mesh vocabulary
reserves an ``expert`` axis; nothing in the 2015 codebase uses one).
Soft (dense) mixture: every expert computes, the router's softmax
weights combine — exact, differentiable, and shardable purely through
GSPMD annotations: the expert-leading parameters shard over the
``expert`` axis (parallel/sharding.py) and XLA partitions the einsum,
no hand-written dispatch. Sparse top-k dispatch with all-to-all is the
production-scale follow-up; the dense form is the correctness anchor it
would be tested against (the framework's "oracle first" discipline).
"""

from __future__ import annotations

from typing import Dict

import numpy

from ..memory import Array
from .. import prng
from .nn_units import ForwardBase, GradientDescentBase, matches


class MoEFFN(ForwardBase):
    """y = Σ_e softmax(x·router)_e · FFN_e(x); input (B, D) or (B, T, D)."""

    MAPPING = "moe_ffn"
    PARAMETERIZED = True
    hide_from_registry = False
    PARAM_NAMES = ("router", "w1", "b1", "w2", "b2")

    def __init__(self, workflow, n_experts: int = 4,
                 hidden: int = 0, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.n_experts = int(n_experts)
        self.hidden = int(hidden)
        self.weights_stddev = kwargs.get("weights_stddev", None)

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    def create_params(self, rng: prng.RandomGenerator) -> Dict[str, Array]:
        d = self.input.shape[-1]
        f = self.hidden or 4 * d
        e = self.n_experts
        stddev = self.weights_stddev or (1.0 / numpy.sqrt(d))

        def mk(name, shape, scale):
            w = numpy.zeros(shape, dtype="float32")
            prng.get("%s.%s" % (self.name, name)).fill_normal(w, scale)
            return Array(w, name="%s.%s" % (self.name, name))

        return {
            "router": mk("router", (d, e), stddev),
            "w1": mk("w1", (e, d, f), stddev),
            "b1": Array(numpy.zeros((e, f), "float32"),
                        name=self.name + ".b1"),
            "w2": mk("w2", (e, f, d), 1.0 / numpy.sqrt(f)),
            "b2": Array(numpy.zeros((e, d), "float32"),
                        name=self.name + ".b2"),
        }

    @staticmethod
    def _mix(params, x, np_mod, precision=None):
        """Shared fwd math; x: (tokens, D)."""
        def ein(expr, *ops):
            if precision is None:
                return np_mod.einsum(expr, *ops)
            return np_mod.einsum(expr, *ops, precision=precision)

        logits = ein("nd,de->ne", x, params["router"])        # (N, E)
        z = logits - logits.max(axis=-1, keepdims=True)
        gates = np_mod.exp(z)
        gates = gates / gates.sum(axis=-1, keepdims=True)
        h = ein("nd,edf->nef", x, params["w1"]) + params["b1"][None]
        h = np_mod.tanh(h)
        y = ein("nef,efd->ned", h, params["w2"]) + params["b2"][None]
        return ein("ne,ned->nd", gates, y)

    def apply(self, params, x, *, train=False, rng=None):
        import jax.numpy as jnp
        from ..ops import matmul_precision
        shape = x.shape
        y = self._mix(params, x.reshape(-1, shape[-1]), jnp,
                      precision=matmul_precision())
        return y.reshape(shape)

    def numpy_apply(self, params, x):
        x = numpy.asarray(x, dtype=numpy.float32)
        shape = x.shape
        y = self._mix(params, x.reshape(-1, shape[-1]), numpy)
        return y.reshape(shape)


@matches(MoEFFN)
class GDMoEFFN(GradientDescentBase):
    """Standard SGD rule over the expert parameter tree."""

    MAPPING = "gd_moe_ffn"
    hide_from_registry = False
