"""Mixture-of-Experts feed-forward layer (`expert` mesh axis consumer).

New capability vs the reference (the SURVEY §5.7 mesh vocabulary
reserves an ``expert`` axis; nothing in the 2015 codebase uses one).

Two gating modes on one layer:
- **dense** (``top_k=0``): every expert computes, the router's softmax
  weights combine — exact, differentiable, the correctness anchor;
- **sparse** (``top_k=k``): GShard/Switch-style capacity-slotted
  dispatch — top-k routing with renormalized gates, position-in-expert
  by cumulative sum, tokens beyond ``capacity_factor · k·N/E`` dropped
  (their combine weight is zero, the residual path carries them).
  Expressed entirely as einsums over an (E, C, D) dispatch tensor, so
  GSPMD shards it over the ``expert`` axis and inserts the all-to-alls
  itself — no hand-written collective (the TPU-native form of the
  reference-era "send tensors to ranks" dispatch).

Expert-leading parameters shard over ``expert`` (parallel/sharding.py);
XLA partitions every einsum.
"""

from __future__ import annotations

from typing import Dict

import numpy

from ..memory import Array
from .. import prng
from .nn_units import ForwardBase, GradientDescentBase, matches


class MoEFFN(ForwardBase):
    """y = Σ_e softmax(x·router)_e · FFN_e(x); input (B, D) or (B, T, D)."""

    MAPPING = "moe_ffn"
    PARAMETERIZED = True
    hide_from_registry = False
    PARAM_NAMES = ("router", "w1", "b1", "w2", "b2")

    def __init__(self, workflow, n_experts: int = 4,
                 hidden: int = 0, top_k: int = 0,
                 capacity_factor: float = 1.25, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.n_experts = int(n_experts)
        self.hidden = int(hidden)
        self.top_k = int(top_k)
        if not 0 <= self.top_k <= self.n_experts:
            from ..error import Bug
            raise Bug("top_k=%d out of range for %d experts (0 = dense)"
                      % (self.top_k, self.n_experts))
        self.capacity_factor = float(capacity_factor)
        self.weights_stddev = kwargs.get("weights_stddev", None)

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    def create_params(self, rng: prng.RandomGenerator) -> Dict[str, Array]:
        d = self.input.shape[-1]
        f = self.hidden or 4 * d
        e = self.n_experts
        stddev = self.weights_stddev or (1.0 / numpy.sqrt(d))

        def mk(name, shape, scale):
            w = numpy.zeros(shape, dtype="float32")
            prng.get("%s.%s" % (self.name, name)).fill_normal(w, scale)
            return Array(w, name="%s.%s" % (self.name, name))

        return {
            "router": mk("router", (d, e), stddev),
            "w1": mk("w1", (e, d, f), stddev),
            "b1": Array(numpy.zeros((e, f), "float32"),
                        name=self.name + ".b1"),
            "w2": mk("w2", (e, f, d), 1.0 / numpy.sqrt(f)),
            "b2": Array(numpy.zeros((e, d), "float32"),
                        name=self.name + ".b2"),
        }

    @staticmethod
    def _mix(params, x, np_mod, precision=None):
        """Shared fwd math; x: (tokens, D)."""
        def ein(expr, *ops):
            if precision is None:
                return np_mod.einsum(expr, *ops)
            return np_mod.einsum(expr, *ops, precision=precision)

        logits = ein("nd,de->ne", x, params["router"])        # (N, E)
        z = logits - logits.max(axis=-1, keepdims=True)
        gates = np_mod.exp(z)
        gates = gates / gates.sum(axis=-1, keepdims=True)
        h = ein("nd,edf->nef", x, params["w1"]) + params["b1"][None]
        h = np_mod.tanh(h)
        y = ein("nef,efd->ned", h, params["w2"]) + params["b2"][None]
        return ein("ne,ned->nd", gates, y)

    def _capacity(self, n_tokens: int) -> int:
        per = self.top_k * n_tokens / self.n_experts
        return max(1, int(numpy.ceil(per * self.capacity_factor)))

    def _mix_sparse(self, params, x, np_mod, precision=None):
        """GShard-style capacity dispatch; x: (N, D) → (N, D)."""
        def ein(expr, *ops):
            if precision is None:
                return np_mod.einsum(expr, *ops)
            return np_mod.einsum(expr, *ops, precision=precision)

        n, d = x.shape
        e, k = self.n_experts, self.top_k
        c = self._capacity(n)
        logits = ein("nd,de->ne", x, params["router"])
        z = logits - logits.max(axis=-1, keepdims=True)
        gates = np_mod.exp(z)
        gates = gates / gates.sum(axis=-1, keepdims=True)     # (N, E)
        # top-k mask + renormalized weights (exact float ties — where
        # >k gates survive — are vanishingly rare; the numpy oracle
        # below enforces strictness for the comparison tests)
        thresh = np_mod.sort(gates, axis=-1)[:, -k][:, None]
        m = (gates >= thresh).astype(gates.dtype)
        # strict top-k even under gate ties: keep the k largest only
        if np_mod is numpy:
            excess = m.sum(-1) > k
            if excess.any():
                for i in numpy.where(excess)[0]:
                    keep = numpy.argsort(gates[i])[-k:]
                    m[i] = 0
                    m[i, keep] = 1
        w = gates * m
        w = w / np_mod.maximum(w.sum(-1, keepdims=True), 1e-9)
        # position of each token within its expert's capacity slots
        pos = np_mod.cumsum(m, axis=0) * m - 1                # (N, E)
        keep = (pos >= 0) & (pos < c)
        pos_c = np_mod.clip(pos, 0, c - 1).astype("int32")
        # dispatch tensor (N, E, C): one-hot in C where kept
        onehot_c = (pos_c[..., None]
                    == np_mod.arange(c)[None, None, :])
        disp = (keep[..., None] & onehot_c).astype(x.dtype)   # (N,E,C)
        xe = ein("nec,nd->ecd", disp, x)                      # (E, C, D)
        h = np_mod.tanh(ein("ecd,edf->ecf", xe, params["w1"])
                        + params["b1"][:, None, :])
        ye = ein("ecf,efd->ecd", h, params["w2"]) \
            + params["b2"][:, None, :]
        comb = disp * w[..., None]                            # (N, E, C)
        return ein("nec,ecd->nd", comb, ye)

    def apply(self, params, x, *, train=False, rng=None):
        import jax.numpy as jnp
        from ..ops import matmul_precision
        shape = x.shape
        flat = x.reshape(-1, shape[-1])
        if self.top_k:
            y = self._mix_sparse(params, flat, jnp,
                                 precision=matmul_precision())
        else:
            y = self._mix(params, flat, jnp,
                          precision=matmul_precision())
        return y.reshape(shape)

    def numpy_apply(self, params, x):
        x = numpy.asarray(x, dtype=numpy.float32)
        shape = x.shape
        flat = x.reshape(-1, shape[-1])
        y = (self._mix_sparse(params, flat, numpy) if self.top_k
             else self._mix(params, flat, numpy))
        return y.reshape(shape)


@matches(MoEFFN)
class GDMoEFFN(GradientDescentBase):
    """Standard SGD rule over the expert parameter tree."""

    MAPPING = "gd_moe_ffn"
    hide_from_registry = False
