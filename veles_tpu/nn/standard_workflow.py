"""StandardWorkflow: build a whole training graph from a layer-list config.

Equivalent of Znicz ``standard_workflow`` (reference surface:
docs/source/manualrst_veles_workflow_creation.rst:8-108 — a workflow is
declared as ``layers=[{"type": "conv", ...}, {"type": "max_pooling", ...},
{"type": "softmax", ...}]`` plus a loader). The graph it builds is the
TPU-era training loop (SURVEY.md §7 stage 4):

    StartPoint → Repeater → Loader → TrainStep → Decision ┐
                    ↑                                      │ (not complete)
                    └──────────────────────────────────────┘
                                                           │ (complete)
                         [Snapshotter] → EndPoint ←────────┘

Forward/GD units exist as real graph-member units (so inference extraction,
snapshots and introspection see them) but per-minibatch compute is the
fused TrainStep. ``extract_forward_workflow`` mirrors the reference's
inference extraction."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from ..accelerated import AcceleratedWorkflow
from ..error import VelesError
from ..units import UnitRegistry
from .decision import DecisionGD, DecisionMSE
from .evaluator import EvaluatorMSE, EvaluatorSoftmax
from .lr_adjust import LearningRateAdjust
from .nn_units import ForwardBase
from ..plumbing import Repeater
from .train_step import TrainStep


def parse_mcdnnic(topology: str,
                  common: Optional[Dict[str, Any]] = None
                  ) -> List[Dict[str, Any]]:
    """Znicz ``mcdnnic_topology`` shorthand → layers list
    (reference: docs/source/manualrst_veles_workflow_parameters.rst, e.g.
    ``"12x256x256-32C4-MP2-64C4-MP3-32N-4N"``): the first dash-token is
    the input geometry (informational), ``<n>C<k>`` a conv layer with n
    kernels of size k, ``MP<k>`` max-pooling k×k, ``<n>N`` a
    fully-connected tanh layer — the last N becomes the softmax output.
    ``common`` kwargs (e.g. learning_rate) are merged into every layer."""
    import re
    common = dict(common or {})
    tokens = topology.split("-")
    if not tokens or len(tokens) < 2:
        raise VelesError("mcdnnic topology needs input+layers: %r"
                         % topology)
    layers: List[Dict[str, Any]] = []
    for tok in tokens[1:]:
        m = re.fullmatch(r"(\d+)C(\d+)", tok)
        if m:
            layers.append(dict(common, type="conv_tanh",
                               n_kernels=int(m.group(1)),
                               kx=int(m.group(2)), ky=int(m.group(2))))
            continue
        m = re.fullmatch(r"MP(\d+)", tok)
        if m:
            layers.append(dict(common, type="max_pooling",
                               kx=int(m.group(1)), ky=int(m.group(1))))
            continue
        m = re.fullmatch(r"(\d+)N", tok)
        if m:
            layers.append(dict(common, type="all2all_tanh",
                               output_sample_shape=int(m.group(1))))
            continue
        raise VelesError("bad mcdnnic token %r in %r" % (tok, topology))
    if layers and layers[-1]["type"] == "all2all_tanh":
        last = layers[-1]
        last["type"] = "softmax"
    return layers


def _unit_class(type_name: str) -> type:
    cls = UnitRegistry.mapping.get(type_name)
    if cls is None:
        raise VelesError("unknown layer type %r (known: %s)" %
                         (type_name, sorted(UnitRegistry.mapping)))
    return cls


class StandardWorkflow(AcceleratedWorkflow):
    """Declarative train-graph builder (Znicz StandardWorkflowBase)."""

    hide_from_registry = True

    def __init__(self, workflow=None, layers: Sequence[Dict[str, Any]] = (),
                 loader_unit=None, loss_function: str = "softmax",
                 decision_config: Optional[Dict[str, Any]] = None,
                 lr_schedule=None, snapshotter_unit=None,
                 steps_per_dispatch: int = 16,
                 epochs_per_dispatch: int = 1, target_mode: str = None,
                 pipeline_microbatches: Optional[int] = None,
                 remat: bool = False, grad_accumulation: int = 1,
                 evaluator_config: Optional[Dict[str, Any]] = None,
                 mcdnnic_topology: str = None,
                 mcdnnic_parameters: Optional[Dict[str, Any]] = None,
                 **kwargs):
        self._steps_per_dispatch = steps_per_dispatch
        self._epochs_per_dispatch = epochs_per_dispatch
        self._target_mode = target_mode
        self._pipeline_microbatches = pipeline_microbatches
        self._remat = remat
        self._grad_accumulation = grad_accumulation
        self._evaluator_config = dict(evaluator_config or {})
        super().__init__(workflow, **kwargs)
        if mcdnnic_topology:
            if layers:
                raise VelesError("pass layers OR mcdnnic_topology, "
                                 "not both")
            layers = parse_mcdnnic(mcdnnic_topology, mcdnnic_parameters)
        self.layers_config = list(layers)
        self.loss_function = loss_function
        self.loader = loader_unit
        if self.loader is not None:
            self.loader.workflow = self
            self.add_ref(self.loader)
        self.forwards: List[ForwardBase] = []
        self.repeater = Repeater(self)
        self._build_forwards()
        self._build_trainer(decision_config or {}, lr_schedule)
        if snapshotter_unit is not None:
            self._attach_snapshotter(snapshotter_unit)
        self._wire_loop()

    # -- builders ------------------------------------------------------------
    def _build_forwards(self) -> None:
        prev = None
        for i, cfg in enumerate(self.layers_config):
            cfg = dict(cfg)
            type_name = cfg.pop("type")
            cls = _unit_class(type_name)
            name = cfg.pop("name", "%s%d" % (type_name, i))
            unit = cls(self, name=name, **cfg)
            if prev is None:
                unit.link_attrs(self.loader, ("input", "minibatch_data"))
            else:
                unit.link_attrs(prev, ("input", "output"))
            self.forwards.append(unit)
            prev = unit

    def _build_trainer(self, decision_config, lr_schedule) -> None:
        n_classes = None
        if self.forwards and hasattr(self.forwards[-1], "neurons_number"):
            n_classes = self.forwards[-1].neurons_number
        if self.loss_function == "softmax":
            self.evaluator = EvaluatorSoftmax(self, n_classes=n_classes,
                                              **self._evaluator_config)
            self.decision = DecisionGD(self, **decision_config)
            target_mode = "labels"
        elif self.loss_function == "softmax_seq":
            # language modeling: per-token CE on (B, T) int targets
            from .evaluator import EvaluatorSoftmaxSeq
            self.evaluator = EvaluatorSoftmaxSeq(self)
            self.decision = DecisionGD(self, **decision_config)
            target_mode = "targets"
        elif self.loss_function == "mse":
            self.evaluator = EvaluatorMSE(self)
            self.decision = DecisionMSE(self, **decision_config)
            # loader data isn't loaded yet — TrainStep resolves at init:
            # targets if the loader carries them, else reconstruct input
            target_mode = self._target_mode or "auto"
        else:
            raise VelesError("unknown loss_function %r" % self.loss_function)
        self.train_step = TrainStep(
            self, forwards=self.forwards, evaluator=self.evaluator,
            loader=self.loader, target_mode=target_mode,
            steps_per_dispatch=self._steps_per_dispatch,
            epochs_per_dispatch=self._epochs_per_dispatch,
            pipeline_microbatches=self._pipeline_microbatches,
            remat=self._remat,
            grad_accumulation=self._grad_accumulation)
        self.decision.loader = self.loader
        self.decision.step_unit = self.train_step
        if self._epochs_per_dispatch > 1 and self.loader is not None:
            # the final block must clamp to the epochs remaining under
            # max_epochs: device weights past the cap would desync from
            # the reported trajectory
            self.loader.block_epochs_cap = self.decision.max_epochs
        if lr_schedule is not None:
            self.lr_adjust = LearningRateAdjust(self, schedule=lr_schedule)
            self.lr_adjust.decision = self.decision
            self.train_step.link_attrs(self.lr_adjust, "lr_scale")
        else:
            self.lr_adjust = None

    def _attach_snapshotter(self, snap) -> None:
        snap.workflow = self
        self.add_ref(snap)
        self.snapshotter = snap

    def _wire_loop(self) -> None:
        self.repeater.link_from(self.start_point)
        self.loader.link_from(self.repeater)
        self.train_step.link_from(self.loader)
        tail = self.train_step
        if self.lr_adjust is not None:
            self.lr_adjust.link_from(self.train_step)
            tail = self.lr_adjust
        self.decision.link_from(tail)
        self.repeater.link_from(self.decision)
        self.repeater.gate_block = self.decision.complete
        after = self.decision
        snap = getattr(self, "snapshotter", None)
        if snap is not None:
            snap.link_from(self.decision)
            snap.gate_skip = ~self.decision.complete & ~self.decision.improved
            after = snap
        self.end_point.link_from(after)
        self.end_point.gate_block = ~self.decision.complete

    # -- inference extraction (Znicz extract_forward_workflow) ---------------
    def extract_forward_workflow(self) -> AcceleratedWorkflow:
        """A plain chained-forward workflow over the same (trained) units."""
        from ..mutable import LinkableAttribute
        from ..ops.fused_fc import install_epilogues
        wf = AcceleratedWorkflow(name=self.name + ".forward")
        self.train_step.sync_params_to_arrays()
        prev = wf.start_point
        for i, f in enumerate(self.forwards):
            f.unlink_all()
            if i == 0:
                # detach from the (fused, never-filled) loader minibatch:
                # the caller assigns f.input directly
                LinkableAttribute.unlink(f, "input")
            wf.add_ref(f)
            f.link_from(prev)
            prev = f
        wf.end_point.link_from(prev)
        # standalone chains dispatch one program PER UNIT per batch —
        # the surface where the fused scale-bias-activation epilogue
        # (engine.fused_epilogue, ops/fused_fc.py) actually removes
        # dispatches: elementwise tail units fold into their producing
        # matmul's program and skip their own
        install_epilogues(self.forwards)
        return wf

    def get_metric_values(self) -> Dict[str, Any]:
        return self.decision.get_metric_values()
