"""KV-cached autoregressive sampling — the LM serving path.

New capability vs the reference (its inference story was the libVeles
chain executor; no autoregressive models existed). Naive sampling
re-forwards the whole window per new token — O(T²) matmuls per token
and a fresh device round trip each step. This module keeps per-block
K/V caches on device and runs the WHOLE generation as one
``lax.scan``: per token only the single-position projections + one
attention row run, and the host gets back the finished sequence.

Operates on the public parameter contract of the ``Embedding`` →
``TransformerBlock``×N → ``LMHead`` stack (optionally with a
``PositionalEmbedding`` after the stem); reuses transformer.py's
layernorm/gelu/rope math so cached and full paths cannot drift.
"""

from __future__ import annotations

from typing import Dict, List

import numpy

from ..error import VelesError
from .transformer import (Embedding, LMHead, PositionalEmbedding,
                          TransformerBlock, _rope, block_ffn,
                          block_norm)


def _count_decode_dispatches(program):
    """Decorator applied DIRECTLY over ``jax.jit`` at every decode
    program definition (here and nn/speculative.py): each invocation
    of the jitted program counts one ``veles_decode_dispatches_total``.
    The counter sits at the device-program boundary, not the public
    generate() entry, so a decode restructured into a host loop of
    per-token jitted steps reads as n_new dispatches — the round-5
    dispatch-count regression lock measures, it does not assert. Any
    new jitted decode program MUST wear this decorator."""
    import functools
    from ..telemetry.counters import inc

    @functools.wraps(program)
    def counted(*args, **kwargs):
        inc("veles_decode_dispatches_total")
        return program(*args, **kwargs)
    return counted


def params_of(wf):
    """The device-side parameter pytree of a workflow's forwards — the
    ONE copy of the extraction every decoding entry point shares."""
    return {f.name: {k: v.device_view()
                     for k, v in f.param_arrays().items()}
            for f in wf.forwards if f.PARAMETERIZED}


def _rope_at(np_mod, x, pos, base=10000.0):
    """RoPE for a SINGLE position: x (B, 1, H, Dh), pos scalar (traced
    ok). Same half-split pairing as transformer._rope."""
    hd = x.shape[-1]
    half = hd // 2
    inv = np_mod.asarray(
        (base ** (-numpy.arange(half, dtype="float32") / half)))
    ang = pos.astype("float32") * inv           # (half,)
    cos = np_mod.cos(ang)[None, None, None, :]
    sin = np_mod.sin(ang)[None, None, None, :]
    x1, x2 = x[..., :half], x[..., half:2 * half]
    rot1 = x1 * cos - x2 * sin
    rot2 = x1 * sin + x2 * cos
    if 2 * half == hd:
        return np_mod.concatenate([rot1, rot2], axis=-1)
    return np_mod.concatenate([rot1, rot2, x[..., 2 * half:]], axis=-1)


def split_stack(forwards) -> Dict[str, object]:
    """Stem / block-list / head decomposition of a generation-capable
    forward chain; raises for anything else."""
    stem = pos_emb = head = None
    blocks: List[TransformerBlock] = []
    for f in forwards:
        if isinstance(f, Embedding):
            stem = f
        elif isinstance(f, PositionalEmbedding):
            pos_emb = f
        elif isinstance(f, TransformerBlock):
            blocks.append(f)
        elif isinstance(f, LMHead):
            head = f
        else:
            raise VelesError(
                "cached sampling supports Embedding → [PositionalEmbedding]"
                " → TransformerBlock* → LMHead chains; found %s"
                % type(f).__name__)
    if stem is None or head is None or not blocks:
        raise VelesError("not a generation stack: stem=%r head=%r "
                         "blocks=%d" % (stem, head, len(blocks)))
    return {"stem": stem, "pos_emb": pos_emb, "blocks": blocks,
            "head": head}


def _block_prefill(block, p, x, cache_k, cache_v, tp=1, tp_axis=None):
    """Full-window pass through one block, writing K/V into the caches'
    first T positions. The attention goes through the SAME per-shape
    chooser as TransformerBlock.apply (attention_core: f32 softmax,
    flash kernel above the crossover) so prefill logits cannot drift
    from the trained forward.

    ``tp``/``tp_axis`` (serving engine's ``--serve-tp``): inside a
    shard_map over a 1D ``("model",)`` mesh, ``p`` holds head-sharded
    weight shards (wq/wk/wv column, wo row) and the caches hold this
    shard's ``kv/tp`` K/V heads (Ulysses-style head sharding); the
    partial wo product psums into the full residual. ``hd`` always
    derives from the FULL head count — the residual ``d`` never
    shards."""
    import jax.numpy as jnp
    from .attention import attention_core
    from ..ops import matmul_precision
    prec = matmul_precision()
    b, t, d = x.shape
    h = block.n_heads // tp
    kv = getattr(block, "n_kv_heads", block.n_heads) // tp
    hd = d // block.n_heads

    a_in = block_norm(jnp, block, p, x, "ln1")
    q = jnp.dot(a_in, p["wq"], precision=prec).reshape(b, t, h, hd)
    k = jnp.dot(a_in, p["wk"], precision=prec).reshape(b, t, kv, hd)
    v = jnp.dot(a_in, p["wv"], precision=prec).reshape(b, t, kv, hd)
    if block.rope:
        base = getattr(block, 'rope_base', 10000.0)
        q, k = _rope(jnp, q, base), _rope(jnp, k, base)
    # the cache stores the UNREPEATED kv heads — with GQA it is
    # n_heads/n_kv_heads times smaller than an MHA cache
    cache_k = cache_k.at[:, :t].set(k)
    cache_v = cache_v.at[:, :t].set(v)
    o = attention_core(q, k, v, causal=True, mesh=None, n_heads=h,
                       window=getattr(block, "window", None)
                       ).reshape(b, t, h * hd)
    proj = jnp.dot(o, p["wo"], precision=prec)
    if tp_axis is not None:
        import jax
        proj = jax.lax.psum(proj, tp_axis)
    x = x + proj
    f_in = block_norm(jnp, block, p, x, "ln2")
    return x + block_ffn(jnp, block, p, f_in, prec, tp_axis=tp_axis), \
        cache_k, cache_v


def _block_step(block, p, x_t, cache_k, cache_v, pos, tp=1,
                tp_axis=None):
    """One-token pass: x_t (B, 1, D), caches (B, T_max, H, Dh), pos =
    tokens already cached. Attention reads the cache rows <= pos.
    ``tp``/``tp_axis``: head-sharded weights + ``kv/tp``-head caches
    inside a shard_map, exactly as :func:`_block_prefill`."""
    import jax.numpy as jnp
    from ..ops import matmul_precision
    prec = matmul_precision()
    b, _, d = x_t.shape
    h = block.n_heads // tp
    kv = getattr(block, "n_kv_heads", block.n_heads) // tp
    g = h // kv
    hd = d // block.n_heads

    a_in = block_norm(jnp, block, p, x_t, "ln1")
    q = jnp.dot(a_in, p["wq"], precision=prec).reshape(b, 1, h, hd)
    k = jnp.dot(a_in, p["wk"], precision=prec).reshape(b, 1, kv, hd)
    v = jnp.dot(a_in, p["wv"], precision=prec).reshape(b, 1, kv, hd)
    if block.rope:
        base = getattr(block, 'rope_base', 10000.0)
        q, k = _rope_at(jnp, q, pos, base), _rope_at(jnp, k, pos, base)
    cache_k = jnp.asarray(cache_k).at[:, pos].set(k[:, 0])
    cache_v = jnp.asarray(cache_v).at[:, pos].set(v[:, 0])
    t_max = cache_k.shape[1]
    # single-row attention over the cache; scores/softmax in f32 like
    # attention_reference so the step matches the full-window forward.
    # GQA reads the unrepeated cache through a (kv, group) view of the
    # query heads — no (B, T, H, Dh) materialization.
    q5 = q.reshape(b, 1, kv, g, hd).astype(jnp.float32)
    s = jnp.einsum("bqkgd,btkd->bkgqt", q5,
                   cache_k.astype(jnp.float32)) / numpy.sqrt(hd)
    valid = jnp.arange(t_max) <= pos
    win = getattr(block, "window", None)
    if win:
        # sliding window: only the last `win` cached rows are visible
        valid = valid & (jnp.arange(t_max) > pos - win)
    valid = valid[None, None, None, None, :]
    s = jnp.where(valid, s, -1e30)
    w = jnp.exp(s - s.max(axis=-1, keepdims=True))
    w = w / w.sum(axis=-1, keepdims=True)
    o = jnp.einsum("bkgqt,btkd->bqkgd", w,
                   cache_v.astype(jnp.float32)).astype(x_t.dtype)
    o = o.reshape(b, 1, h * hd)
    proj = jnp.dot(o, p["wo"], precision=prec)
    if tp_axis is not None:
        import jax
        proj = jax.lax.psum(proj, tp_axis)
    x_t = x_t + proj
    f_in = block_norm(jnp, block, p, x_t, "ln2")
    return x_t + block_ffn(jnp, block, p, f_in, prec,
                           tp_axis=tp_axis), \
        cache_k, cache_v


def _embed_ids(stem, params, ids, tp=1, tp_axis=None):
    """Embedding-table gather for int token ids of ANY shape —
    ``mode="clip"`` semantics. Under ``tp_axis`` the table is a
    vocab-row shard: ids are clipped against the GLOBAL vocab, rows
    this shard owns gather locally, foreign rows contribute EXACT
    zeros, and the psum rebuilds the full embedding bit-exactly (a
    sum of one real row and N-1 exact zeros is the row)."""
    import jax.numpy as jnp
    table = params[stem.name]["table"]
    ids = ids.astype(jnp.int32)
    if tp_axis is None:
        return jnp.take(table, ids, axis=0, mode="clip")
    import jax
    vloc = table.shape[0]
    gids = jnp.clip(ids, 0, vloc * tp - 1)
    local = gids - jax.lax.axis_index(tp_axis) * vloc
    own = (local >= 0) & (local < vloc)
    x = jnp.where(own[..., None],
                  jnp.take(table, jnp.clip(local, 0, vloc - 1),
                           axis=0), 0)
    return jax.lax.psum(x, tp_axis)


def _embed_prompt(stem, pos_emb, params, ids, pos0=0, tp=1,
                  tp_axis=None):
    """(B, T) token ids → (B, T, D): embedding-table gather plus the
    positional rows ``pos0..pos0+T`` — THE stack entry every prompt
    consumer shares (the sampler, the serving engine's bucketed
    prefill, :func:`prompt_logits`). One definition, so a change to
    how the stack enters (a new pos-emb variant, a promotion tweak)
    cannot drift between the serving programs and the float reference
    the quantization gate measures against. ``tp``/``tp_axis``: the
    vocab-row-sharded gather of :func:`_embed_ids`; the positional
    table stays replicated."""
    import jax.numpy as jnp
    x = _embed_ids(stem, params, ids, tp=tp, tp_axis=tp_axis)
    if pos_emb is not None:
        idx = pos0 + jnp.arange(ids.shape[-1])
        x = x + jnp.take(params[pos_emb.name]["table"], idx,
                         axis=0, mode="clip")[None]
    return x


def _prefill_blocks(blocks, params, x, cache_len, dim, tp=1,
                    tp_axis=None):
    """Run every transformer block's ``_block_prefill`` over fresh
    zero K/V caches of ``cache_len`` rows → (x, [(ck, cv), ...]) —
    the shared prompt forward. Each block shapes its OWN cache (the
    layers config allows heterogeneous n_heads; with GQA the cache
    holds the unrepeated n_kv_heads rows; under ``tp`` each shard
    caches its own ``n_kv_heads/tp`` slice)."""
    import jax.numpy as jnp
    b = x.shape[0]
    caches = []
    for blk in blocks:
        bkv = getattr(blk, "n_kv_heads", blk.n_heads) // tp
        hd = dim // blk.n_heads
        ck = jnp.zeros((b, cache_len, bkv, hd), x.dtype)
        cv = jnp.zeros((b, cache_len, bkv, hd), x.dtype)
        x, ck, cv = _block_prefill(blk, params[blk.name], x, ck, cv,
                                   tp=tp, tp_axis=tp_axis)
        caches.append((ck, cv))
    return x, caches


def _head_logits(head, params, x_last, prec, tp_axis=None):
    """Vocabulary head projection, shared by the same three consumers
    as :func:`_embed_prompt`. Under ``tp_axis`` weights/bias are
    vocab-column shards: each shard computes its own logit columns
    (bit-exact — every column is one full-depth dot), and a tiled
    all_gather rebuilds the full replicated (…, V) row so sampling
    runs identically on every shard."""
    import jax.numpy as jnp
    out = (jnp.dot(x_last, params[head.name]["weights"],
                   precision=prec) + params[head.name]["bias"])
    if tp_axis is not None:
        import jax
        out = jax.lax.all_gather(out, tp_axis, axis=out.ndim - 1,
                                 tiled=True)
    return out


def _build_sampler(wf, t_p, n_new, temperature):
    """Compile-once generation program for one (prompt length, n_new,
    temperature) shape; params are ARGUMENTS (not baked constants), so
    repeated calls — and continued training between them — reuse the
    executable."""
    import jax
    import jax.numpy as jnp
    from ..ops import matmul_precision
    stack = split_stack(list(wf.forwards))
    stem, pos_emb = stack["stem"], stack["pos_emb"]
    blocks, head = stack["blocks"], stack["head"]
    t_max = t_p + int(n_new)
    d = stem.dim
    prec = matmul_precision()
    if pos_emb is not None:
        table_len = pos_emb.param_arrays()["table"].shape[0]
        if t_max > table_len:
            raise VelesError(
                "generation to %d positions exceeds the trained "
                "PositionalEmbedding table (%d rows); the real forward "
                "would fail too — use RoPE blocks for open-ended "
                "generation" % (t_max, table_len))
    greedy = temperature <= 0

    def embed(params, ids, pos0):
        return _embed_prompt(stem, pos_emb, params, ids, pos0)

    def sample(logits, keys):
        """``logits`` (B, V), ``keys`` (B, 2): every row draws from its
        OWN key, so a row's token depends only on (its seed, its
        prompt) — never on batch size or on which strangers share the
        dispatch. This is what lets the serving planes coalesce
        ``mode=sample`` requests without breaking the same-request →
        same-tokens contract (for B=1 the bits match the old
        single-key path exactly: categorical noise of shape (1, V) and
        (V,) draw the same stream)."""
        if greedy:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.vmap(
            lambda k, row: jax.random.categorical(k, row / temperature)
        )(keys, logits).astype(jnp.int32)

    def head_logits(params, x_last):
        return _head_logits(head, params, x_last, prec)

    @_count_decode_dispatches
    @jax.jit
    def run(params, prompt_ids, keys):
        x = embed(params, prompt_ids, 0)       # (B, T_p, D)
        x, caches = _prefill_blocks(blocks, params, x, t_max, d)
        # keys (B, 2): one independent stream per row (see sample)
        keys, subs = _split_rows(keys)
        first = sample(head_logits(params, x[:, -1]), subs)   # (B,)

        def step(carry, i):
            tok, caches, keys = carry
            pos = t_p + i
            x_t = embed(params, tok[:, None], pos)   # (B, 1, D)
            new_caches = []
            for blk, (ck, cv) in zip(blocks, caches):
                x_t, ck, cv = _block_step(blk, params[blk.name], x_t,
                                          ck, cv, pos)
                new_caches.append((ck, cv))
            keys, subs = _split_rows(keys)
            nxt = sample(head_logits(params, x_t[:, 0]), subs)
            return (nxt, tuple(new_caches), keys), tok

        (_, _, _), toks = jax.lax.scan(
            step, (first, tuple(caches), keys), jnp.arange(n_new))
        return toks                                  # (n_new, B)

    return run


def prompt_logits(wf, prompt, params=None):
    """Last-position logits for ``prompt`` through the cached-decode
    prefill path (``_block_prefill`` + head) — the float reference the
    quantization bench measures its max-logit-delta against. ``params``
    overrides the workflow's own tree (pass a
    dequantize(quantize(...)) twin to measure pure quantization
    error). Eager, host-sized: a measurement helper, not a serving
    path."""
    import jax.numpy as jnp
    from ..ops import matmul_precision
    stack = split_stack(list(wf.forwards))
    stem, pos_emb = stack["stem"], stack["pos_emb"]
    blocks, head = stack["blocks"], stack["head"]
    prec = matmul_precision()
    if params is None:
        params = params_of(wf)
    ids = jnp.asarray(numpy.asarray(prompt, numpy.int32))[None]
    x = _embed_prompt(stem, pos_emb, params, ids)
    x, _ = _prefill_blocks(blocks, params, x, ids.shape[-1], stem.dim)
    return numpy.asarray(_head_logits(head, params, x[0, -1], prec))


def _split_rows(keys):
    """Advance a batch of per-row PRNG streams one step: ``keys``
    (B, 2) → (new carries (B, 2), subkeys (B, 2)). Row r's stream is
    exactly what ``split`` would produce from that row's key alone, so
    decode outputs are invariant to batch composition."""
    import jax
    out = jax.vmap(jax.random.split)(keys)      # (B, 2, 2)
    return out[:, 0], out[:, 1]


def _row_keys(seed, batch):
    """(B, 2) per-row PRNG keys from ``seed``: an int seeds every row
    identically (same request → same tokens whatever the batch), a
    sequence of B ints gives each row its own stream. Each row's key
    is exactly ``jax.random.PRNGKey(seed_row)`` — any int a solo
    decode accepted before (negative, 64-bit) still works and maps to
    the same key."""
    import jax
    import jax.numpy as jnp
    seeds = numpy.asarray(seed)
    if seeds.ndim == 0:
        seeds = numpy.broadcast_to(seeds, (batch,))
    elif seeds.shape != (batch,):
        raise VelesError("seed must be an int or a sequence of %d ints,"
                         " got shape %s" % (batch, seeds.shape))
    return jnp.asarray(numpy.stack(
        [numpy.asarray(jax.random.PRNGKey(int(s))) for s in seeds]))


def generate(wf, prompt, n_new, temperature=1.0, seed=0):
    """Sample ``n_new`` tokens continuing ``prompt`` from a trained
    Embedding→blocks→LMHead workflow. ``prompt`` is a list of ids (→
    returns a flat token list) or a batch of B equal-length prompts (→
    returns B lists; the whole batch decodes in the same single
    dispatch). Prefill warms the caches in one full-window pass;
    generation is one ``lax.scan``. ``temperature <= 0`` = greedy.
    ``seed`` is an int (every row draws the same per-row stream — a
    request's tokens never depend on who shares the batch) or a
    sequence of B ints giving each row its own stream. Compiled
    programs cache per (batch, prompt length, n_new, temperature)."""
    import jax  # noqa: F401 — backend init before key construction
    import jax.numpy as jnp
    try:
        prompt = numpy.asarray(prompt, dtype=numpy.int32)
    except ValueError as e:
        raise VelesError(
            "batched generation needs EQUAL-length prompts (pad or "
            "group by length): %s" % e) from e
    batched = prompt.ndim == 2
    if not batched:
        prompt = prompt[None, :]
    t_p = prompt.shape[1]
    cache = getattr(wf, "_sampler_cache", None)
    if cache is None:
        cache = wf._sampler_cache = {}
    key = (prompt.shape[0], t_p, int(n_new), float(temperature))
    run = cache.get(key)
    if run is None:
        run = cache[key] = _build_sampler(wf, t_p, n_new, temperature)
    params = params_of(wf)
    from ..telemetry.counters import inc
    from ..telemetry.spans import span
    with span("decode.cached", batch=int(prompt.shape[0]),
              n_new=int(n_new)):
        # prefill + scan is ONE device program, so this whole decode
        # must cost exactly one decode dispatch (the round-5
        # regression lock). The counter rides the PROGRAM wrapper, not
        # this call site: a restructure that invokes the program per
        # token shows up as n_new dispatches, not a hand-asserted 1.
        toks = numpy.asarray(
            run(params, jnp.asarray(prompt),
                _row_keys(seed, prompt.shape[0])))
    inc("veles_decode_tokens_total", int(n_new) * int(prompt.shape[0]))
    if not batched:
        return [int(t) for t in toks[:, 0]]
    return [[int(t) for t in toks[:, i]] for i in range(toks.shape[1])]
