"""Neural-network unit library — the Znicz-equivalent layer set.

The reference's NN plugin (veles/znicz submodule, absent from the checkout;
surface reconstructed in SURVEY.md §2.8) provided forward units paired with
gradient-descent backward units, evaluators, decision logic and a
StandardWorkflow graph builder. This package re-implements that capability
TPU-first: every forward unit declares a *pure* ``apply(params, x)``
function; backward passes come from ``jax.grad`` of the composed
forward+loss instead of hand-written per-layer backward kernels, and the
whole forward/backward/update for a minibatch fuses into one jitted SPMD
step (see train_step.py).
"""

from .nn_units import ForwardBase, GradientDescentBase, MATCHING  # noqa
from .all2all import (All2All, All2AllTanh, All2AllRelu,
                      All2AllSigmoid, All2AllSoftmax)  # noqa
from .activation import (ForwardTanh, ForwardRelu, ForwardStrictRelu,
                         ForwardSigmoid, ForwardLog, ForwardMul)  # noqa
from .conv import Conv, ConvTanh, ConvRelu, ConvSigmoid  # noqa
from .pooling import MaxPooling, AvgPooling, StochasticPooling  # noqa
from .deconv import Deconv  # noqa
from .depooling import Depooling  # noqa
from .dropout import DropoutForward  # noqa
from .normalization import LRNormalizerForward  # noqa
from .evaluator import EvaluatorSoftmax, EvaluatorMSE  # noqa
from .decision import DecisionGD, DecisionMSE  # noqa
from .lr_adjust import (LearningRateAdjust, step_exp, inv,  # noqa
                        exp_decay, warmup_cosine)
from .rnn import LSTM, RNN, GDLSTM, GDRNN  # noqa
from .ssm import SSMBlock, GDSSMBlock  # noqa
from .kohonen import KohonenForward, KohonenTrainer  # noqa
from .rbm import RBM, RBMTrainer  # noqa
from .cutter import Cutter  # noqa
from .channel_split import ChannelSplitter, ChannelMerger  # noqa
from .zerofill import ZeroFiller  # noqa
from .image_saver import ImageSaver  # noqa
from .nn_plotting import Weights2D, KohonenHits  # noqa
from .attention import MultiHeadAttention, attention_core  # noqa
from .moe import MoEFFN  # noqa
from . import sampling  # noqa
from . import speculative  # noqa
from . import beam  # noqa
from .transformer import (TransformerBlock, MeanPool,  # noqa
                          PositionalEmbedding, Embedding, LMHead)
from .evaluator import EvaluatorSoftmaxSeq  # noqa
from .variants import (All2AllRProp, GDRProp,
                       ResizableAll2All)  # noqa
from .train_step import TrainStep  # noqa
from .standard_workflow import StandardWorkflow, parse_mcdnnic  # noqa
