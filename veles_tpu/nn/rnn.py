"""Recurrent units: LSTM and vanilla RNN.

Equivalent of Znicz's RNN/LSTM units ("developed for CUDA, OPENCL and
NUMPY", reference docs/source/manualrst_veles_algorithms.rst:118-143;
source absent with the submodule — SURVEY.md §2.8). TPU-first: the time
recurrence is a ``jax.lax.scan`` (single compiled loop, weights resident
in registers/VMEM across steps); the four gate matmuls are fused into one
(D+H)×4H GEMM per step so the MXU sees one large matmul instead of eight
small ones. Backward = autodiff through the scan (BPTT for free).

Sequence lengths are static per compilation; variable-length batches use
a length mask (same pattern as the loader's minibatch mask).
"""

from __future__ import annotations

from typing import Dict

import numpy

from ..config import root
from ..memory import Array
from .. import prng
from .nn_units import ForwardBase, GradientDescentBase, matches


class LSTM(ForwardBase):
    """Input (B, T, D) → output (B, H) (final hidden state) or (B, T, H)
    when return_sequences=True."""

    MAPPING = "lstm"
    PARAMETERIZED = True
    hide_from_registry = False

    def __init__(self, workflow, hidden_size=128, return_sequences=False,
                 forget_bias=1.0, **kwargs):
        super().__init__(workflow, **kwargs)
        self.hidden_size = int(hidden_size)
        self.return_sequences = return_sequences
        self.forget_bias = float(forget_bias)
        self.weights_stddev = kwargs.get("weights_stddev", None)

    def output_shape_for(self, input_shape):
        b, t, _ = input_shape
        if self.return_sequences:
            return (b, t, self.hidden_size)
        return (b, self.hidden_size)

    def create_params(self, rng: prng.RandomGenerator) -> Dict[str, Array]:
        d = self.input.shape[-1]
        h = self.hidden_size
        stddev = self.weights_stddev or (1.0 / numpy.sqrt(d + h))
        dtype = root.common.engine.precision_type
        w = numpy.zeros((d + h, 4 * h), dtype=dtype)
        prng.get(self.name).fill_normal(w, stddev)
        b = numpy.zeros((4 * h,), dtype=dtype)
        return {"weights": Array(w, name=self.name + ".weights"),
                "bias": Array(b, name=self.name + ".bias")}

    # -- recurrent protocol (shared with nn/ssm.py — the O(1)-state
    # serving lane's uniform surface: serving/recurrent.py drives any
    # unit exposing init_state/step_state/scan_state) ----------------------
    def state_shapes(self, batch: int) -> Dict[str, tuple]:
        return {"h": (batch, self.hidden_size),
                "c": (batch, self.hidden_size)}

    def init_state(self, batch: int, dtype) -> Dict:
        import jax.numpy as jnp
        return {k: jnp.zeros(shape, dtype)
                for k, shape in self.state_shapes(batch).items()}

    def step_state(self, params, x_t, state):
        (h, c), y = self._step(params, (state["h"], state["c"]), x_t)
        return y, {"h": h, "c": c}

    def scan_state(self, params, x, state, length=None):
        from .ssm import recurrent_scan
        return recurrent_scan(self, params, x, state, length)

    # gate order: i, f, g, o
    def _step(self, params, carry, x_t):
        import jax.numpy as jnp
        from ..ops import matmul_precision
        from .ssm import stable_sigmoid
        h_prev, c_prev = carry
        # the gate GEMM is written SPLIT (x@Wx + h@Wh), not as
        # dot(concat([x, h]), W): inside a lax.scan XLA rewrites the
        # concat form into the split form anyway (to hoist x@Wx out of
        # the loop), which re-associates the K-dim accumulation and
        # breaks bit-identity against the standalone step program. The
        # split form compiles to the same accumulation chains in both
        # modes — the serving lane's scan ↔ recurrence id-exactness
        # (tests/test_rnn.py) depends on this; stable_sigmoid likewise
        d = x_t.shape[-1]
        prec = matmul_precision()
        z = (jnp.dot(x_t, params["weights"][:d], precision=prec)
             + jnp.dot(h_prev, params["weights"][d:], precision=prec)
             + params["bias"])
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i = stable_sigmoid(i)
        f = stable_sigmoid(f + self.forget_bias)
        g = jnp.tanh(g)
        o = stable_sigmoid(o)
        c = f * c_prev + i * g
        h = o * jnp.tanh(c)
        return (h, c), h

    def apply(self, params, x, *, train=False, rng=None):
        import jax
        import jax.numpy as jnp
        b = x.shape[0]
        h0 = jnp.zeros((b, self.hidden_size), dtype=x.dtype)
        carry = (h0, h0)
        xs = jnp.swapaxes(x, 0, 1)              # (T, B, D) for scan

        def body(c, x_t):
            return self._step(params, c, x_t)
        (h_last, _), hs = jax.lax.scan(body, carry, xs)
        if self.return_sequences:
            return jnp.swapaxes(hs, 0, 1)       # (B, T, H)
        return h_last

    def numpy_apply(self, params, x):
        def sig(v):
            return 1.0 / (1.0 + numpy.exp(-v))
        b, t, d = x.shape
        hsz = self.hidden_size
        h = numpy.zeros((b, hsz), dtype=numpy.float32)
        c = numpy.zeros((b, hsz), dtype=numpy.float32)
        w, bias = params["weights"], params["bias"]
        hs = numpy.zeros((b, t, hsz), dtype=numpy.float32)
        for step in range(t):
            z = numpy.concatenate([x[:, step, :], h], axis=1) @ w + bias
            i, f, g, o = numpy.split(z, 4, axis=1)
            c = sig(f + self.forget_bias) * c + sig(i) * numpy.tanh(g)
            h = sig(o) * numpy.tanh(c)
            hs[:, step, :] = h
        return hs if self.return_sequences else h


class RNN(ForwardBase):
    """Vanilla tanh RNN: h_t = tanh([x_t, h_{t-1}] @ W + b)."""

    MAPPING = "rnn"
    PARAMETERIZED = True
    hide_from_registry = False

    def __init__(self, workflow, hidden_size=128, return_sequences=False,
                 **kwargs):
        super().__init__(workflow, **kwargs)
        self.hidden_size = int(hidden_size)
        self.return_sequences = return_sequences
        self.weights_stddev = kwargs.get("weights_stddev", None)

    def output_shape_for(self, input_shape):
        b, t, _ = input_shape
        if self.return_sequences:
            return (b, t, self.hidden_size)
        return (b, self.hidden_size)

    def create_params(self, rng: prng.RandomGenerator) -> Dict[str, Array]:
        d = self.input.shape[-1]
        h = self.hidden_size
        stddev = self.weights_stddev or (1.0 / numpy.sqrt(d + h))
        dtype = root.common.engine.precision_type
        w = numpy.zeros((d + h, h), dtype=dtype)
        prng.get(self.name).fill_normal(w, stddev)
        return {"weights": Array(w, name=self.name + ".weights"),
                "bias": Array(numpy.zeros((h,), dtype=dtype),
                              name=self.name + ".bias")}

    # -- recurrent protocol (see LSTM above / nn/ssm.py) ----------------------
    def state_shapes(self, batch: int) -> Dict[str, tuple]:
        return {"h": (batch, self.hidden_size)}

    def init_state(self, batch: int, dtype) -> Dict:
        import jax.numpy as jnp
        return {"h": jnp.zeros((batch, self.hidden_size), dtype)}

    def _step(self, params, h, x_t):
        import jax.numpy as jnp
        from ..ops import matmul_precision
        # split GEMM for scan ↔ step bit-identity — see LSTM._step
        d = x_t.shape[-1]
        prec = matmul_precision()
        z = (jnp.dot(x_t, params["weights"][:d], precision=prec)
             + jnp.dot(h, params["weights"][d:], precision=prec)
             + params["bias"])
        h_new = jnp.tanh(z)
        return h_new, h_new

    def step_state(self, params, x_t, state):
        h, y = self._step(params, state["h"], x_t)
        return y, {"h": h}

    def scan_state(self, params, x, state, length=None):
        from .ssm import recurrent_scan
        return recurrent_scan(self, params, x, state, length)

    def apply(self, params, x, *, train=False, rng=None):
        import jax
        import jax.numpy as jnp
        b = x.shape[0]
        h0 = jnp.zeros((b, self.hidden_size), dtype=x.dtype)
        xs = jnp.swapaxes(x, 0, 1)

        def body(h, x_t):
            return self._step(params, h, x_t)
        h_last, hs = jax.lax.scan(body, h0, xs)
        if self.return_sequences:
            return jnp.swapaxes(hs, 0, 1)
        return h_last

    def numpy_apply(self, params, x):
        b, t, d = x.shape
        h = numpy.zeros((b, self.hidden_size), dtype=numpy.float32)
        hs = numpy.zeros((b, t, self.hidden_size), dtype=numpy.float32)
        for step in range(t):
            z = numpy.concatenate([x[:, step, :], h], axis=1) @ \
                params["weights"] + params["bias"]
            h = numpy.tanh(z)
            hs[:, step, :] = h
        return hs if self.return_sequences else h


@matches(LSTM)
class GDLSTM(GradientDescentBase):
    MAPPING = "gd_lstm"


@matches(RNN)
class GDRNN(GradientDescentBase):
    MAPPING = "gd_rnn"
