"""Standalone activation units (Znicz ``activation`` module; reference
surface SURVEY.md §2.8 — layer types like "activation_tanh",
"activation_str"). Parameterless ForwardBase subclasses; in fused training
they melt into the surrounding XLA fusion for free."""

from __future__ import annotations

import numpy

from .nn_units import ForwardBase


class ActivationForward(ForwardBase):
    hide_from_registry = True

    def output_shape_for(self, input_shape):
        return input_shape


class ForwardTanh(ActivationForward):
    MAPPING = "activation_tanh"
    hide_from_registry = False

    def apply(self, params, x, *, train=False, rng=None):
        import jax.numpy as jnp
        return jnp.tanh(x)

    def numpy_apply(self, params, x):
        return numpy.tanh(x)


class ForwardRelu(ActivationForward):
    """Znicz RELU unit: y = log(1 + exp(x)) (softplus), per the reference's
    docs naming — the hard max(x,0) variant is ForwardStrictRelu."""

    MAPPING = "activation_relu"
    hide_from_registry = False

    def apply(self, params, x, *, train=False, rng=None):
        import jax
        return jax.nn.softplus(x)

    def numpy_apply(self, params, x):
        # stable softplus: max(x,0) + log1p(exp(-|x|))
        return numpy.maximum(x, 0) + numpy.log1p(numpy.exp(-numpy.abs(x)))


class ForwardStrictRelu(ActivationForward):
    MAPPING = "activation_str"
    hide_from_registry = False

    def apply(self, params, x, *, train=False, rng=None):
        import jax.numpy as jnp
        return jnp.maximum(x, 0)

    def numpy_apply(self, params, x):
        return numpy.maximum(x, 0)


class ForwardSigmoid(ActivationForward):
    MAPPING = "activation_sigmoid"
    hide_from_registry = False

    def apply(self, params, x, *, train=False, rng=None):
        import jax
        return jax.nn.sigmoid(x)

    def numpy_apply(self, params, x):
        return 1.0 / (1.0 + numpy.exp(-x))


class ForwardLog(ActivationForward):
    """y = log(x + sqrt(x^2 + 1)) (asinh), Znicz activation_log."""

    MAPPING = "activation_log"
    hide_from_registry = False

    def apply(self, params, x, *, train=False, rng=None):
        import jax.numpy as jnp
        return jnp.arcsinh(x)

    def numpy_apply(self, params, x):
        return numpy.arcsinh(x)


class ForwardMul(ActivationForward):
    """y = k * x elementwise scale (Znicz activation_mul)."""

    MAPPING = "activation_mul"
    hide_from_registry = False

    def __init__(self, workflow, factor=1.0, **kwargs):
        super().__init__(workflow, **kwargs)
        self.factor = factor

    def apply(self, params, x, *, train=False, rng=None):
        return x * self.factor

    def numpy_apply(self, params, x):
        return x * self.factor
