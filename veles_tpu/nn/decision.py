"""Decision units: epoch bookkeeping, best-model tracking, stop conditions.

Equivalent of Znicz ``decision`` (DecisionGD / DecisionMSE, SURVEY.md §2.8 +
docs/manualrst_veles_workflow_parameters.rst:143-144). Runs on the host
between jitted steps — exactly the kind of data-dependent control flow that
must live OUTSIDE the compiled step (SURVEY.md §7 "hard parts").

Contract: accumulates per-minibatch metrics pushed by the train/eval step,
and at epoch boundaries (loader.epoch_ended) computes the epoch metric per
set (TRAIN/VALIDATION/TEST), tracks the best validation result, raises
``complete`` when max_epochs is reached or no improvement for ``fail_iterations``
epochs (the reference's stop conditions).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..mutable import Bool
from ..units import Unit
from ..loader.base import TRAIN, VALID, TEST, CLASS_NAMES


class DecisionBase(Unit):
    hide_from_registry = True

    def __init__(self, workflow, max_epochs=None, fail_iterations=100,
                 **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "TRAINER"
        self.max_epochs = max_epochs
        self.fail_iterations = fail_iterations
        self.complete = Bool(False)
        self.improved = Bool(False)
        self.epoch_number = 0
        self.best_metric: Optional[float] = None
        self.best_epoch = -1
        self._epochs_since_best = 0
        self.epoch_metrics: Dict[int, List[float]] = {TRAIN: [], VALID: [],
                                                      TEST: []}
        self._accum: Dict[int, Dict[str, float]] = {
            TRAIN: {}, VALID: {}, TEST: {}}
        self.demand("loader")
        self.loader = None
        #: optional TrainStep to drain device-accumulated metrics from
        self.step_unit = None

    # -- metric accumulation (called by TrainStep/eval step) ----------------
    def accumulate(self, set_idx: int, metrics: Dict[str, float]) -> None:
        acc = self._accum[set_idx]
        for k, v in metrics.items():
            if hasattr(v, "shape") and getattr(v, "ndim", 0) > 0:
                continue  # confusion matrices handled separately
            acc[k] = acc.get(k, 0.0) + float(v)

    def epoch_metric(self, set_idx: int) -> Optional[float]:
        raise NotImplementedError

    def metric_name(self) -> str:
        raise NotImplementedError

    # -- per-epoch logic ----------------------------------------------------
    def run(self) -> None:
        loader = self.loader
        if not bool(loader.epoch_ended):
            return
        if self.step_unit is not None:
            # one entry per epoch: H entries after a fused epoch-block
            # dispatch (TrainStep.epochs_per_dispatch), one otherwise —
            # bookkeeping replays each epoch exactly as the classic loop
            any_improved = False
            for per_epoch in self.step_unit.drain_epoch_blocks():
                for set_idx, m in per_epoch.items():
                    self.accumulate(set_idx, m)
                self._finish_epoch()
                any_improved |= bool(self.improved)
                # no early break: the device weights already contain the
                # WHOLE block's training (one dispatch), so bookkeeping
                # must record every drained epoch or the trajectory
                # desyncs from the weights; `complete` latches and the
                # repeater stops at the block boundary regardless
            # the snapshot gate reads `improved` once per drain: an
            # improvement at ANY replayed epoch must open it, not just
            # one at the block's final epoch
            self.improved <<= any_improved
        else:
            self._finish_epoch()

    def _finish_epoch(self) -> None:
        self.epoch_number += 1
        line = ["epoch %d" % self.epoch_number]
        for set_idx in (TEST, VALID, TRAIN):
            m = self.epoch_metric(set_idx)
            if m is not None:
                self.epoch_metrics[set_idx].append(m)
                line.append("%s %s=%.6f" % (CLASS_NAMES[set_idx],
                                            self.metric_name(), m))
        self.info("  ".join(line))
        # best tracking on validation (falls back to train if no VALID set)
        watch = VALID if self.epoch_metrics[VALID] else TRAIN
        series = self.epoch_metrics[watch]
        self.improved <<= False
        if series:
            cur = series[-1]
            if self.best_metric is None or cur < self.best_metric:
                self.best_metric = cur
                self.best_epoch = self.epoch_number
                self._epochs_since_best = 0
                self.improved <<= True
            else:
                self._epochs_since_best += 1
        # stop conditions
        if ((self.max_epochs is not None
             and self.epoch_number >= self.max_epochs)
                or (self.fail_iterations
                    and self._epochs_since_best >= self.fail_iterations)):
            self.complete <<= True
        for acc in self._accum.values():
            acc.clear()

    # -- checkpoint protocol -------------------------------------------------
    def state_dict(self):
        return {
            "epoch_number": self.epoch_number,
            "best_metric": self.best_metric,
            "best_epoch": self.best_epoch,
            "epochs_since_best": self._epochs_since_best,
            "epoch_metrics": {k: list(v)
                              for k, v in self.epoch_metrics.items()},
            "complete": bool(self.complete),
        }

    def load_state_dict(self, sd) -> None:
        self.epoch_number = sd["epoch_number"]
        self.best_metric = sd["best_metric"]
        self.best_epoch = sd["best_epoch"]
        self._epochs_since_best = sd["epochs_since_best"]
        self.epoch_metrics = {k: list(v)
                              for k, v in sd["epoch_metrics"].items()}
        self.complete <<= sd["complete"]

    def get_metric_values(self) -> Dict[str, object]:
        return {
            "epochs": self.epoch_number,
            "best_" + self.metric_name(): self.best_metric,
            "best_epoch": self.best_epoch,
            self.metric_name() + "_history":
                {CLASS_NAMES[k]: v for k, v in self.epoch_metrics.items()
                 if v},
        }


class DecisionGD(DecisionBase):
    """Classification decision: metric = error fraction n_err/n_samples."""

    MAPPING = "decision_gd"
    hide_from_registry = False

    def metric_name(self) -> str:
        return "err"

    def epoch_metric(self, set_idx: int) -> Optional[float]:
        acc = self._accum[set_idx]
        n = acc.get("n_samples", 0)
        if not n:
            return None
        return acc.get("n_err", 0.0) / n


class DecisionMSE(DecisionBase):
    """Regression decision: metric = root mean squared error."""

    MAPPING = "decision_mse"
    hide_from_registry = False

    def metric_name(self) -> str:
        return "rmse"

    def epoch_metric(self, set_idx: int) -> Optional[float]:
        acc = self._accum[set_idx]
        n = acc.get("n_samples", 0)
        if not n:
            return None
        return (acc.get("sum_sq", 0.0) / n) ** 0.5
