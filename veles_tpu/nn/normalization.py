"""Local response normalization across channels — Znicz ``normalization``
(layer type "norm", used by AlexNet-style configs; SURVEY.md §2.8).
y = x / (beta + alpha * sum_{j in window} x_j^2)^n_exp over channel axis."""

from __future__ import annotations

import numpy

from .nn_units import ForwardBase


class LRNormalizerForward(ForwardBase):
    MAPPING = "norm"
    hide_from_registry = False

    def __init__(self, workflow, alpha=1e-4, beta=0.75, n=5, k=2.0,
                 **kwargs):
        super().__init__(workflow, **kwargs)
        self.alpha, self.beta, self.n, self.k = alpha, beta, n, k

    def output_shape_for(self, input_shape):
        return input_shape

    def _window_sumsq_np(self, x):
        c = x.shape[-1]
        half = self.n // 2
        sq = numpy.square(x.astype(numpy.float32))
        out = numpy.zeros_like(sq)
        for i in range(c):
            lo, hi = max(0, i - half), min(c, i + half + 1)
            out[..., i] = sq[..., lo:hi].sum(axis=-1)
        return out

    def apply(self, params, x, *, train=False, rng=None):
        import jax.numpy as jnp
        half = self.n // 2
        sq = jnp.square(x.astype(jnp.float32))
        c = x.shape[-1]
        pad = [(0, 0)] * (x.ndim - 1) + [(half, half)]
        sqp = jnp.pad(sq, pad)
        win = sum(sqp[..., i:i + c] for i in range(2 * half + 1))
        return (x / jnp.power(self.k + self.alpha * win,
                              self.beta)).astype(x.dtype)

    def numpy_apply(self, params, x):
        win = self._window_sumsq_np(x)
        return x / numpy.power(self.k + self.alpha * win, self.beta)
