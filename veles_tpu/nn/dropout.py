"""Dropout unit — Znicz ``dropout`` (SURVEY.md §2.8). Inverted dropout:
train-time mask scaled by 1/keep so eval is identity."""

from __future__ import annotations

import numpy

from .nn_units import ForwardBase


class DropoutForward(ForwardBase):
    MAPPING = "dropout"
    hide_from_registry = False
    NEEDS_RNG = True

    def __init__(self, workflow, dropout_ratio=0.5, **kwargs):
        super().__init__(workflow, **kwargs)
        self.dropout_ratio = float(dropout_ratio)

    def output_shape_for(self, input_shape):
        return input_shape

    def apply(self, params, x, *, train=False, rng=None):
        import jax
        if not train or rng is None or self.dropout_ratio <= 0:
            return x
        keep = 1.0 - self.dropout_ratio
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return (x * mask) / keep

    def numpy_apply(self, params, x):
        return x  # eval-mode oracle
