"""Speculative decoding — draft-model lookahead, target-model verify.

Serving-path accelerator on top of the KV-cached sampler
(nn/sampling.py): a small DRAFT model autoregressively proposes
``gamma`` tokens (cheap single-row steps), then the TARGET model scores
all of them in ONE cached multi-position forward — one big-model
dispatch per ~``gamma`` tokens instead of per token. Greedy-exact: the
emitted sequence is IDENTICAL to the target model's own greedy decode
(accept-prefix rule; the first mismatch position emits the target's
argmax instead), so speed never changes results. Beyond the reference
(whose inference story was the libVeles chain executor; SURVEY.md §2.8
names no autoregressive serving at all).

Cache discipline: rejected positions leave stale K/V rows behind; every
read masks strictly by the current position and every write overwrites
from the accepted head, so stale rows are never observed. When ALL
gamma draft tokens are accepted the round emits exactly those gamma
tokens (no bonus token): the bonus's K/V would be missing from the
draft cache and poison later reads — correctness over one extra token.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy

from ..error import VelesError
from .sampling import _block_step, split_stack
from .transformer import block_ffn, block_norm


def _rope_span(np_mod, x, pos0, base=10000.0):
    """RoPE for CONSECUTIVE positions pos0..pos0+g-1: x (B, g, H, Dh),
    pos0 traced scalar. Same half-split pairing as transformer._rope."""
    g = x.shape[1]
    hd = x.shape[-1]
    half = hd // 2
    inv = np_mod.asarray(
        (base ** (-numpy.arange(half, dtype="float32") / half)))
    pos = pos0.astype("float32") + np_mod.arange(g, dtype="float32")
    ang = pos[:, None] * inv[None, :]              # (g, half)
    cos = np_mod.cos(ang)[None, :, None, :]
    sin = np_mod.sin(ang)[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:2 * half]
    rot1 = x1 * cos - x2 * sin
    rot2 = x1 * sin + x2 * cos
    if 2 * half == hd:
        return np_mod.concatenate([rot1, rot2], axis=-1)
    return np_mod.concatenate([rot1, rot2, x[..., 2 * half:]], axis=-1)


def _block_span(block, p, x, cache_k, cache_v, pos0, tp=1,
                tp_axis=None):
    """Multi-position incremental pass: x (B, g, D) are the tokens at
    positions pos0..pos0+g-1 (traced pos0); K/V land in those cache
    rows and attention reads the cache causally by GLOBAL position —
    the g-wide generalization of sampling._block_step (g=1 reduces to
    it). ``tp``/``tp_axis``: head-sharded weights + ``kv/tp``-head
    caches inside a shard_map, same contract as ``_block_step``."""
    import jax
    import jax.numpy as jnp
    from ..ops import matmul_precision
    prec = matmul_precision()
    b, g, d = x.shape
    h = block.n_heads // tp
    kv = getattr(block, "n_kv_heads", block.n_heads) // tp
    grp = h // kv
    hd = d // block.n_heads

    a_in = block_norm(jnp, block, p, x, "ln1")
    q = jnp.dot(a_in, p["wq"], precision=prec).reshape(b, g, h, hd)
    k = jnp.dot(a_in, p["wk"], precision=prec).reshape(b, g, kv, hd)
    v = jnp.dot(a_in, p["wv"], precision=prec).reshape(b, g, kv, hd)
    if block.rope:
        base = getattr(block, "rope_base", 10000.0)
        q = _rope_span(jnp, q, pos0, base)
        k = _rope_span(jnp, k, pos0, base)
    cache_k = jax.lax.dynamic_update_slice(
        jnp.asarray(cache_k), k, (0, pos0, 0, 0))
    cache_v = jax.lax.dynamic_update_slice(
        jnp.asarray(cache_v), v, (0, pos0, 0, 0))
    t_max = cache_k.shape[1]
    q5 = q.reshape(b, g, kv, grp, hd).astype(jnp.float32)
    s = jnp.einsum("bqkgd,btkd->bkgqt", q5,
                   cache_k.astype(jnp.float32)) / numpy.sqrt(hd)
    # causal by global position: row j sees cache rows <= pos0 + j
    t_idx = jnp.arange(t_max)[None, :]
    q_idx = pos0 + jnp.arange(g)[:, None]
    valid = t_idx <= q_idx                          # (g, t_max)
    win = getattr(block, "window", None)
    if win:
        valid = valid & (t_idx > q_idx - win)
    s = jnp.where(valid[None, None, None, :, :], s, -1e30)
    w = jnp.exp(s - s.max(axis=-1, keepdims=True))
    w = w / w.sum(axis=-1, keepdims=True)
    o = jnp.einsum("bkgqt,btkd->bqkgd", w,
                   cache_v.astype(jnp.float32)).astype(x.dtype)
    o = o.reshape(b, g, h * hd)
    proj = jnp.dot(o, p["wo"], precision=prec)
    if tp_axis is not None:
        proj = jax.lax.psum(proj, tp_axis)
    x = x + proj
    f_in = block_norm(jnp, block, p, x, "ln2")
    return x + block_ffn(jnp, block, p, f_in, prec,
                         tp_axis=tp_axis), cache_k, cache_v


def _embed_at(stack, params, ids, pos0):
    """Token+positional embedding at positions pos0..pos0+g-1."""
    import jax.numpy as jnp
    stem, pos_emb = stack["stem"], stack["pos_emb"]
    x = jnp.take(params[stem.name]["table"], ids.astype(jnp.int32),
                 axis=0, mode="clip")
    if pos_emb is not None:
        idx = pos0 + jnp.arange(ids.shape[-1])
        x = x + jnp.take(params[pos_emb.name]["table"], idx, axis=0,
                         mode="clip")[None]
    return x


def _head_logits(stack, params, x):
    import jax.numpy as jnp
    from ..ops import matmul_precision
    head = stack["head"]
    return (jnp.dot(x, params[head.name]["weights"],
                    precision=matmul_precision())
            + params[head.name]["bias"])


def _prefill_batch(stack, params, prompt_ids):
    """Full-window prefill of one model's caches for a (B, T_p) prompt
    batch; returns (caches, next-token logits (B, V))."""
    import jax.numpy as jnp
    from .sampling import _block_prefill
    x = _embed_at(stack, params, prompt_ids, 0)
    caches = []
    d = stack["stem"].dim
    b, t_p = prompt_ids.shape
    for blk in stack["blocks"]:
        bkv = getattr(blk, "n_kv_heads", blk.n_heads)
        hd = d // blk.n_heads
        ck = jnp.zeros((b, stack["t_max"], bkv, hd), x.dtype)
        cv = jnp.zeros((b, stack["t_max"], bkv, hd), x.dtype)
        x, ck, cv = _block_prefill(blk, params[blk.name], x, ck, cv)
        caches.append((ck, cv))
    return tuple(caches), _head_logits(stack, params, x[:, -1])


def _prefill(stack, params, prompt_ids):
    """Single-sequence view of :func:`_prefill_batch` (row-0 logits)."""
    caches, logits = _prefill_batch(stack, params, prompt_ids)
    return caches, logits[0]


def _stochastic_accept(key, pt, pd, d_toks):
    """Rejection-sampling accept rule (Leviathan et al.): token j is
    kept with probability ``min(1, p_t/p_d)`` evaluated at the drafted
    token; the first rejected position resamples from the residual
    ``normalize(max(p_t − p_d, 0))``. Returns ``(a, fix)`` — accepted
    prefix length and the replacement token for position ``a``. Pure
    function of (key, pt (g, V), pd (g, V), d_toks (g,)) so the
    distributional guarantee is Monte-Carlo-testable in isolation: the
    marginal of the NEXT emitted token is exactly p_t."""
    import jax
    import jax.numpy as jnp
    g = d_toks.shape[0]
    ar = jnp.arange(g)
    k_u, k_r = jax.random.split(key)
    u = jax.random.uniform(k_u, (g,), jnp.float32)
    p_t_d = pt[ar, d_toks]
    p_d_d = pd[ar, d_toks]
    # u < p_t/p_d, written multiplicatively: robust when p_d == 0
    acc = u * p_d_d < p_t_d
    a = jnp.minimum(jnp.argmin(acc) + g * acc.all(), g)
    row = jnp.minimum(a, g - 1)
    resid = jnp.maximum(pt[row] - pd[row], 0.0)
    # p_t == p_d pointwise leaves an empty residual, but then the
    # accept test never fails at that row with probability 1; the
    # fallback keeps the (measure-zero) branch well-defined
    resid = jnp.where(resid.sum() > 0, resid, pt[row])
    fix = jax.random.categorical(
        k_r, jnp.log(jnp.maximum(resid, 1e-30))).astype(jnp.int32)
    return a, fix


def _spec_stacks(wf_target, wf_draft, t_p, n_new, gamma):
    """Shared stack construction + positional-table validation for the
    single-sequence and batched builders."""
    tgt = split_stack(list(wf_target.forwards))
    drf = split_stack(list(wf_draft.forwards))
    t_max = t_p + int(n_new) + int(gamma) + 1
    tgt["t_max"] = drf["t_max"] = t_max
    for st, which in ((tgt, "target"), (drf, "draft")):
        pe = st["pos_emb"]
        if pe is not None and \
                pe.param_arrays()["table"].shape[0] < t_max:
            raise VelesError(
                "%s PositionalEmbedding table (%d) is shorter than the "
                "%d positions speculation can reach"
                % (which, pe.param_arrays()["table"].shape[0], t_max))
    return tgt, drf


def _make_round_fns(tgt, drf, gamma, greedy, tau):
    """The two halves of one speculation round, shared by the
    single-sequence and batched (vmapped per row) programs. Both
    operate on batch-1 operands: the batched path lifts each row's
    caches to a singleton batch axis inside ``jax.vmap``."""
    import jax
    import jax.numpy as jnp

    def draft_propose(params_d, caches, tok, pos0, key):
        """gamma single-row draft steps: returns proposed tokens (g,),
        the draft's softmax rows (g, V) (stochastic mode), and the
        draft caches advanced over rows pos0..pos0+g-1."""
        def step(carry, j):
            tok, caches = carry[0], carry[1]
            x_t = _embed_at(drf, params_d, tok[None, None],
                            pos0 + j)[:, :1]
            new_caches = []
            for blk, (ck, cv) in zip(drf["blocks"], caches):
                x_t, ck, cv = _block_step(blk, params_d[blk.name], x_t,
                                          ck, cv, pos0 + j)
                new_caches.append((ck, cv))
            logits = _head_logits(drf, params_d, x_t[:, 0])[0] / tau
            if greedy:
                nxt = jnp.argmax(logits).astype(jnp.int32)
                probs = jnp.zeros_like(logits)
            else:
                nxt = jax.random.categorical(
                    jax.random.fold_in(key, j), logits).astype(
                        jnp.int32)
                probs = jax.nn.softmax(logits)
            return (nxt, tuple(new_caches)), (nxt, probs)

        (_, caches), (d_toks, pd) = jax.lax.scan(
            step, (tok, caches), jnp.arange(gamma))
        return d_toks, pd, caches

    def target_verify(params_t, caches, window_toks, pos0):
        """One multi-position cached forward over the gamma window;
        returns per-position logits/tau (g, V) and the advanced
        caches."""
        x = _embed_at(tgt, params_t, window_toks[None, :], pos0)
        new_caches = []
        for blk, (ck, cv) in zip(tgt["blocks"], caches):
            x, ck, cv = _block_span(blk, params_t[blk.name], x, ck, cv,
                                    pos0)
            new_caches.append((ck, cv))
        return _head_logits(tgt, params_t, x[0]) / tau, tuple(new_caches)

    ar = jnp.arange(gamma)

    def accept_emit(k_a, t_logits, pd, d_toks):
        """Accept rule + emission arithmetic for one round — the ONE
        copy both the solo and batched programs run, so their
        bit-identity (the batched CI gate) cannot drift. Returns
        ``(a, out_vec, n_emit, new_tok)``: accepted-prefix length, the
        gamma-wide emission vector (d1..d_a then the correction), how
        many tokens this round emits, and the next round's seed token.
        All-accepted rounds emit exactly the gamma draft tokens (no
        bonus — cache discipline, module docstring)."""
        if greedy:
            t_arg = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
            match = d_toks == t_arg                   # (g,)
            # a = length of the accepted prefix of draft tokens
            a = jnp.minimum(
                jnp.argmin(match) + gamma * match.all(), gamma)
            fix = t_arg[jnp.minimum(a, gamma - 1)]
        else:
            a, fix = _stochastic_accept(
                k_a, jax.nn.softmax(t_logits, axis=-1), pd, d_toks)
        out_vec = jnp.where(ar < a, d_toks,
                            jnp.where(ar == a, fix, 0))
        n_emit = jnp.minimum(a + 1, gamma)
        new_tok = jnp.where(a < gamma, fix, d_toks[gamma - 1])
        return a, out_vec, n_emit, new_tok

    return draft_propose, target_verify, accept_emit


def _build_spec_sampler(wf_target, wf_draft, t_p, n_new, gamma,
                        temperature=0.0):
    """Compile-once speculative decoder for one (prompt length, n_new,
    gamma, temperature) shape. Whole generation = ONE device program
    (while_loop over rounds); params of BOTH models are arguments.
    ``temperature <= 0``: greedy, output bit-identical to the target's
    own greedy decode. ``temperature > 0``: rejection-sampling
    speculation — every emitted token is marginally distributed as the
    target's softmax at that temperature (_stochastic_accept)."""
    import jax
    import jax.numpy as jnp
    greedy = temperature <= 0
    tau = float(temperature) if not greedy else 1.0
    tgt, drf = _spec_stacks(wf_target, wf_draft, t_p, n_new, gamma)
    n_buf = int(n_new) + int(gamma) + 1
    draft_propose, target_verify, accept_emit = _make_round_fns(
        tgt, drf, gamma, greedy, tau)

    from .sampling import _count_decode_dispatches

    @_count_decode_dispatches
    @jax.jit
    def run(params_t, params_d, prompt_ids, key):
        caches_t, first_logits = _prefill(tgt, params_t, prompt_ids)
        caches_d, _ = _prefill(drf, params_d, prompt_ids)
        key, sub = jax.random.split(key)
        if greedy:
            first = jnp.argmax(first_logits).astype(jnp.int32)
        else:
            first = jax.random.categorical(
                sub, first_logits / tau).astype(jnp.int32)
        buf = jnp.zeros((n_buf,), jnp.int32)
        buf = buf.at[0].set(first)

        def cond(carry):
            return carry[0] < n_new

        def body(carry):
            (count, pos, tok, buf, caches_t, caches_d, rounds, acc,
             key) = carry
            key, k_d, k_a = jax.random.split(key, 3)
            d_toks, pd, caches_d = draft_propose(params_d, caches_d,
                                                 tok, pos, k_d)
            window = jnp.concatenate([tok[None], d_toks[:-1]])
            t_logits, caches_t = target_verify(params_t, caches_t,
                                               window, pos)
            a, out_vec, n_emit, new_tok = accept_emit(k_a, t_logits,
                                                      pd, d_toks)
            buf = jax.lax.dynamic_update_slice(buf, out_vec, (count,))
            return (count + n_emit, pos + n_emit, new_tok, buf,
                    caches_t, caches_d, rounds + 1, acc + a, key)

        count0 = jnp.int32(1)          # `first` is already emitted
        pos0 = jnp.int32(t_p)
        carry = (count0, pos0, first, buf, caches_t, caches_d,
                 jnp.int32(0), jnp.int32(0), key)
        count, _, _, buf, _, _, rounds, acc, _ = jax.lax.while_loop(
            cond, body, carry)
        return buf[:n_new], rounds, acc

    return run


def _build_spec_sampler_batch(wf_target, wf_draft, t_p, n_new, gamma,
                              temperature=0.0):
    """Batched speculative decoder: B prompts decode concurrently with
    PER-ROW accept-length divergence — each row carries its own
    position/count/token and the round body is ``jax.vmap`` of the
    single-row round, so rows advance by their own accepted lengths
    while sharing every model dispatch. The loop runs until every row
    has its n_new tokens; finished rows keep riding the batch (uniform
    shapes) but are masked: they emit nothing, their position is
    frozen, and their spurious buffer writes land in the scratch tail
    beyond n_new (n_buf = n_new + gamma + 1 guarantees the clamped
    write start ≥ n_new). Greedy mode: every row is bit-identical to
    its own solo decode — vmap makes rows independent by construction
    (CI-asserted)."""
    import jax
    import jax.numpy as jnp
    greedy = temperature <= 0
    tau = float(temperature) if not greedy else 1.0
    tgt, drf = _spec_stacks(wf_target, wf_draft, t_p, n_new, gamma)
    n_buf = int(n_new) + int(gamma) + 1
    draft_propose, target_verify, accept_emit = _make_round_fns(
        tgt, drf, gamma, greedy, tau)

    def lift(cs):
        return tuple((ck[None], cv[None]) for ck, cv in cs)

    def unlift(cs):
        return tuple((ck[0], cv[0]) for ck, cv in cs)

    from .sampling import _count_decode_dispatches

    @_count_decode_dispatches
    @jax.jit
    def run(params_t, params_d, prompt_ids, keys):
        """prompt_ids (B, t_p); keys (B, 2) — one PRNG stream per row."""
        caches_t, first_logits = _prefill_batch(tgt, params_t,
                                                prompt_ids)
        caches_d, _ = _prefill_batch(drf, params_d, prompt_ids)
        bsz = prompt_ids.shape[0]
        if greedy:
            first = jnp.argmax(first_logits, axis=-1).astype(jnp.int32)
        else:
            def first_sample(k, logits):
                return jax.random.categorical(
                    jax.random.fold_in(k, -1),
                    logits / tau).astype(jnp.int32)
            first = jax.vmap(first_sample)(keys, first_logits)
        buf = jnp.zeros((bsz, n_buf), jnp.int32).at[:, 0].set(first)

        def row_round(count, pos, tok, buf, ct, cd, rounds, acc, key):
            key, k_d, k_a = jax.random.split(key, 3)
            d_toks, pd, cd1 = draft_propose(params_d, lift(cd), tok,
                                            pos, k_d)
            window = jnp.concatenate([tok[None], d_toks[:-1]])
            t_logits, ct1 = target_verify(params_t, lift(ct), window,
                                          pos)
            a, out_vec, n_emit, new_tok = accept_emit(k_a, t_logits,
                                                      pd, d_toks)
            # finished rows stay in the batch (uniform shapes) but are
            # masked: no emission, frozen position/token; their buffer
            # write lands in the scratch tail beyond n_new
            done = count >= n_new
            n_emit = jnp.where(done, 0, n_emit)
            new_tok = jnp.where(done, tok, new_tok)
            buf = jax.lax.dynamic_update_slice(buf, out_vec, (count,))
            return (count + n_emit, pos + n_emit, new_tok, buf,
                    unlift(ct1), unlift(cd1),
                    rounds + jnp.where(done, 0, 1),
                    acc + jnp.where(done, 0, a), key)

        def cond(carry):
            return jnp.any(carry[0] < n_new)

        def body(carry):
            return jax.vmap(row_round)(*carry)

        carry = (jnp.full((bsz,), 1, jnp.int32),
                 jnp.full((bsz,), t_p, jnp.int32),
                 first, buf, caches_t, caches_d,
                 jnp.zeros((bsz,), jnp.int32),
                 jnp.zeros((bsz,), jnp.int32), keys)
        count, _, _, buf, _, _, rounds, acc, _ = jax.lax.while_loop(
            cond, body, carry)
        return buf[:, :n_new], rounds, acc

    return run


def generate_speculative(wf_target, wf_draft, prompt, n_new,
                         gamma: int = 4, temperature: float = 0.0,
                         seed: int = 0) -> Tuple[List[int],
                                                 Dict[str, float]]:
    """Decode ``n_new`` tokens with draft-model speculation. Returns
    ``(tokens, stats)``; stats carries ``rounds`` and the mean
    ``acceptance`` per round.

    ``temperature <= 0``: greedy — tokens IDENTICAL to
    ``sampling.generate(wf_target, prompt, n_new, temperature=0)``.
    ``temperature > 0``: rejection-sampling speculation — every token
    is marginally distributed exactly as the target's softmax sample
    at that temperature (``_stochastic_accept``), regardless of draft
    quality (a bad draft only costs speed).

    ``prompt`` may be a flat id list (returns a flat token list) or a
    batch of B EQUAL-LENGTH prompts (returns B lists): rows then
    decode concurrently with per-row accept-length divergence
    (``_build_spec_sampler_batch``) — in greedy mode each row is
    bit-identical to its own solo decode. Batched stats carry per-row
    ``acceptance``/``rounds`` lists plus their means."""
    import jax
    import jax.numpy as jnp
    if int(gamma) < 1:
        raise ValueError("gamma must be >= 1")
    try:
        prompt = numpy.asarray(prompt, dtype=numpy.int32)
    except ValueError as e:
        raise VelesError(
            "batched speculation needs EQUAL-length prompts (pad or "
            "group by length): %s" % e) from e
    if prompt.ndim not in (1, 2):
        raise VelesError("prompt must be a flat id list or a (B, T_p) "
                         "batch")
    batched = prompt.ndim == 2
    t_p = prompt.shape[-1]
    bsz = prompt.shape[0] if batched else 1
    cache = getattr(wf_target, "_spec_cache", None)
    if cache is None:
        cache = wf_target._spec_cache = {}
    # the DRAFT workflow rides in the cache value and is identity-
    # compared: an id()-keyed entry would survive the draft's death and
    # misfire on address reuse with a different architecture
    key = (t_p, int(n_new), int(gamma), float(temperature),
           bsz if batched else None)
    entry = cache.get(key)
    if entry is None or entry[0] is not wf_draft:
        builder = _build_spec_sampler_batch if batched \
            else _build_spec_sampler
        entry = cache[key] = (wf_draft, builder(
            wf_target, wf_draft, t_p, int(n_new), int(gamma),
            float(temperature)))
    run = entry[1]

    from .sampling import params_of
    from ..telemetry.counters import inc
    from ..telemetry.spans import span
    if not batched:
        with span("decode.speculative", batch=1, n_new=int(n_new),
                  gamma=int(gamma)):
            # the whole speculation loop (draft proposes, target
            # verifies, lax.while on device) is ONE program — its
            # dispatch is counted by the _count_decode_dispatches
            # wrapper per invocation, so the round-5 dispatch-count
            # story is measured, not hand-derived
            toks, rounds, acc = run(
                params_of(wf_target), params_of(wf_draft),
                jnp.asarray(prompt[None, :]), jax.random.PRNGKey(seed))
        inc("veles_decode_tokens_total", int(n_new))
        rounds = max(int(rounds), 1)
        return ([int(t) for t in numpy.asarray(toks)],
                {"rounds": rounds,
                 "acceptance": float(acc) / (rounds * int(gamma))})
    keys = jax.vmap(jax.random.fold_in, (None, 0))(
        jax.random.PRNGKey(seed), jnp.arange(bsz))
    with span("decode.speculative", batch=bsz, n_new=int(n_new),
              gamma=int(gamma)):
        toks, rounds, acc = run(params_of(wf_target),
                                params_of(wf_draft),
                                jnp.asarray(prompt), keys)
    inc("veles_decode_tokens_total", int(n_new) * bsz)
    toks = numpy.asarray(toks)
    rounds = numpy.maximum(numpy.asarray(rounds), 1)
    acc = numpy.asarray(acc, dtype=numpy.float64)
    per_row_acc = (acc / (rounds * int(gamma))).tolist()
    return ([[int(t) for t in row] for row in toks],
            {"rounds": [int(r) for r in rounds],
             "acceptance": per_row_acc,
             "mean_rounds": float(numpy.mean(rounds)),
             "mean_acceptance": float(numpy.mean(per_row_acc))})
