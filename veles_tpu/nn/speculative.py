"""Speculative decoding — draft-model lookahead, target-model verify.

Serving-path accelerator on top of the KV-cached sampler
(nn/sampling.py): a small DRAFT model autoregressively proposes
``gamma`` tokens (cheap single-row steps), then the TARGET model scores
all of them in ONE cached multi-position forward — one big-model
dispatch per ~``gamma`` tokens instead of per token. Greedy-exact: the
emitted sequence is IDENTICAL to the target model's own greedy decode
(accept-prefix rule; the first mismatch position emits the target's
argmax instead), so speed never changes results. Beyond the reference
(whose inference story was the libVeles chain executor; SURVEY.md §2.8
names no autoregressive serving at all).

Cache discipline: rejected positions leave stale K/V rows behind; every
read masks strictly by the current position and every write overwrites
from the accepted head, so stale rows are never observed. When ALL
gamma draft tokens are accepted the round emits exactly those gamma
tokens (no bonus token): the bonus's K/V would be missing from the
draft cache and poison later reads — correctness over one extra token.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy

from ..error import VelesError
from .sampling import _block_step, split_stack
from .transformer import block_ffn, block_norm


def _rope_span(np_mod, x, pos0, base=10000.0):
    """RoPE for CONSECUTIVE positions pos0..pos0+g-1: x (B, g, H, Dh),
    pos0 traced scalar. Same half-split pairing as transformer._rope."""
    g = x.shape[1]
    hd = x.shape[-1]
    half = hd // 2
    inv = np_mod.asarray(
        (base ** (-numpy.arange(half, dtype="float32") / half)))
    pos = pos0.astype("float32") + np_mod.arange(g, dtype="float32")
    ang = pos[:, None] * inv[None, :]              # (g, half)
    cos = np_mod.cos(ang)[None, :, None, :]
    sin = np_mod.sin(ang)[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:2 * half]
    rot1 = x1 * cos - x2 * sin
    rot2 = x1 * sin + x2 * cos
    if 2 * half == hd:
        return np_mod.concatenate([rot1, rot2], axis=-1)
    return np_mod.concatenate([rot1, rot2, x[..., 2 * half:]], axis=-1)


def _block_span(block, p, x, cache_k, cache_v, pos0):
    """Multi-position incremental pass: x (B, g, D) are the tokens at
    positions pos0..pos0+g-1 (traced pos0); K/V land in those cache
    rows and attention reads the cache causally by GLOBAL position —
    the g-wide generalization of sampling._block_step (g=1 reduces to
    it)."""
    import jax
    import jax.numpy as jnp
    from ..ops import matmul_precision
    prec = matmul_precision()
    b, g, d = x.shape
    h = block.n_heads
    kv = getattr(block, "n_kv_heads", h)
    grp = h // kv
    hd = d // h

    a_in = block_norm(jnp, block, p, x, "ln1")
    q = jnp.dot(a_in, p["wq"], precision=prec).reshape(b, g, h, hd)
    k = jnp.dot(a_in, p["wk"], precision=prec).reshape(b, g, kv, hd)
    v = jnp.dot(a_in, p["wv"], precision=prec).reshape(b, g, kv, hd)
    if block.rope:
        base = getattr(block, "rope_base", 10000.0)
        q = _rope_span(jnp, q, pos0, base)
        k = _rope_span(jnp, k, pos0, base)
    cache_k = jax.lax.dynamic_update_slice(
        jnp.asarray(cache_k), k, (0, pos0, 0, 0))
    cache_v = jax.lax.dynamic_update_slice(
        jnp.asarray(cache_v), v, (0, pos0, 0, 0))
    t_max = cache_k.shape[1]
    q5 = q.reshape(b, g, kv, grp, hd).astype(jnp.float32)
    s = jnp.einsum("bqkgd,btkd->bkgqt", q5,
                   cache_k.astype(jnp.float32)) / numpy.sqrt(hd)
    # causal by global position: row j sees cache rows <= pos0 + j
    t_idx = jnp.arange(t_max)[None, :]
    q_idx = pos0 + jnp.arange(g)[:, None]
    valid = t_idx <= q_idx                          # (g, t_max)
    win = getattr(block, "window", None)
    if win:
        valid = valid & (t_idx > q_idx - win)
    s = jnp.where(valid[None, None, None, :, :], s, -1e30)
    w = jnp.exp(s - s.max(axis=-1, keepdims=True))
    w = w / w.sum(axis=-1, keepdims=True)
    o = jnp.einsum("bkgqt,btkd->bqkgd", w,
                   cache_v.astype(jnp.float32)).astype(x.dtype)
    o = o.reshape(b, g, d)
    x = x + jnp.dot(o, p["wo"], precision=prec)
    f_in = block_norm(jnp, block, p, x, "ln2")
    return x + block_ffn(jnp, block, p, f_in, prec), cache_k, cache_v


def _embed_at(stack, params, ids, pos0):
    """Token+positional embedding at positions pos0..pos0+g-1."""
    import jax.numpy as jnp
    stem, pos_emb = stack["stem"], stack["pos_emb"]
    x = jnp.take(params[stem.name]["table"], ids.astype(jnp.int32),
                 axis=0, mode="clip")
    if pos_emb is not None:
        idx = pos0 + jnp.arange(ids.shape[-1])
        x = x + jnp.take(params[pos_emb.name]["table"], idx, axis=0,
                         mode="clip")[None]
    return x


def _head_logits(stack, params, x):
    import jax.numpy as jnp
    from ..ops import matmul_precision
    head = stack["head"]
    return (jnp.dot(x, params[head.name]["weights"],
                    precision=matmul_precision())
            + params[head.name]["bias"])


def _prefill(stack, params, prompt_ids):
    """Full-window prefill of one model's caches; returns (caches,
    greedy next token)."""
    import jax.numpy as jnp
    from .sampling import _block_prefill
    x = _embed_at(stack, params, prompt_ids, 0)
    caches = []
    d = stack["stem"].dim
    b, t_p = prompt_ids.shape
    for blk in stack["blocks"]:
        bkv = getattr(blk, "n_kv_heads", blk.n_heads)
        hd = d // blk.n_heads
        ck = jnp.zeros((b, stack["t_max"], bkv, hd), x.dtype)
        cv = jnp.zeros((b, stack["t_max"], bkv, hd), x.dtype)
        x, ck, cv = _block_prefill(blk, params[blk.name], x, ck, cv)
        caches.append((ck, cv))
    tok = jnp.argmax(_head_logits(stack, params, x[:, -1]),
                     axis=-1).astype(jnp.int32)
    return tuple(caches), tok[0]


def _build_spec_sampler(wf_target, wf_draft, t_p, n_new, gamma):
    """Compile-once greedy speculative decoder for one (prompt length,
    n_new, gamma) shape. Whole generation = ONE device program
    (while_loop over rounds); params of BOTH models are arguments."""
    import jax
    import jax.numpy as jnp

    tgt = split_stack(list(wf_target.forwards))
    drf = split_stack(list(wf_draft.forwards))
    t_max = t_p + int(n_new) + int(gamma) + 1
    tgt["t_max"] = drf["t_max"] = t_max
    for st, which in ((tgt, "target"), (drf, "draft")):
        pe = st["pos_emb"]
        if pe is not None and \
                pe.param_arrays()["table"].shape[0] < t_max:
            raise VelesError(
                "%s PositionalEmbedding table (%d) is shorter than the "
                "%d positions speculation can reach"
                % (which, pe.param_arrays()["table"].shape[0], t_max))
    n_buf = int(n_new) + int(gamma) + 1

    def draft_propose(params_d, caches, tok, pos0):
        """gamma single-row draft steps: returns proposed tokens (g,)
        and the draft caches advanced over rows pos0..pos0+g-1."""
        def step(carry, j):
            tok, caches, = carry[0], carry[1]
            x_t = _embed_at(drf, params_d, tok[None, None],
                            pos0 + j)[:, :1]
            new_caches = []
            for blk, (ck, cv) in zip(drf["blocks"], caches):
                x_t, ck, cv = _block_step(blk, params_d[blk.name], x_t,
                                          ck, cv, pos0 + j)
                new_caches.append((ck, cv))
            nxt = jnp.argmax(_head_logits(drf, params_d, x_t[:, 0]),
                             axis=-1).astype(jnp.int32)[0]
            return (nxt, tuple(new_caches)), nxt

        (_, caches), d_toks = jax.lax.scan(
            step, (tok, caches), jnp.arange(gamma))
        return d_toks, caches

    def target_verify(params_t, caches, window_toks, pos0):
        """One multi-position cached forward over the gamma window;
        returns greedy argmax (g,) at each position and the advanced
        caches."""
        x = _embed_at(tgt, params_t, window_toks[None, :], pos0)
        new_caches = []
        for blk, (ck, cv) in zip(tgt["blocks"], caches):
            x, ck, cv = _block_span(blk, params_t[blk.name], x, ck, cv,
                                    pos0)
            new_caches.append((ck, cv))
        t_arg = jnp.argmax(_head_logits(tgt, params_t, x[0]),
                           axis=-1).astype(jnp.int32)       # (g,)
        return t_arg, tuple(new_caches)

    @jax.jit
    def run(params_t, params_d, prompt_ids):
        caches_t, first = _prefill(tgt, params_t, prompt_ids)
        caches_d, _ = _prefill(drf, params_d, prompt_ids)
        buf = jnp.zeros((n_buf,), jnp.int32)
        buf = buf.at[0].set(first)
        ar = jnp.arange(gamma)

        def cond(carry):
            return carry[0] < n_new

        def body(carry):
            count, pos, tok, buf, caches_t, caches_d, rounds, acc = carry
            d_toks, caches_d = draft_propose(params_d, caches_d, tok,
                                             pos)
            window = jnp.concatenate([tok[None], d_toks[:-1]])
            t_arg, caches_t = target_verify(params_t, caches_t, window,
                                            pos)
            match = d_toks == t_arg                       # (g,)
            # a = length of the accepted prefix of draft tokens
            a = jnp.argmin(match) + gamma * match.all()
            a = jnp.minimum(a, gamma)
            # emitted tokens: d1..d_a then (a < gamma) the target's
            # correction t_{a+1}; all-accepted rounds emit exactly the
            # gamma draft tokens (no bonus — cache discipline, above)
            out_vec = jnp.where(ar < a, d_toks,
                                jnp.where(ar == a, t_arg, 0))
            n_emit = jnp.minimum(a + 1, gamma)
            new_tok = jnp.where(a < gamma, t_arg[jnp.minimum(a,
                                                             gamma - 1)],
                                d_toks[gamma - 1])
            buf = jax.lax.dynamic_update_slice(buf, out_vec, (count,))
            return (count + n_emit, pos + n_emit, new_tok, buf,
                    caches_t, caches_d, rounds + 1, acc + a)

        count0 = jnp.int32(1)          # `first` is already emitted
        pos0 = jnp.int32(t_p)
        carry = (count0, pos0, first, buf, caches_t, caches_d,
                 jnp.int32(0), jnp.int32(0))
        count, _, _, buf, _, _, rounds, acc = jax.lax.while_loop(
            cond, body, carry)
        return buf[:n_new], rounds, acc

    return run


def generate_speculative(wf_target, wf_draft, prompt, n_new,
                         gamma: int = 4) -> Tuple[List[int],
                                                  Dict[str, float]]:
    """Greedy decode of ``n_new`` tokens with draft-model speculation.
    Returns ``(tokens, stats)`` where tokens are IDENTICAL to
    ``sampling.generate(wf_target, prompt, n_new, temperature=0)`` and
    stats carries ``rounds`` and the mean ``acceptance`` per round.

    Single-sequence only (accepted counts diverge per row; batched
    speculation needs per-row positions — out of scope)."""
    import jax.numpy as jnp
    if int(gamma) < 1:
        raise ValueError("gamma must be >= 1")
    prompt = numpy.asarray(prompt, dtype=numpy.int32)
    if prompt.ndim != 1:
        raise VelesError("speculative decoding is single-sequence; "
                         "got a batch")
    t_p = len(prompt)
    cache = getattr(wf_target, "_spec_cache", None)
    if cache is None:
        cache = wf_target._spec_cache = {}
    # the DRAFT workflow rides in the cache value and is identity-
    # compared: an id()-keyed entry would survive the draft's death and
    # misfire on address reuse with a different architecture
    key = (t_p, int(n_new), int(gamma))
    entry = cache.get(key)
    if entry is None or entry[0] is not wf_draft:
        entry = cache[key] = (wf_draft, _build_spec_sampler(
            wf_target, wf_draft, t_p, int(n_new), int(gamma)))
    run = entry[1]

    def params_of(wf):
        return {f.name: {k: v.device_view()
                         for k, v in f.param_arrays().items()}
                for f in wf.forwards if f.PARAMETERIZED}

    toks, rounds, acc = run(params_of(wf_target), params_of(wf_draft),
                            jnp.asarray(prompt[None, :]))
    rounds = max(int(rounds), 1)
    return ([int(t) for t in numpy.asarray(toks)],
            {"rounds": rounds,
             "acceptance": float(acc) / (rounds * int(gamma))})
