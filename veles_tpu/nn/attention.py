"""Multi-head attention forward unit.

New capability vs the reference (its sequence models were Znicz RNN/LSTM
only, SURVEY.md §5.7); required for long-context parity goals. The unit is
a standard ForwardBase: pure ``apply``, numpy oracle, matched GD unit.
When the attached mesh has a 'sequence' axis larger than 1, the attention
core routes through parallel.ring_attention (exact, sequence-sharded,
K/V rotating over ICI); otherwise a single fused softmax(QK^T)V.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy

from ..config import root
from ..memory import Array
from .. import prng
from .nn_units import ForwardBase, GradientDescentBase, matches


def expand_kv(np_mod, x, n_heads: int):
    """(B, T, KV, Dh) → (B, T, H, Dh): share each KV head across
    H/KV query-head groups (GQA). Expressed as broadcast+reshape, NOT
    repeat, so XLA lowers a broadcast it can fuse into the consuming
    dot on the reference path. Honest caveat for the flash path: the
    Pallas kernel takes concrete folded operands, so there (and in its
    custom-vjp residuals) the expansion IS materialized — GQA's
    training-memory saving needs a group-aware kernel, which this
    kernel does not have; the *serving* cache saving is real
    (sampling._block_step reads the unrepeated cache)."""
    if np_mod is None:
        import jax.numpy as np_mod
    b, t, kv, hd = x.shape
    g = n_heads // kv
    if g == 1:
        return x
    return np_mod.broadcast_to(
        x[:, :, :, None, :], (b, t, kv, g, hd)).reshape(
        b, t, n_heads, hd)


def attention_core(q, k, v, *, causal=False, mesh=None, n_heads=1,
                   window=None):
    """The per-shape attention chooser, shared by MultiHeadAttention and
    TransformerBlock. q: (B, T, H, Dh); k/v may carry FEWER heads (GQA
    — H must divide by their count) → (B, T, H, Dh).
    sequence-mesh → ring/Ulysses; long T on TPU → Pallas flash; else the
    fused XLA reference (crossover: engine.flash_attention_min_t,
    docs/perf.md). ``window``: sliding-window span (causal only). The
    flash path skips dead blocks (O(T·window) compute) and consumes
    GROUPED k/v natively (index-map head remapping — no expanded
    operands or residuals); the other paths expand via broadcast. The
    ring path additionally SHORTENS the rotation scan to the blocks
    the window can reach; Ulysses passes the window to its inner
    attention."""
    from ..ops import flash_attention as fa
    from ..parallel.ring_attention import (ring_attention,
                                           attention_reference)
    t, hd = q.shape[1], q.shape[-1]
    h = q.shape[2]
    if mesh is not None:
        k, v = expand_kv(None, k, h), expand_kv(None, v, h)
        scheme = root.common.engine.sequence_parallel
        n_seq = mesh.shape["sequence"]
        if scheme == "ulysses" and n_heads % n_seq == 0:
            from ..parallel.ulysses import ulysses_attention
            return ulysses_attention(q, k, v, mesh, causal=causal,
                                     window=window)
        return ring_attention(q, k, v, mesh, causal=causal,
                              window=window)
    if fa.choose_flash(t, hd):
        return fa.flash_attention(q, k, v, causal=causal,
                                  window=window)
    return attention_reference(q, expand_kv(None, k, h),
                               expand_kv(None, v, h), causal=causal,
                               window=window)


class MultiHeadAttention(ForwardBase):
    """(B, T, D) → (B, T, D); params wq/wk/wv/wo each (D, D)."""

    MAPPING = "multi_head_attention"
    PARAMETERIZED = True
    hide_from_registry = False

    def __init__(self, workflow, n_heads=4, causal=False,
                 n_kv_heads=None, **kwargs):
        super().__init__(workflow, **kwargs)
        self.n_heads = int(n_heads)
        #: grouped-query attention (n_kv_heads < n_heads): K/V heads
        #: shared across query-head groups; None = classic MHA
        self.n_kv_heads = int(n_kv_heads) if n_kv_heads else self.n_heads
        if self.n_heads % self.n_kv_heads:
            raise ValueError("n_heads %d not divisible by n_kv_heads %d"
                             % (self.n_heads, self.n_kv_heads))
        self.causal = causal
        self.mesh = None          # set at initialize from the device
        self.weights_stddev = kwargs.get("weights_stddev", None)

    PARAM_NAMES = ("wq", "wk", "wv", "wo")

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    def create_params(self, rng: prng.RandomGenerator) -> Dict[str, Array]:
        d = self.input.shape[-1]
        if d % self.n_heads:
            raise ValueError("model dim %d not divisible by %d heads" %
                             (d, self.n_heads))
        stddev = self.weights_stddev or (1.0 / numpy.sqrt(d))
        dtype = root.common.engine.precision_type
        kv_d = (d // self.n_heads) * self.n_kv_heads
        params = {}
        for k, cols in (("wq", d), ("wk", kv_d), ("wv", kv_d),
                        ("wo", d)):
            w = numpy.zeros((d, cols), dtype=dtype)
            prng.get("%s.%s" % (self.name, k)).fill_normal(w, stddev)
            params[k] = Array(w, name="%s.%s" % (self.name, k))
        return params

    def initialize(self, device=None, **kwargs):
        res = super().initialize(device=device, **kwargs)
        if res:
            return res
        mesh = getattr(device, "mesh", None)
        if mesh is not None and "sequence" in mesh.axis_names \
                and mesh.shape["sequence"] > 1:
            self.mesh = mesh
        return None

    def apply(self, params, x, *, train=False, rng=None):
        import jax.numpy as jnp
        from ..ops import matmul_precision
        prec = matmul_precision()
        b, t, d = x.shape
        h = self.n_heads
        kv = getattr(self, "n_kv_heads", h)   # absent in old snapshots
        hd = d // h
        q = jnp.dot(x, params["wq"], precision=prec).reshape(b, t, h, hd)
        k = jnp.dot(x, params["wk"],
                    precision=prec).reshape(b, t, kv, hd)
        v = jnp.dot(x, params["wv"],
                    precision=prec).reshape(b, t, kv, hd)
        o = attention_core(q, k, v, causal=self.causal, mesh=self.mesh,
                           n_heads=h)
        o = o.reshape(b, t, d)
        return jnp.dot(o, params["wo"], precision=prec)

    def numpy_apply(self, params, x):
        b, t, d = x.shape
        h = self.n_heads
        kv = getattr(self, "n_kv_heads", h)
        hd = d // h

        q = (x @ params["wq"]).reshape(b, t, h, hd)
        k = (x @ params["wk"]).reshape(b, t, kv, hd)
        v = (x @ params["wv"]).reshape(b, t, kv, hd)
        k = expand_kv(numpy, k, h)
        v = expand_kv(numpy, v, h)
        s = numpy.einsum("bqhd,bkhd->bhqk", q, k) / numpy.sqrt(hd)
        if self.causal:
            mask = numpy.tril(numpy.ones((t, t), bool))
            s = numpy.where(mask[None, None], s, -1e30)
        s = s - s.max(axis=-1, keepdims=True)
        p = numpy.exp(s)
        p /= p.sum(axis=-1, keepdims=True)
        o = numpy.einsum("bhqk,bkhd->bqhd", p, v).reshape(b, t, d)
        return (o @ params["wo"]).astype(numpy.float32)


@matches(MultiHeadAttention)
class GDMultiHeadAttention(GradientDescentBase):
    MAPPING = "gd_multi_head_attention"
