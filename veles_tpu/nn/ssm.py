"""SSD/linear-attention block with scan ↔ recurrence duality.

The O(1)-state model lane (PAPERS.md "Compiler-First State Space
Duality and Portable O(1) Autoregressive Caching for Inference"): the
same weights run as a chunked parallel scan for training/prefill and
as a constant-state per-token recurrence for decode. The duality here
is COMPILER-FIRST — there is exactly ONE per-token step body
(:meth:`SSMBlock.step_state`); "scan mode" is ``jax.lax.scan`` of that
body and "recurrent mode" is a single application of it, so the two
modes cannot drift numerically: bit-identity is structural, not a
tolerance. (A chunked-quadratic reformulation would be faster on long
prefills but is NOT bit-exact against the recurrence — this repo's
serving plane stakes id-exactness on every path, so it is deliberately
not offered.)

Per head ``h`` with head dim ``e`` the state is an ``e x e`` matrix
``S`` updated by a learned scalar decay ``a_h = sigmoid(a_log_h)``::

    S_t = a_h * S_{t-1} + k_t ⊗ v_t          # (e, e) outer product
    y_t = (q_t · S_t) / sqrt(e)              # linear-attention read
    out = (concat_h y_t * sigmoid(x_t W_g)) W_o
    x_t ← x_t + out                          # residual, shape-preserving

so a decode step touches ``heads x e x e`` state floats per slot —
O(1) in sequence length, vs the transformer's O(context) KV rows.

The uniform recurrent protocol (``init_state`` / ``step_state`` /
``scan_state``) is shared with ``nn/rnn.py``'s LSTM/RNN, which is what
lets ``serving/recurrent.py`` host either family on the same
fixed-shape slot programs.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy

from ..config import root
from ..error import VelesError
from ..memory import Array
from .. import prng
from .nn_units import ForwardBase, GradientDescentBase, matches


def stable_sigmoid(v):
    """``sigmoid`` written out as ``0.5 * (tanh(v/2) + 1)``. XLA
    expands ``lax.logistic`` differently depending on the surrounding
    fusion (observed on CPU: a sigmoid*tanh product drifts ~1 ULP
    between a ``lax.scan`` body and the identical standalone step
    program), which would break the serving lane's scan ↔ recurrence
    bit-identity. The explicit tanh form compiles to the same chain in
    every program; every recurrent-unit gate goes through here."""
    import jax.numpy as jnp
    return 0.5 * (jnp.tanh(0.5 * v) + 1.0)


def mask_keep(keep, new, old):
    """``where(keep, new, old)`` with ``keep`` broadcast over state
    leaves: a scalar applies to the whole leaf, a ``(B,)`` row mask
    broadcasts over each leaf's trailing dims. Masked-OUT positions
    keep the old state BIT-UNTOUCHED — padding a sequence can never
    perturb the carried state, which is what makes the serving lane's
    fixed-width chunk scan id-exact vs the unpadded recurrence."""
    import jax.numpy as jnp
    k = jnp.asarray(keep)
    if k.ndim:
        k = k.reshape(k.shape + (1,) * (new.ndim - k.ndim))
    return jnp.where(k, new, old)


def recurrent_scan(unit, params, x, state, length=None):
    """``jax.lax.scan`` of ``unit.step_state`` over time — THE shared
    scan-mode driver for every recurrent unit (SSMBlock, LSTM, RNN).
    ``x`` is (B, T, D); ``length`` (scalar or (B,) int) masks the
    state update for positions ``t >= length`` so fixed-shape padded
    scans carry exactly the state the unpadded sequence would.
    Returns ``(ys (B, T, H_out), final state)``."""
    import jax
    import jax.numpy as jnp
    xs = jnp.swapaxes(x, 0, 1)                  # (T, B, D)
    idx = jnp.arange(x.shape[1])

    def body(st, inp):
        x_t, t = inp
        y, st2 = unit.step_state(params, x_t, st)
        if length is not None:
            keep = t < length
            st2 = jax.tree_util.tree_map(
                lambda new, old: mask_keep(keep, new, old), st2, st)
        return st2, y

    state, ys = jax.lax.scan(body, state, (xs, idx))
    return jnp.swapaxes(ys, 0, 1), state


class SSMBlock(ForwardBase):
    """Gated linear-attention (SSD) block: input (B, T, D) → output
    (B, T, D), residual. ``n_heads`` must divide D; each head carries
    an (D/n_heads)² state matrix with its own learned scalar decay."""

    MAPPING = "ssm_block"
    PARAMETERIZED = True
    hide_from_registry = False
    PARAM_NAMES = ("wq", "wk", "wv", "wg", "wo", "a_log")
    LORA_TARGETS = ()

    def __init__(self, workflow, n_heads=4, decay_min=0.6,
                 decay_max=0.95, **kwargs):
        super().__init__(workflow, **kwargs)
        self.n_heads = int(n_heads)
        if self.n_heads < 1:
            raise VelesError("ssm_block needs n_heads >= 1")
        #: decay init range: heads start spread over [decay_min,
        #: decay_max] so short- and long-memory heads coexist at step 0
        self.decay_min = float(decay_min)
        self.decay_max = float(decay_max)
        self.weights_stddev = kwargs.get("weights_stddev", None)

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    # -- params ---------------------------------------------------------------
    def create_params(self, rng: prng.RandomGenerator) -> Dict[str, Array]:
        d = int(self.input.shape[-1])
        if d % self.n_heads:
            raise VelesError(
                "ssm_block dim %d not divisible by n_heads %d"
                % (d, self.n_heads))
        self.dim = d
        dtype = root.common.engine.precision_type
        stddev = self.weights_stddev or (1.0 / numpy.sqrt(d))
        out: Dict[str, Array] = {}
        for k in ("wq", "wk", "wv", "wg", "wo"):
            w = numpy.zeros((d, d), dtype=dtype)
            prng.get("%s.%s" % (self.name, k)).fill_normal(w, stddev)
            out[k] = Array(w, name="%s.%s" % (self.name, k))
        # a_h = sigmoid(a_log_h) spread over the decay range — a
        # DETERMINISTIC init (like forget_bias): the decay spectrum is
        # a design choice, not noise
        a = numpy.linspace(self.decay_min, self.decay_max,
                           self.n_heads).astype(numpy.float64)
        a = numpy.clip(a, 1e-4, 1.0 - 1e-4)
        a_log = numpy.log(a / (1.0 - a)).astype(dtype)
        out["a_log"] = Array(a_log, name=self.name + ".a_log")
        return out

    # -- recurrent protocol ---------------------------------------------------
    def state_shapes(self, batch: int) -> Dict[str, tuple]:
        """Abstract per-batch state geometry (the serving lane's slot
        pool and the artifact signature are shaped from this)."""
        d = getattr(self, "dim", None)
        if d is None:
            arrays = self.param_arrays()
            d = (arrays["wq"].shape[0] if "wq" in arrays
                 else self.input.shape[-1])
        d = int(d)
        hd = d // self.n_heads
        return {"s": (batch, self.n_heads, hd, hd)}

    def init_state(self, batch: int, dtype) -> Dict:
        import jax.numpy as jnp
        return {k: jnp.zeros(shape, dtype)
                for k, shape in self.state_shapes(batch).items()}

    def step_state(self, params, x_t, state):
        """ONE token for every row: ``x_t`` (B, D), state ``{"s": (B,
        H, e, e)}`` → (y_t (B, D), new state). This body IS both
        modes — scan-mode prefill is ``lax.scan`` of it, recurrent-
        mode decode is a single application."""
        import jax.numpy as jnp
        from ..ops import matmul_precision
        prec = matmul_precision()
        b, d = x_t.shape
        h = self.n_heads
        hd = d // h
        q = jnp.dot(x_t, params["wq"], precision=prec).reshape(b, h, hd)
        k = jnp.dot(x_t, params["wk"], precision=prec).reshape(b, h, hd)
        v = jnp.dot(x_t, params["wv"], precision=prec).reshape(b, h, hd)
        a = stable_sigmoid(params["a_log"]).astype(x_t.dtype)   # (H,)
        s = (a[None, :, None, None] * state["s"]
             + k[..., :, None] * v[..., None, :])
        y = jnp.einsum("bhd,bhde->bhe", q, s,
                       precision=prec) * (1.0 / numpy.sqrt(hd))
        gate = stable_sigmoid(
            jnp.dot(x_t, params["wg"], precision=prec))
        out = jnp.dot(y.reshape(b, d).astype(x_t.dtype) * gate,
                      params["wo"], precision=prec)
        return x_t + out, {"s": s}

    def scan_state(self, params, x, state, length=None):
        return recurrent_scan(self, params, x, state, length)

    # -- the pure function ----------------------------------------------------
    def apply(self, params, x, *, train=False, rng=None):
        state = self.init_state(x.shape[0], x.dtype)
        ys, _ = self.scan_state(params, x, state)
        return ys

    def numpy_apply(self, params, x):
        def sig(v):
            return 1.0 / (1.0 + numpy.exp(-v))
        b, t, d = x.shape
        h = self.n_heads
        hd = d // h
        a = sig(numpy.asarray(params["a_log"],
                              numpy.float32))           # (H,)
        s = numpy.zeros((b, h, hd, hd), numpy.float32)
        ys = numpy.zeros((b, t, d), numpy.float32)
        for step in range(t):
            x_t = x[:, step, :].astype(numpy.float32)
            q = (x_t @ params["wq"]).reshape(b, h, hd)
            k = (x_t @ params["wk"]).reshape(b, h, hd)
            v = (x_t @ params["wv"]).reshape(b, h, hd)
            s = (a[None, :, None, None] * s
                 + k[..., :, None] * v[..., None, :])
            y = numpy.einsum("bhd,bhde->bhe", q, s) \
                / numpy.sqrt(hd)
            gate = sig(x_t @ params["wg"])
            ys[:, step, :] = x_t + (y.reshape(b, d) * gate) \
                @ params["wo"]
        return ys


@matches(SSMBlock)
class GDSSMBlock(GradientDescentBase):
    MAPPING = "gd_ssm_block"
