"""ImageSaver: dump interesting (usually misclassified) samples as PNGs.

Equivalent of Znicz ``image_saver`` (reference surface: SURVEY.md §2.8):
writes per-class directories of the samples the model got wrong, with the
truth/prediction encoded in the file name — the classic "show me what it
confuses" debugging loop.
"""

from __future__ import annotations

import os
import shutil
from typing import Optional

import numpy

from ..config import root
from ..units import Unit


class ImageSaver(Unit):
    """Saves up to ``limit`` wrong samples per run.

    Wire after the evaluator:
        saver = ImageSaver(wf, out_dir=...)
        saver.link_attrs(loader, ("input", "minibatch_data"),
                         ("labels", "minibatch_labels"))
        saver.link_attrs(evaluator, ("output", "output"))
    """

    MAPPING = "image_saver"
    hide_from_registry = False
    # NOT side_effect_only: run() reads the loader's per-minibatch
    # buffers (input/labels/output), which the next scheduler step
    # overwrites IN PLACE — a deferred side-plane run would pair
    # data/labels/predictions from different minibatches (or read a
    # buffer mid-overwrite). Offload-safe units must read state that
    # is stable across steps (docs/overlap.md).

    def __init__(self, workflow, out_dir: Optional[str] = None,
                 limit: int = 64, only_wrong: bool = True,
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.out_dir = out_dir or os.path.join(root.common.dirs.cache,
                                               "image_saver")
        self.limit = int(limit)
        self.only_wrong = only_wrong
        self.input = None       # minibatch data (B, ...) floats
        self.labels = None      # (B,) int truth
        self.output = None      # (B, classes) predictions
        self.saved_count = 0
        self.demand("input", "output", "labels")

    def reset_epoch(self) -> None:
        """Clear the directory + counter (link from decision on epoch end)."""
        self.saved_count = 0
        if os.path.isdir(self.out_dir):
            shutil.rmtree(self.out_dir)

    @staticmethod
    def _to_image(sample: numpy.ndarray) -> numpy.ndarray:
        img = numpy.asarray(sample, dtype=numpy.float32)
        if img.ndim == 1:           # flat: try square
            side = int(round(img.shape[0] ** 0.5))
            if side * side == img.shape[0]:
                img = img.reshape(side, side)
            else:
                img = img[None, :]
        lo, hi = float(img.min()), float(img.max())
        scaled = (img - lo) / (hi - lo) if hi > lo else img * 0
        return (scaled * 255).astype(numpy.uint8)

    def run(self) -> None:
        if self.saved_count >= self.limit:
            return
        data = self._read(self.input)
        labels = self._read(self.labels).astype(int)
        out = self._read(self.output)
        preds = (out.argmax(axis=1) if out.ndim > 1
                 else out.astype(int))
        n = min(len(data), len(labels), len(preds))
        for i in range(n):
            if self.saved_count >= self.limit:
                break
            truth, pred = int(labels[i]), int(preds[i])
            if self.only_wrong and truth == pred:
                continue
            sub = os.path.join(self.out_dir, str(truth))
            os.makedirs(sub, exist_ok=True)
            fname = "%05d_truth%d_pred%d.png" % (self.saved_count, truth,
                                                 pred)
            self._write_png(self._to_image(data[i]),
                            os.path.join(sub, fname))
            self.saved_count += 1

    @staticmethod
    def _read(arr):
        return numpy.asarray(arr.map_read() if hasattr(arr, "map_read")
                             else arr)

    @staticmethod
    def _write_png(img: numpy.ndarray, path: str) -> None:
        from PIL import Image
        Image.fromarray(img).save(path)

    def get_metric_values(self):
        return {"images_saved": self.saved_count} if self.saved_count \
            else {}
