"""TransformerBlock: attention + FFN + layernorm as ONE forward unit.

New capability vs the reference (sequence models there were Znicz
RNN/LSTM, SURVEY.md §5.7). Fusing the whole pre-LN residual block into
one shape-preserving unit is deliberate TPU-first design: a stack of
``{"type": "transformer_block", ...} * N`` layers is exactly the
"contiguous identical shape-preserving run" that TrainStep's pipeline
stage-grouper consumes (parallel/pipeline.plan_pipeline), so the same
model pipelines over ``{'pipeline': P}`` with no model changes — and
the attention core routes through the shared per-shape chooser
(flash / ring / Ulysses / fused-XLA, nn/attention.attention_core).

Block (pre-LN, GPT-style):
    h = x + W_o · attn(LN1(x))
    y = h + W2 · gelu(W1 · LN2(h))
"""

from __future__ import annotations

from typing import Dict

import numpy

from ..config import root
from ..memory import Array
from .. import prng
from .nn_units import ForwardBase, GradientDescentBase, matches
from .attention import attention_core


def _layernorm(np_mod, x, g, b, eps=1e-5):
    mu = x.mean(axis=-1, keepdims=True)
    var = ((x - mu) ** 2).mean(axis=-1, keepdims=True)
    return (x - mu) / np_mod.sqrt(var + eps) * g + b


def _gelu(np_mod, x):
    # tanh approximation — identical formula on both jnp and numpy
    c = numpy.sqrt(2.0 / numpy.pi).astype("float32")
    return 0.5 * x * (1.0 + np_mod.tanh(c * (x + 0.044715 * x ** 3)))


def _rmsnorm(np_mod, x, g, eps=1e-5):
    return x / np_mod.sqrt((x ** 2).mean(axis=-1, keepdims=True)
                           + eps) * g


def _silu(np_mod, x):
    return x / (1.0 + np_mod.exp(-x))


def block_norm(np_mod, block, p, x, which: str):
    """The block's normalization sub-layer (``which``: "ln1"/"ln2") —
    one definition shared by training (apply/numpy_apply) and the
    KV-cached sampler so the two cannot drift. norm="rms" drops the
    mean-centering and the bias (llama convention)."""
    if getattr(block, "norm", "layer") == "rms":
        return _rmsnorm(np_mod, x, p[which + "_g"])
    return _layernorm(np_mod, x, p[which + "_g"], p[which + "_b"])


def block_ffn(np_mod, block, p, x, prec=None, tp_axis=None):
    """The block's FFN sub-layer, shared the same way. ffn="swiglu":
    W2·(silu(W1 x) ⊙ W3 x), no biases (llama convention); default
    GELU: W2·gelu(W1 x + b1) + b2.

    ``tp_axis`` names a tensor-parallel mesh axis the caller is
    shard_mapped over (serving engine, ``--serve-tp``): w1/w3 are then
    column shards, b1 a hidden shard and w2 a row shard, so the
    partial W2 products psum into the full output — with b2 kept
    REPLICATED and added once AFTER the psum (a sharded b2 would be
    N-counted). ``tp_axis=None`` is bit-identical to the pre-TP
    path."""
    if np_mod is numpy:
        def dot(a, b):
            return a @ b
    else:
        def dot(a, b):
            return np_mod.dot(a, b, precision=prec)
    if getattr(block, "ffn", "gelu") == "swiglu":
        out = dot(_silu(np_mod, dot(x, p["w1"])) * dot(x, p["w3"]),
                  p["w2"])
        if tp_axis is not None:
            from jax import lax
            out = lax.psum(out, tp_axis)
        return out
    out = dot(_gelu(np_mod, dot(x, p["w1"]) + p["b1"]), p["w2"])
    if tp_axis is not None:
        from jax import lax
        out = lax.psum(out, tp_axis)
    return out + p["b2"]


def _rope(np_mod, x, base=10000.0):
    """Rotary position embedding on (B, T, H, Dh), HALF-SPLIT pairing
    (GPT-NeoX convention: feature j rotates with j+half — NOT the
    interleaved even/odd RoFormer layout; the two are not weight-
    compatible). Relative by construction, so it needs no learned table
    and no length cap; applied to the GLOBAL q/k before attention_core,
    it stays correct under every attention path (single-chip, flash,
    ring, Ulysses)."""
    t, hd = x.shape[1], x.shape[-1]
    half = hd // 2
    inv = (base ** (-numpy.arange(half, dtype="float32") / half))
    ang = np_mod.asarray(
        numpy.arange(t, dtype="float32")[:, None] * inv[None, :])
    cos, sin = np_mod.cos(ang), np_mod.sin(ang)
    cos = cos[None, :, None, :]
    sin = sin[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:2 * half]
    rot1 = x1 * cos - x2 * sin
    rot2 = x1 * sin + x2 * cos
    if 2 * half == hd:
        return np_mod.concatenate([rot1, rot2], axis=-1)
    return np_mod.concatenate([rot1, rot2, x[..., 2 * half:]], axis=-1)


class TransformerBlock(ForwardBase):
    """(B, T, D) → (B, T, D); the canonical pipelineable stage."""

    MAPPING = "transformer_block"
    PARAMETERIZED = True
    hide_from_registry = False
    PARAM_NAMES = ("wq", "wk", "wv", "wo", "w1", "b1", "w2", "b2",
                   "w3", "ln1_g", "ln1_b", "ln2_g", "ln2_b")

    def __init__(self, workflow, n_heads=4, ffn_hidden=0, causal=True,
                 rope=False, n_kv_heads=None, window=None,
                 norm="layer", ffn="gelu", rope_base=10000.0,
                 **kwargs):
        super().__init__(workflow, **kwargs)
        self.n_heads = int(n_heads)
        #: "layer" (GPT: centered, with bias) | "rms" (llama: scale
        #: only); "gelu" (W1+b1 → gelu → W2+b2) | "swiglu" (llama:
        #: W2·(silu(W1 x) ⊙ W3 x), no biases)
        if norm not in ("layer", "rms"):
            raise ValueError("norm must be 'layer' or 'rms'")
        if ffn not in ("gelu", "swiglu"):
            raise ValueError("ffn must be 'gelu' or 'swiglu'")
        self.norm = norm
        self.ffn = ffn
        #: sliding-window attention span (self + window-1 predecessors,
        #: Mistral convention); unset = full attention. Causal only.
        #: The attribute only exists when set, so full-attention
        #: exports carry no null config key.
        if window is not None:
            if int(window) < 1:
                raise ValueError("window must be a positive span, got "
                                 "%r" % (window,))
            if not causal:
                raise ValueError("window requires causal=True")
            self.window = int(window)
        #: grouped-query attention: n_kv_heads < n_heads shares each K/V
        #: head across n_heads/n_kv_heads query heads — the KV cache
        #: (and wk/wv) shrink by that factor; None = classic MHA
        self.n_kv_heads = int(n_kv_heads) if n_kv_heads else self.n_heads
        if self.n_heads % self.n_kv_heads:
            raise ValueError("n_heads %d not divisible by n_kv_heads %d"
                             % (self.n_heads, self.n_kv_heads))
        self.ffn_hidden = int(ffn_hidden)
        self.causal = causal
        #: rotary position embedding on q/k — position information with
        #: no learned table and no trained-length cap (the alternative
        #: to a pos_embedding unit ahead of the stack)
        self.rope = bool(rope)
        #: RoPE frequency base (theta); raising it stretches the
        #: positional wavelengths for longer contexts (the llama-2/3
        #: long-context lever). Only meaningful with rope=True.
        self.rope_base = float(rope_base)
        self.mesh = None
        self.weights_stddev = kwargs.get("weights_stddev", None)

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    def create_params(self, rng: prng.RandomGenerator) -> Dict[str, Array]:
        d = self.input.shape[-1]
        if d % self.n_heads:
            raise ValueError("model dim %d not divisible by %d heads"
                             % (d, self.n_heads))
        f = self.ffn_hidden or 4 * d
        stddev = self.weights_stddev or (1.0 / numpy.sqrt(d))
        dtype = root.common.engine.precision_type

        def mk(name, shape, scale):
            w = numpy.zeros(shape, dtype=dtype)
            prng.get("%s.%s" % (self.name, name)).fill_normal(w, scale)
            return Array(w, name="%s.%s" % (self.name, name))

        ones = numpy.ones((d,), dtype=dtype)
        zeros = numpy.zeros((d,), dtype=dtype)
        kv_d = (d // self.n_heads) * self.n_kv_heads
        params = {
            "wq": mk("wq", (d, d), stddev),
            "wk": mk("wk", (d, kv_d), stddev),
            "wv": mk("wv", (d, kv_d), stddev),
            "wo": mk("wo", (d, d), stddev),
            "w1": mk("w1", (d, f), stddev),
            "w2": mk("w2", (f, d), 1.0 / numpy.sqrt(f)),
            "ln1_g": Array(ones.copy(), name=self.name + ".ln1_g"),
            "ln2_g": Array(ones.copy(), name=self.name + ".ln2_g"),
        }
        if self.ffn == "swiglu":
            params["w3"] = mk("w3", (d, f), stddev)
        else:
            params["b1"] = Array(numpy.zeros((f,), dtype=dtype),
                                 name=self.name + ".b1")
            params["b2"] = Array(zeros.copy(), name=self.name + ".b2")
        if self.norm == "layer":
            params["ln1_b"] = Array(zeros.copy(),
                                    name=self.name + ".ln1_b")
            params["ln2_b"] = Array(zeros.copy(),
                                    name=self.name + ".ln2_b")
        return params

    def initialize(self, device=None, **kwargs):
        res = super().initialize(device=device, **kwargs)
        if res:
            return res
        mesh = getattr(device, "mesh", None)
        if mesh is not None and "sequence" in mesh.axis_names \
                and mesh.shape["sequence"] > 1:
            self.mesh = mesh
        return None

    def apply(self, params, x, *, train=False, rng=None):
        import jax.numpy as jnp
        from ..ops import matmul_precision
        prec = matmul_precision()
        b, t, d = x.shape
        h = self.n_heads
        kv = getattr(self, "n_kv_heads", h)   # absent in old snapshots
        hd = d // h

        a_in = block_norm(jnp, self, params, x, "ln1")
        q = jnp.dot(a_in, params["wq"],
                    precision=prec).reshape(b, t, h, hd)
        k = jnp.dot(a_in, params["wk"],
                    precision=prec).reshape(b, t, kv, hd)
        v = jnp.dot(a_in, params["wv"],
                    precision=prec).reshape(b, t, kv, hd)
        if getattr(self, "rope", False):   # absent in pre-rope exports
            base = getattr(self, 'rope_base', 10000.0)
            q, k = _rope(jnp, q, base), _rope(jnp, k, base)
        o = attention_core(q, k, v, causal=self.causal, mesh=self.mesh,
                           n_heads=h,
                           window=getattr(self, "window", None)
                           ).reshape(b, t, d)
        x = x + jnp.dot(o, params["wo"], precision=prec)
        f_in = block_norm(jnp, self, params, x, "ln2")
        return x + block_ffn(jnp, self, params, f_in, prec)

    def numpy_apply(self, params, x):
        x = numpy.asarray(x, dtype=numpy.float32)
        b, t, d = x.shape
        h = self.n_heads
        kv = getattr(self, "n_kv_heads", h)
        hd = d // h
        a_in = block_norm(numpy, self, params, x, "ln1")

        q = (a_in @ params["wq"]).reshape(b, t, h, hd)
        k = (a_in @ params["wk"]).reshape(b, t, kv, hd)
        v = (a_in @ params["wv"]).reshape(b, t, kv, hd)
        if getattr(self, "rope", False):   # absent in pre-rope exports
            base = getattr(self, 'rope_base', 10000.0)
            q, k = _rope(numpy, q, base), _rope(numpy, k, base)
        from .attention import expand_kv
        k = expand_kv(numpy, k, h)
        v = expand_kv(numpy, v, h)
        s = numpy.einsum("bqhd,bkhd->bhqk", q, k) / numpy.sqrt(hd)
        if self.causal:
            rel = numpy.arange(t)[:, None] - numpy.arange(t)[None, :]
            mask = rel >= 0
            win = getattr(self, "window", None)
            if win:
                mask = mask & (rel < win)
            s = numpy.where(mask[None, None], s, -1e30)
        s = s - s.max(axis=-1, keepdims=True)
        p = numpy.exp(s)
        p /= p.sum(axis=-1, keepdims=True)
        o = numpy.einsum("bhqk,bkhd->bqhd", p, v).reshape(b, t, d)
        x = x + o @ params["wo"]
        f_in = block_norm(numpy, self, params, x, "ln2")
        return (x + block_ffn(numpy, self, params, f_in)).astype(
            numpy.float32)


@matches(TransformerBlock)
class GDTransformerBlock(GradientDescentBase):
    MAPPING = "gd_transformer_block"
    hide_from_registry = False


class PositionalEmbedding(ForwardBase):
    """(B, T, D) → (B, T, D): adds a learned per-position table.
    Transformer blocks are permutation-equivariant; position-dependent
    tasks need this (or a rotary variant) ahead of the stack. Shape-
    preserving, so it sits in `pre` when the block run pipelines."""

    MAPPING = "pos_embedding"
    PARAMETERIZED = True
    hide_from_registry = False
    PARAM_NAMES = ("table",)

    def __init__(self, workflow, stddev=0.02, **kwargs):
        super().__init__(workflow, **kwargs)
        self.stddev = float(stddev)

    def output_shape_for(self, input_shape):
        return tuple(input_shape)

    def create_params(self, rng: prng.RandomGenerator) -> Dict[str, Array]:
        t, d = self.input.shape[1], self.input.shape[2]
        w = numpy.zeros((t, d), dtype=root.common.engine.precision_type)
        prng.get(self.name + ".table").fill_normal(w, self.stddev)
        return {"table": Array(w, name=self.name + ".table")}

    def apply(self, params, x, *, train=False, rng=None):
        return x + params["table"][None, :x.shape[1]]

    def numpy_apply(self, params, x):
        return (numpy.asarray(x, dtype=numpy.float32)
                + params["table"][None, :x.shape[1]])


@matches(PositionalEmbedding)
class GDPositionalEmbedding(GradientDescentBase):
    MAPPING = "gd_pos_embedding"
    hide_from_registry = False


class Embedding(ForwardBase):
    """(B, T) int tokens → (B, T, D) vectors: the text-model stem.
    The lookup is a device-side take, so the fused step's gradient is
    the usual scatter-add into the table (jax.grad of jnp.take)."""

    MAPPING = "embedding"
    PARAMETERIZED = True
    hide_from_registry = False
    PARAM_NAMES = ("table",)

    def __init__(self, workflow, vocab_size: int, dim: int,
                 stddev: float = 0.02, **kwargs):
        super().__init__(workflow, **kwargs)
        self.vocab_size = int(vocab_size)
        self.dim = int(dim)
        self.stddev = float(stddev)

    def output_shape_for(self, input_shape):
        return tuple(input_shape) + (self.dim,)

    def create_params(self, rng: prng.RandomGenerator) -> Dict[str, Array]:
        w = numpy.zeros((self.vocab_size, self.dim),
                        dtype=root.common.engine.precision_type)
        prng.get(self.name + ".table").fill_normal(w, self.stddev)
        return {"table": Array(w, name=self.name + ".table")}

    def apply(self, params, x, *, train=False, rng=None):
        import jax.numpy as jnp
        # mode="clip" made explicit: out-of-range ids clamp to the edge
        # rows, and ALL runtimes (oracle, C++ twin) mirror exactly that
        # — XLA cannot raise on device, so clip is the one semantic
        # every path can share
        return jnp.take(params["table"], x.astype(jnp.int32), axis=0,
                        mode="clip")

    def numpy_apply(self, params, x):
        ids = numpy.clip(numpy.asarray(x, dtype=numpy.int64), 0,
                         params["table"].shape[0] - 1)
        return params["table"][ids]


@matches(Embedding)
class GDEmbedding(GradientDescentBase):
    MAPPING = "gd_embedding"
    hide_from_registry = False


class LMHead(ForwardBase):
    """(B, T, D) → (B, T, V) per-position logits — the language-model
    output head, paired with ``loss_function="softmax_seq"`` (per-token
    cross-entropy on shifted targets)."""

    MAPPING = "lm_head"
    PARAMETERIZED = True
    hide_from_registry = False

    def __init__(self, workflow, vocab_size: int, **kwargs):
        super().__init__(workflow, **kwargs)
        self.vocab_size = int(vocab_size)
        self.weights_stddev = kwargs.get("weights_stddev", None)

    def output_shape_for(self, input_shape):
        return tuple(input_shape[:-1]) + (self.vocab_size,)

    def create_params(self, rng: prng.RandomGenerator) -> Dict[str, Array]:
        d = self.input.shape[-1]
        stddev = self.weights_stddev or (1.0 / numpy.sqrt(d))
        dtype = root.common.engine.precision_type
        w = numpy.zeros((d, self.vocab_size), dtype=dtype)
        prng.get(self.name + ".weights").fill_normal(w, stddev)
        return {"weights": Array(w, name=self.name + ".weights"),
                "bias": Array(numpy.zeros((self.vocab_size,),
                                          dtype=dtype),
                              name=self.name + ".bias")}

    def apply(self, params, x, *, train=False, rng=None):
        import jax.numpy as jnp
        from ..ops import matmul_precision
        return (jnp.dot(x, params["weights"],
                        precision=matmul_precision())
                + params["bias"])

    def numpy_apply(self, params, x):
        return (numpy.asarray(x, dtype=numpy.float32)
                @ params["weights"] + params["bias"]).astype(
            numpy.float32)


@matches(LMHead)
class GDLMHead(GradientDescentBase):
    MAPPING = "gd_lm_head"
    hide_from_registry = False


class MeanPool(ForwardBase):
    """(B, T, D) → (B, D): mean over the sequence axis (classification
    head plumbing for sequence stacks)."""

    MAPPING = "mean_pool"
    hide_from_registry = False

    def output_shape_for(self, input_shape):
        return (input_shape[0],) + tuple(input_shape[2:])

    def apply(self, params, x, *, train=False, rng=None):
        return x.mean(axis=1)

    def numpy_apply(self, params, x):
        return numpy.asarray(x, dtype=numpy.float32).mean(axis=1)
