"""All2All variants: RProp training and resizable topology.

Equivalent of Znicz ``rprop_all`` and ``resizable_all`` (reference
surface: SURVEY.md §2.8 "variants rprop_all, resizable_all"):

- ``All2AllRProp`` / ``GDRProp``: fully-connected layer trained with
  resilient backpropagation — per-weight adaptive step sizes driven by
  gradient sign agreement, not magnitude (Riedmiller & Braun '93 rule:
  grow the step ×1.2 on same sign, shrink ×0.5 on flip). The rule is a
  pure elementwise function of (grad, prev_grad, step), so it fuses into
  the train step like any optimizer.
- ``ResizableAll2All``: output width can change after initialization;
  existing rows/columns are preserved, new ones freshly initialized —
  the reference used this for grow-as-you-train experiments.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy

from ..memory import Array
from .. import prng
from .all2all import All2All
from .nn_units import GradientDescentBase, matches


class All2AllRProp(All2All):
    """Forward identical to All2All; paired with GDRProp
    (Znicz ``rprop_all``)."""

    MAPPING = "rprop_all2all"
    hide_from_registry = False


@matches(All2AllRProp)
class GDRProp(GradientDescentBase):
    """Resilient backpropagation update rule."""

    MAPPING = "gd_rprop"
    hide_from_registry = False

    ETA_PLUS = 1.2
    ETA_MINUS = 0.5
    STEP_MIN = 1e-6
    STEP_MAX = 50.0

    def __init__(self, workflow, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.initial_step = kwargs.get("initial_step", 0.01)

    def init_state(self, params: Dict[str, Any]) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp
        return {
            "step": jax.tree_util.tree_map(
                lambda p: jnp.full_like(p, self.initial_step), params),
            "prev_grad": jax.tree_util.tree_map(
                lambda p: p * 0, params),
        }

    def update(self, params: Dict[str, Any], grads: Dict[str, Any],
               state: Dict[str, Any], lr_scale: Any = 1.0
               ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        import jax.numpy as jnp
        new_params: Dict[str, Any] = {}
        new_step: Dict[str, Any] = {}
        new_prev: Dict[str, Any] = {}
        for k, p in params.items():
            g = grads[k]
            if self.weight_decay:
                g = g + self.weight_decay * p
            sign = g * state["prev_grad"][k]
            step = state["step"][k]
            step = jnp.where(sign > 0, step * self.ETA_PLUS,
                             jnp.where(sign < 0, step * self.ETA_MINUS,
                                       step))
            step = jnp.clip(step, self.STEP_MIN, self.STEP_MAX)
            # on sign flip: no move this round, forget the gradient
            move = jnp.where(sign < 0, 0.0, jnp.sign(g) * step)
            new_params[k] = p - move * lr_scale
            new_step[k] = step
            new_prev[k] = jnp.where(sign < 0, 0.0, g)
        return new_params, {"step": new_step, "prev_grad": new_prev}


class ResizableAll2All(All2All):
    """All2All whose output width can change after initialization
    (Znicz ``resizable_all``)."""

    MAPPING = "resizable_all2all"
    hide_from_registry = False

    def resize(self, new_neurons: int) -> None:
        """Grow or shrink the output dimension in place; preserved slice
        keeps its trained values, new columns are freshly initialized."""
        old = self.neurons_number
        if new_neurons == old:
            return
        self.output_sample_shape = (int(new_neurons),)
        if not self.param_arrays():
            return                      # not initialized yet: nothing to do
        w_old = numpy.asarray(self.weights.map_read())
        b_old = (numpy.asarray(self.bias.map_read())
                 if getattr(self, "bias", None) else None)
        fresh = self.create_params(prng.get(self.name + ".resize"))
        w_new = numpy.asarray(fresh["weights"].map_read())
        keep = min(old, new_neurons)
        w_new[:, :keep] = w_old[:, :keep]
        self.weights.reset(w_new)
        if b_old is not None and "bias" in fresh:
            b_new = numpy.asarray(fresh["bias"].map_read())
            b_new[:keep] = b_old[:keep]
            self.bias.reset(b_new)
        if self.input is not None and self.input:
            self.output.reset(numpy.zeros(
                self.output_shape_for(self.input.shape),
                dtype=numpy.float32))
        # any compiled apply is stale now
        self._jit_cache.clear()
        self.info("%s: resized %d → %d neurons", self.name, old,
                  new_neurons)
