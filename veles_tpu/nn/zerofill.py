"""ZeroFiller: keep a fixed sparsity mask on a forward unit's weights.

Equivalent of Znicz ``weights_zerofilling`` (reference surface: SURVEY.md
§2.8): after every update, masked weight entries are forced back to zero
— used for grouped/local connectivity experiments. When the target
participates in the fused train step the mask is *registered with the
step* and applied after every optimizer update inside the compiled scan
(so the contract holds within a multi-step dispatch, not merely at
dispatch boundaries); otherwise it is a device-side elementwise multiply
on the unit's own weight Array.
"""

from __future__ import annotations

from typing import Optional

import numpy

from ..error import VelesError
from ..memory import Array
from ..units import Unit


class ZeroFiller(Unit):
    MAPPING = "zero_filler"
    hide_from_registry = False

    def __init__(self, workflow, target=None,
                 mask: Optional[numpy.ndarray] = None,
                 grouping: int = 0, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.view_group = "WORKER"
        self.target = target
        self.mask = None if mask is None else Array(
            numpy.asarray(mask, dtype=numpy.float32),
            name=self.name + ".mask")
        self.grouping = int(grouping)
        self.demand("target")

    @staticmethod
    def grouping_mask(shape, groups: int) -> numpy.ndarray:
        """Block-diagonal mask: weights (in, out) partitioned into
        ``groups`` input/output blocks (the reference's grouped-conv-era
        pattern)."""
        mask = numpy.zeros(shape, dtype=numpy.float32)
        gi, go = shape[0] // groups, shape[1] // groups
        if gi * groups != shape[0] or go * groups != shape[1]:
            raise VelesError("shape %s not divisible into %d groups"
                             % (shape, groups))
        for g in range(groups):
            mask[g * gi:(g + 1) * gi, g * go:(g + 1) * go] = 1.0
        return mask

    def initialize(self, **kwargs):
        res = super().initialize(**kwargs)
        if res:
            return res
        weights = getattr(self.target, "weights", None)
        if not isinstance(weights, Array) or not weights:
            return True     # target not allocated yet: re-queue
        if self.mask is None:
            if not self.grouping:
                raise VelesError("%s: pass mask= or grouping=" % self.name)
            self.mask = Array(self.grouping_mask(weights.shape,
                                                 self.grouping),
                              name=self.name + ".mask")
        if tuple(self.mask.shape) != tuple(weights.shape):
            raise VelesError("%s: mask %s != weights %s" %
                             (self.name, self.mask.shape, weights.shape))
        self.run()          # enforce at init (reference zeroed on attach)
        return None

    def run(self) -> None:
        step = getattr(self.workflow, "train_step", None)
        if step is not None and getattr(step, "params", None) and \
                self.target.name in step.params:
            # enforced after EVERY update inside the fused scan; re-runs
            # with an unchanged mask are a no-op (no recompile)
            step.register_param_mask(self.target.name, "weights",
                                     self.mask.map_read())
            return
        weights = self.target.weights
        if weights.devmem is not None:
            weights.assign_devmem(
                weights.device_view() * self.mask.device_view())
        else:
            weights.reset(weights.map_read() * self.mask.map_read())
