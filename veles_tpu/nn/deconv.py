"""Deconvolution (transposed convolution) unit — Znicz ``deconv`` /
``gd_deconv`` (used by the ImagenetAE autoencoder, SURVEY.md §2.8).
TPU-native via ``jax.lax.conv_transpose`` (NHWC/HWIO)."""

from __future__ import annotations

from typing import Dict

import numpy

from ..config import root
from ..memory import Array
from .. import prng
from .nn_units import ForwardBase, GradientDescentBase, matches


class Deconv(ForwardBase):
    """Mirror of Conv: input (B, H, W, n_kernels) → (B, H', W', n_channels),
    H' = (H-1)*sy + ky - pt - pb."""

    MAPPING = "deconv"
    PARAMETERIZED = True
    hide_from_registry = False

    def __init__(self, workflow, n_channels=3, kx=3, ky=3,
                 sliding=(1, 1), padding=(0, 0, 0, 0), **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.n_channels = n_channels
        self.kx, self.ky = kx, ky
        self.sliding = tuple(sliding)
        self.padding = tuple(padding)
        self.weights_stddev = kwargs.get("weights_stddev", None)
        self.include_bias = kwargs.get("include_bias", False)

    def output_shape_for(self, input_shape):
        b, h, w, _ = input_shape
        left, top, right, bottom = self.padding
        sx, sy = self.sliding
        oh = (h - 1) * sy + self.ky - top - bottom
        ow = (w - 1) * sx + self.kx - left - right
        return (b, oh, ow, self.n_channels)

    def create_params(self, rng: prng.RandomGenerator) -> Dict[str, Array]:
        c_in = self.input.shape[-1]
        fan_in = self.kx * self.ky * c_in
        stddev = self.weights_stddev or (1.0 / numpy.sqrt(fan_in))
        dtype = root.common.engine.precision_type
        w = numpy.zeros((self.ky, self.kx, c_in, self.n_channels),
                        dtype=dtype)
        prng.get(self.name).fill_normal(w, stddev)
        params = {"weights": Array(w, name=self.name + ".weights")}
        if self.include_bias:
            params["bias"] = Array(
                numpy.zeros((self.n_channels,), dtype=dtype),
                name=self.name + ".bias")
        return params

    def apply(self, params, x, *, train=False, rng=None):
        import jax
        import jax.numpy as jnp
        from ..ops import matmul_precision
        from ..ops.precision import promote_operands
        params = self.merged_params(params)
        left, top, right, bottom = self.padding
        sx, sy = self.sliding
        # conv_transpose pads the dilated input directly; transposed-conv
        # semantics (out = (i-1)*s + k - pad) need pairs of k-1-p.
        # Kernel spatially flipped: conv_transpose cross-correlates, deconv
        # stamps. Precision (not dtype casts) steers the MXU.
        xx, ww, ct = promote_operands(x, params["weights"][::-1, ::-1])
        # lane-width channel padding (see conv.py): the deconv's
        # input-channel dim is HWIO axis 2, same as the conv's
        from .conv import _lane_pad_channels
        xx, ww = _lane_pad_channels(xx, ww, in_axis=2)
        # see Conv._conv: f32 result only for f32 operands — an f32
        # RESULT on bf16 operands breaks the transpose rule at grad time
        pref = jnp.float32 if ct == jnp.float32 else None
        y = jax.lax.conv_transpose(
            xx, ww,
            strides=(sy, sx),
            padding=((self.ky - 1 - top, self.ky - 1 - bottom),
                     (self.kx - 1 - left, self.kx - 1 - right)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            precision=matmul_precision(),
            preferred_element_type=pref)
        if "bias" in params:
            y = y + params["bias"]
        return y.astype(ct)

    def numpy_apply(self, params, x):
        """Oracle: scatter-add of kernel stamps."""
        params = self.merged_params(params)
        b, h, w, c_in = x.shape
        _, oh, ow, c_out = self.output_shape_for(x.shape)
        left, top, right, bottom = self.padding
        sx, sy = self.sliding
        full = numpy.zeros((b, oh + top + bottom, ow + left + right, c_out),
                           dtype=numpy.float32)
        wk = params["weights"].astype(numpy.float32)  # (ky,kx,cin,cout)
        for i in range(h):
            for j in range(w):
                stamp = numpy.einsum("bc,yxcd->byxd", x[:, i, j, :], wk)
                full[:, i * sy:i * sy + self.ky,
                     j * sx:j * sx + self.kx, :] += stamp
        y = full[:, top:top + oh, left:left + ow, :]
        if "bias" in params:
            y = y + params["bias"]
        return y


@matches(Deconv)
class GDDeconv(GradientDescentBase):
    MAPPING = "gd_deconv"
