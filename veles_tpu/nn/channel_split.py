"""Channel splitting / merging of NHWC minibatches.

Equivalent of Znicz ``channel_splitting`` (reference surface: SURVEY.md
§2.8). ``ChannelSplitter`` carves the channel axis into groups, exposing
``outputs[i]`` Arrays (plus ``output`` = first group so it chains like any
forward unit); ``ChannelMerger`` concatenates multiple producers' outputs
back — the device-side concat reuses the same fused path as InputJoiner.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy

from ..error import VelesError
from ..memory import Array
from .nn_units import ForwardBase


class ChannelSplitter(ForwardBase):
    """Split the trailing (channel) axis into ``groups`` equal parts or
    explicit ``sizes``."""

    MAPPING = "channel_splitter"
    hide_from_registry = False

    def __init__(self, workflow, groups: int = 0,
                 sizes: Sequence[int] = (), **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        if bool(groups) == bool(sizes):
            raise VelesError("%s: pass exactly one of groups / sizes"
                             % self.name)
        self.groups = int(groups)
        self.sizes: Tuple[int, ...] = tuple(int(s) for s in sizes)
        self.outputs: List[Array] = []

    def _resolve_sizes(self, channels: int) -> Tuple[int, ...]:
        if self.sizes:
            if sum(self.sizes) != channels:
                raise VelesError("%s: sizes %s != %d channels"
                                 % (self.name, self.sizes, channels))
            return self.sizes
        if channels % self.groups:
            raise VelesError("%s: %d channels not divisible into %d groups"
                             % (self.name, channels, self.groups))
        return (channels // self.groups,) * self.groups

    def output_shape_for(self, input_shape):
        sizes = self._resolve_sizes(input_shape[-1])
        return tuple(input_shape[:-1]) + (sizes[0],)

    def _bounds(self, channels: int) -> List[Tuple[int, int]]:
        sizes = self._resolve_sizes(channels)
        starts = numpy.cumsum((0,) + sizes[:-1])
        return [(int(s), int(s + n)) for s, n in zip(starts, sizes)]

    def apply(self, params, x, *, train=False, rng=None):
        return x[..., slice(*self._bounds(x.shape[-1])[0])]

    def numpy_apply(self, params, x):
        return numpy.ascontiguousarray(
            x[..., slice(*self._bounds(x.shape[-1])[0])])

    def initialize(self, device=None, **kwargs):
        res = super().initialize(device=device, **kwargs)
        if res:
            return res
        if self.input is not None and self.input:
            self.outputs = [
                Array(numpy.zeros(self.input.shape[:-1] + (b - a,),
                                  dtype=numpy.float32),
                      name="%s.out%d" % (self.name, i))
                for i, (a, b) in enumerate(
                    self._bounds(self.input.shape[-1]))]
            self.output = self.outputs[0]
        return None

    def xla_run(self) -> None:
        x = self.input.device_view()
        for arr, (a, b) in zip(self.outputs, self._bounds(x.shape[-1])):
            arr.assign_devmem(x[..., a:b])

    def numpy_run(self) -> None:
        x = self.input.map_read()
        for arr, (a, b) in zip(self.outputs, self._bounds(x.shape[-1])):
            arr.reset(numpy.ascontiguousarray(x[..., a:b]))


class ChannelMerger(ForwardBase):
    """Concatenate several producers' outputs along the channel axis."""

    MAPPING = "channel_merger"
    hide_from_registry = False

    def __init__(self, workflow, inputs: Sequence[Array] = (),
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.inputs: List[Array] = list(inputs)
        self._demanded.discard("input")

    def verify_demands(self):
        missing = super().verify_demands()
        if not self.inputs:
            missing.append("inputs")
        return missing

    def output_shape_for(self, input_shape=None):
        first = self.inputs[0].shape
        ch = sum(a.shape[-1] for a in self.inputs)
        return tuple(first[:-1]) + (ch,)

    def initialize(self, device=None, **kwargs):
        if not self.inputs or any(not a for a in self.inputs):
            return True
        self.input = self.inputs[0]     # satisfies the base demand
        res = super().initialize(device=device, **kwargs)
        if res:
            return res
        self.output.reset(numpy.zeros(self.output_shape_for(),
                                      dtype=numpy.float32))
        return None

    def xla_run(self) -> None:
        import jax.numpy as jnp
        self.output.assign_devmem(jnp.concatenate(
            [a.device_view() for a in self.inputs], axis=-1))

    def numpy_run(self) -> None:
        self.output.reset(numpy.concatenate(
            [a.map_read() for a in self.inputs], axis=-1))
