"""Depooling unit — Znicz ``depooling`` (autoencoder decoder side,
SURVEY.md §2.8): nearest-neighbor upsampling that inverts AvgPooling."""

from __future__ import annotations

import numpy

from .nn_units import ForwardBase


class Depooling(ForwardBase):
    MAPPING = "depooling"
    hide_from_registry = False

    def __init__(self, workflow, kx=2, ky=2, **kwargs):
        super().__init__(workflow, **kwargs)
        self.kx, self.ky = kx, ky

    def output_shape_for(self, input_shape):
        b, h, w, c = input_shape
        return (b, h * self.ky, w * self.kx, c)

    def apply(self, params, x, *, train=False, rng=None):
        import jax.numpy as jnp
        return jnp.repeat(jnp.repeat(x, self.ky, axis=1), self.kx, axis=2)

    def numpy_apply(self, params, x):
        return numpy.repeat(numpy.repeat(x, self.ky, axis=1), self.kx,
                            axis=2)
