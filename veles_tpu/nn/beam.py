"""Beam-search decoding over the KV-cached sampler machinery.

Completes the decoding family (sampling.generate: greedy/temperature;
speculative.generate_speculative: draft-accelerated) with width-W
maximum-likelihood search: W hypotheses advance in lockstep sharing a
batched KV cache; each step expands W×V continuations, keeps the top W
by total log-probability, and REORDERS the caches by surviving parent
(a batch-axis gather — the TPU-friendly formulation; no per-hypothesis
python state). Beyond the reference, whose inference story had no
autoregressive decoding at all (SURVEY.md §2.8).

``eos_id``: a finished hypothesis is frozen — its only continuation is
``eos_id`` at zero cost, so its score stays fixed while others keep
extending; ranking at the end uses an optional GNMT-style length
normalization (score / n_tokens**alpha).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy

from ..error import VelesError
from .sampling import _block_step, params_of, split_stack
from .speculative import _embed_at, _head_logits, _prefill


def _build_beam(wf, t_p, n_new, beam, eos_id):
    import jax
    import jax.numpy as jnp

    stack = split_stack(list(wf.forwards))
    # prefill fills rows 0..t_p-1 and the scan's last step embeds at
    # position t_p + n_new - 2 — rows beyond t_p + n_new - 1 would be
    # dead weight tiled across the beam AND make the positional-table
    # guard stricter than sampling.generate's for the same length
    t_max = t_p + max(int(n_new) - 1, 0)
    stack["t_max"] = max(t_max, t_p)
    pe = stack["pos_emb"]
    if pe is not None and \
            pe.param_arrays()["table"].shape[0] < stack["t_max"]:
        raise VelesError(
            "beam search to %d positions exceeds the trained "
            "PositionalEmbedding table (%d rows)"
            % (stack["t_max"], pe.param_arrays()["table"].shape[0]))
    eos = -1 if eos_id is None else int(eos_id)

    @jax.jit
    def run(params, prompt_ids):
        # prefill ONCE (batch 1), then tile the caches across the beam
        caches1, logits0 = _prefill(stack, params, prompt_ids)
        caches = tuple(
            (jnp.repeat(ck, beam, axis=0), jnp.repeat(cv, beam, axis=0))
            for ck, cv in caches1)
        logp0 = jax.nn.log_softmax(logits0.astype(jnp.float32))
        v = logp0.shape[-1]
        # first expansion from the SINGLE prefix: top-beam distinct
        # tokens (expanding identical rows would duplicate hypotheses)
        top0, tok0 = jax.lax.top_k(logp0, beam)
        scores = top0                               # (beam,)
        toks = jnp.zeros((beam, n_new), jnp.int32)
        toks = toks.at[:, 0].set(tok0)
        finished = (tok0 == eos)

        def step(carry, i):
            toks, scores, finished, caches = carry
            pos = t_p + i
            cur = toks[jnp.arange(beam), i]         # (beam,)
            x_t = _embed_at(stack, params, cur[:, None], pos)
            new_caches = []
            for blk, (ck, cv) in zip(stack["blocks"], caches):
                x_t, ck, cv = _block_step(blk, params[blk.name], x_t,
                                          ck, cv, pos)
                new_caches.append((ck, cv))
            logits = _head_logits(stack, params, x_t[:, 0])
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            # a finished hypothesis only "continues" with eos at zero
            # cost — its score freezes, everything else is impossible
            if eos >= 0:
                frozen = jnp.full((v,), -jnp.inf).at[eos].set(0.0)
                logp = jnp.where(finished[:, None], frozen[None, :],
                                 logp)
            joint = scores[:, None] + logp          # (beam, V)
            flat, idx = jax.lax.top_k(joint.reshape(-1), beam)
            parent = idx // v
            tok = (idx % v).astype(jnp.int32)
            toks = toks[parent].at[:, i + 1].set(tok)
            finished = finished[parent] | (tok == eos)
            caches = tuple((ck[parent], cv[parent])
                           for ck, cv in new_caches)
            return (toks, flat, finished, caches), None

        (toks, scores, finished, _), _ = jax.lax.scan(
            step, (toks, scores, finished, caches),
            jnp.arange(n_new - 1))
        return toks, scores, finished

    return run


def beam_generate(wf, prompt, n_new, beam: int = 4,
                  eos_id: Optional[int] = None,
                  length_penalty: float = 0.0
                  ) -> Tuple[List[int], Dict[str, object]]:
    """Width-``beam`` search for the most probable ``n_new``-token
    continuation of ``prompt``. Returns ``(best_tokens, stats)`` with
    stats carrying every hypothesis (``beams``: token lists) and its
    total log-probability (``scores``). ``beam=1`` IS greedy decoding
    (CI-asserted vs sampling.generate). ``length_penalty=a`` ranks by the
    GNMT-style normalization ``score / n_tokens**a`` (only meaningful
    with ``eos_id``, where hypothesis lengths differ)."""
    import jax.numpy as jnp
    if int(beam) < 1:
        raise ValueError("beam must be >= 1")
    # beam > V would hit jax.lax.top_k(logp0, beam) with an opaque
    # in-jit shape error; fail at the API boundary instead (ADVICE r4)
    vocab = int(split_stack(list(wf.forwards))["head"].vocab_size)
    if int(beam) > vocab:
        raise ValueError("beam=%d exceeds the head's vocab size %d"
                         % (int(beam), vocab))
    if int(n_new) < 1:
        raise ValueError("n_new must be >= 1")
    prompt = numpy.asarray(prompt, dtype=numpy.int32)
    if prompt.ndim != 1:
        raise VelesError("beam search decodes a single prompt")
    t_p = len(prompt)
    cache = getattr(wf, "_beam_cache", None)
    if cache is None:
        cache = wf._beam_cache = {}
    key = (t_p, int(n_new), int(beam),
           -1 if eos_id is None else int(eos_id))
    run = cache.get(key)
    if run is None:
        run = cache[key] = _build_beam(wf, t_p, int(n_new), int(beam),
                                       eos_id)
    toks, scores, finished = run(params_of(wf),
                                 jnp.asarray(prompt[None, :]))
    toks = numpy.asarray(toks)
    scores = numpy.asarray(scores, dtype=numpy.float64)
    lengths = numpy.full(len(scores), toks.shape[1], dtype=numpy.float64)
    if eos_id is not None:
        for bi in range(len(scores)):
            hits = numpy.where(toks[bi] == int(eos_id))[0]
            if hits.size:
                lengths[bi] = hits[0] + 1
    ranked = (scores / lengths ** float(length_penalty)
              if length_penalty else scores)
    order = numpy.argsort(-ranked)
    best = int(order[0])
    return ([int(t) for t in toks[best]],
            {"beams": [[int(t) for t in toks[i]] for i in order],
             "scores": [float(scores[i]) for i in order],
             "finished": [bool(finished[i]) for i in order]})
