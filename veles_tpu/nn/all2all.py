"""Fully-connected (All2All) forward units + matched GD units.

Equivalent of Znicz ``all`` / ``gd`` modules (layer types "all2all",
"all2all_tanh", "all2all_relu", "all2all_sigmoid", "softmax" — reference
surface: SURVEY.md §2.8, docs/source/manualrst_veles_workflow_creation.rst).

The GEMM rides the MXU: inputs flatten to (batch, features) and matmul in
the configured compute dtype (bfloat16 by default) with float32 accumulation
via ``preferred_element_type`` — the TPU-native replacement for the
reference's hand-tiled OpenCL GEMM (ocl/matrix_multiplication.cl) and its
Kahan-summation precision levels.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy

from ..config import root
from ..memory import Array
from .. import prng
from .nn_units import ForwardBase, GradientDescentBase, matches


class All2All(ForwardBase):
    """y = act(x @ W + b), weights stored (in_features, out_features)."""

    MAPPING = "all2all"
    PARAMETERIZED = True
    hide_from_registry = False

    def __init__(self, workflow, output_sample_shape=(), **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        if isinstance(output_sample_shape, int):
            output_sample_shape = (output_sample_shape,)
        self.output_sample_shape = tuple(output_sample_shape)
        self.weights_stddev = kwargs.get("weights_stddev", None)
        self.bias_stddev = kwargs.get("bias_stddev", None)
        self.include_bias = kwargs.get("include_bias", True)

    # -- shape ---------------------------------------------------------------
    @property
    def neurons_number(self) -> int:
        return int(numpy.prod(self.output_sample_shape))

    def output_shape_for(self, input_shape):
        return (input_shape[0],) + self.output_sample_shape

    def create_params(self, rng: prng.RandomGenerator) -> Dict[str, Array]:
        n_in = int(numpy.prod(self.input.shape[1:]))
        n_out = self.neurons_number
        # Znicz default init: uniform-ish scaled by 1/sqrt(fan_in)
        stddev = self.weights_stddev or (1.0 / numpy.sqrt(n_in))
        dtype = root.common.engine.precision_type
        w = numpy.zeros((n_in, n_out), dtype=dtype)
        prng.get(self.name).fill_normal(w, stddev)
        params = {"weights": Array(w, name=self.name + ".weights")}
        if self.include_bias:
            b = numpy.zeros((n_out,), dtype=dtype)
            if self.bias_stddev:
                prng.get(self.name + ".bias").fill_normal(b, self.bias_stddev)
            params["bias"] = Array(b, name=self.name + ".bias")
        return params

    # -- pure forward --------------------------------------------------------
    def _linear(self, params, x):
        import jax.numpy as jnp
        from ..ops import matmul_precision
        from ..ops.precision import promote_operands
        x2 = x.reshape(x.shape[0], -1)
        # precision (not dtype casting) steers the MXU: bf16 compute =
        # Precision.DEFAULT, keeping autodiff dtype-consistent
        xx, ww, ct = promote_operands(x2, params["weights"])
        y = jnp.dot(xx, ww, precision=matmul_precision(),
                    preferred_element_type=jnp.float32)
        if "bias" in params:
            y = y + params["bias"]
        return y.astype(ct).reshape((x.shape[0],)
                                    + self.output_sample_shape)

    def activation(self, a):
        return a

    def numpy_activation(self, a):
        return a

    def apply(self, params, x, *, train=False, rng=None):
        return self.activation(self._linear(
            self.merged_params(params), x))

    def numpy_apply(self, params, x):
        params = self.merged_params(params)
        x2 = x.reshape(len(x), -1).astype(numpy.float32)
        y = x2 @ params["weights"]
        if "bias" in params:
            y = y + params["bias"]
        return self.numpy_activation(y).reshape((len(x),)
                                                + self.output_sample_shape)


class All2AllTanh(All2All):
    """Znicz all2all_tanh: y = 1.7159 * tanh(0.6666 * a) (LeCun scaled)."""

    MAPPING = "all2all_tanh"
    A, B = 1.7159, 0.6666

    def activation(self, a):
        import jax.numpy as jnp
        return self.A * jnp.tanh(self.B * a)

    def numpy_activation(self, a):
        return self.A * numpy.tanh(self.B * a)


class All2AllRelu(All2All):
    MAPPING = "all2all_relu"

    def activation(self, a):
        import jax.numpy as jnp
        return jnp.maximum(a, 0)

    def numpy_activation(self, a):
        return numpy.maximum(a, 0)


class All2AllSigmoid(All2All):
    MAPPING = "all2all_sigmoid"

    def activation(self, a):
        import jax

        return jax.nn.sigmoid(a)

    def numpy_activation(self, a):
        return 1.0 / (1.0 + numpy.exp(-a))


class All2AllSoftmax(All2All):
    """Softmax output layer (Znicz layer type "softmax"). Emits
    ``max_idx`` like the reference for the evaluator/decision pair."""

    MAPPING = "softmax"

    def activation(self, a):
        import jax

        return jax.nn.softmax(a, axis=-1)

    def numpy_activation(self, a):
        e = numpy.exp(a - a.max(axis=-1, keepdims=True))
        return e / e.sum(axis=-1, keepdims=True)

    def logits(self, params, x):
        """Pre-softmax activations — the evaluator consumes these for a
        numerically-stable fused softmax-cross-entropy."""
        return self._linear(self.merged_params(params), x)


@matches(All2All)
class GradientDescent(GradientDescentBase):
    MAPPING = "gd"
    hide_from_registry = False


@matches(All2AllTanh)
class GDTanh(GradientDescentBase):
    MAPPING = "gd_tanh"


@matches(All2AllRelu)
class GDRelu(GradientDescentBase):
    MAPPING = "gd_relu"


@matches(All2AllSigmoid)
class GDSigmoid(GradientDescentBase):
    MAPPING = "gd_sigmoid"


@matches(All2AllSoftmax)
class GDSoftmax(GradientDescentBase):
    MAPPING = "gd_softmax"
