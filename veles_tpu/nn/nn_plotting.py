"""NN-specific plotting units.

Equivalent of Znicz ``nn_plotting_units`` (reference surface: SURVEY.md
§2.8): weight-matrix image grids and Kohonen map views, built on the
declarative snapshot plotters (veles_tpu/plotting_units.py).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy

from ..plotting_units import ImagePlotter, MatrixPlotter


class Weights2D(ImagePlotter):
    """Each output neuron's incoming weights rendered as a tile
    (Znicz ``nn_plotting_units.Weights2D``)."""

    MAPPING = "weights_2d_plotter"
    hide_from_registry = False

    def __init__(self, workflow, unit=None, param: str = "weights",
                 **kwargs) -> None:
        kwargs.setdefault("max_images", 25)
        super().__init__(workflow, **kwargs)
        self.unit = unit
        self.param = param

    def fill_snapshot(self) -> Optional[Dict[str, Any]]:
        target = self.unit
        if target is None:
            return None
        step = getattr(target.workflow, "train_step", None)
        if step is not None and getattr(step, "params", None) and \
                target.name in step.params and \
                self.param in step.params[target.name]:
            w = numpy.asarray(step.params[target.name][self.param],
                              dtype=numpy.float32)
        else:
            arr = getattr(target, self.param, None)
            if arr is None or not arr:
                return None
            w = numpy.asarray(arr.map_read(), dtype=numpy.float32)
        # (in_features, out_neurons) → one tile per neuron
        tiles = w.T[:self.max_images]
        side = int(round(tiles.shape[1] ** 0.5))
        if side * side == tiles.shape[1]:
            tiles = tiles.reshape(-1, side, side)
        else:
            tiles = tiles[:, None, :]
        return {"images": numpy.stack(
            [self.normalize(t) for t in tiles])}


class KohonenHits(MatrixPlotter):
    """Winner-count heatmap over the SOM grid
    (Znicz ``nn_plotting_units.KohonenHits``)."""

    MAPPING = "kohonen_hits_plotter"
    hide_from_registry = False

    def __init__(self, workflow, trainer=None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.trainer = trainer
        self._hits: Optional[numpy.ndarray] = None

    def fill_snapshot(self) -> Optional[Dict[str, Any]]:
        t = self.trainer
        if t is None or t.winners is None:
            return None
        sy, sx = t.shape
        if self._hits is None:
            self._hits = numpy.zeros((sy, sx), dtype=numpy.int64)
        counts = numpy.bincount(t.winners, minlength=sy * sx)
        self._hits += counts.reshape(sy, sx)
        return {"matrix": self._hits.astype(numpy.float64),
                "row_labels": [str(i) for i in range(sy)],
                "column_labels": [str(i) for i in range(sx)]}
