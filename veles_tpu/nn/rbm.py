"""Restricted Boltzmann Machine: forward + CD-1 trainer.

Equivalent of Znicz ``rbm`` (reference surface: SURVEY.md §2.8;
docs/source/manualrst_veles_algorithms.rst lists RBM with a numpy
backend only — here the jitted XLA path is primary and numpy stays the
oracle). Bernoulli–Bernoulli RBM, contrastive divergence with one Gibbs
step (Hinton's CD-1): both GEMMs of the positive/negative phase ride the
MXU.

Determinism design: the sampling uniforms are an explicit *input* of the
pure update function, so the jitted path and the numpy oracle can be fed
identical noise and agree bit-for-bit-ish (same reduction order caveats)
— the "numpy is the oracle" testing property survives stochastic units.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy

from ..memory import Array
from .. import prng
from .nn_units import ForwardBase


def _sigmoid(z, np_mod):
    return 1.0 / (1.0 + np_mod.exp(-z))


def cd1_step(params, v0, u_h0, lr, np_mod=numpy):
    """One CD-1 update from visible batch ``v0`` with sampling uniforms
    ``u_h0``; returns (new_params, reconstruction_error)."""
    w, vb, hb = params["weights"], params["vbias"], params["hbias"]
    h0_prob = _sigmoid(v0 @ w + hb, np_mod)
    h0 = (u_h0 < h0_prob).astype(v0.dtype)
    v1_prob = _sigmoid(h0 @ w.T + vb, np_mod)
    h1_prob = _sigmoid(v1_prob @ w + hb, np_mod)
    n = v0.shape[0]
    dw = (v0.T @ h0_prob - v1_prob.T @ h1_prob) / n
    dvb = (v0 - v1_prob).mean(axis=0)
    dhb = (h0_prob - h1_prob).mean(axis=0)
    new = {"weights": w + lr * dw, "vbias": vb + lr * dvb,
           "hbias": hb + lr * dhb}
    err = ((v0 - v1_prob) ** 2).mean()
    return new, err


class RBM(ForwardBase):
    """Forward: hidden unit probabilities ``sigmoid(x·W + hbias)``."""

    MAPPING = "rbm"
    PARAMETERIZED = True
    hide_from_registry = False
    PARAM_NAMES = ("weights", "vbias", "hbias")

    def __init__(self, workflow, n_hidden: int = 64, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.n_hidden = int(n_hidden)
        self.weights_stddev = kwargs.get("weights_stddev", 0.01)

    def output_shape_for(self, input_shape):
        return (input_shape[0], self.n_hidden)

    def create_params(self, rng: prng.RandomGenerator) -> Dict[str, Array]:
        n_vis = int(numpy.prod(self.input.shape[1:]))
        return {
            "weights": Array(rng.normal(
                0.0, self.weights_stddev,
                (n_vis, self.n_hidden)).astype("float32"),
                name=self.name + ".weights"),
            "vbias": Array(numpy.zeros(n_vis, dtype="float32"),
                           name=self.name + ".vbias"),
            "hbias": Array(numpy.zeros(self.n_hidden, dtype="float32"),
                           name=self.name + ".hbias"),
        }

    def apply(self, params, x, *, train=False, rng=None):
        import jax.numpy as jnp
        x = x.reshape(x.shape[0], -1)
        return _sigmoid(x @ params["weights"] + params["hbias"], jnp)

    def numpy_apply(self, params, x):
        x = numpy.asarray(x, dtype=numpy.float32).reshape(x.shape[0], -1)
        return _sigmoid(x @ params["weights"] + params["hbias"], numpy)

    def reconstruct_np(self, params, x):
        """v → h_prob → v̂ (deterministic mean-field reconstruction)."""
        h = self.numpy_apply(params, x)
        return _sigmoid(h @ params["weights"].T + params["vbias"], numpy)


class RBMTrainer(RBM):
    """CD-1 trainer owning the RBM parameters
    (Znicz ``rbm`` gradient units)."""

    MAPPING = "rbm_trainer"
    hide_from_registry = False

    def __init__(self, workflow, n_hidden: int = 64,
                 learning_rate: float = 0.1, **kwargs) -> None:
        super().__init__(workflow, n_hidden=n_hidden, **kwargs)
        self.learning_rate = float(learning_rate)
        self.reconstruction_error = float("nan")
        self.steps = 0
        self._rng = prng.get(self.name)

    # -- one CD-1 step -------------------------------------------------------
    def xla_run(self) -> None:
        import jax
        import jax.numpy as jnp

        def step(p, v0, u, lr):
            v0 = v0.reshape(v0.shape[0], -1)
            return cd1_step(p, v0, u, lr, jnp)

        fn = self.jit("cd1", step)
        params = {k: v.device_view()
                  for k, v in self.param_arrays().items()}
        u = jax.random.uniform(
            self._rng.jax_key(),
            (self.input.shape[0], self.n_hidden), dtype=jnp.float32)
        new, err = fn(params, self.input.device_view(), u,
                      self.learning_rate)
        for k, arr in self.param_arrays().items():
            arr.assign_devmem(new[k])
        self.reconstruction_error = float(err)
        self.steps += 1

    def numpy_run(self) -> None:
        v0 = numpy.asarray(self.input.map_read(),
                           dtype=numpy.float32)
        v0 = v0.reshape(v0.shape[0], -1)
        u = self._rng.rand(v0.shape[0], self.n_hidden).astype("float32")
        new, err = cd1_step(self.params_np(), v0, u,
                            self.learning_rate, numpy)
        for k, arr in self.param_arrays().items():
            arr.reset(new[k].astype("float32"))
        self.reconstruction_error = float(err)
        self.steps += 1

    def get_metric_values(self) -> Dict[str, Any]:
        return {"rbm_reconstruction_error": self.reconstruction_error,
                "rbm_steps": self.steps}
