"""Pooling units (Znicz ``pooling`` / ``gd_pooling``; layer types
"max_pooling", "avg_pooling", "stochastic_pooling" — SURVEY.md §2.8).
TPU-native via ``jax.lax.reduce_window`` (NHWC)."""

from __future__ import annotations

import numpy

from .nn_units import ForwardBase, GradientDescentBase, matches


class Pooling(ForwardBase):
    hide_from_registry = True

    def __init__(self, workflow, kx=2, ky=2, sliding=None, **kwargs):
        super().__init__(workflow, **kwargs)
        self.kx, self.ky = kx, ky
        self.sliding = tuple(sliding) if sliding else (kx, ky)

    def output_shape_for(self, input_shape):
        b, h, w, c = input_shape
        sx, sy = self.sliding
        # ceil-mode like the reference (partial windows at the edge count)
        oh = -(-(h - self.ky) // sy) + 1 if h >= self.ky else 1
        ow = -(-(w - self.kx) // sx) + 1 if w >= self.kx else 1
        return (b, oh, ow, c)

    def _windows(self, x):
        """Iterate (i, j, window) over the pooling grid — oracle helper."""
        b, h, w, c = x.shape
        _, oh, ow, _ = self.output_shape_for(x.shape)
        sx, sy = self.sliding
        for i in range(oh):
            for j in range(ow):
                yield i, j, x[:, i * sy:i * sy + self.ky,
                              j * sx:j * sx + self.kx, :]


class MaxPooling(Pooling):
    MAPPING = "max_pooling"
    hide_from_registry = False

    def apply(self, params, x, *, train=False, rng=None):
        import jax
        import jax.numpy as jnp
        sx, sy = self.sliding
        b, h, w, c = x.shape
        _, oh, ow, _ = self.output_shape_for(x.shape)
        pad_h = (oh - 1) * sy + self.ky - h
        pad_w = (ow - 1) * sx + self.kx - w
        return jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max,
            window_dimensions=(1, self.ky, self.kx, 1),
            window_strides=(1, sy, sx, 1),
            padding=((0, 0), (0, max(pad_h, 0)), (0, max(pad_w, 0)),
                     (0, 0)))

    def numpy_apply(self, params, x):
        _, oh, ow, _ = self.output_shape_for(x.shape)
        y = numpy.zeros((x.shape[0], oh, ow, x.shape[3]),
                        dtype=numpy.float32)
        for i, j, win in self._windows(x):
            y[:, i, j, :] = win.max(axis=(1, 2))
        return y


class AvgPooling(Pooling):
    MAPPING = "avg_pooling"
    hide_from_registry = False

    def apply(self, params, x, *, train=False, rng=None):
        import jax
        import jax.numpy as jnp
        sx, sy = self.sliding
        b, h, w, c = x.shape
        _, oh, ow, _ = self.output_shape_for(x.shape)
        pad_h = max((oh - 1) * sy + self.ky - h, 0)
        pad_w = max((ow - 1) * sx + self.kx - w, 0)
        summed = jax.lax.reduce_window(
            x, 0.0, jax.lax.add,
            window_dimensions=(1, self.ky, self.kx, 1),
            window_strides=(1, sy, sx, 1),
            padding=((0, 0), (0, pad_h), (0, pad_w), (0, 0)))
        # divide by the true (edge-clipped) window size, matching the oracle
        counts = jax.lax.reduce_window(
            jnp.ones((1, h, w, 1), dtype=x.dtype), 0.0, jax.lax.add,
            window_dimensions=(1, self.ky, self.kx, 1),
            window_strides=(1, sy, sx, 1),
            padding=((0, 0), (0, pad_h), (0, pad_w), (0, 0)))
        return summed / counts

    def numpy_apply(self, params, x):
        _, oh, ow, _ = self.output_shape_for(x.shape)
        y = numpy.zeros((x.shape[0], oh, ow, x.shape[3]),
                        dtype=numpy.float32)
        for i, j, win in self._windows(x):
            y[:, i, j, :] = win.mean(axis=(1, 2))
        return y


class StochasticPooling(MaxPooling):
    """Znicz stochastic pooling: training samples a window element with
    probability proportional to its activation; eval = probability-weighted
    average. TPU version: use uniform sampling over softmax(window) via
    Gumbel trick inside reduce_window is awkward — implemented with
    explicit window extraction (sizes are small, XLA fuses it)."""

    MAPPING = "stochastic_pooling"
    hide_from_registry = False

    def apply(self, params, x, *, train=False, rng=None):
        import jax
        import jax.numpy as jnp
        if not train or rng is None:
            return super().apply(params, x, train=train, rng=rng)
        sx, sy = self.sliding
        b, h, w, c = x.shape
        _, oh, ow, _ = self.output_shape_for(x.shape)
        pad_h = max((oh - 1) * sy + self.ky - h, 0)
        pad_w = max((ow - 1) * sx + self.kx - w, 0)
        xp = jnp.pad(x, ((0, 0), (0, pad_h), (0, pad_w), (0, 0)),
                     constant_values=-jnp.inf)
        # gather all windows: (B, OH, OW, ky*kx, C)
        idx_i = (jnp.arange(oh) * sy)[:, None] + jnp.arange(self.ky)[None]
        idx_j = (jnp.arange(ow) * sx)[:, None] + jnp.arange(self.kx)[None]
        wins = xp[:, idx_i[:, None, :, None], idx_j[None, :, None, :], :]
        wins = wins.reshape(b, oh, ow, self.ky * self.kx, c)
        logits = jnp.where(jnp.isfinite(wins), wins, -1e30)
        g = jax.random.gumbel(rng, wins.shape, dtype=wins.dtype)
        choice = jnp.argmax(logits + g, axis=3, keepdims=True)
        return jnp.take_along_axis(wins, choice, axis=3)[:, :, :, 0, :]

    def numpy_apply(self, params, x):
        return super().numpy_apply(params, x)


@matches(MaxPooling)
class GDMaxPooling(GradientDescentBase):
    MAPPING = "gd_max_pooling"


@matches(AvgPooling)
class GDAvgPooling(GradientDescentBase):
    MAPPING = "gd_avg_pooling"
