"""Learning-rate schedules — Znicz ``lr_adjust`` (SURVEY.md §2.8).

A schedule is a pure function epoch→scale applied as ``lr_scale`` in the
fused step (so changing LR does NOT retrigger XLA compilation — the scale is
a traced scalar argument, not a baked constant)."""

from __future__ import annotations

from typing import Callable

from ..units import Unit


def step_exp(gamma: float = 0.1, step: int = 10) -> Callable[[int], float]:
    """lr *= gamma every `step` epochs (Caffe 'step' policy)."""
    return lambda epoch: gamma ** (epoch // step)


def exp_decay(gamma: float = 0.99) -> Callable[[int], float]:
    return lambda epoch: gamma ** epoch


def inv(gamma: float = 1e-4, power: float = 0.75) -> Callable[[int], float]:
    return lambda epoch: (1.0 + gamma * epoch) ** (-power)


class LearningRateAdjust(Unit):
    """Unit form: recomputes ``lr_scale`` from the decision's epoch counter
    each epoch; the TrainStep reads ``lr_scale`` every minibatch."""

    MAPPING = "lr_adjust"
    hide_from_registry = False

    def __init__(self, workflow, schedule: Callable[[int], float] = None,
                 **kwargs):
        super().__init__(workflow, **kwargs)
        self.schedule = schedule or (lambda epoch: 1.0)
        self.lr_scale = 1.0
        self.decision = None
        self.demand("decision")

    def run(self) -> None:
        self.lr_scale = float(self.schedule(self.decision.epoch_number))
