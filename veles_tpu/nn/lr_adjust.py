"""Learning-rate schedules — Znicz ``lr_adjust`` (SURVEY.md §2.8).

A schedule is a pure function epoch→scale applied as ``lr_scale`` in the
fused step (so changing LR does NOT retrigger XLA compilation — the scale is
a traced scalar argument, not a baked constant)."""

from __future__ import annotations

from typing import Callable

from ..units import Unit


def step_exp(gamma: float = 0.1, step: int = 10) -> Callable[[int], float]:
    """lr *= gamma every `step` epochs (Caffe 'step' policy)."""
    return lambda epoch: gamma ** (epoch // step)


def exp_decay(gamma: float = 0.99) -> Callable[[int], float]:
    return lambda epoch: gamma ** epoch


def inv(gamma: float = 1e-4, power: float = 0.75) -> Callable[[int], float]:
    return lambda epoch: (1.0 + gamma * epoch) ** (-power)


def warmup_cosine(warmup_epochs: int, total_epochs: int,
                  floor: float = 0.0) -> Callable[[int], float]:
    """Linear warmup then cosine decay to ``floor`` — the standard
    schedule for adam-trained attention stacks (epoch granularity: the
    scale feeds the fused step as a traced scalar)."""
    import math

    def schedule(epoch: int) -> float:
        if warmup_epochs > 0 and epoch < warmup_epochs:
            return (epoch + 1) / warmup_epochs
        span = max(1, total_epochs - warmup_epochs)
        frac = min(1.0, (epoch - warmup_epochs) / span)
        return floor + (1 - floor) * 0.5 * (1 + math.cos(math.pi * frac))
    return schedule


class LearningRateAdjust(Unit):
    """Unit form: recomputes ``lr_scale`` from the decision's epoch counter
    each epoch; the TrainStep reads ``lr_scale`` every minibatch."""

    MAPPING = "lr_adjust"
    hide_from_registry = False

    def __init__(self, workflow, schedule: Callable[[int], float] = None,
                 **kwargs):
        super().__init__(workflow, **kwargs)
        self.schedule = schedule or (lambda epoch: 1.0)
        self.lr_scale = 1.0
        self.decision = None
        self.demand("decision")

    def run(self) -> None:
        self.lr_scale = float(self.schedule(self.decision.epoch_number))
