"""Kohonen self-organizing map: forward (winner lookup) + trainer.

Equivalent of Znicz ``kohonen`` (reference surface: SURVEY.md §2.8;
docs/source/manualrst_veles_algorithms.rst:72-117 lists Kohonen with
OpenCL+numpy backends). TPU-first formulation: the whole batch-SOM update
is one pure function — pairwise distances ride the MXU as a GEMM
(``x·Wᵀ`` expansion of ‖x−w‖²), the winner argmin / Gaussian neighborhood
/ weight pull are fused elementwise XLA ops — instead of the reference's
per-sample winner search kernels.

The classic SOM trains by per-sample sequential pulls; the batch variant
computed here (neighborhood-weighted mean pull per minibatch) is the
standard data-parallel formulation and is what makes the unit shardable
over the ``data`` mesh axis.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy

from ..config import root
from ..memory import Array
from .. import prng
from .nn_units import ForwardBase


def _grid_coords(sy: int, sx: int) -> numpy.ndarray:
    yy, xx = numpy.mgrid[0:sy, 0:sx]
    return numpy.stack([yy.ravel(), xx.ravel()], axis=1).astype("float32")


def _pairwise_sqdist(x, w, np_mod):
    """‖x−w‖² per (sample, neuron) via the GEMM expansion."""
    x2 = (x * x).sum(axis=1)[:, None]
    w2 = (w * w).sum(axis=1)[None, :]
    return x2 - 2.0 * (x @ w.T) + w2


def som_step(weights, grid, x, lr, sigma, np_mod=numpy):
    """One batch-SOM update; pure in both numpy and jax.numpy.

    Returns (new_weights, winners, quantization_error)."""
    d2 = _pairwise_sqdist(x, weights, np_mod)
    winners = np_mod.argmin(d2, axis=1)
    qerr = np_mod.sqrt(np_mod.maximum(
        d2[np_mod.arange(x.shape[0]), winners], 0.0)).mean()
    # neighborhood over the 2-D grid: h[i, j] = exp(-‖g_win(i) − g_j‖²/2σ²)
    gwin = grid[winners]                          # (batch, 2)
    gd2 = ((gwin[:, None, :] - grid[None, :, :]) ** 2).sum(axis=2)
    h = np_mod.exp(-gd2 / (2.0 * sigma * sigma))  # (batch, neurons)
    # neighborhood-weighted mean pull toward the batch
    num = h.T @ x                                 # (neurons, features)
    den = h.sum(axis=0)[:, None]                  # (neurons, 1)
    target = num / np_mod.maximum(den, 1e-12)
    new_w = weights + lr * np_mod.minimum(den, 1.0) * (target - weights)
    return new_w, winners.astype("int32"), qerr


class KohonenForward(ForwardBase):
    """Maps each sample to its best-matching unit index
    (Znicz ``kohonen.KohonenForward``)."""

    MAPPING = "kohonen_forward"
    PARAMETERIZED = True
    hide_from_registry = False
    PARAM_NAMES = ("weights",)

    def __init__(self, workflow, shape: Tuple[int, int] = (8, 8),
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.shape = tuple(shape)
        self.weights_stddev = kwargs.get("weights_stddev", 0.05)

    @property
    def neurons_number(self) -> int:
        return self.shape[0] * self.shape[1]

    def output_shape_for(self, input_shape):
        return (input_shape[0],)

    def create_params(self, rng: prng.RandomGenerator) -> Dict[str, Array]:
        n_features = int(numpy.prod(self.input.shape[1:]))
        w = rng.normal(0.0, self.weights_stddev,
                       (self.neurons_number, n_features)).astype("float32")
        return {"weights": Array(w, name=self.name + ".weights")}

    def apply(self, params, x, *, train=False, rng=None):
        import jax.numpy as jnp
        x = x.reshape(x.shape[0], -1)
        d2 = _pairwise_sqdist(x, params["weights"], jnp)
        return jnp.argmin(d2, axis=1).astype(jnp.int32)

    def numpy_apply(self, params, x):
        x = numpy.asarray(x, dtype=numpy.float32).reshape(x.shape[0], -1)
        d2 = _pairwise_sqdist(x, params["weights"], numpy)
        return numpy.argmin(d2, axis=1).astype(numpy.int32)

    def initialize(self, device=None, **kwargs):
        res = super().initialize(device=device, **kwargs)
        if res:
            return res
        # winner indices are int32, not the float minibatch dtype
        if self.input is not None and self.input:
            self.output.reset(numpy.zeros(self.input.shape[0],
                                          dtype=numpy.int32))
        return None


class KohonenTrainer(ForwardBase):
    """Batch-SOM trainer with exponentially decaying radius and rate
    (Znicz ``kohonen.KohonenTrainer``). Owns the weights; a
    KohonenForward can link_attrs to them for inference."""

    MAPPING = "kohonen_trainer"
    PARAMETERIZED = True
    hide_from_registry = False
    PARAM_NAMES = ("weights",)

    def __init__(self, workflow, shape: Tuple[int, int] = (8, 8),
                 sigma0: Optional[float] = None, lr0: float = 0.5,
                 decay: float = 200.0, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.shape = tuple(shape)
        self.sigma0 = float(sigma0 if sigma0 is not None
                            else max(self.shape) / 2.0)
        self.lr0 = float(lr0)
        self.decay = float(decay)
        self.time = 0
        self.weights_stddev = kwargs.get("weights_stddev", 0.05)
        self.grid = _grid_coords(*self.shape)
        #: last winner per sample + quantization error (metrics surface)
        self.winners: Optional[numpy.ndarray] = None
        self.quantization_error = float("nan")

    neurons_number = KohonenForward.neurons_number
    create_params = KohonenForward.create_params

    def output_shape_for(self, input_shape):
        return (input_shape[0],)

    def schedule(self) -> Tuple[float, float]:
        t = float(self.time)
        factor = numpy.exp(-t / self.decay)
        return (max(self.lr0 * factor, 1e-4),
                max(self.sigma0 * factor, 0.35))

    # -- one training step ---------------------------------------------------
    def xla_run(self) -> None:
        import jax.numpy as jnp
        lr, sigma = self.schedule()

        def step(w, g, x, lr_, sig_):
            x = x.reshape(x.shape[0], -1)
            return som_step(w, g, x, lr_, sig_, jnp)

        fn = self.jit("som_step", step)
        w, winners, qerr = fn(self.weights.device_view(),
                              self.grid, self.input.device_view(),
                              lr, sigma)
        self.weights.assign_devmem(w)
        self.winners = numpy.asarray(winners)
        self.quantization_error = float(qerr)
        self.time += 1

    def numpy_run(self) -> None:
        lr, sigma = self.schedule()
        x = self.input.map_read().reshape(self.input.shape[0], -1)
        w, winners, qerr = som_step(
            self.weights.map_read().astype(numpy.float32), self.grid,
            numpy.asarray(x, dtype=numpy.float32), lr, sigma, numpy)
        self.weights.reset(w)
        self.winners = winners
        self.quantization_error = float(qerr)
        self.time += 1

    def get_metric_values(self) -> Dict[str, Any]:
        return {"som_qerr": self.quantization_error,
                "som_steps": self.time}

    # trainer state beyond params: the decay clock
    def state_dict(self):
        sd = super().state_dict()
        sd["__time__"] = numpy.int64(self.time)
        return sd

    def load_state_dict(self, sd):
        sd = dict(sd)
        t = sd.pop("__time__", None)
        if t is not None:
            self.time = int(t)
        super().load_state_dict(sd)
