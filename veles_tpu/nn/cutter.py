"""Cutter: static spatial crop of NHWC minibatches.

Equivalent of Znicz ``cutter`` (reference surface: SURVEY.md §2.8 "cutter,
channel_splitting, weights_zerofilling … tensor plumbing layers"). A pure
slice — statically shaped, so XLA fuses it for free; its backward (zero-pad
of the gradient, a hand-written kernel in the reference era) comes from
autodiff of the slice inside the fused train step.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy

from .nn_units import ForwardBase


class Cutter(ForwardBase):
    """Crops ``padding = (left, top, right, bottom)`` pixels off NHWC."""

    MAPPING = "cutter"
    hide_from_registry = False

    def __init__(self, workflow, padding: Tuple[int, int, int, int] =
                 (0, 0, 0, 0), **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        if len(padding) != 4 or any(p < 0 for p in padding):
            raise ValueError("padding must be 4 non-negative ints "
                             "(left, top, right, bottom), got %r"
                             % (padding,))
        self.padding = tuple(int(p) for p in padding)

    def output_shape_for(self, input_shape):
        n, h, w = input_shape[0], input_shape[1], input_shape[2]
        left, top, right, bottom = self.padding
        oh, ow = h - top - bottom, w - left - right
        if oh <= 0 or ow <= 0:
            raise ValueError("%s: padding %s consumes the whole %dx%d "
                             "input" % (self.name, self.padding, h, w))
        return (n, oh, ow) + tuple(input_shape[3:])

    def _slices(self, shape):
        left, top, right, bottom = self.padding
        return (slice(None), slice(top, shape[1] - bottom),
                slice(left, shape[2] - right))

    def apply(self, params, x, *, train=False, rng=None):
        return x[self._slices(x.shape)]

    def numpy_apply(self, params, x):
        return numpy.ascontiguousarray(x[self._slices(x.shape)])
