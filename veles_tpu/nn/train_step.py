"""TrainStep: the fused, jitted, SPMD training step.

THIS is the architectural heart of the TPU build (SURVEY.md §7 design
stance). The reference executed one GPU kernel per unit per minibatch from
Python threads (veles/units.py:782-505 hot loop) and aggregated gradients
through a ZeroMQ master–slave parameter server (veles/server.py,
veles/client.py). Here the entire minibatch — on-device dataset gather
(fullbatch_loader.cl equivalent), every forward, the loss, every gradient
(jax.grad — replacing all hand-written gd_* kernels), every optimizer
update, and metric accumulation — is ONE compiled XLA program. Data
parallelism falls out of sharding the minibatch over the mesh 'data' axis:
XLA's SPMD partitioner inserts the gradient psum over ICI automatically
(the BASELINE.json north star: "ZeroMQ master–slave → jax.lax.psum").

Per-step host traffic is ZERO except the int32 index vector; metrics
accumulate on device and are drained once per epoch by the Decision unit.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy

from ..accelerated import AcceleratedUnit
from ..backends import XLADevice
from ..error import Bug
from ..loader.base import TEST, VALID, TRAIN
from .. import prng
from .nn_units import ForwardBase, GradientDescentBase, MATCHING
from .all2all import All2AllSoftmax
from .evaluator import EvaluatorSoftmax


class TrainStep(AcceleratedUnit):
    """Owns the canonical device-side parameter pytree and the compiled
    train/eval step functions."""

    MAPPING = "train_step"
    hide_from_registry = False

    def __init__(self, workflow, forwards: List[ForwardBase] = (),
                 evaluator=None, loader=None, gds=None,
                 target_mode: str = "labels", steps_per_dispatch: int = 16,
                 epochs_per_dispatch: int = 1,
                 pipeline_microbatches: Optional[int] = None,
                 remat: bool = False, grad_accumulation: int = 1,
                 **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "TRAINER"
        self.forwards = list(forwards)
        self.evaluator = evaluator
        self.loader = loader
        #: H > 1 fuses H WHOLE epochs (eval+train segments) into one
        #: dispatch — the per-epoch host round trips (train dispatch +
        #: eval dispatch + metric drain) collapse to 1/H. Decision
        #: bookkeeping stays per-epoch (drain_epoch_blocks); early-stop
        #: granularity coarsens to the block (documented trade).
        self.epochs_per_dispatch = max(1, int(epochs_per_dispatch))
        #: G > 1: each optimizer step back-propagates G sequential
        #: minibatch chunks (activation memory / G) and applies ONE
        #: update from their weighted-mean gradient — the large-
        #: effective-batch lever when activations, not params, bound
        #: HBM (see _train_step_accum_fn)
        self.grad_accumulation = max(1, int(grad_accumulation))
        if loader is not None:
            # fused consumption: host minibatch fill skipped; K minibatches
            # scanned per dispatch (must be set before loader.initialize)
            loader.fused = True
            loader.plan_steps = max(1, int(steps_per_dispatch))
            if self.epochs_per_dispatch > 1:
                loader.block_epochs = self.epochs_per_dispatch
        #: "labels" (classification) | "targets" (regression) | "input"
        #: (autoencoder: reconstruct the input batch) | "auto" (resolve at
        #: initialize, after the loader has loaded: targets if present)
        self.target_mode = target_mode
        self.gds: List[GradientDescentBase] = list(gds) if gds else []
        self.lr_scale = 1.0        # linked from LearningRateAdjust
        #: --test mode: TRAIN minibatches evaluate without updating params
        #: (property: setting it downgrades block serving, see setter)
        self.evaluation_mode = False
        self.params: Dict[str, Dict[str, Any]] = {}
        self.opt_state: Dict[str, Dict[str, Any]] = {}
        #: microbatches per minibatch under a 'pipeline' mesh axis
        #: (default: one per stage; more shrinks the fill/drain bubble)
        self.pipeline_microbatches = pipeline_microbatches
        #: pipeline plan ({"pipeline": N} mesh axis): set by
        #: _setup_pipeline when the mesh has the axis, else None
        self._pp = None
        #: heterogeneous-pipeline plan (shape-changing chains the
        #: uniform planner refuses): list-of-stage-groups; params stay
        #: per-unit (replicated over the axis), so checkpoints/masks
        #: need no special casing
        self._pp_hetero = None
        #: rematerialize the forward under jax.checkpoint: activations
        #: are recomputed in the backward instead of living in HBM for
        #: the whole step — FLOPs traded for memory (SURVEY.md HBM
        #: guidance); numerics are identical
        self.remat = bool(remat)
        #: classic AMP (resolved at initialize from
        #: root.common.engine.mixed_precision): forward/backward run on a
        #: bfloat16 cast of params + batch, so ACTIVATION STORAGE halves —
        #: conv nets at image scale are HBM-bandwidth-bound, not
        #: FLOP-bound, and bf16 activations double the effective
        #: bandwidth. Master params, optimizer state, loss and metric
        #: accumulation stay float32 (evaluators upcast); MXU
        #: accumulation stays f32 via preferred_element_type. The
        #: compute_dtype knob (ops/precision.py) only steers MXU operand
        #: rounding — THIS one changes what lives in HBM between layers.
        self.mixed_precision = False
        #: {unit name: {param key: mask array}} — applied multiplicatively
        #: after EVERY optimizer update inside the fused step (ZeroFiller's
        #: sparsity contract must hold within a multi-step dispatch, not
        #: just at dispatch boundaries)
        self.param_masks: Dict[str, Dict[str, Any]] = {}
        self._param_masks_np: Dict[Any, numpy.ndarray] = {}
        self._accum: Dict[int, Any] = {}
        self._zero_accum = None
        #: ops/fused_fc.py whole-epoch kernel plan (engine.fused_fc_scan
        #: + strict eligibility, _setup_fused_fc); None = general path
        self._fused_fc = None
        #: fused scale-bias-activation epilogue plan
        #: (engine.fused_epilogue, _setup_epilogue); None = unfused
        self._epilogue = None
        #: bf16 interlayer activation storage under AMP
        #: (engine.bf16_activations, resolved at initialize)
        self._bf16_acts = False
        #: tensormon plan (telemetry/tensormon.py, resolved at
        #: initialize from root.common.telemetry.tensormon): None = no
        #: taps — the step traces EXACTLY as a build without the
        #: feature (bit-identical state trees, same dispatch count,
        #: locked by tests/test_tensormon.py)
        self._tensormon = None
        #: (stacked device accums, H) from the last block dispatch —
        #: converted to per-epoch dicts lazily in drain_epoch_blocks
        self._block_metrics = None
        #: {(class, h): (idx, mask) device arrays} — eval plans are
        #: epoch-invariant, uploaded once per scan length
        self._eval_plan_dev: Dict[Any, Any] = {}
        self.last_loss = None
        self.demand("evaluator", "loader")

    # -- construction helpers ------------------------------------------------
    def _ensure_gds(self) -> None:
        """Create matched GD units for parameterized forwards lacking one
        (Znicz MatchingObject pairing)."""
        have = {gd.forward for gd in self.gds}
        for f in self.forwards:
            if f.PARAMETERIZED and f not in have:
                gd_cls = MATCHING.get(type(f))
                if gd_cls is None:
                    for klass in type(f).__mro__:
                        if klass in MATCHING:
                            gd_cls = MATCHING[klass]
                            break
                if gd_cls is None:
                    raise Bug("no GD unit matched for %s" % type(f).__name__)
                gd = gd_cls(self.workflow, name="gd_" + f.name,
                            **getattr(f, "gd_config", {}))
                gd.forward = f
                self.gds.append(gd)

    def initialize(self, device=None, **kwargs):
        res = super().initialize(device=device, **kwargs)
        if res:
            return res
        # forwards must be initialized (params created) before us — they
        # are if they appear earlier in dependency order; otherwise re-queue
        for f in self.forwards:
            if f.PARAMETERIZED and not f.param_arrays():
                return True
        self._ensure_gds()
        gd_by_fwd = {gd.forward: gd for gd in self.gds}
        self._gd_for = {f.name: gd_by_fwd[f]
                        for f in self.forwards if f.PARAMETERIZED}
        # canonical device pytree
        import jax
        self.params = {
            f.name: {k: v.device_view() for k, v in f.param_arrays().items()}
            for f in self.forwards if f.PARAMETERIZED}
        self.opt_state = {
            name: self._gd_for[name].init_state(p)
            for name, p in self.params.items()}
        # the step owns (and donates) the device-side params from here on;
        # the forwards' Arrays keep their host mirror only
        for f in self.forwards:
            for arr in f.param_arrays().values():
                arr.detach_devmem()
        self._rng = prng.get(self.name)
        from ..config import root
        # Config.get treats auto-vivified empty nodes as unset
        self.mixed_precision = bool(
            root.common.engine.get("mixed_precision", False))
        # model-health taps (telemetry/tensormon.py): resolved ONCE
        # here — the flag keys what the jitted step traces, so a
        # mid-run config flip must not desync the jit cache
        from ..telemetry import tensormon
        self._tensormon = tensormon.settings() if tensormon.enabled() \
            else None
        if self.target_mode == "auto":
            # resolvable only now: the loader's load_data has run
            has_t = getattr(self.loader, "original_targets", None)
            self.target_mode = ("targets" if has_t is not None and has_t
                                else "input")
        self._setup_pipeline()
        if self.grad_accumulation > 1:
            if self._pp is not None or self._pp_hetero is not None:
                raise Bug("grad_accumulation does not compose with a "
                          "'pipeline' mesh axis (both re-chunk the "
                          "minibatch); drop one")
            mb = self.loader.max_minibatch_size
            if mb % self.grad_accumulation:
                raise Bug("minibatch size %d not divisible into %d "
                          "gradient-accumulation chunks"
                          % (mb, self.grad_accumulation))
            if isinstance(self.device, XLADevice):
                n_data = dict(self.device.mesh.shape).get("data", 1)
                if (mb // self.grad_accumulation) % n_data:
                    raise Bug("accumulation chunk size %d not divisible "
                              "by data-axis size %d"
                              % (mb // self.grad_accumulation, n_data))
        self._bf16_acts = bool(
            root.common.engine.get("bf16_activations", False))
        if self._bf16_acts and not self.mixed_precision:
            # bf16 ACTIVATION storage only makes sense under AMP: the
            # masters stay f32 either way, and without the bf16 cast
            # of params+batch the interlayer casts would just round a
            # full-precision forward for nothing
            self.warning("bf16_activations needs "
                         "engine.mixed_precision — ignored")
            self._bf16_acts = False
        self._setup_shardings()
        self._setup_fused_fc()
        self._setup_epilogue()
        return None

    def _setup_epilogue(self) -> None:
        """Fused scale-bias-activation epilogue plan
        (``root.common.engine.fused_epilogue``, ops/fused_fc.py): runs
        of standalone elementwise units (``activation_*`` layers) fold
        into their producing matmul's consumer inside the traced step
        — identical ops in identical order, so ON is bit-identical to
        OFF here; the dispatch win lives on the standalone forward
        path (install_epilogues). Composes with TensorMonitor taps:
        the taps read the post-epilogue head output, so monitoring
        NEVER forces the unfused path (test-locked — a future
        incompatibility must warn and count, not silently unfuse)."""
        from ..config import root
        from ..ops import fused_fc as _ff
        self._epilogue = None
        if not root.common.engine.get("fused_epilogue", False):
            return
        if self._pp is not None or self._pp_hetero is not None:
            self.warning("fused_epilogue does not fold across "
                         "pipeline stage boundaries — running the "
                         "unfused chain")
            return
        plan = _ff.plan_epilogues(self.forwards)
        if not plan:
            return
        self._epilogue = plan
        self.info("fused epilogue engaged%s: %s",
                  " (composes with tensormon taps)"
                  if self._tensormon is not None else "",
                  "; ".join("%s ← %s" % (p.name,
                                         "+".join(t.name for t in ts))
                            for p, ts in plan))

    def _setup_fused_fc(self) -> None:
        """Opt-in whole-epoch Pallas fast path
        (``root.common.engine.fused_fc_scan``, ops/fused_fc.py): the
        sequential-SGD-bound FC configs (the MNIST-784 headline) run
        each epoch's K optimizer steps as ONE kernel with VMEM-resident
        weights. Strict eligibility — anything outside the proven
        envelope silently keeps the general scan path (and logs why)."""
        from ..config import root
        self._fused_fc = None
        flag = root.common.engine.get("fused_fc_scan", False)
        if not flag:
            return

        def reject(why):
            self.info("fused_fc_scan requested but ineligible: %s", why)

        # the kernel computes in f32; the general path's matmuls follow
        # the compute_dtype policy — on TPU the default bfloat16 policy
        # means one bf16 MXU pass (Precision.DEFAULT), so the two paths
        # would not be trajectory-exact there. On CPU DEFAULT is full
        # f32 and parity holds. "force" opts out of the parity claim
        # (bench A/Bs carry their own method tag instead)
        import jax
        if flag != "force" and jax.default_backend() == "tpu" \
                and str(root.common.engine.get(
                    "compute_dtype", "bfloat16")) in ("bfloat16",
                                                      "bf16"):
            return reject("TPU compute_dtype policy is bfloat16 — the "
                          "f32 kernel would not be trajectory-exact "
                          "vs the bf16-pass scan path (set "
                          "compute_dtype=float32 or fused_fc_scan="
                          "'force' to opt out of the parity claim)")

        from .all2all import All2AllSoftmax, All2AllTanh
        fs = [f for f in self.forwards if f.PARAMETERIZED]
        if (len(self.forwards) != len(fs) or len(fs) < 2
                or any(type(f) is not All2AllTanh for f in fs[:-1])
                or type(fs[-1]) is not All2AllSoftmax):
            return reject("needs an [all2all_tanh ... all2all_tanh, "
                          "softmax] chain")
        if not isinstance(self.evaluator, EvaluatorSoftmax) \
                or getattr(self.evaluator, "label_smoothing", 0.0) \
                or getattr(self.evaluator, "compute_confusion", False):
            return reject("needs plain softmax-CE evaluator")
        if self.mixed_precision or self.remat \
                or self.grad_accumulation > 1:
            return reject("amp/remat/grad-accumulation not fused")
        if self._tensormon is not None:
            return reject("tensormon taps are not computed by the "
                          "fused kernel — the general scan path keeps "
                          "the fused scale-bias-activation epilogue "
                          "(engine.fused_epilogue), so the elementwise "
                          "tail stays fused there; disable "
                          "telemetry.tensormon or fused_fc_scan")
        if self._pp is not None or self._pp_hetero is not None:
            return reject("pipeline mesh not fused")
        if isinstance(self.device, XLADevice) \
                and self.device.mesh.devices.size != 1:
            return reject("single-device only (the kernel owns the "
                          "whole update; no psum inside)")
        if self.param_masks:
            return reject("sparsity masks not fused")
        knobs = set()
        for f in fs:
            if set(self.params[f.name]) != {"weights", "bias"}:
                return reject("%s params beyond weights+bias (LoRA?)"
                              % f.name)
            if getattr(f, "freeze_base", False):
                return reject("%s is frozen (freeze_base) — the "
                              "kernel updates unconditionally" % f.name)
            gd = self._gd_for[f.name]
            if gd.solver != "sgd" or gd.gradient_clip \
                    or gd.gradient_clip_norm:
                return reject("%s: fused path is Znicz SGD only "
                              "(momentum/decay ok; no clipping)"
                              % f.name)
            knobs.add((float(gd.learning_rate),
                       float(gd.learning_rate_bias),
                       float(gd.weight_decay),
                       float(gd.weight_decay_bias),
                       float(gd.momentum)))
        if len(knobs) != 1:
            return reject("per-layer SGD knobs differ (uniform "
                          "lr/decay/momentum required)")
        # the kernel bakes ONE (A, B) tanh scaling for the whole chain
        # (fused_fc._kernel act_a/act_b) — a per-layer override would
        # silently diverge from the scan trajectory while still
        # claiming parity (ADVICE r4)
        acts = {(float(f.A), float(f.B)) for f in fs[:-1]}
        if len(acts) > 1:
            return reject("per-layer tanh (A, B) scales differ "
                          "(uniform activation required)")
        lr, lr_bias, wd, wd_bias, momentum = knobs.pop()
        if lr <= 0:
            return reject("non-positive learning rate")
        if getattr(self.loader, "device_augment_fn", None) is not None:
            return reject("device-side augmentation not fused")
        if self.target_mode != "labels":
            return reject("labels targets only")
        # VMEM budget: the kernel holds weights + biases + the delta
        # recurrence (×2) plus a minibatch block resident; an oversized
        # chain must FALL BACK, not die in an opaque Mosaic allocation
        # error inside the jitted epoch block. The residency estimate
        # is the kernel owner's (ops.fused_fc.analytic_cost
        # peak_memory) — ONE formula for the gate and the cost model
        from ..ops.fused_fc import analytic_cost as _ff_cost
        mb = self.loader.max_minibatch_size
        peak = _ff_cost([self.params[f.name]["weights"].shape
                         for f in fs], mb, steps=1).peak_memory
        budget = 12 * 2 ** 20          # leave headroom in ~16 MiB VMEM
        if peak > budget:
            return reject("VMEM budget: ~%.1f MiB state + batch "
                          "exceeds the %.0f MiB kernel budget"
                          % (peak / 2 ** 20, budget / 2 ** 20))
        ds = self.loader.original_data
        if ds is None or ds.mem.ndim != 2:
            return reject("flat (N, features) dataset only")
        self._fused_fc = {
            "lr": lr, "lr_bias_ratio": lr_bias / lr,
            "wd": wd, "wd_bias": wd_bias, "momentum": momentum,
            "act_a": float(fs[0].A), "act_b": float(fs[0].B),
            "names": tuple(f.name for f in fs),
        }
        self.info("fused_fc_scan engaged: whole-epoch Pallas SGD "
                  "kernel (%s)", " → ".join(f.name for f in fs))

    def _setup_pipeline(self) -> None:
        """{"pipeline": N} mesh axis: stage-group the forward chain and
        restructure the canonical pytree so each device on the axis holds
        only its stages' parameters (pipeline.py gpipe schedule inside
        the fused step — a capability the reference never had, SURVEY.md
        §2.4 'new capability' row)."""
        dev = self.device
        if not isinstance(dev, XLADevice):
            return
        mesh = dev.mesh
        n_stages = dict(mesh.shape).get("pipeline", 1)
        if n_stages <= 1:
            return
        if "sequence" in mesh.axis_names:
            # ring/Ulysses attention wraps its own shard_map over
            # 'sequence'; inside the pipeline's manual mesh region that
            # nests two manual meshes and XLA refuses with an opaque
            # context-mesh mismatch — fail at plan time with the real
            # reason instead (v1 scope: pipeline composes with
            # data/tensor/fsdp/expert, sequence composes with
            # data/tensor; not with each other)
            raise Bug(
                "'pipeline' and 'sequence' mesh axes cannot compose: "
                "sequence-parallel attention runs its own shard_map, "
                "which cannot nest inside the pipelined region. Drop "
                "one of the axes.")
        from ..parallel.pipeline import plan_pipeline
        from ..parallel.sharding import PP_BLOCK
        try:
            pre, block, post = plan_pipeline(self.forwards, n_stages)
        except ValueError as uniform_err:
            # no identical shape-preserving run: fall back to the
            # heterogeneous schedule (lax.switch per stage, padded-wire
            # ppermute ring) — AlexNet/ImagenetAE-shaped chains pipeline
            # too, trading parameter-memory scaling for compute overlap
            # (parallel/pipeline.py gpipe_hetero docstring)
            self._setup_pipeline_hetero(n_stages, mesh, uniform_err)
            return
        import jax.numpy as jnp
        names = [f.name for f in block]
        for masked in self.param_masks:
            if masked in names:
                raise Bug("ZeroFiller masks are not supported on "
                          "pipelined layers (%s)" % masked)
        stacked = {k: jnp.stack([self.params[n][k] for n in names])
                   for k in self.params[names[0]]}
        gd = self._gd_for[names[0]]
        for n in names:
            del self.params[n]
            del self.opt_state[n]
            del self._gd_for[n]
        self.params[PP_BLOCK] = stacked
        self.opt_state[PP_BLOCK] = gd.init_state(stacked)
        self._gd_for[PP_BLOCK] = gd
        # per-layer semantics (e.g. gradient_clip_norm) must survive the
        # stacking: tell the GD its tree now carries a leading layer axis
        gd.stacked_layers = len(names)
        n_micro = self._plan_microbatches(mesh, n_stages)
        self._pp = {"pre": pre, "block": block, "post": post,
                    "names": names, "n_stages": n_stages,
                    "n_micro": n_micro, "mesh": mesh}
        self.info("pipeline plan: %d stages x %d layers, %d microbatches "
                  "(%d pre, %d post replicated)",
                  n_stages, len(names) // n_stages, n_micro,
                  len(pre), len(post))

    @property
    def evaluation_mode(self) -> bool:
        return self._evaluation_mode

    @evaluation_mode.setter
    def evaluation_mode(self, value) -> None:
        """Entering evaluation mode downgrades epoch-block serving to the
        classic per-epoch loop: evaluation has no dispatch-amortization
        need, and a fused H-epoch block would re-evaluate the same sets H
        times — so ``--test`` of a snapshot trained with
        ``epochs_per_dispatch>1`` is a capability, not an error."""
        self._evaluation_mode = bool(value)
        loader = getattr(self, "loader", None)
        if self._evaluation_mode and loader is not None \
                and getattr(loader, "block_epochs", 1) > 1:
            loader.block_epochs = 1

    def _plan_microbatches(self, mesh, n_stages: int) -> int:
        """Resolve the microbatch count (default: one per stage) and
        check the divisibility chain: minibatch → microbatches →
        data-axis shards."""
        mb = self.loader.max_minibatch_size
        n_micro = int(self.pipeline_microbatches or n_stages)
        if mb % n_micro:
            raise Bug("minibatch size %d not divisible into %d pipeline "
                      "microbatches" % (mb, n_micro))
        n_data = dict(mesh.shape).get("data", 1)
        if (mb // n_micro) % n_data:
            raise Bug("pipeline microbatch size %d not divisible by "
                      "data-axis size %d" % (mb // n_micro, n_data))
        return n_micro

    def _setup_pipeline_hetero(self, n_stages, mesh, uniform_err) -> None:
        """Stage-group a shape-changing forward chain for the
        heterogeneous gpipe schedule. The head (last forward) stays
        outside the pipelined region so the softmax-logits/loss fusion
        and evaluator wiring are untouched; everything before it is
        split into ``n_stages`` contiguous groups balanced by the
        stage_cost FLOP proxy. Params remain per-unit (replicated over
        the axis), so snapshots, masks and the update loop are exactly
        the non-pipelined ones."""
        from ..parallel.pipeline import plan_pipeline_hetero
        pipe = self.forwards[:-1]
        try:
            stages = plan_pipeline_hetero(pipe, n_stages)
        except ValueError as e:
            raise Bug("%s (uniform-stage plan also failed: %s)"
                      % (e, uniform_err))
        n_micro = self._plan_microbatches(mesh, n_stages)
        self._pp_hetero = {"stages": stages, "post": [self.forwards[-1]],
                           "n_micro": n_micro, "mesh": mesh}
        # Quantify the documented memory trade (VERDICT r4 item 8)
        # instead of just naming it: per-stage param bytes, the
        # transient in-region gather (lax.switch needs every branch's
        # operands, so ALL stages' params are device-resident during
        # the pipelined region), and — when 'fsdp' coexists — the
        # persistent-storage scaling the sharding planner already
        # applies to these per-unit params (param_shardings shards
        # them over 'fsdp'/'tensor' exactly like non-pipelined ones;
        # only the transient peak stays O(total)).
        def _stage_bytes(us):
            return sum(a.nbytes for f in us if f.PARAMETERIZED
                       for a in f.param_arrays().values())
        per_stage = [_stage_bytes(us) for us in stages]
        total_mb = sum(per_stage) / 2 ** 20
        n_fsdp = dict(mesh.shape).get("fsdp", 1)
        self.info(
            "heterogeneous pipeline plan: %d stages (%s units each), %d "
            "microbatches; stage params %s MiB, transient in-region "
            "gather %.2f MiB/device, persistent storage %s",
            n_stages, "/".join(str(len(s)) for s in stages), n_micro,
            "/".join("%.2f" % (b / 2 ** 20) for b in per_stage),
            total_mb,
            ("~%.2f MiB/device (fsdp=%d shards the divisible params)"
             % (total_mb / n_fsdp, n_fsdp) if n_fsdp > 1
             else "%.2f MiB/device (replicated — add an 'fsdp' axis "
                  "to shard it)" % total_mb))
        self._pp_hetero["stage_param_bytes"] = per_stage

    def _setup_shardings(self) -> None:
        """SPMD parallelism from mesh axes (see veles_tpu/parallel/):
        minibatch sharded over 'data' (grad psum over ICI — the reference's
        entire ZeroMQ master–slave plane, veles/server.py + veles/client.py,
        collapses to this annotation); params sharded over 'tensor'
        (column-parallel kernels) and/or 'fsdp' (ZeRO-3 style) when those
        axes exist, else replicated. XLA inserts every collective."""
        self._shardings = None
        dev = self.device
        if not isinstance(dev, XLADevice):
            return
        mesh = dev.mesh
        if mesh.devices.size <= 1:
            return
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..parallel.sharding import param_shardings, replicated
        repl = replicated(mesh)
        if "data" in mesh.axis_names:
            batch = NamedSharding(mesh, P("data"))
            n_data = mesh.shape["data"]
            if self.loader.max_minibatch_size % n_data:
                raise Bug(
                    "minibatch size %d not divisible by data-axis size %d"
                    % (self.loader.max_minibatch_size, n_data))
        else:
            batch = repl
        self._shardings = {"repl": repl, "batch": batch}
        from ..parallel.sharding import state_shardings
        pspec = param_shardings(self.params, mesh)
        sspec = state_shardings(self.opt_state, self.params, pspec, mesh)
        self.params = jax.tree_util.tree_map(
            jax.device_put, self.params, pspec)
        self.opt_state = jax.tree_util.tree_map(
            jax.device_put, self.opt_state, sspec)

    def register_param_mask(self, unit_name: str, key: str, mask) -> None:
        """Install (or refresh) a sparsity mask enforced after every update
        inside the compiled step. Masks are baked into the jitted program as
        constants, so (re)registration invalidates the jit cache — callers
        re-registering an identical mask are a no-op (checked host-side:
        no device transfer or stream sync on the steady-state path)."""
        if self._pp is not None and unit_name in self._pp["names"]:
            raise Bug("ZeroFiller masks are not supported on pipelined "
                      "layers (%s)" % unit_name)
        m_np = numpy.asarray(mask)
        cur_np = self._param_masks_np.get((unit_name, key))
        if cur_np is not None and numpy.array_equal(cur_np, m_np):
            return
        self._param_masks_np[(unit_name, key)] = m_np
        import jax.numpy as jnp
        m = jnp.asarray(m_np)
        self.param_masks.setdefault(unit_name, {})[key] = m
        self._jit_cache.clear()
        # enforce immediately on the canonical pytree too
        if self.params.get(unit_name) and key in self.params[unit_name]:
            p = dict(self.params[unit_name])
            p[key] = p[key] * m.astype(p[key].dtype)
            self.params[unit_name] = p

    @property
    def _step_impl(self):
        return (self._train_step_accum_fn if self.grad_accumulation > 1
                else self._train_step_fn)

    # -- pure functions -------------------------------------------------------
    def _apply_chain(self, units, params, x, train: bool, rng, base: int):
        """Apply a replicated run of forwards (``base`` offsets the
        per-layer rng streams); the softmax head yields logits when the
        evaluator fuses the stable cross-entropy. The single copy of
        the head-handling loop all three forward paths share.

        Epilogue plan active: each producer's planned elementwise
        tails apply through ``ops.fused_fc.apply_epilogue`` right
        after it and are skipped at their own position — the SAME ops
        in the SAME order (and enumerate indices, hence dropout rng
        streams, unchanged), so the traced program is bit-identical
        to the unfused chain. ``bf16_activations``: interlayer
        activations that left a unit as float32 are stored bfloat16
        (masters, loss and metric accumulation stay f32 — this knob
        only changes what lives in HBM between layers)."""
        import jax
        import jax.numpy as jnp
        from ..ops.fused_fc import apply_epilogue
        last = self.forwards[-1] if self.forwards else None
        use_logits = (isinstance(last, All2AllSoftmax)
                      and isinstance(self.evaluator, EvaluatorSoftmax))
        folded = set()
        prod_tails = {}
        if self._epilogue:
            for prod, tails in self._epilogue:
                prod_tails[id(prod)] = tails
                folded.update(id(t) for t in tails)
        for i, f in enumerate(units):
            if id(f) in folded:
                continue        # applied by its producer's epilogue
            layer_rng = (jax.random.fold_in(rng, base + i)
                         if rng is not None else None)
            p = params.get(f.name, {})
            if f is last and use_logits:
                return f.logits(p, x)
            x = f.apply(p, x, train=train, rng=layer_rng)
            tails = prod_tails.get(id(f))
            if tails:
                x = apply_epilogue(x, tails, train=train)
            # the HEAD output feeds the evaluator (which upcasts to
            # f32 itself) — only INTERLAYER activations store bf16
            head = f is last or (tails and tails[-1] is last)
            if self._bf16_acts and not head \
                    and x.dtype == jnp.float32:
                x = x.astype(jnp.bfloat16)
        return x

    def _forward_pure(self, params, x, train: bool, rng):
        """Compose the forward chain; softmax head yields logits for the
        fused stable cross-entropy."""
        if self._pp is not None:
            return self._forward_pure_pp(params, x, train, rng)
        if self._pp_hetero is not None:
            return self._forward_pure_pp_hetero(params, x, train, rng)
        return self._apply_chain(self.forwards, params, x, train, rng, 0)

    def _forward_pure_pp(self, params, x, train: bool, rng):
        """Pipelined forward: pre-chain replicated → gpipe over the
        stage-grouped block (ppermute ring inside shard_map; jax.grad
        derives the reverse schedule) → post-chain replicated. Dropout
        inside the block runs rng-less (deterministic) — per-layer rng
        streams do not thread through the stage scan."""
        import jax
        from jax.sharding import PartitionSpec as P
        from ..parallel.pipeline import gpipe, microbatch, unmicrobatch
        from ..parallel.sharding import PP_BLOCK
        pp = self._pp
        x = self._apply_chain(pp["pre"], params, x, train, rng, 0)
        mesh = pp["mesh"]
        n_stages, n_micro = pp["n_stages"], pp["n_micro"]
        layers_per_stage = len(pp["names"]) // n_stages
        staged = jax.tree_util.tree_map(
            lambda a: a.reshape((n_stages, layers_per_stage)
                                + a.shape[1:]),
            params[PP_BLOCK])
        block_apply = pp["block"][0].apply

        def stage_fn(stage_params, h):
            # stage_params leaves: (layers_per_stage, …) — this stage's
            # slice; scan composes its layers
            def body(h, layer_p):
                return block_apply(layer_p, h, train=train, rng=None), None
            h, _ = jax.lax.scan(body, h, stage_params)
            return h

        bspec = (P(None, "data") if "data" in mesh.axis_names else P())
        xs = microbatch(x, n_micro)
        y = gpipe(stage_fn, staged, xs, mesh, batch_spec=bspec)
        x = unmicrobatch(y)
        return self._apply_chain(pp["post"], params, x, train, rng, 1000)

    def _forward_pure_pp_hetero(self, params, x, train: bool, rng):
        """Heterogeneous pipelined forward: the staged chain runs under
        gpipe_hetero (lax.switch selects each device's stage; activations
        hop the ppermute ring as padded flat buffers), the head runs
        replicated after. Dropout inside stages is rng-less, as in the
        uniform schedule."""
        from jax.sharding import PartitionSpec as P
        from ..parallel.pipeline import (gpipe_hetero, microbatch,
                                         unmicrobatch)
        pp = self._pp_hetero
        mesh = pp["mesh"]

        def make_stage(units):
            def stage_fn(stage_params, h):
                for f in units:
                    h = f.apply(stage_params.get(f.name, {}), h,
                                train=train, rng=None)
                return h
            return stage_fn

        stage_fns = [make_stage(us) for us in pp["stages"]]
        stage_params = [
            {f.name: params.get(f.name, {})
             for f in us if f.PARAMETERIZED}
            for us in pp["stages"]]
        bspec = (P(None, "data") if "data" in mesh.axis_names else P())
        xs = microbatch(x, pp["n_micro"])
        y = gpipe_hetero(stage_fns, stage_params, xs, mesh,
                         batch_spec=bspec)
        x = unmicrobatch(y)
        return self._apply_chain(pp["post"], params, x, train, rng, 1000)

    def _gather(self, dataset, indices):
        import jax.numpy as jnp
        return jnp.take(dataset, indices, axis=0)

    def _amp_cast(self, tree):
        """bf16 view of a float32 pytree (mixed_precision): autodiff
        through the cast returns float32 grads for the f32 masters."""
        import jax
        import jax.numpy as jnp

        def cast(a):
            return (a.astype(jnp.bfloat16)
                    if hasattr(a, "dtype") and a.dtype == jnp.float32
                    else a)
        return jax.tree_util.tree_map(cast, tree)

    def _target_for(self, batch, labels, targets, indices):
        if self.target_mode == "labels":
            return self._gather(labels, indices)
        if self.target_mode == "input":
            return batch
        if self.target_mode == "targets":
            if getattr(self.loader, "targets_by_label", False):
                # per-label template TABLE: row → label → template,
                # composed gathers (the table is n_labels rows, stored
                # once — never materialized per dataset row)
                return self._gather(targets,
                                    self._gather(labels, indices))
            return self._gather(targets, indices)
        raise Bug("bad target_mode %r" % self.target_mode)

    def _train_step_fn(self, params, opt_state, accum, dataset, labels,
                       targets, indices, mask, lr_scale, rng):
        import jax
        batch = self._gather(dataset, indices)
        # loader-supplied on-device augmentation (e.g. random mirror/crop
        # fused into the step — loader/image.py device_augmentation)
        aug = getattr(self.loader, "device_augment_fn", None)
        if aug is not None:
            batch = aug(batch, jax.random.fold_in(rng, 0x417))
        tgt = self._target_for(batch, labels, targets, indices)
        if self.mixed_precision:
            batch = self._amp_cast(batch)

        def loss_fn(p):
            if self.mixed_precision:
                p = self._amp_cast(p)
            if self.remat:
                out = jax.checkpoint(
                    lambda pp, bb: self._forward_pure(pp, bb, True,
                                                      rng))(p, batch)
            else:
                out = self._forward_pure(p, batch, True, rng)
            return self.evaluator.loss(out, tgt, mask), out

        (loss, out), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params)
        valid = mask.sum() > 0  # all-padded plan rows must not decay params
        new_params, new_opt = self._apply_updates(params, grads,
                                                  opt_state, lr_scale,
                                                  valid)
        metrics = self.evaluator.metrics_fn(out, tgt, mask)
        metrics["sum_loss"] = loss * self.evaluator.sum_loss_weight(
            out, mask)
        if self._tensormon is not None:
            # auxiliary tensor taps (telemetry/tensormon.py): pure
            # scalars over values this step already computed — extra
            # accumulator outputs, zero extra dispatches or host syncs
            from ..telemetry import tensormon
            metrics.update(tensormon.step_stats(
                params, new_params, grads, loss, out,
                self._tensormon["sat_threshold"]))
        accum = jax.tree_util.tree_map(
            lambda a, m: a + m, accum,
            {k: metrics[k] for k in accum})
        return new_params, new_opt, accum, loss

    def _apply_updates(self, params, grads, opt_state, lr_scale, valid):
        """One copy of the optimizer application (per-unit GD rules,
        all-padded-row gating, sparsity masks), shared by the direct
        and gradient-accumulating steps."""
        import jax
        import jax.numpy as jnp
        new_params, new_opt = {}, {}
        for name, p in params.items():
            gd = self._gd_for[name]
            up_p, up_s = gd.update(p, grads[name], opt_state[name],
                                   lr_scale)
            new_params[name] = jax.tree_util.tree_map(
                lambda new, old: jnp.where(valid, new, old), up_p, p)
            new_opt[name] = jax.tree_util.tree_map(
                lambda new, old: jnp.where(valid, new, old), up_s,
                opt_state[name])
        for name, masks in self.param_masks.items():
            if name in new_params:
                for k, m in masks.items():
                    # cast: the product must keep the param dtype or the
                    # scan carry structure would change
                    new_params[name][k] = (new_params[name][k]
                                           * m.astype(new_params[name][k].dtype))
        return new_params, new_opt

    def _train_step_accum_fn(self, params, opt_state, accum, dataset,
                             labels, targets, indices, mask, lr_scale,
                             rng):
        """Gradient accumulation (``grad_accumulation=G``): the
        minibatch splits into G sequential chunks; the forward/backward
        runs per chunk (activation memory ∝ mb/G) and ONE optimizer
        step applies the valid-count-weighted mean of the chunk
        gradients — exactly the full-minibatch gradient up to reduction
        order (chunk losses are valid-masked means, so chunk grads are
        recombined with w_c/Σw weights). Dropout streams fold per
        chunk, so rng-using nets match the direct step only in
        distribution."""
        import jax
        import jax.numpy as jnp
        ga = self.grad_accumulation
        # the monitor's aux entries accumulate from the FINAL aggregate
        # (mean gradient + the one applied update), not per chunk —
        # split them out so the chunk scan carries the classic key set
        mon_zero = {k: v for k, v in accum.items()
                    if k.startswith("mon_")}
        accum = {k: v for k, v in accum.items()
                 if not k.startswith("mon_")}
        batch = self._gather(dataset, indices)
        aug = getattr(self.loader, "device_augment_fn", None)
        if aug is not None:
            batch = aug(batch, jax.random.fold_in(rng, 0x417))
        tgt = self._target_for(batch, labels, targets, indices)
        if self.mixed_precision:
            batch = self._amp_cast(batch)
        mb = batch.shape[0]

        def chunk(x):
            return x.reshape((ga, mb // ga) + x.shape[1:])

        total = jnp.maximum(mask.sum().astype(jnp.float32), 1.0)

        def body(carry, xs):
            g_sum, l_sum, a = carry
            b_i, t_i, m_i, ci = xs

            def loss_fn(p):
                if self.mixed_precision:
                    p = self._amp_cast(p)
                chunk_rng = jax.random.fold_in(rng, ci)
                if self.remat:
                    out = jax.checkpoint(
                        lambda pp, bb: self._forward_pure(
                            pp, bb, True, chunk_rng))(p, b_i)
                else:
                    out = self._forward_pure(p, b_i, True, chunk_rng)
                return self.evaluator.loss(out, t_i, m_i), out

            (loss, out), g = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            w = m_i.sum().astype(jnp.float32)
            g_sum = jax.tree_util.tree_map(
                lambda s, gg: s + gg.astype(jnp.float32) * w, g_sum, g)
            metrics = self.evaluator.metrics_fn(out, t_i, m_i)
            metrics["sum_loss"] = loss * self.evaluator.sum_loss_weight(
                out, m_i)
            a = jax.tree_util.tree_map(
                lambda av, m: av + m, a, {k: metrics[k] for k in a})
            return (g_sum, l_sum + loss * w, a), None

        zero_g = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        (g_sum, l_sum, accum), _ = jax.lax.scan(
            body, (zero_g, jnp.float32(0.0), accum),
            (chunk(batch), chunk(tgt), chunk(mask),
             jnp.arange(ga)))
        grads = jax.tree_util.tree_map(
            lambda s, p: (s / total).astype(p.dtype), g_sum, params)
        valid = mask.sum() > 0
        new_params, new_opt = self._apply_updates(params, grads,
                                                  opt_state, lr_scale,
                                                  valid)
        if mon_zero:
            from ..telemetry import tensormon
            stats = tensormon.step_stats(
                params, new_params, grads, l_sum / total, None,
                self._tensormon["sat_threshold"])
            accum = dict(accum)
            accum.update({k: mon_zero[k] + stats[k] for k in mon_zero})
        return new_params, new_opt, accum, l_sum / total

    def _train_plan_fn(self, params, opt_state, accum, dataset, labels,
                       targets, idx_plan, mask_plan, lr_scale, rng):
        """lax.scan over a (K, mb) index plan: K optimizer steps in ONE
        dispatch. The TPU-era answer to per-unit dispatch overhead —
        sequential dependence between steps is real (param updates), so
        scan, not vmap."""
        import jax

        def body(carry, xs):
            p, o, a = carry
            idx, msk, i = xs
            step_rng = jax.random.fold_in(rng, i)
            p, o, a, loss = self._step_impl(
                p, o, a, dataset, labels, targets, idx, msk, lr_scale,
                step_rng)
            return (p, o, a), loss
        import jax.numpy as jnp
        steps = jnp.arange(idx_plan.shape[0])
        (params, opt_state, accum), losses = jax.lax.scan(
            body, (params, opt_state, accum), (idx_plan, mask_plan, steps))
        return params, opt_state, accum, losses[-1]

    def _eval_step_fn(self, params, accum, dataset, labels, targets,
                      indices, mask):
        import jax
        batch = self._gather(dataset, indices)
        ev = getattr(self.loader, "device_eval_fn", None)
        if ev is not None:
            batch = ev(batch)       # deterministic center crop
        tgt = self._target_for(batch, labels, targets, indices)
        if self.mixed_precision:
            batch = self._amp_cast(batch)
            params = self._amp_cast(params)
        out = self._forward_pure(params, batch, False, None)
        metrics = self.evaluator.metrics_fn(out, tgt, mask)
        metrics["sum_loss"] = (self.evaluator.loss(out, tgt, mask)
                               * self.evaluator.sum_loss_weight(out,
                                                                mask))
        return jax.tree_util.tree_map(
            lambda a, m: a + m, accum, {k: metrics[k] for k in accum})

    def _eval_plan_fn(self, params, accum, dataset, labels, targets,
                      idx_plan, mask_plan):
        import jax

        def body(a, xs):
            idx, msk = xs
            return self._eval_step_fn(params, a, dataset, labels, targets,
                                      idx, msk), None
        accum, _ = jax.lax.scan(body, accum, (idx_plan, mask_plan))
        return accum

    def _make_zero_accum(self, mon: bool = False):
        """``mon=True`` (train contexts with tensormon enabled) adds
        the monitor's auxiliary accumulator entries — eval accums and
        monitoring-off runs carry exactly the classic key set."""
        import jax.numpy as jnp
        from .evaluator import EvaluatorSoftmaxSeq
        zeros = {"n_samples": jnp.zeros((), jnp.float32),
                 "sum_loss": jnp.zeros((), jnp.float32)}
        if isinstance(self.evaluator, (EvaluatorSoftmax,
                                       EvaluatorSoftmaxSeq)):
            zeros["n_err"] = jnp.zeros((), jnp.float32)
        else:
            zeros["sum_sq"] = jnp.zeros((), jnp.float32)
        if mon and self._tensormon is not None:
            from ..telemetry import tensormon
            zeros.update(tensormon.zero_stats(sorted(self.params)))
        return zeros

    # -- execution -----------------------------------------------------------
    def _inputs(self):
        loader = self.loader
        sh = self._shardings
        repl = sh["repl"] if sh else None
        batch = sh["batch"] if sh else None
        ds_sh = repl
        if sh is not None and getattr(loader, "shard_dataset", False):
            mesh = repl.mesh
            if "data" in mesh.axis_names and mesh.shape["data"] > 1:
                n_data = mesh.shape["data"]
                n_rows = loader.original_data.shape[0]
                if n_rows % n_data:
                    # the stored array is what shards, not the (possibly
                    # train_ratio-subsetted) logical sample count
                    raise Bug(
                        "shard_dataset: dataset of %d rows not "
                        "divisible by data-axis size %d"
                        % (n_rows, n_data))
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P
                ds_sh = NamedSharding(mesh, P("data"))
            elif mesh.devices.size > 1 and \
                    not getattr(self, "_warned_shard_dataset", False):
                self._warned_shard_dataset = True   # once, not per step
                self.warning(
                    "%s: shard_dataset=True but the mesh has no 'data' "
                    "axis (>1) — dataset stays fully replicated on "
                    "every chip", loader.name)
        dataset = loader.original_data.device_view(sharding=ds_sh)
        labels = (loader.original_labels.device_view(sharding=ds_sh)
                  if loader.original_labels else None)
        targets = getattr(loader, "original_targets", None)
        # a label-indexed table has n_labels rows, not n_rows — row
        # sharding over 'data' would be wrong AND wasteful (it is tiny:
        # replicate it)
        tgt_sh = (repl if getattr(loader, "targets_by_label", False)
                  else ds_sh)
        targets = (targets.device_view(sharding=tgt_sh)
                   if targets is not None and targets else dataset)
        if labels is None:
            labels = self._dummy_labels(dataset)
        if batch is not None and loader.plan_steps > 1 \
                and "data" in batch.mesh.axis_names:
            # plans are (K, mb): shard the minibatch axis, not the scan axis
            from jax.sharding import NamedSharding, PartitionSpec as P
            batch = NamedSharding(batch.mesh, P(None, "data"))
        indices = loader.minibatch_indices.device_view(sharding=batch)
        mask = loader.minibatch_mask.device_view(sharding=batch)
        return dataset, labels, targets, indices, mask

    def _dummy_labels(self, dataset):
        import jax.numpy as jnp
        return jnp.zeros((dataset.shape[0],), jnp.int32)

    def _epoch_block_fn(self, params, opt_state, dataset, labels,
                        targets, xs_template_keys, xs, rng):
        """H whole epochs in one program: lax.scan over epochs; each
        epoch runs the eval plans (test, validation) then the train
        plan, in the classic loop's offset order. Per-epoch metric
        accums come back stacked (H,) for the Decision to replay."""
        import jax

        def one_epoch(carry, per_epoch):
            p, o = carry
            e_rng = jax.random.fold_in(rng, per_epoch["e"])
            outs = {}
            for cls in (TEST, VALID):
                key = "c%d" % cls
                if key + "_idx" not in xs_template_keys:
                    continue
                acc = self._eval_plan_fn(
                    p, self._make_zero_accum(), dataset, labels,
                    targets, per_epoch[key + "_idx"],
                    per_epoch[key + "_mask"])
                outs[cls] = acc
            if getattr(self, "_fused_fc_active", False):
                # whole-epoch Pallas SGD kernel (ops/fused_fc.py):
                # weights AND the SGD delta recurrence stay VMEM-
                # resident for all K steps; both are returned so
                # opt_state continues the identical trajectory.
                import jax.numpy as jnp
                from ..ops.fused_fc import fused_fc_sgd_epoch
                ff = self._fused_fc
                names = ff["names"]
                plan = per_epoch["c%d_idx" % TRAIN]
                ws, bs, vws, vbs, loss_sum, err = fused_fc_sgd_epoch(
                    [p[n]["weights"] for n in names],
                    [p[n]["bias"] for n in names],
                    [o[n]["weights"] for n in names],
                    [o[n]["bias"] for n in names],
                    dataset, labels, plan,
                    per_epoch["lr"] * ff["lr"],
                    act_a=ff["act_a"], act_b=ff["act_b"],
                    lr_bias_ratio=ff["lr_bias_ratio"],
                    wd=ff["wd"], wd_bias=ff["wd_bias"],
                    momentum=ff["momentum"])
                p, o = dict(p), dict(o)
                for i2, n2 in enumerate(names):
                    p[n2] = {"weights": ws[i2], "bias": bs[i2]}
                    o[n2] = {"weights": vws[i2], "bias": vbs[i2]}
                n = jnp.float32(plan.shape[0] * plan.shape[1])
                outs[TRAIN] = {"n_samples": n, "sum_loss": loss_sum,
                               "n_err": err}
                # the general path reports the LAST batch's mean loss;
                # the kernel returns the epoch sum — report the epoch
                # mean (same scale, logging-only)
                return (p, o), (outs, loss_sum / n)
            p, o, acc_tr, loss = self._train_plan_fn(
                p, o, self._make_zero_accum(mon=True), dataset, labels,
                targets,
                per_epoch["c%d_idx" % TRAIN],
                per_epoch["c%d_mask" % TRAIN],
                per_epoch["lr"], e_rng)
            outs[TRAIN] = acc_tr
            return (p, o), (outs, loss)

        (params, opt_state), (stacked, losses) = jax.lax.scan(
            one_epoch, (params, opt_state), xs)
        return params, opt_state, stacked, losses[-1]

    def _run_epoch_block(self) -> None:
        import jax
        import numpy as _np
        from ..telemetry.counters import inc
        from ..telemetry.spans import span
        loader = self.loader
        dataset, labels, targets, _, _ = self._inputs()
        sh = self._shardings
        plan_sh = None
        if sh is not None and "data" in sh["repl"].mesh.axis_names:
            from jax.sharding import NamedSharding, PartitionSpec as P
            plan_sh = NamedSharding(sh["repl"].mesh,
                                    P(None, None, "data"))
        # the loader may have clamped the FINAL block below H
        # (block_epochs_cap); slice the host plans to what was served —
        # the tail block traces/compiles once at its own scan length
        h = loader.block_length or loader.block_epochs
        xs = {"e": _np.arange(h, dtype=_np.int32)}
        for cls, (idx, mask) in sorted(loader.block_plans.items()):
            if cls != TRAIN:
                # eval plans never change (only the TRAIN tail of the
                # shuffle permutes per epoch): upload once per scan
                # length, reuse the device copies across blocks
                cached = self._eval_plan_dev.get((cls, h))
                if cached is None:
                    idx_h = idx.map_read()[:h]
                    mask_h = mask.map_read()[:h]
                    inc("veles_h2d_bytes_total",
                        idx_h.nbytes + mask_h.nbytes)
                    cached = (jax.device_put(idx_h, plan_sh),
                              jax.device_put(mask_h, plan_sh))
                    self._eval_plan_dev[(cls, h)] = cached
                xs["c%d_idx" % cls], xs["c%d_mask" % cls] = cached
                continue
            idx_h, mask_h = idx.map_read()[:h], mask.map_read()[:h]
            inc("veles_h2d_bytes_total", idx_h.nbytes + mask_h.nbytes)
            xs["c%d_idx" % cls] = jax.device_put(idx_h, plan_sh)
            xs["c%d_mask" % cls] = jax.device_put(mask_h, plan_sh)
        # per-epoch LR scales from the schedule, host-evaluated exactly
        # as the classic loop would have (epoch k trains at schedule(k))
        lr_adjust = getattr(self.workflow, "lr_adjust", None)
        decision = getattr(self.workflow, "decision", None)
        e0 = decision.epoch_number if decision is not None else 0
        if lr_adjust is not None:
            scales = [float(lr_adjust.schedule(e0 + i)) for i in range(h)]
        else:
            scales = [float(self.lr_scale)] * h
        xs["lr"] = _np.asarray(scales, dtype=_np.float32)
        keys = frozenset(xs)

        # fused kernel assumes whole minibatches: any padded plan row
        # (partial tail batch) falls back to the masked general path.
        # The flag keys the jit cache — flipping it must not reuse the
        # other variant's trace.
        self._fused_fc_active = (
            self._fused_fc is not None
            and all(float(m.map_read()[:h].min()) >= 1.0
                    for cls, (i_, m) in loader.block_plans.items()
                    if cls == TRAIN))

        def fn(params, opt_state, dataset, labels, targets, xs, rng):
            return self._epoch_block_fn(params, opt_state, dataset,
                                        labels, targets, keys, xs, rng)

        jitted = self.jit(
            "epoch_block_fused" if self._fused_fc_active
            else "epoch_block", fn, donate_argnums=(0, 1))
        with span("train_step.epoch_block", unit=self.name, epochs=h,
                  fused_fc=bool(self._fused_fc_active)):
            self.params, self.opt_state, stacked, self.last_loss = \
                jitted(self.params, self.opt_state, dataset, labels,
                       targets, xs, self._rng.jax_key())
        # stays on device until the Decision drains: the host must NOT
        # block here, or consecutive blocks lose their async overlap
        self._block_metrics = (stacked, h)

    def drain_epoch_blocks(self) -> List[Dict[int, Dict[str, float]]]:
        """Per-epoch metric dicts since the last drain: H entries after
        a block dispatch, one entry in the classic per-epoch mode.
        When tensormon is on, the monitor's auxiliary entries ride this
        SAME drain (zero extra host syncs), are stripped before the
        Decision sees the dicts, and the NaN sentinel may raise
        :class:`~veles_tpu.telemetry.tensormon.ModelHealthError` here —
        on the scheduler path, exactly where a crashed dispatch would
        have surfaced."""
        if self._block_metrics is not None:
            import jax
            from ..telemetry.counters import inc
            stacked, h = self._block_metrics
            self._block_metrics = None
            host = jax.device_get(stacked)
            inc("veles_d2h_bytes_total",
                sum(a.nbytes for a in jax.tree_util.tree_leaves(host)))
            entries = [
                {cls: {k: float(v[e]) for k, v in acc.items()}
                 for cls, acc in host.items()}
                for e in range(h)]
        else:
            entries = [self.drain_epoch_metrics()]
        if self._tensormon is not None:
            from ..telemetry import tensormon
            for mon in tensormon.extract_mon(entries, TRAIN):
                tensormon.monitor.observe(self, mon)
        return entries

    def cost_report(self):
        """Telemetry cost of every program this unit has dispatched
        (``AcceleratedUnit.program_cost`` per jit key), with the
        analytic fused-FC cost merged in when the Pallas kernel is
        active — the custom call is opaque to XLA's HLO cost model, so
        the kernel's FLOPs/bytes come from ``ops.fused_fc.
        analytic_cost``. Returns ``{"key", "cost", "costs"}`` (primary
        key + its cost, plus per-key costs so sections that mix
        programs — classic mode runs 'train' AND 'eval' per epoch —
        bill each dispatch at its own program's cost) or None before
        the first dispatch. This is what bench.py's measured-MFU rows
        read."""
        costs = {}
        for key in ("epoch_block_fused", "epoch_block", "train",
                    "eval"):
            if key not in self._jit_arg_shapes:
                continue
            cost = self.program_cost(key)
            if cost is None:
                continue
            if key == "epoch_block_fused" and self._fused_fc is not None:
                from ..ops import fused_fc as _ff
                names = self._fused_fc["names"]
                shapes = [self.params[n]["weights"].shape
                          for n in names]
                loader = self.loader
                h = loader.block_length or loader.block_epochs
                per_epoch = _ff.analytic_cost(
                    shapes, loader.max_minibatch_size,
                    loader.plan_steps)
                cost = cost + per_epoch.scaled(h)
            costs[key] = cost
        if not costs:
            return None
        primary = next(iter(costs))
        return {"key": primary, "cost": costs[primary], "costs": costs}

    def xla_run(self) -> None:
        import jax
        if self.loader.block_epochs > 1:
            if self.evaluation_mode:
                raise Bug("epochs_per_dispatch>1 requires training mode")
            return self._run_epoch_block()
        cls = self.loader.minibatch_class
        accum = self._accum.get(cls)
        if accum is None:
            # fresh zeros per class: accum buffers are donated to the step
            accum = self._accum[cls] = self._make_zero_accum(
                mon=(cls == TRAIN and not self.evaluation_mode))
        dataset, labels, targets, indices, mask = self._inputs()
        planned = self.loader.plan_steps > 1
        if cls == TRAIN and not self.evaluation_mode:
            fn = self.jit("train",
                          self._train_plan_fn if planned
                          else self._step_impl,
                          donate_argnums=(0, 1, 2))
            self.params, self.opt_state, self._accum[cls], self.last_loss \
                = fn(self.params, self.opt_state, accum, dataset, labels,
                     targets, indices, mask,
                     numpy.float32(self.lr_scale), self._rng.jax_key())
        else:
            fn = self.jit("eval",
                          self._eval_plan_fn if planned
                          else self._eval_step_fn, donate_argnums=(1,))
            self._accum[cls] = fn(self.params, accum, dataset, labels,
                                  targets, indices, mask)

    def numpy_run(self) -> None:
        # the fused step IS jax; on the numpy device it runs un-jitted on
        # host arrays (oracle path exercised by tests via forwards'
        # numpy_apply separately)
        self.xla_run()

    # -- epoch drain (Decision pulls these) ----------------------------------
    def drain_epoch_metrics(self) -> Dict[int, Dict[str, float]]:
        import jax
        from ..telemetry.counters import inc
        out = {}
        drained = 0
        for cls, accum in self._accum.items():
            host = jax.device_get(accum)
            drained += sum(a.nbytes
                           for a in jax.tree_util.tree_leaves(host))
            out[cls] = {k: float(v) for k, v in host.items()}
        if drained:
            inc("veles_d2h_bytes_total", drained)
        self._accum.clear()
        return out

    # -- checkpoint/pickle support -------------------------------------------
    def sync_params_to_arrays(self) -> None:
        """Copy the canonical device params back into the forwards' host
        Arrays (so snapshots and host-side units observe trained weights).
        Host copies, not buffer refs: the step donates its param buffers on
        the next dispatch, which would leave the Arrays dangling."""
        from ..parallel.distributed import fetch_global
        from ..parallel.sharding import PP_BLOCK
        pp_names = self._pp["names"] if self._pp is not None else []
        # fetch_global, not device_get: fsdp/tensor params on a multi-
        # process mesh span non-addressable devices and must all-gather
        # (every rank reaches here — see fetch_global's collective note)
        host = fetch_global(self.params)
        stacked = host.get(PP_BLOCK, {}) if pp_names else {}
        for f in self.forwards:
            if not f.PARAMETERIZED:
                continue
            arrays = f.param_arrays()
            if f.name in pp_names:
                i = pp_names.index(f.name)
                for k in arrays:
                    arrays[k].reset(numpy.array(stacked[k][i]))
                continue
            for k, v in host.get(f.name, {}).items():
                arrays[k].reset(numpy.array(v))

    def stop(self) -> None:
        if self.params:
            # workflow stop fires on every rank in the same order
            from ..parallel.distributed import lockstep
            with lockstep():
                self.sync_params_to_arrays()

    # -- checkpoint protocol -------------------------------------------------
    def on_snapshot(self) -> None:
        if self.params:
            self.sync_params_to_arrays()

    def state_dict(self):
        import jax
        from ..parallel.distributed import fetch_global
        opt = fetch_global(self.opt_state)
        if self._pp is not None:
            # snapshots stay per-layer so a checkpoint moves freely
            # between pipeline topologies (resume-with-different-mesh
            # guarantee, SURVEY.md §5.4). Works for any state structure:
            # per-param buffers unstack along the layer axis, scalars
            # (e.g. Adam's shared step counter) copy to every layer.
            from ..parallel.sharding import PP_BLOCK
            blk = opt.pop(PP_BLOCK)
            for i, n in enumerate(self._pp["names"]):
                opt[n] = jax.tree_util.tree_map(
                    lambda v, _i=i: v[_i] if numpy.ndim(v) else v, blk)
        return {"opt_state": opt, "lr_scale": float(self.lr_scale)}

    def load_state_dict(self, sd) -> None:
        """Called after the forwards restored their Arrays (apply order =
        unit construction order): rebuild the canonical device pytree."""
        import jax
        self.params = {
            f.name: {k: v.device_view() for k, v in
                     f.param_arrays().items()}
            for f in self.forwards if f.PARAMETERIZED}
        self.opt_state = {k: v for k, v in sd["opt_state"].items()}
        # a restored state may not cover every current param (resuming
        # a base snapshot into a lora_rank config): grow it with fresh
        # zero state for the new keys; restored leaves win
        for name, p in self.params.items():
            if name in self.opt_state and name in self._gd_for:
                self.opt_state[name] = self._gd_for[name].extend_state(
                    self.opt_state[name], p)
        if self._pp is not None:
            # restack the per-layer snapshot into the pipeline block;
            # scalar leaves (shared counters) take the first layer's
            import jax.numpy as jnp
            from ..parallel.sharding import PP_BLOCK
            names = self._pp["names"]
            self.params[PP_BLOCK] = {
                k: jnp.stack([self.params[n][k] for n in names])
                for k in self.params[names[0]]}
            self.opt_state[PP_BLOCK] = jax.tree_util.tree_map(
                lambda *ls: (jnp.stack([numpy.asarray(x) for x in ls])
                             if numpy.ndim(ls[0]) else ls[0]),
                *[self.opt_state[n] for n in names])
            for n in names:
                del self.params[n]
                del self.opt_state[n]
        if self._shardings is not None:
            from ..parallel.sharding import (param_shardings,
                                             state_shardings)
            pspec = param_shardings(self.params, self.device.mesh)
            sspec = state_shardings(self.opt_state, self.params, pspec,
                                    self.device.mesh)
            self.params = jax.tree_util.tree_map(
                jax.device_put, self.params, pspec)
            self.opt_state = jax.tree_util.tree_map(
                jax.device_put, self.opt_state, sspec)
        # the step re-takes device ownership (buffers will be donated)
        for f in self.forwards:
            for arr in f.param_arrays().values():
                arr.detach_devmem()
        # restore the schedule scale so the first resumed dispatch trains
        # at the snapshot's learning rate (identical-continuation guarantee)
        if "lr_scale" in sd:
            try:
                self.lr_scale = float(sd["lr_scale"])
            except AttributeError:
                pass  # linked read-only alias; LearningRateAdjust rules
        self._accum.clear()

    def __getstate__(self):
        self.sync_params_to_arrays()
        d = super().__getstate__()
        for k in ("params", "opt_state", "_accum", "_zero_accum",
                  "last_loss", "_pp", "_pp_hetero", "_block_metrics",
                  "_eval_plan_dev"):
            d[k] = ({} if k in ("params", "opt_state", "_accum",
                                "_eval_plan_dev") else None)
        d["param_masks"] = {
            n: {k: numpy.asarray(m) for k, m in ms.items()}
            for n, ms in self.param_masks.items()}
        return d
