"""NN unit bases: ForwardBase, GradientDescentBase, MatchingObject registry.

Equivalent of Znicz ``nn_units`` (reference surface: SURVEY.md §2.8,
docs/generate_units_args.py:16-40): forward units paired with gradient-
descent units through a matching registry.

TPU-first redesign of the forward/backward contract:
- a forward unit is a *parameterized pure function*: ``apply(params, x,
  train, rng)`` built from jax.numpy — traceable, fuseable, shardable;
- ``numpy_apply(params, x)`` is the host oracle (reference "numpy is the
  oracle" property, SURVEY.md §4);
- there are NO hand-written backward kernels: the paired GD unit carries
  *optimizer hyper-parameters* (learning rate, momentum, weight decay,
  gradient clipping) and its pure ``update(param, grad, state)`` rule;
  gradients come from ``jax.grad`` over the composed step (train_step.py).
  Standalone ``GradientDescentBase.run`` still works for unit tests via
  ``jax.vjp`` of the matched forward.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple, Type

import numpy

from ..accelerated import AcceleratedUnit
from ..config import root
from ..error import Bug
from ..memory import Array
from .. import prng

#: forward class → gd class (reference: Znicz MatchingObject registry)
MATCHING: Dict[type, type] = {}


def matches(forward_cls: type) -> Callable[[type], type]:
    """Class decorator registering a GD unit as the backward pair of a
    forward unit."""
    def deco(gd_cls: type) -> type:
        MATCHING[forward_cls] = gd_cls
        return gd_cls
    return deco


class ForwardBase(AcceleratedUnit):
    """Base of all forward (inference) units (Znicz ``nn_units.ForwardBase``).

    Data contract (matches the reference unit attribute names so workflow
    wiring code reads the same): ``input`` / ``output`` are Arrays;
    parameters live in ``self.weights`` / ``self.bias`` Arrays when present.
    """

    hide_from_registry = True
    #: subclasses with trainable parameters set this
    PARAMETERIZED = False

    #: layer-config keys routed to the paired GD unit (Znicz put these on
    #: the layer dict too, e.g. {"type": "conv", "learning_rate": …})
    GD_KEYS = ("learning_rate", "learning_rate_bias", "weights_decay",
               "weight_decay", "weights_decay_bias", "gradient_moment",
               "momentum", "gradient_clip", "gradient_clip_norm",
               "solver", "beta1", "beta2", "epsilon", "rho")

    def __init__(self, workflow, **kwargs) -> None:
        #: hyper-parameters for the matched GD unit, captured from the
        #: layer config before Unit.__init__ would discard them
        self.gd_config = {k: kwargs.pop(k) for k in list(kwargs)
                          if k in self.GD_KEYS}
        #: LoRA fine-tuning (parameter-efficient transfer learning —
        #: beyond the reference, whose transfer story was snapshot
        #: resume + retrain): rank r adds W_eff = W + A·B·(alpha/r)
        #: low-rank deltas to every LORA_TARGET weight; base params
        #: freeze by default (freeze_base=False trains both). Units
        #: whose apply routes through merged_params support it
        #: (All2All/Conv families); see LORA_TARGETS.
        self.lora_rank = int(kwargs.pop("lora_rank", 0) or 0)
        self.lora_alpha = float(kwargs.pop("lora_alpha",
                                           self.lora_rank or 1))
        self.freeze_base = bool(kwargs.pop("freeze_base",
                                           self.lora_rank > 0))
        self._lora_names = ()
        super().__init__(workflow, **kwargs)
        self.view_group = "WORKER"
        self.input: Optional[Array] = None
        self.output = Array(name=self.name + ".output")
        self.weights_transposed = kwargs.get("weights_transposed", False)
        self.demand("input")

    # -- parameter protocol --------------------------------------------------
    def create_params(self, rng: prng.RandomGenerator) -> Dict[str, Array]:
        """Allocate+initialize parameter Arrays; default: none."""
        return {}

    def params_np(self) -> Dict[str, numpy.ndarray]:
        """Host view of parameters (oracle side)."""
        return {k: v.map_read() for k, v in self.param_arrays().items()}

    #: parameter attribute names (subclasses with other params override)
    PARAM_NAMES = ("weights", "bias")
    #: weight keys eligible for LoRA deltas (only units whose apply
    #: calls merged_params honor them)
    LORA_TARGETS = ("weights",)

    def param_arrays(self) -> Dict[str, Array]:
        out = {}
        for k in self.PARAM_NAMES + getattr(self, "_lora_names", ()):
            arr = getattr(self, k, None)
            if isinstance(arr, Array) and arr:
                out[k] = arr
        return out

    def _create_lora_params(self) -> None:
        """A (fan_in, r) ~ N(0, 1/sqrt(fan_in)) and B (r, fan_out) = 0
        per LORA_TARGET — the standard init (delta starts at zero, so a
        lora_rank!=0 model is exactly the base model at step 0)."""
        if not self.lora_rank or self._lora_names:
            return
        names = []
        for k in self.LORA_TARGETS:
            arr = getattr(self, k, None)
            if not (isinstance(arr, Array) and arr) or arr.mem.ndim < 2:
                continue
            w = arr.mem
            fin = int(numpy.prod(w.shape[:-1]))
            fout = int(w.shape[-1])
            a = numpy.zeros((fin, self.lora_rank), w.dtype)
            prng.get("%s.%s_lora_a" % (self.name, k)).fill_normal(
                a, 1.0 / numpy.sqrt(fin))
            b = numpy.zeros((self.lora_rank, fout), w.dtype)
            setattr(self, k + "_lora_a",
                    Array(a, name="%s.%s_lora_a" % (self.name, k)))
            setattr(self, k + "_lora_b",
                    Array(b, name="%s.%s_lora_b" % (self.name, k)))
            names += [k + "_lora_a", k + "_lora_b"]
        if not names:
            # a silent pass would freeze the whole layer (freeze_base
            # defaults True) while training nothing
            from ..error import VelesError
            raise VelesError(
                "lora_rank=%d on %s (%s): no LORA_TARGET weights to "
                "adapt — LoRA supports the All2All/Conv/Deconv "
                "families; drop the knob from this layer"
                % (self.lora_rank, self.name, type(self).__name__))
        self._lora_names = tuple(names)

    def merged_params(self, params):
        """W_eff = W + A·B·(alpha/r) for every LoRA'd weight — called at
        the top of supporting applies; identity without LoRA. Traced
        inside the step, so the merge fuses into the consuming matmul."""
        if not getattr(self, "lora_rank", 0):
            return params
        out = dict(params)
        scale = self.lora_alpha / self.lora_rank
        for k in self.LORA_TARGETS:
            if k + "_lora_a" not in params or k not in params:
                continue
            w = params[k]
            delta = (params[k + "_lora_a"] @ params[k + "_lora_b"]
                     ) * scale
            out[k] = w + delta.reshape(w.shape).astype(w.dtype)
        return out

    def export_param_arrays(self) -> Dict[str, Array]:
        """param_arrays with LoRA deltas MERGED into the base weights —
        exports/serving see a plain dense model (the C++ runtime needs
        no adapter concept)."""
        arrays = self.param_arrays()
        if not getattr(self, "lora_rank", 0) or not self._lora_names:
            return arrays
        scale = self.lora_alpha / self.lora_rank
        out = {}
        for k, v in arrays.items():
            if k.endswith(("_lora_a", "_lora_b")):
                continue
            if k + "_lora_a" in arrays:
                w = numpy.array(v.map_read())
                a = numpy.asarray(arrays[k + "_lora_a"].map_read())
                b = numpy.asarray(arrays[k + "_lora_b"].map_read())
                w = w + ((a @ b) * scale).reshape(w.shape).astype(w.dtype)
                out[k] = Array(w, name="%s.%s(merged)" % (self.name, k))
            else:
                out[k] = v
        return out

    # -- the pure function ---------------------------------------------------
    def apply(self, params: Dict[str, Any], x: Any, *, train: bool = False,
              rng: Any = None) -> Any:
        """Pure jax forward. MUST be jit-traceable (static shapes, no host
        side effects)."""
        raise NotImplementedError

    def numpy_apply(self, params: Dict[str, numpy.ndarray],
                    x: numpy.ndarray) -> numpy.ndarray:
        """Host oracle forward."""
        raise NotImplementedError

    def output_shape_for(self, input_shape: Tuple[int, ...]
                         ) -> Tuple[int, ...]:
        """Static shape inference used at graph-build time."""
        raise NotImplementedError

    # -- standalone execution (inference graphs, unit tests) -----------------
    def initialize(self, device=None, **kwargs):
        res = super().initialize(device=device, **kwargs)
        if res:
            return res
        if self.PARAMETERIZED and not self.param_arrays():
            rng = prng.get(self.name)
            for k, v in self.create_params(rng).items():
                setattr(self, k, v)
        if self.PARAMETERIZED:
            self._create_lora_params()
        if self.input is not None and self.input:
            shape = self.output_shape_for(self.input.shape)
            if self.output.mem is None or self.output.shape != shape:
                self.output.reset(numpy.zeros(
                    shape, dtype=root.common.engine.precision_type))
        return None

    # -- checkpoint protocol (SURVEY.md §5.4 explicit state schema) ----------
    def state_dict(self) -> Dict[str, numpy.ndarray]:
        return {k: numpy.array(v.map_read())
                for k, v in self.param_arrays().items()}

    def load_state_dict(self, sd: Dict[str, numpy.ndarray]) -> None:
        for k, v in sd.items():
            arr = getattr(self, k, None)
            if isinstance(arr, Array):
                arr.reset(numpy.array(v))
            else:
                setattr(self, k, Array(numpy.array(v),
                                       name="%s.%s" % (self.name, k)))

    def xla_run(self) -> None:
        if getattr(self, "_epilogue_folded", False):
            # this unit's elementwise work already ran inside the
            # producing matmul's program (ops/fused_fc.py
            # install_epilogues) — its separate dispatch is REMOVED,
            # which is the whole point of the fused epilogue
            return
        params = {k: v.device_view() for k, v in self.param_arrays().items()}
        tails = getattr(self, "_epilogue_tails", None)
        if tails:
            # fused scale-bias-activation epilogue: the elementwise
            # tail units fold into THIS matmul's program. EVERY
            # stage's output array is still assigned (the program
            # returns each intermediate) — a non-chain consumer
            # linked to the producer's (or a mid-tail's) output reads
            # exactly what the unfused path would have written, at
            # one dispatch instead of 1 + len(tails)
            def fused(p, x):
                y = self.apply(p, x, train=False)
                outs = [y]
                for t in tails:
                    y = t.apply({}, y, train=False, rng=None)
                    outs.append(y)
                return outs
            outs = self.jit("apply_epilogue", fused)(
                params, self.input.device_view())
            self.output.assign_devmem(outs[0])
            for t, o in zip(tails, outs[1:]):
                t.output.assign_devmem(o)
            return
        fn = self.jit("apply", lambda p, x: self.apply(p, x, train=False))
        self.output.assign_devmem(fn(params, self.input.device_view()))

    def numpy_run(self) -> None:
        y = self.numpy_apply(self.params_np(), self.input.map_read())
        self.output.reset(numpy.asarray(y))


class GradientDescentBase(AcceleratedUnit):
    """Base of gradient-descent (backward/update) units (Znicz
    ``nn_units.GradientDescentBase``).

    In the reference each GD unit computed err_input and applied the weight
    delta with its own kernel; here the unit carries the *update rule* and
    hyper-parameters, applied inside the fused train step. ``run`` as a
    standalone unit computes gradients with jax.vjp against the matched
    forward — used by tests and by graphs that want explicit per-layer
    backward stages.
    """

    hide_from_registry = True

    def __init__(self, workflow, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.view_group = "TRAINER"
        self.forward: Optional[ForwardBase] = None
        self.learning_rate = kwargs.get("learning_rate", 0.01)
        self.learning_rate_bias = kwargs.get("learning_rate_bias",
                                             self.learning_rate)
        self.momentum = kwargs.get("gradient_moment",
                                   kwargs.get("momentum", 0.0))
        self.weight_decay = kwargs.get("weights_decay",
                                       kwargs.get("weight_decay", 0.0))
        self.weight_decay_bias = kwargs.get("weights_decay_bias", 0.0)
        self.gradient_clip = kwargs.get("gradient_clip", 0.0)
        #: clip this layer's gradients by their joint L2 norm (the
        #: transformer-era stabilizer; gradient_clip stays the
        #: element-wise Znicz semantic)
        self.gradient_clip_norm = kwargs.get("gradient_clip_norm", 0.0)
        #: per-layer update rule: "sgd" (Znicz semantics) | "adam" |
        #: "adamw" (decoupled weight decay) | "adagrad" | "rmsprop" |
        #: "adadelta" — routed from the layer dict like the lr knobs
        self.solver = kwargs.get("solver", "sgd")
        self.beta1 = kwargs.get("beta1", 0.9)
        self.beta2 = kwargs.get("beta2", 0.999)
        self.epsilon = kwargs.get("epsilon", 1e-8)
        #: rmsprop/adadelta accumulator decay
        self.rho = kwargs.get("rho", 0.95)
        if self.solver not in ("sgd", "adam", "adamw", "adagrad",
                               "rmsprop", "adadelta"):
            raise Bug("unknown solver %r (sgd | adam | adamw | adagrad "
                      "| rmsprop | adadelta)" % self.solver)

    def extend_state(self, state, params):
        """Grow a RESTORED optimizer state to cover params it lacks
        state for (e.g. resuming a base snapshot into a lora_rank
        config: the adapters need fresh zero state). Walks a fresh
        init_state; restored leaves win wherever present."""
        fresh = self.init_state(params)

        def merge(f, s):
            if isinstance(f, dict):
                return {k: (merge(v, s[k])
                            if isinstance(s, dict) and k in s else v)
                        for k, v in f.items()}
            return s

        return merge(fresh, state)

    def _frozen(self, k: str) -> bool:
        """freeze_base (LoRA fine-tuning): every key except the adapter
        pairs is held fixed — zero step AND zero weight decay."""
        fwd = getattr(self, "forward", None)
        return (fwd is not None
                and getattr(fwd, "freeze_base", False)
                and not k.endswith(("_lora_a", "_lora_b")))

    # -- pure update rule ----------------------------------------------------
    def init_state(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """Optimizer state pytree (momentum / Adam moments / AdaGrad
        accumulators), zeros-like params."""
        import jax
        import jax.numpy as jnp
        zeros = jax.tree_util.tree_map(lambda p: p * 0, params)

        def fresh():
            return jax.tree_util.tree_map(lambda p: p * 0, params)

        if self.solver in ("adam", "adamw"):
            return {"m": zeros, "v": fresh(),
                    "t": jnp.zeros((), jnp.int32)}
        if self.solver in ("adagrad", "rmsprop"):
            return {"a": zeros}
        if self.solver == "adadelta":
            return {"a": zeros, "d": fresh()}
        return zeros

    def update(self, params: Dict[str, Any], grads: Dict[str, Any],
               state: Dict[str, Any], lr_scale: Any = 1.0
               ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """Per-layer update rule. Default: SGD + momentum + L2 weight
        decay + optional clip (the Znicz GD semantics:
        delta = lr*(grad + wd*w) + mom*prev); "adam"/"adagrad" keep the
        same lr/wd/clip knobs around their own accumulators."""
        import jax.numpy as jnp

        if self.gradient_clip_norm:
            # joint L2 over this LAYER's grad tree. When TrainStep hands
            # this GD a stacked pipeline block (leaves carry a leading
            # layer axis; stacked_layers set by _setup_pipeline), the
            # norm is computed per layer slice so pipelined and plain
            # runs clip identically.
            import jax
            leaves = jax.tree_util.tree_leaves(grads)
            n_stk = getattr(self, "stacked_layers", 0)
            if n_stk:
                sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))
                                 .reshape(n_stk, -1), axis=1)
                         for g in leaves)                       # (L,)
                factor = jnp.minimum(
                    1.0, self.gradient_clip_norm
                    / jnp.maximum(jnp.sqrt(sq), 1e-12))
                grads = jax.tree_util.tree_map(
                    lambda g: (g * factor.reshape(
                        (n_stk,) + (1,) * (g.ndim - 1))).astype(g.dtype),
                    grads)
            else:
                sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                         for g in leaves)
                factor = jnp.minimum(
                    1.0, self.gradient_clip_norm
                    / jnp.maximum(jnp.sqrt(sq), 1e-12))
                grads = jax.tree_util.tree_map(
                    lambda g: (g * factor).astype(g.dtype), grads)

        def knobs(k, g):
            """Per-key hyper-parameters: (lr, wd, clipped grad). The ONE
            place lr/decay/clip/freeze routing lives — every solver
            folds wd into its own rule (coupled: g + wd*p; adamw:
            decoupled step)."""
            if self._frozen(k):
                # freeze_base (LoRA): no step, no decay drift
                return 0.0, 0.0, g * 0
            lr = (self.learning_rate_bias if k == "bias"
                  else self.learning_rate) * lr_scale
            wd = (self.weight_decay_bias if k == "bias"
                  else self.weight_decay)
            if self.gradient_clip:
                g = jnp.clip(g, -self.gradient_clip, self.gradient_clip)
            return lr, wd, g

        if self.solver in ("adam", "adamw"):
            # adamw: DECOUPLED weight decay (p -= lr*wd*p outside the
            # moments) — knobs() folds wd into g, so for adamw the raw
            # gradient goes through the moments and decay applies after
            decoupled = self.solver == "adamw"
            t = state["t"] + 1
            new_m, new_v, new_params = {}, {}, {}
            for k, p in params.items():
                lr, wd, g = knobs(k, grads[k])
                if not decoupled:
                    g = g + wd * p
                m = self.beta1 * state["m"][k] + (1 - self.beta1) * g
                v = self.beta2 * state["v"][k] + (1 - self.beta2) * g * g
                mhat = m / (1 - self.beta1 ** t.astype(m.dtype))
                vhat = v / (1 - self.beta2 ** t.astype(v.dtype))
                step = lr * mhat / (jnp.sqrt(vhat) + self.epsilon)
                if decoupled:
                    step = step + lr * wd * p
                new_params[k] = p - step
                new_m[k], new_v[k] = m, v
            return new_params, {"m": new_m, "v": new_v, "t": t}
        if self.solver == "adagrad":
            new_a, new_params = {}, {}
            for k, p in params.items():
                lr, wd, g = knobs(k, grads[k])
                g = g + wd * p
                a = state["a"][k] + g * g
                new_params[k] = p - lr * g / (jnp.sqrt(a) + self.epsilon)
                new_a[k] = a
            return new_params, {"a": new_a}
        if self.solver == "rmsprop":
            new_a, new_params = {}, {}
            for k, p in params.items():
                lr, wd, g = knobs(k, grads[k])
                g = g + wd * p
                a = self.rho * state["a"][k] + (1 - self.rho) * g * g
                new_params[k] = p - lr * g / (jnp.sqrt(a) + self.epsilon)
                new_a[k] = a
            return new_params, {"a": new_a}
        if self.solver == "adadelta":
            # Zeiler 2012: unit-correcting running deltas; the
            # learning_rate knob scales the final step (1.0 = paper)
            new_a, new_d, new_params = {}, {}, {}
            for k, p in params.items():
                lr, wd, g = knobs(k, grads[k])
                g = g + wd * p
                a = self.rho * state["a"][k] + (1 - self.rho) * g * g
                delta = (jnp.sqrt(state["d"][k] + self.epsilon)
                         / jnp.sqrt(a + self.epsilon)) * g
                new_params[k] = p - lr * delta
                new_d[k] = (self.rho * state["d"][k]
                            + (1 - self.rho) * delta * delta)
                new_a[k] = a
            return new_params, {"a": new_a, "d": new_d}
        new_params, new_state = {}, {}
        for k, p in params.items():
            lr, wd, g = knobs(k, grads[k])
            delta = lr * (g + wd * p) + self.momentum * state[k]
            new_params[k] = p - delta
            new_state[k] = delta
        return new_params, new_state

    # -- standalone backward (tests / explicit graphs) -----------------------
    def initialize(self, device=None, **kwargs):
        if self.forward is None:
            raise Bug("%s: no forward unit attached" % self.name)
        return super().initialize(device=device, **kwargs)

    def compute_grads(self, err_output):
        """vjp of the matched forward at its current input/params:
        returns (err_input, param_grads)."""
        import jax
        fwd = self.forward
        params = {k: v.device_view() for k, v in fwd.param_arrays().items()}
        x = fwd.input.device_view()

        def f(p, xx):
            return fwd.apply(p, xx, train=True)

        _, vjp = jax.vjp(f, params, x)
        pgrads, xgrad = vjp(err_output)
        return xgrad, pgrads

    def xla_run(self) -> None:
        err = getattr(self, "err_output", None)
        if err is None:
            raise Bug("%s: err_output not linked" % self.name)
        xgrad, pgrads = self.compute_grads(err.device_view())
        self.err_input = Array(numpy.asarray(xgrad),
                               name=self.name + ".err_input")
        params = {k: v.device_view()
                  for k, v in self.forward.param_arrays().items()}
        if params:
            state = getattr(self, "_state", None)
            if state is None:
                state = self._state = self.init_state(params)
            new_params, self._state = self.update(params, pgrads, state)
            for k, v in new_params.items():
                self.forward.param_arrays()[k].assign_devmem(v)

    def numpy_run(self) -> None:
        # host path delegates to the same jax code on CPU — autodiff has no
        # separate numpy oracle; correctness is anchored by forward oracles
        self.xla_run()
