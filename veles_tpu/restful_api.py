"""RESTful serving: HTTP POST a sample, get the model's answer.

Equivalent of the reference's veles/restful_api.py:78 (RESTfulAPI unit:
twisted Site; POST /api JSON → RestfulLoader feed → workflow run in test
mode → JSON result). Stdlib ``http.server`` replaces twisted (not in this
environment); the serving workflow itself is the same shape: a Repeater
loop of RestfulLoader → forwards → RESTfulAPI, where this unit runs after
the forwards each pass and answers the HTTP request that fed the sample.

The HTTP thread and the workflow thread meet through per-request tickets:
the handler feeds (sample, ticket) to the loader and blocks on the
ticket's event; this unit's ``run()`` fills the ticket from the forward
output and sets the event.
"""

from __future__ import annotations

import threading
import time
from http.server import BaseHTTPRequestHandler
from typing import Any, Dict, Optional

import numpy

from ._http import (HTTPService, bytes_reply, handle_alerts,
                    handle_metrics_history, handle_trace_spans,
                    json_reply, read_json_object)
from .config import root
from .error import VelesError
from .resilience import health
from .resilience.faults import FaultInjected, fire as fire_fault
from .serving.scheduler import (Ticket as _Ticket, shed_expired,
                                split_expired)
from .units import Unit


class RESTfulAPI(Unit):
    """Serving endpoint unit (reference: veles/restful_api.py:78).

    Wire into a forward workflow:
        api = RESTfulAPI(wf, port=8080, loader=rest_loader)
        api.link_attrs(last_forward, ("input", "output"))
        api.link_from(last_forward); repeater.link_from(api)
    """

    MAPPING = "restful_api"
    hide_from_registry = False

    def __init__(self, workflow, loader=None, port: int = 0,
                 path: str = "/api", request_timeout: float = 60.0,
                 max_pending: int = None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.loader = loader
        self.port = port
        self.path = path
        self.request_timeout = request_timeout
        #: in-flight bound: requests beyond it are SHED (503 +
        #: Retry-After) instead of queueing without limit
        self.max_pending = int(max_pending if max_pending is not None
                               else root.common.resilience.get(
                                   "max_pending", 64) or 64)
        self._pending = 0
        self._pending_lock = threading.Lock()
        #: tickets fed but not yet terminal — what a stop()/drain
        #: sweep settles via the first-terminal fail() (503 +
        #: Retry-After + request_id) instead of letting the handlers
        #: rot to a silent 504
        self._outstanding: set = set()
        #: forward output to answer from (link_attrs from the last forward)
        self.input = None
        self._service: Optional[HTTPService] = None
        self.requests_served = 0
        self.demand("loader")

    # -- lifecycle ----------------------------------------------------------
    def initialize(self, **kwargs):
        res = super().initialize(**kwargs)
        if res:
            return res
        if self._service is not None:
            return None
        api = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route into our logger
                api.debug("http: " + fmt, *args)

            def do_GET(self):
                if health.handle_health(self, self.path):
                    return
                if handle_trace_spans(self, self.path,
                                      name="rest.%s" % api.name):
                    return
                if handle_metrics_history(self, self.path,
                                          name="rest.%s" % api.name):
                    return
                if handle_alerts(self, self.path):
                    return
                if self.path != "/metrics":
                    self.send_error(404)
                    return
                from .telemetry.alerts import render_firing
                from .telemetry.counters import (METRICS_CONTENT_TYPE,
                                                 metrics_text)
                text = metrics_text({
                    "veles_rest_requests_served": api.requests_served,
                    "veles_rest_pending": api._pending}) \
                    + render_firing()
                bytes_reply(self, 200, text.encode(),
                            METRICS_CONTENT_TYPE)

            def do_POST(self):
                if self.path != api.path:
                    self.send_error(404)
                    return
                try:
                    fire_fault("serve.request")
                except FaultInjected as e:
                    # an injected serving fault DEGRADES (shed +
                    # Retry-After, counted), never crashes the handler
                    from .serving.scheduler import new_request_id
                    health.shed(self, retry_after=1.0, reason=str(e),
                                request_id=new_request_id())
                    return
                with api._pending_lock:
                    if api._pending >= api.max_pending:
                        from .serving.scheduler import new_request_id
                        health.shed(
                            self, retry_after=1.0,
                            reason="%d requests in flight (bound %d)"
                            % (api._pending, api.max_pending),
                            request_id=new_request_id())
                        return
                    api._pending += 1
                try:
                    self._serve()
                finally:
                    with api._pending_lock:
                        api._pending -= 1

            def _serve(self):
                try:
                    body = read_json_object(self)
                    # the LOADER owns its wire format (image loaders
                    # decode base64 payloads; the base reads "input")
                    sample = api.loader.parse_request(body)
                except (ValueError, KeyError, VelesError) as e:
                    # client-fault only — a server-side bug (missing
                    # parse_request, broken override) must surface as
                    # a 5xx, not masquerade as a bad request
                    self._reply(400, {"error": "bad request: %s" % e})
                    return
                ticket = _Ticket()
                try:
                    api.loader.feed(sample, ticket=ticket)
                except VelesError as e:
                    from .loader.stream import LoaderClosed
                    # shape rejection is the CLIENT's fault; a closed
                    # loader is the server shutting down
                    code = 503 if isinstance(e, LoaderClosed) else 400
                    self._reply(code, {"error": str(e)})
                    return
                except Exception as e:
                    self._reply(503, {"error": str(e)})
                    return
                with api._pending_lock:
                    api._outstanding.add(ticket)
                try:
                    settled = ticket.event.wait(api.request_timeout)
                finally:
                    with api._pending_lock:
                        api._outstanding.discard(ticket)
                if not settled:
                    self._reply(504, {"error": "inference timed out",
                                      "request_id": ticket.request_id})
                    return
                if ticket.error is not None:
                    headers = None
                    if ticket.retry_after:
                        headers = {"Retry-After": str(max(1, int(
                            ticket.retry_after)))}
                    json_reply(self, ticket.code,
                               ticket.error_payload(), headers=headers)
                    return
                self._reply(200, {"result": ticket.result,
                                  "request_id": ticket.request_id})

            def _reply(self, code: int, payload: Dict[str, Any]):
                json_reply(self, code, payload)

        self._service = HTTPService(Handler, self.port,
                                    self.name + ".http")
        self.port = self._service.port
        self._service.start_serving()
        # watchtower sampler (telemetry/timeseries.py): a no-op config
        # read unless root.common.telemetry.watch.enabled
        from .telemetry import timeseries
        timeseries.add_gauge_provider(
            "rest.%s" % self.name,
            lambda: {"veles_rest_requests_served": self.requests_served,
                     "veles_rest_pending": self._pending})
        timeseries.maybe_start()
        health.mark_ready("rest.%s" % self.name)
        health.heartbeats.beat("rest.%s" % self.name)
        self.info("%s: REST API on http://127.0.0.1:%d%s", self.name,
                  self.port, self.path)
        return None

    # -- graph side ---------------------------------------------------------
    def run(self) -> None:
        # the serving loop's liveness beat: a stuck forward stops this
        # aging and /healthz flips unhealthy
        health.heartbeats.beat("rest.%s" % self.name)
        tickets = list(getattr(self.loader, "current_tickets", ()))
        real = [(i, t) for i, t in enumerate(tickets)
                if isinstance(t, _Ticket)]
        if not real:
            return      # samples came from somewhere else (e.g. warm-up)
        try:
            out = self.input
            if out is None:
                raise VelesError("%s: no forward output linked" % self.name)
            if hasattr(out, "map_read"):
                out = out.map_read()
            out = numpy.asarray(out)
            # the linked output's FIRST axis is minibatch rows (the
            # serving wiring links the batched forward output): row i
            # answers ticket i — also when each row is a scalar
            # (ndim==1), where returning the whole vector would leak
            # every client's result to every client. Terminals go
            # through succeed()/fail() — first-terminal exactly-once,
            # histograms + flight events recorded — never a bare
            # result/event poke a shutdown sweep could double-settle.
            served = 0
            for i, ticket in real:
                ticket.mark_admitted()
                if ticket.succeed(numpy.asarray(out[i]).tolist()):
                    served += 1
            self.requests_served += served
        except Exception as e:
            for _, ticket in real:
                ticket.mark_admitted()
                ticket.fail("%s: %s" % (type(e).__name__, e), code=500)
        finally:
            self.loader.current_tickets = []

    def stop(self) -> None:
        health.forget("rest.%s" % self.name)
        from .telemetry import timeseries
        timeseries.remove_gauge_provider("rest.%s" % self.name)
        if self._service is not None:
            self._service.stop_serving()
            self._service = None
        # straggler sweep: every fed-but-unanswered ticket settles
        # through the first-terminal fail() — 503 + Retry-After +
        # request_id (error_payload), histograms/flight recorded
        # exactly once however many stop()/drain sweeps run
        with self._pending_lock:
            stragglers = list(self._outstanding)
        for ticket in stragglers:
            ticket.fail("server shutting down", code=503,
                        retry_after=5.0)


class GenerationAPI(Unit):
    """REST serving for the autoregressive generation stack: POST
    ``{"prompt": [ids], "n_new": N}`` (+ optional ``mode``:
    ``greedy`` | ``sample`` | ``speculative`` | ``beam``,
    ``temperature``, ``gamma``, ``beam``, ``seed``) →
    ``{"tokens": [...]}`` plus decode stats.

    Two decode planes serve the queue (reference equivalent:
    `veles/restful_api.py:78` + `veles/loader/restful.py:52`, which
    served one forward per request):

    - ``engine="continuous"`` (default): greedy and sample requests
      ride the continuous-batching engine (``veles_tpu/serving/``) — a
      persistent ``max_slots``-row KV-cache pool with ONE fixed-shape
      jitted decode step, prefill padded to ``buckets`` (jit cache
      bounded by len(buckets)+1 programs), iteration-level admission
      into free slots and per-row retirement at ``eos_id`` / own
      ``n_new``. Per-slot PRNG streams keep every row id-exact vs its
      solo decode, so batching never changes answers — stochastic
      decodes included. Requests the pool cannot hold (prompt longer
      than the largest bucket, context overflow) fall back to the
      window worker below.
    - ``engine="window"``: the legacy micro-batcher — a worker thread
      coalesces the queue for ``batch_window`` seconds and batches
      requests sharing an exact shape key into one
      ``sampling.generate`` / ``generate_speculative`` call.
      ``speculative`` and ``beam`` requests always take this path.

    A ticket older than its ``request_timeout`` deadline is answered
    503 + Retry-After by whichever plane dequeues it — it never sits
    in the queue past its useful life.

    Standalone service unit: not part of the Repeater loop — the
    device program IS the generation; ``initialize`` starts the HTTP
    service + worker(s), ``stop`` drains them.
    """

    MAPPING = "generation_api"
    hide_from_registry = False

    MODES = ("greedy", "sample", "speculative", "beam")

    def __init__(self, workflow, draft=None, port: int = 0,
                 path: str = "/generate", max_new: int = 512,
                 batch_window: float = 0.02,
                 request_timeout: float = 120.0,
                 max_queue: int = None, engine: str = None,
                 max_slots: int = None, buckets=None,
                 max_context: int = None,
                 decode_block: int = None,
                 page_size: int = None, pages: int = None,
                 spec_gamma: int = None, beam_width: int = None,
                 quant_weights: bool = None, quant_kv: bool = None,
                 artifact: str = None,
                 prefix_cache: bool = None,
                 prefill_chunk: int = None,
                 state_cache: bool = None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        #: the TARGET model workflow is the unit's own workflow; an
        #: optional DRAFT workflow enables mode=speculative
        self.draft = draft
        self.port = port
        self.path = path
        self.max_new = int(max_new)
        #: queue bound: requests arriving beyond it are SHED (503 +
        #: Retry-After) instead of growing the queue unboundedly
        self.max_queue = int(max_queue if max_queue is not None
                             else root.common.resilience.get(
                                 "max_queue", 256) or 256)
        self.batch_window = float(batch_window)
        self.request_timeout = float(request_timeout)
        # continuous-batching knobs (root.common.serving.* defaults —
        # see veles_tpu/serving/ and docs/services.md)
        serving_cfg = root.common.serving
        self.engine_kind = str(engine or serving_cfg.get(
            "engine", "continuous"))
        self.max_slots = int(max_slots if max_slots is not None
                             else serving_cfg.get("max_slots", 8))
        self.buckets = (buckets if buckets is not None
                        else serving_cfg.get("buckets",
                                             [16, 32, 64, 128]))
        self.max_context = int(
            max_context if max_context is not None
            else serving_cfg.get("max_context", 640))
        self.decode_block = int(
            decode_block if decode_block is not None
            else serving_cfg.get("decode_block", 1))
        # paged-pool + pooled-decode-mode knobs (None defers to
        # root.common.serving.* inside the engine; see serving/pages.py
        # and docs/services.md "Paged KV cache")
        self.page_size = page_size
        self.pages = pages
        self.spec_gamma = spec_gamma
        self.beam_width = beam_width
        # quantization / AOT-artifact policy (veles_tpu/quant/,
        # docs/services.md "Quantized serving"): None defers to
        # root.common.quant.* / root.common.serving.artifact inside
        # the engine, keeping CLI flags, config and kwargs one policy
        self.quant_weights = quant_weights
        self.quant_kv = quant_kv
        self.artifact = artifact
        # heavy-traffic request plane (docs/services.md "Prefix
        # sharing & streaming"): None defers to
        # root.common.serving.{prefix_cache,prefill_chunk} inside the
        # engine; streaming is per-request (``stream=true``), gated by
        # root.common.serving.stream
        self.prefix_cache = prefix_cache
        self.prefill_chunk = prefill_chunk
        # O(1)-state lane knob (docs/services.md "O(1)-state
        # serving"): None defers to root.common.serving.state_cache
        # inside the RecurrentEngine
        self.state_cache = state_cache
        self._engine = None
        self._service: Optional[HTTPService] = None
        #: serializes initialize()/stop(): a supervisor respawning a
        #: replica whose injected death is still tearing down must
        #: wait for the teardown, not interleave with it (the old
        #: stop() would otherwise kill the freshly built engine)
        self._lifecycle = threading.RLock()
        self._queue: list = []
        self._cv = threading.Condition()
        self._worker: Optional[threading.Thread] = None
        self._closing = False
        #: graceful drain: admission stopped, in-flight finishing —
        #: /readyz reports "draining" while /healthz stays green
        self._draining = False
        #: requests currently inside do_POST past admission (what a
        #: drain waits on before tearing the service down)
        self._inflight = 0
        self._uniq = 0
        self.requests_served = 0
        self.batches_run = 0
        self.max_batch = 0

    # -- request intake ------------------------------------------------------
    def _parse(self, body: Dict[str, Any]) -> Dict[str, Any]:
        prompt = body.get("prompt")
        if (not isinstance(prompt, list) or not prompt
                or not all(isinstance(t, int) for t in prompt)):
            raise ValueError("'prompt' must be a non-empty list of "
                             "token ids")
        n_new = body.get("n_new", 16)
        if not isinstance(n_new, int) or not 1 <= n_new <= self.max_new:
            raise ValueError("'n_new' must be an int in [1, %d]"
                             % self.max_new)
        mode = body.get("mode", "greedy")
        if mode not in self.MODES:
            raise ValueError("'mode' must be one of %s" % (self.MODES,))
        if mode == "speculative" and self.draft is None:
            raise ValueError("mode=speculative needs a draft model "
                             "configured on the server")
        # gamma/beam default to the ENGINE's fixed shapes, so a client
        # that omits them lands on the pooled plane whatever
        # --serve-spec-gamma/--serve-beam-width the server runs with
        # (a hard 4 would silently route such requests to the window
        # worker on any non-default server); without an engine the
        # window plane serves any width, 4 stays the wire default
        engine = self._engine
        try:
            temperature = float(body.get("temperature", 0.0))
            seed = int(body.get("seed", 0))
            gamma = int(body.get(
                "gamma", engine.spec_gamma if engine is not None
                else 4))
            beam = int(body.get(
                "beam", engine.beam_width if engine is not None
                else 4))
        except (TypeError, ValueError) as e:
            # float(None)/int({}) raise TypeError — it must surface as
            # a 400, not escape the handler as an unanswered traceback
            raise ValueError("non-numeric knob: %s" % e) from None
        if mode == "greedy":
            temperature = 0.0
        elif mode == "sample" and temperature <= 0:
            raise ValueError("mode=sample needs temperature > 0")
        eos_id = body.get("eos_id")
        if eos_id is not None and (isinstance(eos_id, bool)
                                   or not isinstance(eos_id, int)):
            # bool IS an int in python — JSON true/false must not pass
            # as token ids 1/0
            raise ValueError("'eos_id' must be an int token id")
        # a fleet router retrying a request on another replica sends
        # ITS id along — the ticket adopts it so every response body
        # (success, shed, expiry) correlates with the router's attempt
        request_id = body.get("request_id")
        if request_id is not None and (
                not isinstance(request_id, str)
                or not 1 <= len(request_id) <= 200):
            raise ValueError("'request_id' must be a non-empty string "
                             "of at most 200 chars")
        # fleet tracing (docs/observability.md "Fleet tracing"): the
        # router also forwards the trace_id it minted at admission and
        # the 1-based attempt number — the ticket adopts both, so this
        # replica's request spans and flight events stitch into the
        # router's route.attempt bracket in a merged fleet trace
        trace_id = body.get("trace_id")
        if trace_id is not None and (
                not isinstance(trace_id, str)
                or not 1 <= len(trace_id) <= 200):
            raise ValueError("'trace_id' must be a non-empty string "
                             "of at most 200 chars")
        attempt = body.get("attempt", 1)
        if isinstance(attempt, bool) or not isinstance(attempt, int) \
                or attempt < 1:
            raise ValueError("'attempt' must be an int >= 1")
        # token-level failover resume (docs/services.md "Lossless
        # request plane"): a retry of a died-mid-decode request
        # carries the tokens already emitted; they fold into the
        # prompt (re-prefilled in one bucketed pass — never
        # re-decoded) and n_new is the REMAINING budget. resume_k
        # tells the engine how far to advance the request's per-slot
        # PRNG stream so sampled resumes stay id-exact.
        resume_tokens = body.get("resume_tokens")
        if resume_tokens is not None:
            if (not isinstance(resume_tokens, list)
                    or not all(isinstance(t, int)
                               and not isinstance(t, bool)
                               for t in resume_tokens)):
                raise ValueError("'resume_tokens' must be a list of "
                                 "int token ids")
            if mode not in ("greedy", "sample"):
                raise ValueError(
                    "resume_tokens serve mode=greedy/sample only "
                    "(speculative/beam retries restart from scratch)")
        resume_tokens = [int(t) for t in (resume_tokens or ())]
        # token streaming (docs/services.md "Prefix sharing &
        # streaming"): stream=true answers with SSE events at step
        # boundaries instead of one buffered body. The knob
        # root.common.serving.stream (default on) can force buffered
        # answers fleet-wide without clients changing their requests.
        stream = body.get("stream", False)
        if not isinstance(stream, bool):
            raise ValueError("'stream' must be a boolean")
        if stream and not bool(root.common.serving.get("stream", True)):
            stream = False
        # QoS class + deadline (docs/services.md "Overload & QoS"):
        # unlabeled requests are interactive (batch is OPT-IN to
        # throttling/preemption); deadline_ms replaces the global
        # request_timeout for this request's queue sweep and handler
        # wait, capped by it — a client can only tighten
        from .serving.overload import QOS_PRIORITIES
        priority = body.get("priority", "interactive")
        if priority not in QOS_PRIORITIES:
            raise ValueError("'priority' must be one of %s"
                             % (QOS_PRIORITIES,))
        deadline_ms = body.get("deadline_ms")
        if deadline_ms is not None:
            if isinstance(deadline_ms, bool) \
                    or not isinstance(deadline_ms, (int, float)) \
                    or deadline_ms <= 0:
                raise ValueError("'deadline_ms' must be a positive "
                                 "number of milliseconds")
            deadline_ms = float(deadline_ms)
        req = {"prompt": [int(t) for t in prompt] + resume_tokens,
               "n_new": n_new, "resume_k": len(resume_tokens),
               "mode": mode, "temperature": temperature, "seed": seed,
               "gamma": gamma, "beam": beam, "eos_id": eos_id,
               "request_id": request_id, "trace_id": trace_id,
               "attempt": attempt, "stream": stream,
               "priority": priority, "deadline_ms": deadline_ms}
        if req["gamma"] < 1:
            raise ValueError("'gamma' must be >= 1")
        if req["beam"] < 1:
            raise ValueError("'beam' must be >= 1")
        if req["temperature"] > 0 and mode == "speculative":
            # stochastic SPECULATIVE decodes are never coalesced: the
            # rejection-sampling accept path draws batch-shaped noise,
            # so a request's tokens would depend on which strangers
            # arrived with it — seed determinism wins over batching
            # there. mode=sample HAS no such dependence any more:
            # sampling.generate draws per-row PRNG streams (a row's
            # noise is a pure function of its own seed), so sample
            # requests sharing a shape key batch exactly like greedy,
            # id-exact vs their solo decodes.
            with self._cv:
                self._uniq += 1
                req["_solo"] = self._uniq
        return req

    @staticmethod
    def _batch_key(req):
        """Requests sharing this key ride one batched decode — greedy,
        temperature-0 speculative AND mode=sample (per-row PRNG
        streams in sampling.generate make every row bit-identical to
        its solo decode, so batching never changes answers). Only
        stochastic speculative requests carry a unique _solo tag (see
        _parse) and form singleton groups."""
        return (req["mode"], len(req["prompt"]), req["n_new"],
                req["temperature"], req["gamma"], req["seed"],
                req.get("_solo"))

    # -- worker --------------------------------------------------------------
    @staticmethod
    def _trim_eos(tokens, eos_id):
        """Host-side stop-token truncation (through the first eos_id,
        inclusive): the decode itself runs the requested n_new — fixed
        shapes keep the compiled program shared — so per-request eos
        never fragments a batch and costs nothing device-side."""
        if eos_id is None:
            return list(tokens)
        out = []
        for t in tokens:
            out.append(t)
            if t == eos_id:
                break
        return out

    def _serve_group(self, reqs, tickets) -> None:
        from .nn import beam as beam_mod
        from .nn import sampling
        from .nn.speculative import generate_speculative
        mode = reqs[0]["mode"]
        try:
            if mode == "beam":
                # single-sequence search; stays per-request (beam has
                # NATIVE eos handling — frozen hypotheses)
                for req, ticket in zip(reqs, tickets):
                    toks, stats = beam_mod.beam_generate(
                        self.workflow, req["prompt"], req["n_new"],
                        beam=req["beam"], eos_id=req["eos_id"])
                    ticket.succeed(
                        {"tokens": [int(t) for t in toks],
                         "scores": [float(s) for s in
                                    stats["scores"]]})
                return
            prompts = [req["prompt"] for req in reqs]
            if mode == "speculative":
                rows, stats = generate_speculative(
                    self.workflow, self.draft, prompts,
                    reqs[0]["n_new"], gamma=reqs[0]["gamma"],
                    temperature=reqs[0]["temperature"],
                    seed=reqs[0]["seed"])
                for i, (req, ticket) in enumerate(zip(reqs, tickets)):
                    ticket.succeed({
                        "tokens": self._trim_eos(rows[i],
                                                 req["eos_id"]),
                        "acceptance": stats["acceptance"][i],
                        "rounds": stats["rounds"][i],
                        "batched_with": len(reqs) - 1})
                return
            rows = sampling.generate(
                self.workflow, prompts, reqs[0]["n_new"],
                temperature=reqs[0]["temperature"],
                seed=reqs[0]["seed"])
            for i, (req, ticket) in enumerate(zip(reqs, tickets)):
                ticket.succeed({
                    "tokens": self._trim_eos(rows[i], req["eos_id"]),
                    "batched_with": len(reqs) - 1})
        except Exception as e:        # noqa: BLE001 — answer, don't die
            # decoder-raised ValueError/VelesError on a parsed request
            # is the CLIENT's shape problem (beam > vocab, generation
            # past the positional table) — 400, not a server fault
            code = 400 if isinstance(e, (ValueError, VelesError)) \
                else 500
            for ticket in tickets:
                ticket.fail("%s: %s" % (type(e).__name__, e),
                            code=code)

    def _worker_loop(self) -> None:
        hb_name = "serve.%s" % self.name
        try:
            self._worker_iterations(hb_name)
        finally:
            # the worker's own exit drops its beat — a late beat after
            # stop()'s forget() must not leave an entry that ages into
            # a permanent /healthz failure
            health.heartbeats.unregister(hb_name)

    def _worker_iterations(self, hb_name: str) -> None:
        while True:
            if not self._closing:
                health.heartbeats.beat(hb_name)
            with self._cv:
                while not self._queue and not self._closing:
                    # bounded wait so the idle worker still beats the
                    # health registry (liveness, not just progress)
                    self._cv.wait(timeout=10.0)
                    if not self._closing:
                        health.heartbeats.beat(hb_name)
                if self._closing and not self._queue:
                    return
            # coalesce: let near-simultaneous requests join the batch
            if self.batch_window > 0:
                import time as _time
                _time.sleep(self.batch_window)
            with self._cv:
                pending, self._queue = self._queue, []
            # request_timeout holds while QUEUED, not just while
            # decoding: a ticket past its deadline is answered 503 +
            # Retry-After now, instead of burning a decode nobody is
            # waiting for (its handler would time out mid-batch) —
            # the same expiry answer the continuous engine gives
            pending, expired = split_expired(pending)
            shed_expired(expired)
            # queue exit is the window plane's admission boundary —
            # the queue-wait histogram sample for the live tickets
            # (expired ones above recorded their full wait instead)
            for _req, _ticket in pending:
                _ticket.mark_admitted()
            groups: Dict[Any, list] = {}
            for req, ticket in pending:
                groups.setdefault(self._batch_key(req),
                                  []).append((req, ticket))
            for group in groups.values():
                reqs = [r for r, _ in group]
                tickets = [t for _, t in group]
                self._serve_group(reqs, tickets)
                with self._cv:
                    self.batches_run += 1
                    self.max_batch = max(self.max_batch, len(reqs))
                    self.requests_served += len(reqs)

    # -- lifecycle -----------------------------------------------------------
    def _build_recurrent_engine(self):
        """Start the O(1)-state slot pool (serving/recurrent.py) for
        this API's workflow — raises :class:`VelesError` when the
        stack is not a recurrent LM chain (callers degrade)."""
        from .serving import RecurrentEngine
        engine = RecurrentEngine(
            self.workflow, max_slots=self.max_slots,
            max_context=self.max_context,
            decode_block=self.decode_block,
            page_size=self.page_size,
            state_cache=self.state_cache,
            artifact=self.artifact,
            name=self.name).start()
        engine.on_death = self._on_replica_death
        return engine

    def _metrics_gauges(self) -> Dict[str, Any]:
        """Gauge dict behind ``GET /metrics`` — also registered as this
        replica's watchtower gauge provider (telemetry/timeseries.py),
        so the sampled series and the scrape surface cannot drift."""
        gauges = {
            "veles_generate_requests_served": self.requests_served,
            "veles_generate_batches_run": self.batches_run,
            "veles_generate_max_batch": self.max_batch,
            "veles_generate_queue_depth": len(self._queue),
            "veles_generate_queue_bound": self.max_queue,
        }
        engine = self._engine          # stop() may null it mid-read
        if engine is not None:
            # continuous-batching occupancy (the gauges an operator
            # sizes max_slots/buckets with; the web_status surface
            # serves the same names suffixed _<engine-name> — this
            # port has ONE engine, so no suffix)
            st = engine.stats()
            gauges.update({
                "veles_serving_slots": st["slots"],
                "veles_serving_slots_busy": st["slots_busy"],
                "veles_serving_peak_slots": st["peak_slots"],
                "veles_serving_queue_depth": st["queue_depth"],
                "veles_serving_programs": st["programs"],
                # quantization/AOT mode gauges (veles_tpu/quant/):
                # 1 = the plane is active on this engine — dashboards
                # must know whether a throughput number is fp or int8,
                # live jit or artifact
                "veles_serving_artifact_mode": st["artifact_mode"],
                "veles_quant_weights_mode": st["quant_weights"],
                "veles_quant_kv_mode": st["quant_kv"],
                "veles_serving_kv_pool_bytes": st["kv_pool_bytes"],
                # prefix sharing & chunked prefill (docs/services.md
                # "Prefix sharing & streaming"): index occupancy and
                # the per-tick decode stall chunking bounds
                "veles_prefix_cache_enabled": st["prefix_cache"],
                "veles_prefix_cached_blocks": st["prefix_blocks"],
                "veles_serving_prefilling": st["prefilling"],
                "veles_serving_prefill_stall_seconds":
                    st["prefill_stall_seconds"],
                # mesh-slice width this replica spans (1 = solo chip).
                # fleet.merge folds it into veles_fleet_chips instead
                # of the generic gauge sum — N chips must never read
                # as N replicas in the fleet roll-up
                "veles_serving_tp": st.get("tp", 1),
            })
            if st.get("slot_kind", "paged") != "state":
                # paged-pool occupancy (serving/pages.py): the gauges
                # an operator sizes pages/page_size with —
                # fragmentation is the allocated-but-unoccupied
                # fraction of in-use pages (tail-of-page waste).
                # Rendered ONLY for paged engines: a pageless
                # O(1)-state replica must never put zero rows into
                # the fleet's page math
                gauges.update({
                    "veles_serving_pages_total": st["pages_total"],
                    "veles_serving_pages_in_use": st["pages_in_use"],
                    "veles_serving_page_size": st["page_size"],
                    "veles_serving_page_fragmentation":
                        st["page_fragmentation"],
                })
            else:
                # O(1)-state lane occupancy (serving/recurrent.py):
                # per-slot state HBM is CONSTANT in sequence length —
                # the gauges an operator sizes max_slots and the
                # state-cache budget with
                gauges.update({
                    "veles_o1_state_bytes_per_slot":
                        st["state_bytes_per_slot"],
                    "veles_o1_state_cache_blocks":
                        st["state_cache_blocks"],
                    "veles_o1_state_cache_bytes":
                        st["state_cache_bytes"],
                    "veles_o1_checkpoint_interval": st["page_size"],
                })
        # elastic training plane (resilience/elastic.py): generation/
        # world-size gauges ride this surface too (a training host can
        # serve status while elastic) — no rows while the plane is off
        from .resilience import elastic as _elastic
        gauges.update(_elastic.gauges())
        return gauges

    def initialize(self, **kwargs):
        with self._lifecycle:
            return self._initialize_locked(**kwargs)

    def _initialize_locked(self, **kwargs):
        res = super().initialize(**kwargs)
        if res:
            return res
        if self._service is not None:
            return None
        if self.engine_kind == "recurrent" and self._engine is None:
            # operator pinned the O(1)-state lane: a non-recurrent
            # stack degrades to the window worker (same answers, no
            # in-flight batching) exactly like the continuous path's
            # VelesError degrade; geometry ValueErrors still propagate
            try:
                self._engine = self._build_recurrent_engine()
            except VelesError as e:
                self.warning("%s: O(1)-state serving unavailable "
                             "(%s); serving via the window worker",
                             self.name, e)
                self._engine = None
        if self.engine_kind == "continuous" and self._engine is None:
            from .serving import ContinuousEngine
            try:
                self._engine = ContinuousEngine(
                    self.workflow, max_slots=self.max_slots,
                    buckets=self.buckets,
                    max_context=self.max_context,
                    decode_block=self.decode_block,
                    page_size=self.page_size, pages=self.pages,
                    spec_gamma=self.spec_gamma,
                    beam_width=self.beam_width,
                    draft=self.draft,
                    quant_weights=self.quant_weights,
                    quant_kv=self.quant_kv,
                    artifact=self.artifact,
                    prefix_cache=self.prefix_cache,
                    prefill_chunk=self.prefill_chunk,
                    name=self.name).start()
                # the engine-side serve.replica_death site (fired per
                # decode tick) settles the in-flight tickets with
                # resume progress, then this hook tears the HTTP
                # front down — on its own thread: the tick thread
                # must not join itself through engine.stop()
                self._engine.on_death = self._on_replica_death
            except VelesError as e:
                # a stack the paged pool cannot serve may still be a
                # recurrent LM (Embedding → LSTM/SSM → LMHead): try
                # the O(1)-state slot pool before degrading to the
                # window worker — same request plane, pageless slots.
                # Knob-geometry mistakes (bucket > max_context,
                # max_slots < 1) raise ValueError and PROPAGATE: the
                # operator asked for slot-pool batching and must not
                # silently get the per-shape-compiling worker instead.
                try:
                    self._engine = self._build_recurrent_engine()
                    self.info("%s: recurrent stack (paged pool said: "
                              "%s); serving via the O(1)-state slot "
                              "pool", self.name, e)
                except VelesError:
                    self.warning("%s: continuous batching unavailable "
                                 "(%s); serving via the window worker",
                                 self.name, e)
                    self._engine = None
        api = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                api.debug("http: " + fmt, *args)

            def do_GET(self):
                if health.handle_health(self, self.path):
                    return
                if handle_trace_spans(self, self.path,
                                      name="serve.%s" % api.name):
                    return
                if handle_metrics_history(self, self.path,
                                          name="serve.%s" % api.name):
                    return
                if handle_alerts(self, self.path):
                    return
                if self.path == "/metrics":
                    # Prometheus scrape surface (telemetry counters —
                    # the structured successor of the /stats dict; the
                    # decode dispatch/token counters land here from
                    # nn/sampling.py + nn/speculative.py), plus this
                    # unit's serving gauges
                    from .telemetry.alerts import render_firing
                    from .telemetry.counters import (
                        METRICS_CONTENT_TYPE, metrics_text)
                    text = metrics_text(api._metrics_gauges()) \
                        + render_firing()
                    bytes_reply(self, 200, text.encode(),
                                METRICS_CONTENT_TYPE)
                    return
                # legacy ops surface: the micro-batcher's effectiveness
                # as one JSON dict (predates /metrics; kept for
                # dashboards that already read it)
                if self.path != api.path + "/stats":
                    self.send_error(404)
                    return
                engine = api._engine       # stop() may null it mid-GET
                stats = {
                    "requests_served": api.requests_served,
                    "batches_run": api.batches_run,
                    "max_batch": api.max_batch,
                    "queue_depth": len(api._queue),
                    "speculative_enabled": api.draft is not None,
                    "engine": ("continuous" if engine is not None
                               else "window"),
                    "modes": list(api.MODES)}
                if engine is not None:
                    stats["continuous"] = engine.stats()
                json_reply(self, 200, stats)

            def do_POST(self):
                if self.path == api.path + "/drain":
                    # admin face of the SIGTERM drain: flip /readyz
                    # to draining, stop admission, reply immediately —
                    # the drain itself (finish in-flight, tear down)
                    # runs on its own thread so this handler answers
                    started = api.begin_drain()
                    threading.Thread(target=api.drain, daemon=True,
                                     name=api.name + ".drain").start()
                    json_reply(self, 200, {
                        "status": "draining",
                        "already_draining": not started,
                        "in_flight": api._inflight,
                        "queue_depth": len(api._queue)})
                    return
                if self.path != api.path:
                    self.send_error(404)
                    return
                try:
                    fire_fault("serve.request")
                except FaultInjected as e:
                    # injected serving faults DEGRADE (shed + Retry-
                    # After, counted), never escape as a traceback.
                    # No ticket exists yet — mint an id so even this
                    # shed is correlatable by a router retry
                    from .serving.scheduler import new_request_id
                    health.shed(self, retry_after=1.0, reason=str(e),
                                request_id=new_request_id())
                    return
                try:
                    req = api._parse(read_json_object(self))
                except (ValueError, KeyError) as e:
                    json_reply(self, 400, {"error":
                                           "bad request: %s" % e})
                    return
                # API admission assigns the request's id (threaded
                # through lifecycle spans, flight events and the
                # response body by the Ticket itself) — unless a fleet
                # router already assigned one upstream
                # the request's own deadline (when set) replaces the
                # global request_timeout for the queue sweep AND this
                # handler's wait — capped by the global so a client
                # can only tighten, never extend
                wait_budget = api.request_timeout
                if req.get("deadline_ms"):
                    wait_budget = min(wait_budget,
                                      req["deadline_ms"] / 1000.0)
                ticket = _Ticket(
                    deadline=time.time() + wait_budget,
                    request_id=req.get("request_id"),
                    mode=req.get("mode", "greedy"),
                    trace_id=req.get("trace_id"),
                    attempt=req.get("attempt", 1),
                    stream=bool(req.get("stream")))
                if api._draining:
                    health.shed(self, retry_after=5.0,
                                reason="server draining",
                                request_id=ticket.request_id)
                    return
                engine = api._engine
                # every decode mode rides the slot pool when the
                # engine can hold it — speculative needs the pooled
                # draft + the engine's fixed gamma, beam the engine's
                # fixed width; anything else (and any geometry the
                # pool rejects) falls back to the window worker
                reject = (None if engine is None
                          else engine.accepts(req))
                via_engine = engine is not None and reject is None
                if req.get("resume_k") and not via_engine \
                        and req["mode"] != "greedy":
                    # a sampled resume re-enters a per-slot PRNG
                    # stream only the slot pool owns — the window
                    # plane cannot honor it id-exactly (greedy is
                    # deterministic and MAY ride the window plane
                    # with its folded prompt). 409 tells the router:
                    # drop the resume, retry this request from
                    # scratch.
                    json_reply(self, 409, {
                        "error": "resume not servable here (%s); "
                                 "retry without resume_tokens"
                                 % (reject or "no continuous engine"),
                        "request_id": ticket.request_id})
                    return
                if via_engine:
                    # the continuous-batching plane: admitted into a
                    # KV-cache slot at the next step boundary; a full
                    # queue sheds exactly like the window plane
                    if api._closing:
                        health.shed(self, retry_after=5.0,
                                    reason="server shutting down",
                                    request_id=ticket.request_id)
                        return
                    if not engine.submit(req, ticket,
                                         max_queue=api.max_queue,
                                         checked=True):
                        # False means queue bound OR a closing engine
                        # (stop() racing this handler) — the shutdown
                        # answer must match the api._closing path above
                        if engine.closing:
                            health.shed(self, retry_after=5.0,
                                        reason="server shutting down",
                                        request_id=ticket.request_id)
                        else:
                            health.shed(
                                self, retry_after=1.0,
                                reason="generation queue full (%d/%d)"
                                % (engine.scheduler.queue_depth(),
                                   api.max_queue),
                                request_id=ticket.request_id)
                        return
                else:
                    with api._cv:
                        if api._closing:
                            health.shed(self, retry_after=5.0,
                                        reason="server shutting down",
                                        request_id=ticket.request_id)
                            return
                        if len(api._queue) >= api.max_queue:
                            health.shed(
                                self, retry_after=1.0,
                                reason="generation queue full (%d/%d)"
                                % (len(api._queue), api.max_queue),
                                request_id=ticket.request_id)
                            return
                        api._queue.append((req, ticket))
                        api._cv.notify()
                with api._cv:
                    api._inflight += 1
                try:
                    if ticket.stream:
                        self._stream_reply(ticket, via_engine,
                                           wait_budget)
                    else:
                        self._await_and_reply(ticket, via_engine,
                                              wait_budget)
                finally:
                    with api._cv:
                        api._inflight -= 1
                        api._cv.notify_all()

            def _await_and_reply(self, ticket, via_engine,
                                 wait_budget=None):
                if wait_budget is None:
                    wait_budget = api.request_timeout
                try:
                    # the replica-death chaos point, request-path
                    # site: the request IS in flight (admitted to a
                    # plane above) when the fault fires — raise tears
                    # this replica's HTTP front down. The teardown's
                    # abort settles every in-flight ticket with its
                    # resume progress, and this handler waits for
                    # that settle to emit the DYING GASP: a 503 whose
                    # body carries {resume: {tokens, tokens_done}},
                    # the record a failover retry continues from. A
                    # teardown too wedged to settle the ticket drops
                    # the connection as before (a true SIGKILL — the
                    # retry re-decodes from scratch); crash exits the
                    # process with the slave-death code either way.
                    fire_fault("serve.replica_death")
                except FaultInjected:
                    api.warning("%s: injected replica death — tearing "
                                "down the serving front mid-request",
                                api.name)
                    threading.Thread(target=api.stop, daemon=True,
                                     name=api.name + ".death").start()
                    self.close_connection = True
                    if not ticket.event.wait(10.0) \
                            or ticket.error is None:
                        return      # wedged: the client sees a dead peer
                    json_reply(self, ticket.code,
                               ticket.error_payload(),
                               headers={"Retry-After": "1"})
                    return
                # slack past the deadline: the queue-side expiry
                # (503 + Retry-After, counted) should win the race
                # against this handler's own last-resort 504
                if not ticket.event.wait(wait_budget + 1.0):
                    json_reply(self, 504,
                               {"error": "generation timed out",
                                "request_id": ticket.request_id})
                    return
                if via_engine and not (ticket.error is not None
                                       and ticket.code == 503):
                    # the window worker counts requests its batches
                    # actually decoded — decode errors included, but
                    # never 503 sheds/expiries (those are answered
                    # before any batch runs); engine answers are
                    # tallied here on the same terms so /stats compares
                    # the planes like for like. Handler threads run
                    # concurrently — the += must not lose updates
                    # against them or the worker.
                    with api._cv:
                        api.requests_served += 1
                if ticket.error is not None:
                    headers = None
                    # pressure-scaled backoff hint (no-op with QoS
                    # off: the hint equals the stamped value then)
                    retry_after = ticket.retry_after_hint()
                    if retry_after:
                        import math as _math
                        headers = {"Retry-After": str(max(1, int(
                            _math.ceil(retry_after))))}
                    json_reply(self, ticket.code,
                               ticket.error_payload(),
                               headers=headers)
                    return
                json_reply(self, 200, ticket.result)

            def _stream_reply(self, ticket, via_engine,
                              wait_budget=None):
                """``stream=true``: chunked-transfer SSE — one
                ``data: {tokens, i}`` event per step boundary (the
                engine pushes at chunk ends; window-plane requests
                burst once at completion) and a terminal
                ``data: {done: true, ...}`` event carrying the full
                result (success) or ``error_payload()`` (failure —
                resume progress included, so a router proxying this
                stream re-streams only the remainder after a replica
                death)."""
                if wait_budget is None:
                    wait_budget = api.request_timeout
                import queue as _q
                try:
                    # the replica-death chaos point, request-path
                    # site — same contract as the buffered path: the
                    # teardown's abort settles the ticket with resume
                    # progress, and the gasp goes out as the only
                    # (terminal) event of the stream
                    fire_fault("serve.replica_death")
                except FaultInjected:
                    api.warning("%s: injected replica death — tearing "
                                "down the serving front mid-request",
                                api.name)
                    threading.Thread(target=api.stop, daemon=True,
                                     name=api.name + ".death").start()
                    self.close_connection = True
                    if not ticket.event.wait(10.0) \
                            or ticket.error is None:
                        return
                    json_reply(self, ticket.code,
                               ticket.error_payload(),
                               headers={"Retry-After": "1"})
                    return
                from ._http import sse_event, sse_headers
                sse_headers(self)

                def event(payload):
                    sse_event(self, payload)

                sent = 0
                deadline = time.time() + wait_budget + 1.0
                try:
                    while True:
                        budget = deadline - time.time()
                        if budget <= 0:
                            event({"done": True, "code": 504,
                                   "error": "generation timed out",
                                   "request_id": ticket.request_id})
                            return
                        try:
                            item = ticket.next_stream_item(
                                timeout=min(budget, 2.0))
                        except _q.Empty:
                            continue
                        if item is None:
                            break
                        event({"tokens": item, "i": sent,
                               "request_id": ticket.request_id})
                        sent += len(item)
                    # /stats parity with the buffered path: count
                    # every via-engine terminal the batch actually
                    # decoded — decode errors included, never 503
                    # sheds/expiries
                    if via_engine and not (ticket.error is not None
                                           and ticket.code == 503):
                        with api._cv:
                            api.requests_served += 1
                    if ticket.error is not None:
                        event(dict(ticket.error_payload(),
                                   done=True, code=ticket.code))
                        return
                    result = ticket.result if isinstance(
                        ticket.result, dict) else {
                            "tokens": list(ticket.result or ())}
                    # window-plane (and early-retired) tokens the
                    # step-boundary pushes never covered burst out
                    # before the terminal event
                    tail = list(result.get("tokens") or ())[sent:]
                    if tail:
                        event({"tokens": tail, "i": sent,
                               "request_id": ticket.request_id})
                    event(dict(result, done=True))
                except (BrokenPipeError, ConnectionResetError,
                        OSError):
                    # client went away mid-stream: the decode settles
                    # the ticket on its own; nothing to answer
                    api.debug("%s: streaming client disconnected "
                              "(%s)", api.name, ticket.request_id)

        self._closing = False
        self._draining = False
        self._inflight = 0
        self._worker = threading.Thread(target=self._worker_loop,
                                        daemon=True,
                                        name=self.name + ".genworker")
        self._worker.start()
        self._service = HTTPService(Handler, self.port,
                                    self.name + ".http")
        self.port = self._service.port
        self._service.start_serving()
        # watchtower sampler (telemetry/timeseries.py): a no-op config
        # read unless root.common.telemetry.watch.enabled — the
        # provider shares _metrics_gauges with /metrics, so the ring
        # records exactly what a scrape would have seen
        from .telemetry import timeseries
        timeseries.add_gauge_provider("serve.%s" % self.name,
                                      self._metrics_gauges)
        timeseries.maybe_start()
        # a tensor-parallel engine publishes its mesh-slice shape on
        # /readyz so a fleet router learns replica = N-chip slice from
        # the probe it already makes (router.py folds it into
        # veles_router_chips; the replica count stays per-slice)
        if getattr(self._engine, "tp", 1) > 1:
            health.set_info("tp", {"devices": int(self._engine.tp),
                                   "axis": "model"})
        health.mark_ready("serve.%s" % self.name)
        self.info("%s: generation API on http://127.0.0.1:%d%s "
                  "(modes: %s%s)", self.name, self.port, self.path,
                  "/".join(self.MODES),
                  "" if self.draft is not None else "; no draft — "
                  "speculative disabled")
        return None

    def run(self) -> None:
        """Standalone service: nothing to do per graph pass."""

    # -- graceful drain ------------------------------------------------------
    def begin_drain(self) -> bool:
        """Stop admission and flip ``/readyz`` to draining (the load
        balancer's cue to spill elsewhere) while in-flight tickets
        keep decoding; ``/healthz`` stays green throughout. True when
        this call started the drain, False when one was already under
        way. The actual wait + teardown is :meth:`drain`."""
        with self._cv:
            if self._draining:
                return False
            self._draining = True
        health.mark_draining("serve.%s" % self.name)
        self.info("%s: draining — admission stopped, %d in flight",
                  self.name, self._inflight)
        return True

    def _on_replica_death(self) -> None:
        """Engine-tick ``serve.replica_death`` hook: the engine has
        already settled every in-flight ticket with its resume
        progress (the dying gasp the waiting handlers reply with);
        tear the front down on a fresh thread — never the tick
        thread, which ``engine.stop()`` would join into itself."""
        threading.Thread(target=self.stop, daemon=True,
                         name=self.name + ".death").start()

    def drain(self, grace: Optional[float] = None,
              handoff: Optional[bool] = None) -> bool:
        """SIGTERM-grade graceful shutdown: :meth:`begin_drain`, then
        — with ``handoff`` (default
        ``root.common.serving.drain_handoff`` = True) — the engine
        HANDS BACK every in-flight request at the next step boundary:
        each ticket settles 503 + Retry-After with its emitted-token
        prefix attached, so a fleet router re-dispatches it elsewhere
        with ``resume_tokens`` and the drain's latency is bounded by
        a step boundary plus the handlers' replies — never by the
        longest co-tenant generation. Window-plane stragglers (and
        ``handoff=False`` drains) wait out up to ``grace`` seconds
        (default ``root.common.serving.drain_grace`` = 30) before
        ``stop()`` aborts them through the same first-terminal
        ``fail()`` path (503 + resume progress, counted once). True
        when nothing was still in flight at teardown."""
        self.begin_drain()
        if handoff is None:
            handoff = bool(root.common.serving.get("drain_handoff",
                                                   True))
        if handoff and self._engine is not None:
            handed = self._engine.handoff()
            if handed:
                self.info("%s: drain handed %d in-flight request(s) "
                          "back with resume progress", self.name,
                          handed)
        if grace is None:
            # no falsy-zero rewrite: drain_grace = 0 legitimately
            # means "abort stragglers immediately"
            grace = float(root.common.serving.get("drain_grace", 30.0))
        deadline = time.time() + grace
        with self._cv:
            while self._inflight and time.time() < deadline:
                self._cv.wait(timeout=min(
                    0.2, max(0.01, deadline - time.time())))
            drained = self._inflight == 0
        self.info("%s: drain %s (%d still in flight)", self.name,
                  "complete" if drained else "grace expired",
                  self._inflight)
        self.stop()
        return drained

    def stop(self) -> None:
        with self._lifecycle:
            from .telemetry import timeseries
            timeseries.remove_gauge_provider("serve.%s" % self.name)
            if self._service is not None:
                self._service.stop_serving()
                self._service = None
            with self._cv:
                self._closing = True
                self._cv.notify_all()
            if self._worker is not None:
                self._worker.join(timeout=5)
                self._worker = None
            if self._engine is not None:
                if getattr(self._engine, "tp", 1) > 1:
                    health.set_info("tp")
                self._engine.stop()
                self._engine = None
            # after the worker is down — its beats must not
            # re-register a heartbeat that would age out on a
            # long-lived process
            health.forget("serve.%s" % self.name)
