"""RESTful serving: HTTP POST a sample, get the model's answer.

Equivalent of the reference's veles/restful_api.py:78 (RESTfulAPI unit:
twisted Site; POST /api JSON → RestfulLoader feed → workflow run in test
mode → JSON result). Stdlib ``http.server`` replaces twisted (not in this
environment); the serving workflow itself is the same shape: a Repeater
loop of RestfulLoader → forwards → RESTfulAPI, where this unit runs after
the forwards each pass and answers the HTTP request that fed the sample.

The HTTP thread and the workflow thread meet through per-request tickets:
the handler feeds (sample, ticket) to the loader and blocks on the
ticket's event; this unit's ``run()`` fills the ticket from the forward
output and sets the event.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler
from typing import Any, Dict, Optional

import numpy

from ._http import HTTPService, json_reply, read_json_object
from .error import VelesError
from .units import Unit


class _Ticket:
    __slots__ = ("event", "result", "error")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.result: Any = None
        self.error: Optional[str] = None


class RESTfulAPI(Unit):
    """Serving endpoint unit (reference: veles/restful_api.py:78).

    Wire into a forward workflow:
        api = RESTfulAPI(wf, port=8080, loader=rest_loader)
        api.link_attrs(last_forward, ("input", "output"))
        api.link_from(last_forward); repeater.link_from(api)
    """

    MAPPING = "restful_api"
    hide_from_registry = False

    def __init__(self, workflow, loader=None, port: int = 0,
                 path: str = "/api", request_timeout: float = 60.0,
                 **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.loader = loader
        self.port = port
        self.path = path
        self.request_timeout = request_timeout
        #: forward output to answer from (link_attrs from the last forward)
        self.input = None
        self._service: Optional[HTTPService] = None
        self.requests_served = 0
        self.demand("loader")

    # -- lifecycle ----------------------------------------------------------
    def initialize(self, **kwargs):
        res = super().initialize(**kwargs)
        if res:
            return res
        if self._service is not None:
            return None
        api = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route into our logger
                api.debug("http: " + fmt, *args)

            def do_POST(self):
                if self.path != api.path:
                    self.send_error(404)
                    return
                try:
                    body = read_json_object(self)
                    # the LOADER owns its wire format (image loaders
                    # decode base64 payloads; the base reads "input")
                    sample = api.loader.parse_request(body)
                except (ValueError, KeyError, VelesError) as e:
                    # client-fault only — a server-side bug (missing
                    # parse_request, broken override) must surface as
                    # a 5xx, not masquerade as a bad request
                    self._reply(400, {"error": "bad request: %s" % e})
                    return
                ticket = _Ticket()
                try:
                    api.loader.feed(sample, ticket=ticket)
                except VelesError as e:
                    from .loader.stream import LoaderClosed
                    # shape rejection is the CLIENT's fault; a closed
                    # loader is the server shutting down
                    code = 503 if isinstance(e, LoaderClosed) else 400
                    self._reply(code, {"error": str(e)})
                    return
                except Exception as e:
                    self._reply(503, {"error": str(e)})
                    return
                if not ticket.event.wait(api.request_timeout):
                    self._reply(504, {"error": "inference timed out"})
                    return
                if ticket.error is not None:
                    self._reply(500, {"error": ticket.error})
                    return
                self._reply(200, {"result": ticket.result})

            def _reply(self, code: int, payload: Dict[str, Any]):
                json_reply(self, code, payload)

        self._service = HTTPService(Handler, self.port,
                                    self.name + ".http")
        self.port = self._service.port
        self._service.start_serving()
        self.info("%s: REST API on http://127.0.0.1:%d%s", self.name,
                  self.port, self.path)
        return None

    # -- graph side ---------------------------------------------------------
    def run(self) -> None:
        tickets = list(getattr(self.loader, "current_tickets", ()))
        real = [(i, t) for i, t in enumerate(tickets)
                if isinstance(t, _Ticket)]
        if not real:
            return      # samples came from somewhere else (e.g. warm-up)
        try:
            out = self.input
            if out is None:
                raise VelesError("%s: no forward output linked" % self.name)
            if hasattr(out, "map_read"):
                out = out.map_read()
            out = numpy.asarray(out)
            # the linked output's FIRST axis is minibatch rows (the
            # serving wiring links the batched forward output): row i
            # answers ticket i — also when each row is a scalar
            # (ndim==1), where returning the whole vector would leak
            # every client's result to every client
            for i, ticket in real:
                ticket.result = numpy.asarray(out[i]).tolist()
            self.requests_served += len(real)
        except Exception as e:
            for _, ticket in real:
                ticket.error = "%s: %s" % (type(e).__name__, e)
        finally:
            self.loader.current_tickets = []
            for _, ticket in real:
                ticket.event.set()

    def stop(self) -> None:
        if self._service is not None:
            self._service.stop_serving()
            self._service = None
