"""Auto-vivifying configuration tree.

TPU-era equivalent of the reference's veles/config.py:60-325: a global
attribute tree ``root`` where any ``root.a.b.c = v`` path springs into
existence, with layered overrides (site file, user file, environment,
explicit ``update()``), protected keys, and a printable/dumpable form.

Differences from the reference, by design:
- overrides come from python/JSON files and ``VELES_TPU_*`` env vars instead
  of runpy-exec'd model config files (those still work via ``update_from_file``);
- engine defaults describe the XLA/TPU backend (dtype policy, mesh axes,
  compilation cache) instead of OpenCL block sizes.
"""

from __future__ import annotations

import json
import os
import runpy
from typing import Any, Dict, Iterator, Tuple

_PROTECTED = "_protected_"


class Config:
    """A node in the auto-vivifying config tree."""

    def __init__(self, path: str = "root") -> None:
        object.__setattr__(self, "_path_", path)
        object.__setattr__(self, _PROTECTED, set())

    # -- attribute protocol -------------------------------------------------
    def __getattr__(self, name: str) -> "Config":
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        child = Config("%s.%s" % (self._path_, name))
        object.__setattr__(self, name, child)
        return child

    def __setattr__(self, name: str, value: Any) -> None:
        if name in (self._protected_set()):
            raise AttributeError(
                "config key %s.%s is protected" % (self._path_, name))
        object.__setattr__(self, name, value)

    def _protected_set(self):
        return object.__getattribute__(self, _PROTECTED)

    def protect(self, *names: str) -> None:
        """Forbid further assignment of the given child keys
        (reference: veles/config.py:79-84)."""
        self._protected_set().update(names)

    # -- collection-ish protocol -------------------------------------------
    def __contains__(self, name: str) -> bool:
        return name in self.__dict__ and not name.endswith("_")

    def _is_husk(self) -> bool:
        """True when this node holds NOTHING but (recursively) empty
        Config children — the shape mere reads auto-vivify."""
        for _k, v in self.items():
            if not (isinstance(v, Config) and v._is_husk()):
                return False
        return True

    def get(self, name: str, default: Any = None) -> Any:
        """Like dict.get — and a node vivified by mere READS counts as
        unset. ``__getattr__`` auto-vivifies (truthy) nodes, so
        ``if root.x.y.z:`` creates the whole x→y→z chain; the husk test
        recurses, or ``get("y")`` one level up would still hand back
        the all-husk subtree (the footgun guards in
        train_step/publishing existed for exactly this)."""
        if name in self:
            val = self.__dict__[name]
            if isinstance(val, Config) and val._is_husk():
                return default
            return val
        return default

    def items(self) -> Iterator[Tuple[str, Any]]:
        # insertion order preserved: mesh-axis order etc. is semantic
        for k, v in self.__dict__.items():
            if k.endswith("_") or k.startswith("_"):
                continue
            yield k, v

    def update(self, tree: Dict[str, Any] = None, **kwargs: Any) -> "Config":
        """Deep-merge a nested dict (or kwargs) into this subtree
        (reference: veles/config.py:103-133 ``Config.update``)."""
        tree = dict(tree or {})
        tree.update(kwargs)
        for k, v in tree.items():
            if isinstance(v, dict):
                getattr(self, k).update(v)
            else:
                setattr(self, k, v)
        return self

    def update_from_file(self, path: str) -> "Config":
        """Apply a .py (exec'd with ``root`` in scope, like the reference's
        runpy path, veles/__main__.py:426-472) or .json override file."""
        if path.endswith(".json"):
            with open(path, "r") as fin:
                self.update(json.load(fin))
        else:
            runpy.run_path(path, init_globals={"root": self})
        return self

    def update_from_env(self, prefix: str = "VELES_TPU_CFG_") -> "Config":
        """``VELES_TPU_CFG_ENGINE__FORCE_NUMPY=true`` → engine.force_numpy.
        Path components are separated by a DOUBLE underscore so config keys
        containing single underscores survive; the CFG_ prefix keeps
        non-config control variables (VELES_TPU_TEST, ...) out of the
        tree."""
        for key, val in os.environ.items():
            if not key.startswith(prefix):
                continue
            node = self
            *parents, leaf = key[len(prefix):].lower().split("__")
            for part in parents:
                node = getattr(node, part)
            try:
                val = json.loads(val)
            except ValueError:
                pass
            setattr(node, leaf, val)
        return self

    # -- introspection ------------------------------------------------------
    def as_dict(self) -> Dict[str, Any]:
        out = {}
        for k, v in self.items():
            out[k] = v.as_dict() if isinstance(v, Config) else v
        return out

    def print_(self, indent: int = 0, file=None) -> None:
        """Dump the tree (reference ``--dump-config``, veles/config.py:136)."""
        import sys
        file = file or sys.stdout
        for k, v in self.items():
            if isinstance(v, Config):
                print("%s%s:" % ("  " * indent, k), file=file)
                v.print_(indent + 1, file)
            else:
                print("%s%s: %r" % ("  " * indent, k, v), file=file)

    def __repr__(self) -> str:
        return "<Config %s: %s>" % (self._path_, sorted(
            k for k, _ in self.items()))


def _default_root() -> Config:
    r = Config("root")
    r.common.update({
        "dirs": {
            "cache": os.path.expanduser("~/.veles_tpu/cache"),
            "snapshots": os.path.expanduser("~/.veles_tpu/snapshots"),
            "datasets": os.path.expanduser("~/.veles_tpu/datasets"),
        },
        "engine": {
            # dtype policy: params/compute dtype (reference precision_type,
            # veles/config.py:241-248; on TPU the MXU wants bfloat16 compute)
            "precision_type": "float32",
            "compute_dtype": "bfloat16",
            "backend": "auto",       # auto | tpu | cpu | numpy
            "sync_run": False,       # block after each step (profiling aid)
            "force_numpy": False,    # run numpy oracle instead of XLA
            # pallas flash-attention kernel for the single-chip attention
            # core. True = use it when compiled on a TPU backend and the
            # shapes qualify; False = always the fused XLA reference;
            # "force" = run it even off-TPU via pallas interpret mode
            # (slow — test harness use only)
            "flash_attention": True,
            # below this sequence length the fused-XLA reference wins on
            # the MXU. "auto" (default) = the per-device MEASURED
            # crossover from the chip attn sweep (ops/autotune.py
            # flash_min_t; falls back to the v5e-measured 4096 — naive
            # 4.7 vs flash 2.9 TFLOP/s at T=2048, flash 12.6x at
            # T=8192 where naive's (T,T) scores saturate HBM,
            # docs/perf.md — until a sweep has run on this
            # device_kind); an int pins it; "force" engine mode
            # ignores the threshold entirely
            "flash_attention_min_t": "auto",
            # long-context scheme over the 'sequence' mesh axis:
            # "ring" (K/V rotation, memory-flat in T) or "ulysses"
            # (all-to-all head re-sharding; needs heads % n_seq == 0)
            "sequence_parallel": "ring",
            # persistent XLA compilation cache (replaces the reference's
            # kernel-binary tarball cache, veles/accelerated_units.py:
            # 605-673): compiled programs survive process restarts, so
            # resume/relaunch skips the 20-40 s first-compile. "" = off.
            "compilation_cache": os.path.expanduser(
                "~/.veles_tpu/cache/xla"),
            # per-device Pallas block-shape DB (ops/autotune.py — the
            # build's port of the reference's measured-per-device GEMM
            # block sizes, veles/backends.py:623-731). "auto" = reuse
            # persisted winners, sweep-and-persist on first use of an
            # unseen (device_kind, shape) on a real TPU; "reuse" =
            # lookup only; False = hard-coded defaults
            "kernel_autotune": "auto",
        },
        "mesh": {
            # logical mesh axes reserved up front (SURVEY.md §5.7/§5.8):
            # data, fsdp, tensor, sequence, expert, pipeline
            "axes": {"data": -1},    # -1 = all remaining devices
        },
        # trace.spans: telemetry span recording — honored centrally by
        # the recorder, so it covers Unit.run, workflow.run/initialize,
        # the train step and the decoders (veles_tpu/telemetry/
        # spans.py — in-memory ring + optional --trace-file JSONL; a
        # deque append per span, cheap enough to stay on by default)
        "trace": {"run": False, "timings": False, "spans": True},
        # model-health observability (veles_tpu/telemetry/tensormon.py
        # + recorder.py, docs/observability.md "Model health")
        "telemetry": {
            # in-graph tensor-statistics taps on the fused train step.
            # OFF by default: the off path is bit-identical to a build
            # without the feature (locked by tests/test_tensormon.py)
            "tensormon": {
                "enabled": False,
                # host-side observation cadence: process every Nth
                # drained sample (the device accumulators always ride
                # the existing per-epoch metric drain — zero extra
                # host syncs either way); NaN detection runs on every
                # sample regardless
                "every": 1,
                # NaN/Inf sentinel: warn | halt | snapshot_and_halt
                "nan_policy": "warn",
                # |activation| at/above this counts as saturated
                "sat_threshold": 6.0,
            },
            # flight recorder (crash black box): bounded in-memory ring
            # subscribed to span closes, alarm-counter increments,
            # logger events, health transitions and tensormon samples
            "recorder": {
                "enabled": True,
                "capacity": 4096,
                # dump blackbox-<ts>.jsonl on unhandled Workflow.run
                # exceptions / watchdog trips / SIGTERM (the NaN
                # sentinel's halt policies always dump)
                "autodump": False,
                # additionally record any single counter increment of
                # at least this value (0 = alarm counters only)
                "counter_threshold": 0,
            },
        },
        # resilience subsystem (veles_tpu/resilience/, docs/resilience.md)
        "resilience": {
            # fault-injection spec (point:action[:k=v,...];...);
            # the VELES_FAULTS env var overrides this key
            "faults": "",
            # default RetryPolicy knobs (exponential backoff + jitter)
            "retry": {"max_attempts": 4, "base_delay": 0.5,
                      "max_delay": 30.0},
            "keep_last": 0,           # snapshot retention; 0 = keep all
            "download_timeout": 60.0,  # socket timeout per HTTP attempt
            "max_pending": 64,        # RESTfulAPI in-flight bound
            "max_queue": 256,         # GenerationAPI queue bound
            "heartbeat_timeout": 300.0,
        },
        # continuous-batching serving engine (veles_tpu/serving/,
        # docs/services.md "Continuous batching"): GenerationAPI's
        # decode plane — a persistent max_slots-row KV-cache pool with
        # iteration-level scheduling. "recurrent" pins the O(1)-state
        # slot pool (serving/recurrent.py — fixed per-slot recurrent
        # state instead of a page table; "continuous" auto-falls-back
        # to it for Embedding→LSTM/SSM→LMHead stacks). "window" falls
        # back to the legacy shape-keyed coalescing worker. The O(1)
        # lane's own knobs ride this block too: state_cache (bool,
        # default False — the state-checkpoint prefix cache) and
        # state_cache_blocks (soft LRU budget, 0/None = unbounded);
        # page_size doubles as its checkpoint interval.
        "serving": {
            "engine": "continuous",
            # KV-cache slot rows decoded by the one fixed-shape step
            "max_slots": 8,
            # prefill pad-to lengths: jit cache is bounded by
            # len(buckets)+1 programs, not by distinct prompt lengths
            "buckets": [16, 32, 64, 128],
            # per-row KV capacity; admission requires
            # len(prompt) + n_new <= max_context (else the request
            # falls back to the window path)
            "max_context": 640,
            # decode steps fused per dispatch (lax.scan): 1 = pure
            # per-token scheduling; larger amortizes dispatch overhead
            # at the cost of up to N-1 wasted row-steps per retirement
            "decode_block": 1,
            # AOT serving artifact (veles-tpu export serve-artifact):
            # a package directory whose pre-exported prefill/decode
            # programs the engine loads at initialize — zero jit
            # traces/compiles on the serving path. "" = live jit.
            # A missing/corrupt/mismatched artifact falls back to
            # live jit with a counted warning, never a crash.
            "artifact": "",
        },
        # quantization subsystem (veles_tpu/quant/, docs/services.md
        # "Quantized serving"): OFF by default — the off path is
        # bit-identical to a build without the feature (locked by
        # tests/test_quant.py)
        "quant": {
            # per-channel symmetric int8 decode matmul weights,
            # dequantized on read inside the serving programs
            "weights": False,
            # int8 KV-cache slot pool with per-slot/-position scales
            # (half the pool HBM at the same max_slots)
            "kv": False,
            # weight scale granularity: per_channel (one scale per
            # output column — the accuracy default) | per_tensor
            "granularity": "per_channel",
        },
        # overlap engine (veles_tpu/overlap/, docs/overlap.md): async
        # side-plane for side-effect units, non-blocking checkpoints,
        # data-plane prefetch. Off by default — identical results
        # either way (locked by tests/test_overlap.py), enabling only
        # changes WHEN host I/O happens
        "overlap": {
            "enabled": False,
            "queue_depth": 64,        # per-lane bounded queue (backpressure)
            "async_snapshots": False,  # Snapshotter default async_mode
            "prefetch_depth": 0,       # Loader default prefetch depth
        },
        "disable": {"plotting": bool(os.environ.get("VELES_TPU_TEST"))},
        "random_seed": 1234,
    })
    # layered overrides, weakest first (reference: veles/config.py:294-308):
    # site file < user file < CWD file < environment
    for site in ("/etc/veles_tpu.json",
                 os.path.expanduser("~/.veles_tpu.json"),
                 os.path.join(os.getcwd(), ".veles_tpu.json")):
        if os.path.exists(site):
            r.update_from_file(site)
    r.common.update_from_env()
    return r


#: The global configuration tree (reference: veles/config.py:152 ``root``).
root = _default_root()
