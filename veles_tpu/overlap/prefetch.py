"""Data-plane prefetcher: stage the NEXT batch while the current step runs.

Keeping the accelerator fed means the host must be *ahead* of the
device: while step k computes, the host should already be gathering —
and optionally ``jax.device_put``-staging — batch k+1 (the standard
TPU input-pipeline recipe; cf. PAPERS.md on host/device overlap at
scale). :class:`Prefetcher` wraps any batch producer (an iterator, or
a callable returning successive batches) with an N-deep background
queue:

- **depth**: at most ``depth`` staged batches exist at once; a full
  queue blocks the *producer thread* (backpressure — memory stays
  bounded), never the consumer;
- **device staging**: ``device_put=True`` runs ``jax.device_put`` over
  each batch (pytree) in the background thread, so the h2d transfer
  overlaps compute too;
- **accounting**: ``veles_prefetch_batches_total`` (staged),
  ``veles_prefetch_hits_total`` (consumer found a batch ready),
  ``veles_prefetch_misses_total`` + ``veles_prefetch_stall_seconds_total``
  (consumer had to wait — the stall the overlap engine exists to
  remove);
- **chaos**: every produced batch passes the ``prefetch.batch``
  fault-injection point; a raised fault surfaces at the consumer's
  ``get()``, exactly where an inline loader error would;
- **clean shutdown**: :meth:`close` stops the worker and joins it —
  no orphan threads (tests assert), even when the producer is blocked
  on a full queue.

Determinism: the producer runs the *same* code in the same order as
the inline path — prefetching changes when work happens, never what is
computed. ``Loader`` integrates this via ``prefetch_depth`` (see
loader/base.py): the serving state machine (offsets, flags, PRNG
shuffles) stays on the main thread, and only the pure per-batch gather
(``fetch_batch``) runs ahead, one epoch at a time.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, Optional, Union

from ..logger import Logger

_END = object()


class _Error:
    __slots__ = ("exc",)

    def __init__(self, exc: BaseException) -> None:
        self.exc = exc


class Prefetcher(Logger):
    """N-deep background staging queue over a batch producer."""

    def __init__(self, source: Union[Iterable, Callable[[], Any]],
                 depth: int = 2, device_put: bool = False,
                 sharding: Any = None, name: str = "prefetch") -> None:
        super().__init__()
        if depth < 1:
            raise ValueError("prefetch depth must be >= 1, got %d" % depth)
        self.name = name
        self.depth = int(depth)
        self.device_put = bool(device_put)
        self.sharding = sharding
        if callable(source) and not hasattr(source, "__next__"):
            def _gen():
                while True:
                    yield source()
            self._it: Iterator = _gen()
        else:
            self._it = iter(source)
        self._q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._producer, daemon=True, name="prefetch:" + name)
        self._thread.start()

    # -- producer side ------------------------------------------------------
    def _stage(self, item: Any) -> Any:
        if not self.device_put:
            return item
        import jax
        return jax.tree_util.tree_map(
            lambda leaf: jax.device_put(leaf, self.sharding), item)

    def _put(self, item: Any) -> bool:
        """Bounded put that stays responsive to close(); True = stored."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _producer(self) -> None:
        from ..resilience.faults import fire as fire_fault
        from ..telemetry.counters import inc
        while not self._stop.is_set():
            try:
                item = next(self._it)
                fire_fault("prefetch.batch", prefetcher=self.name)
                item = self._stage(item)
            except StopIteration:
                self._put(_END)
                return
            except BaseException as e:  # noqa: BLE001 — delivered at get()
                self._put(_Error(e))
                return
            inc("veles_prefetch_batches_total")
            if not self._put(item):
                return

    # -- consumer side ------------------------------------------------------
    def get(self, timeout: Optional[float] = None) -> Any:
        """Next staged batch. Raises ``StopIteration`` when the source
        is exhausted, the producer's exception if it died, or
        ``TimeoutError`` when ``timeout`` elapses with nothing staged
        (a wedged producer must fail callers loudly, not leak a bare
        ``queue.Empty``). A batch already waiting is a *hit*; an empty
        queue is a *miss* and the wait — timed out or not — is counted
        as prefetch stall."""
        from ..telemetry.counters import inc
        if self._q.empty():
            inc("veles_prefetch_misses_total")
            t0 = time.time()
            try:
                item = self._q.get(timeout=timeout)
            except queue.Empty:
                inc("veles_prefetch_stall_seconds_total",
                    time.time() - t0)
                raise TimeoutError(
                    "prefetcher %s produced nothing in %.1fs (producer "
                    "wedged or starved)" % (self.name, timeout)) \
                    from None
            inc("veles_prefetch_stall_seconds_total", time.time() - t0)
        else:
            inc("veles_prefetch_hits_total")
            item = self._q.get_nowait()
        if item is _END:
            self._q.put(_END)       # stay exhausted for later calls
            raise StopIteration
        if isinstance(item, _Error):
            self._q.put(item)       # stay broken for later calls
            raise item.exc
        return item

    def __iter__(self) -> "Prefetcher":
        return self

    def __next__(self) -> Any:
        return self.get()

    @property
    def ready(self) -> int:
        """Staged batches waiting right now (the queue-depth gauge)."""
        return self._q.qsize()

    # -- lifecycle -----------------------------------------------------------
    def close(self, timeout: float = 10.0) -> None:
        """Stop the producer and join its thread. Idempotent; safe to
        call with the producer blocked on a full queue (the bounded put
        polls the stop flag). After this returns the worker thread is
        dead — no orphans."""
        self._stop.set()
        # unblock a producer sitting in put(): make room
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():      # pragma: no cover - defensive
            self.warning("prefetch worker %s did not stop in %.1fs",
                         self.name, timeout)

    @property
    def closed(self) -> bool:
        return self._stop.is_set() and not self._thread.is_alive()

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
