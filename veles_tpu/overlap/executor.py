"""Side-plane executor: bounded worker pool with named ordered lanes.

The reference VELES dispatched every unit onto a thread pool per
minibatch (veles/workflow.py:351-364 → veles/units.py:782); the TPU
port deliberately serialized the scheduler for determinism
(veles_tpu/workflow.py). That left all host I/O — snapshot fsyncs,
plotter/publisher rendering, web-status pushes — *inline* with the
jitted step: the device idles while Python writes files. This module
restores the overlap for work that is **side-effect only** (nothing
the compute path reads back), without touching the deterministic
scheduler:

- **lanes**: tasks submitted to one named lane run FIFO on that
  lane's worker thread (commit ordering — the checkpoint chain's
  crash-safety invariant); distinct lanes run concurrently;
- **backpressure**: each lane's queue is bounded
  (``root.common.overlap.queue_depth``); a full lane blocks the
  submitter, and the blocked time is counted in
  ``veles_sideplane_stall_seconds_total``;
- **drain barriers**: :meth:`SidePlane.drain` blocks until every
  queued task completed — the Workflow drains at EndPoint and before
  ``gather_results`` so results/snapshots are never read half-written;
- **error routing**: a task that raises is counted
  (``veles_sideplane_errors_total``), logged, marks
  ``sideplane.<lane>`` unready in the resilience health plane, and is
  re-raised from the next ``drain()`` — async execution must not
  swallow what inline execution would have crashed on;
- **chaos**: every task passes the ``sideplane.task`` fault-injection
  point (resilience/faults.py), so crash/delay/raise chaos drives the
  same code path tests assert on.

The process-global plane (:func:`plane`) is what ``Workflow.run`` and
the async :class:`~veles_tpu.snapshotter.Snapshotter` share; tests
construct private :class:`SidePlane` instances and ``shutdown()`` them.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..error import VelesError
from ..logger import Logger


class SidePlaneError(VelesError):
    """A side-plane task raised; carries every captured error in
    ``.errors`` (the first one is the ``__cause__``)."""

    def __init__(self, message: str, errors: List[BaseException]) -> None:
        super().__init__(message)
        self.errors = errors


_STOP = object()


class _Lane:
    __slots__ = ("name", "queue", "thread", "errors", "submitted", "done")

    def __init__(self, name: str, depth: int) -> None:
        self.name = name
        self.queue: "queue.Queue" = queue.Queue(maxsize=depth)
        self.thread: Optional[threading.Thread] = None
        self.errors: List[BaseException] = []
        self.submitted = 0
        self.done = 0


class SidePlane(Logger):
    """Named-lane async executor (see module docstring)."""

    def __init__(self, name: str = "sideplane",
                 queue_depth: Optional[int] = None) -> None:
        super().__init__()
        from ..config import root
        self.name = name
        self.queue_depth = int(
            queue_depth if queue_depth is not None
            else root.common.overlap.get("queue_depth", 64) or 64)
        self._lock = threading.Lock()
        self._lanes: Dict[str, _Lane] = {}
        self._closed = False

    # -- lane plumbing ------------------------------------------------------
    def _lane(self, name: str) -> _Lane:
        with self._lock:
            if self._closed:
                raise SidePlaneError(
                    "%s is shut down" % self.name, [])
            lane = self._lanes.get(name)
            if lane is None:
                lane = self._lanes[name] = _Lane(name, self.queue_depth)
                lane.thread = threading.Thread(
                    target=self._worker, args=(lane,), daemon=True,
                    name="%s:%s" % (self.name, name))
                lane.thread.start()
            return lane

    def _worker(self, lane: _Lane) -> None:
        from ..resilience.faults import fire as fire_fault
        from ..resilience.health import heartbeats, mark_unready
        from ..telemetry.counters import inc
        hb = "%s.%s" % (self.name, lane.name)
        while True:
            item = lane.queue.get()
            if item is _STOP:
                lane.queue.task_done()
                return
            fn, args, kwargs = item
            try:
                inc("veles_sideplane_tasks_total")
                # chaos hook: crash/delay/raise the side-plane here so
                # tests prove drain + lane ordering survive
                fire_fault("sideplane.task", lane=lane.name)
                fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — routed, not lost
                inc("veles_sideplane_errors_total")
                with self._lock:
                    lane.errors.append(e)
                mark_unready(hb)
                self.warning("side-plane task failed on lane %r: %s: %s",
                             lane.name, type(e).__name__, e)
            finally:
                # liveness: a wedged lane (hung fsync, stuck socket)
                # shows as this beat aging out on /healthz
                heartbeats.beat(hb)
                lane.done += 1
                lane.queue.task_done()

    # -- public surface -----------------------------------------------------
    def submit(self, lane: str, fn: Callable[..., Any],
               *args: Any, **kwargs: Any) -> None:
        """Enqueue ``fn(*args, **kwargs)`` on ``lane`` (FIFO within the
        lane). Blocks when the lane queue is full — backpressure, not
        unbounded growth; the blocked time lands in
        ``veles_sideplane_stall_seconds_total``."""
        from ..telemetry.counters import inc
        entry = self._lane(lane)
        item = (fn, args, kwargs)
        try:
            entry.queue.put_nowait(item)
        except queue.Full:
            t0 = time.time()
            entry.queue.put(item)
            inc("veles_sideplane_stall_seconds_total", time.time() - t0)
        entry.submitted += 1

    def drain(self, lane: Optional[str] = None,
              raise_errors: bool = True) -> List[BaseException]:
        """Barrier: block until every task queued so far (on ``lane``,
        or on all lanes) has completed. Waiting time is counted as
        stall. Captured task errors are popped and — unless
        ``raise_errors=False`` — re-raised as :class:`SidePlaneError`;
        the lanes' unready marks are cleared either way (the errors
        have been delivered to the caller)."""
        from ..resilience.health import forget
        from ..telemetry.counters import inc
        with self._lock:
            lanes = ([self._lanes[lane]] if lane in self._lanes else []
                     ) if lane is not None else list(self._lanes.values())
        t0 = time.time()
        for entry in lanes:
            entry.queue.join()
        stalled = time.time() - t0
        if stalled > 0:
            inc("veles_sideplane_stall_seconds_total", stalled)
        errors: List[BaseException] = []
        with self._lock:
            for entry in lanes:
                errors.extend(entry.errors)
                entry.errors = []
        for entry in lanes:
            forget("%s.%s" % (self.name, entry.name))
        if errors and raise_errors:
            raise SidePlaneError(
                "%d side-plane task(s) failed (first: %s: %s)"
                % (len(errors), type(errors[0]).__name__, errors[0]),
                errors) from errors[0]
        return errors

    def depth(self, lane: str) -> int:
        with self._lock:
            entry = self._lanes.get(lane)
        return entry.queue.qsize() if entry is not None else 0

    def stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-lane {depth, submitted, done, errors} — the queue-depth
        gauge surface (web_status /metrics renders it)."""
        with self._lock:
            return {name: {"depth": lane.queue.qsize(),
                           "submitted": lane.submitted,
                           "done": lane.done,
                           "errors": len(lane.errors)}
                    for name, lane in self._lanes.items()}

    def shutdown(self, timeout: float = 10.0) -> None:
        """Stop every lane worker and join its thread — after this
        returns no side-plane thread of this instance is alive (tests
        assert exactly that). Queued tasks run to completion first."""
        from ..resilience.health import forget
        with self._lock:
            self._closed = True
            lanes = list(self._lanes.values())
            self._lanes = {}
        for entry in lanes:
            entry.queue.put(_STOP)
        for entry in lanes:
            if entry.thread is not None:
                entry.thread.join(timeout=timeout)
            forget("%s.%s" % (self.name, entry.name))
        with self._lock:
            self._closed = False

    def __enter__(self) -> "SidePlane":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.shutdown()


_plane: Optional[SidePlane] = None
_plane_lock = threading.Lock()


def plane() -> SidePlane:
    """THE process-global side plane (mirrors counters.counters /
    faults.plane): Workflow.run and the async Snapshotter share it so
    lane ordering holds across subsystems."""
    global _plane
    with _plane_lock:
        if _plane is None:
            _plane = SidePlane()
        return _plane


def enabled() -> bool:
    """One switch for the whole overlap engine:
    ``root.common.overlap.enabled`` (CLI: ``--overlap``)."""
    from ..config import root
    return bool(root.common.overlap.get("enabled", False))
