"""Overlap engine: keep the device busy while the host does I/O.

The deterministic serial scheduler (veles_tpu/workflow.py) is correct
but leaves snapshot fsyncs, plot rendering, publisher uploads and host
batch staging inline with the jitted step — the accelerator idles
while Python touches disks and sockets. This package overlaps that
host work with device compute **without touching the deterministic
compute path** (docs/overlap.md is the operator guide):

- :mod:`executor` — :class:`~veles_tpu.overlap.executor.SidePlane`, a
  bounded worker pool with named ordered lanes (FIFO within a lane,
  lanes concurrent), explicit ``drain()`` barriers, and errors routed
  into resilience health + telemetry counters. Units that declare
  ``side_effect_only = True`` (plotters, publishers) are
  dispatched here by ``Workflow.run`` instead of running inline;
- :mod:`prefetch` — :class:`~veles_tpu.overlap.prefetch.Prefetcher`,
  an N-deep background staging queue (optionally including
  ``jax.device_put``) with backpressure and clean shutdown; ``Loader``
  wires it via ``prefetch_depth`` so the next minibatch's gather runs
  while the current step computes;
- non-blocking checkpoints: ``Snapshotter(async_mode=True)`` collects
  the state tree on the main thread (the cheap device→host copy) and
  commits+fsyncs+hashes on the ``checkpoint`` lane, preserving the
  chain's crash-safety invariants (per-lane commit order, quarantine
  on verify failure).

The contract, locked by tests/test_overlap.py: train/decode results
are **bit-identical** with overlap on vs. off. Enable with
``--overlap`` (CLI) or ``root.common.overlap.enabled = True``; tune
``queue_depth``, ``async_snapshots`` and ``prefetch_depth`` under
``root.common.overlap``.
"""

from __future__ import annotations

from .executor import (SidePlane, SidePlaneError,       # noqa: F401
                       enabled, plane)
from .prefetch import Prefetcher                        # noqa: F401

#: every counter this subsystem increments — registered with HELP
#: strings in telemetry.counters.DESCRIPTIONS; ``python bench.py
#: gate``'s overlap section asserts they read zero in overlap-off runs
OVERLAP_COUNTERS = (
    "veles_sideplane_tasks_total",
    "veles_sideplane_errors_total",
    "veles_sideplane_stall_seconds_total",
    "veles_prefetch_batches_total",
    "veles_prefetch_hits_total",
    "veles_prefetch_misses_total",
    "veles_prefetch_stall_seconds_total",
)
