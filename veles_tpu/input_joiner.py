"""InputJoiner: concatenate several input vectors on device.

Equivalent of the reference's veles/input_joiner.py:49 with its Jinja2
templated ocl/join.jcl kernel — here a single jnp.concatenate the XLA
fusion absorbs."""

from __future__ import annotations

from typing import List

import numpy

from .accelerated import AcceleratedUnit
from .error import Bug
from .memory import Array


class InputJoiner(AcceleratedUnit):
    MAPPING = "input_joiner"
    hide_from_registry = False

    def __init__(self, workflow, inputs: List[Array] = (), **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "WORKER"
        self.inputs = list(inputs)
        self.output = Array(name=self.name + ".output")

    def initialize(self, device=None, **kwargs):
        res = super().initialize(device=device, **kwargs)
        if res:
            return res
        if not self.inputs:
            raise Bug("%s: no inputs to join" % self.name)
        b = self.inputs[0].shape[0]
        width = sum(int(numpy.prod(a.shape[1:])) for a in self.inputs)
        self.output.reset(numpy.zeros((b, width), dtype=numpy.float32))
        return None

    def apply(self, *xs):
        import jax.numpy as jnp
        return jnp.concatenate(
            [x.reshape(x.shape[0], -1) for x in xs], axis=1)

    def numpy_apply(self, params, *xs):
        """Package-executor twin of :meth:`apply` (export/package.py
        run_package oracle; params is empty — the joiner is
        parameter-free)."""
        return numpy.concatenate(
            [numpy.asarray(x).reshape(len(x), -1) for x in xs], axis=1)

    def param_arrays(self):
        return {}

    def xla_run(self) -> None:
        fn = self.jit("join", self.apply)
        self.output.assign_devmem(
            fn(*[a.device_view() for a in self.inputs]))

    def numpy_run(self) -> None:
        self.output.reset(numpy.concatenate(
            [a.map_read().reshape(len(a.mem), -1) for a in self.inputs],
            axis=1))
