"""Workflow: unit container + scheduler + results root.

Equivalent of the reference's veles/workflow.py:87-1051, re-architected for
TPU (SURVEY.md §7): the reference dispatched each unit onto a thread pool per
minibatch (event-driven hot loop, veles/workflow.py:351-364 →
veles/units.py:782); here the scheduler is a deterministic, serial,
gate-driven loop in Python — cheap because the actual compute inside any
step-like unit is a single jitted XLA call (often covering forward+backward+
update fused). Threads would only add nondeterminism; XLA owns the devices.

Preserved surface: dependency-ordered ``initialize`` with partial-init
re-queue, ``run`` until EndPoint, ``stopped``/``on_workflow_finished``,
graphviz export, per-unit timing stats, ``gather_results``, checksums.
The master–slave job plane (generate/apply_data_for_slave,
veles/workflow.py:478-615) is intentionally absent: data parallelism is SPMD
``psum`` inside the step function (see veles_tpu/parallel/).
"""

from __future__ import annotations

import collections
import hashlib
import inspect
import time
from typing import Any, Dict, List, Optional

from .error import Bug
from .logger import Logger, SpanTimer
from .mutable import Bool
from .plumbing import EndPoint, StartPoint
from .units import Unit


class Workflow(Unit):
    """Container of units; itself a Unit so workflows nest
    (reference: veles/workflow.py:87, Container veles/units.py:925)."""

    hide_from_registry = True

    def __init__(self, workflow=None, **kwargs):
        self._units: List[Unit] = []
        super().__init__(workflow, **kwargs)
        self.stopped = Bool(False)
        self.start_point = StartPoint(self)
        self.end_point = EndPoint(self)
        self._run_time = 0.0
        self._max_steps = kwargs.get("max_steps", None)  # safety valve
        #: the async side-plane of the overlap engine (veles_tpu/
        #: overlap/): attached by run() when root.common.overlap.
        #: enabled; None = fully serial (the default)
        self.side_plane = None
        #: task errors captured by intermediate drain barriers (the
        #: EndPoint drain cannot raise mid-stop) — re-raised by run()
        self._side_errors: List[BaseException] = []

    # -- container protocol -------------------------------------------------
    def add_ref(self, unit: Unit) -> None:
        if unit is not self:
            self._units.append(unit)

    def del_ref(self, unit: Unit) -> None:
        if unit in self._units:
            self._units.remove(unit)
            unit.unlink_all()

    @property
    def units(self) -> List[Unit]:
        return list(self._units)

    def __iter__(self):
        return iter(self._units)

    def __len__(self):
        return len(self._units)

    def __getitem__(self, name: str) -> Unit:
        for u in self._units:
            if u.name == name:
                return u
        raise KeyError(name)

    # -- dependency order ---------------------------------------------------
    def units_in_dependency_order(self) -> List[Unit]:
        """BFS from start_point over control links; unreachable units are
        appended last (reference: veles/units.py:507)."""
        seen: Dict[Unit, None] = {}
        queue = collections.deque([self.start_point])
        while queue:
            u = queue.popleft()
            if u in seen:
                continue
            seen[u] = None
            for v in sorted(u.links_to, key=lambda x: x.name):
                queue.append(v)
        for u in self._units:
            if u not in seen:
                seen[u] = None
        return list(seen)

    # -- lifecycle ----------------------------------------------------------
    def initialize(self, **kwargs) -> Optional[bool]:
        """Initialize units in dependency order; a unit returning True is
        re-queued until the set stops shrinking
        (reference: veles/workflow.py:303-336)."""
        from .telemetry.spans import span
        with SpanTimer(self, "workflow.initialize", workflow=self.name), \
                span("workflow.initialize", workflow=self.name):
            pending = self.units_in_dependency_order()
            while pending:
                again: List[Unit] = []
                for u in pending:
                    if u.initialize(**kwargs):
                        again.append(u)
                if len(again) == len(pending):
                    missing = {u.name: u.verify_demands() for u in again}
                    raise Bug("initialization deadlock; unsatisfied demands: "
                              "%s" % missing)
                pending = again
        self._initialized = True
        return None

    def run(self) -> None:
        """Deterministic gate-driven scheduler: process units breadth-first
        from start_point until stopped (reference hot loop:
        veles/workflow.py:351-364 + veles/units.py:782-505, serialized)."""
        if not self._initialized:
            raise Bug("workflow %s run before initialize" % self.name)
        self.stopped <<= False
        # re-zero gate fired-flags: an interrupted previous run may have
        # left join gates half-open
        for u in self._units:
            u._reset_fired()
        t0 = time.time()
        self.event("workflow.run", "begin", workflow=self.name)
        from .resilience.health import heartbeats
        from .telemetry.spans import recorder
        _span_frame = recorder.begin("workflow.run", workflow=self.name)
        _hb_name = "workflow.%s" % self.name
        # overlap engine: side_effect_only units (plotters,
        # publishers) run on the async side-plane instead of stalling
        # the step loop; scheduling itself stays serial + deterministic
        from . import overlap
        self.side_plane = overlap.plane() if overlap.enabled() else None
        queue = collections.deque([self.start_point])
        steps = 0
        try:
            while queue and not bool(self.stopped):
                # liveness: a wedged unit (hung collective, stuck I/O)
                # shows as this heartbeat aging out on /healthz
                heartbeats.beat(_hb_name)
                unit = queue.popleft()
                for downstream in unit.process(side_plane=self.side_plane):
                    if bool(self.stopped):
                        break
                    if downstream.open_gate(unit):
                        queue.append(downstream)
                steps += 1
                if self._max_steps is not None and steps > self._max_steps:
                    raise Bug("workflow %s exceeded max_steps=%d" %
                              (self.name, self._max_steps))
            # final drain barrier: every offloaded run and queued
            # checkpoint commit lands before run() returns, and a task
            # error surfaces HERE — exactly where the serial scheduler
            # would have crashed. Errors stashed by intermediate drains
            # (EndPoint, an async Snapshotter.stop — which works even
            # with the side-plane off) are re-raised too.
            errors = list(self._side_errors)
            if self.side_plane is not None:
                errors += self.side_plane.drain(raise_errors=False)
            self._side_errors = []
            if errors:
                from .overlap import SidePlaneError
                raise SidePlaneError(
                    "%d side-plane task(s) failed during %s "
                    "(first: %s: %s)"
                    % (len(errors), self.name,
                       type(errors[0]).__name__, errors[0]),
                    errors) from errors[0]
        except Exception as exc:
            # crash black box (telemetry/recorder.py): the ring holds
            # the final seconds of spans/events/alarm counters —
            # crash_dump honors the autodump knob and never raises,
            # so the original exception always propagates. The NaN
            # sentinel dumps before raising ModelHealthError; a second
            # dump here would land on the same <ts>_<pid> name and
            # overwrite the sentinel's header reason
            from .telemetry.recorder import flight
            from .telemetry.tensormon import ModelHealthError
            if not isinstance(exc, ModelHealthError):
                flight.crash_dump("workflow.run %s: %s: %s" % (
                    self.name, type(exc).__name__, exc))
            raise
        finally:
            if self.side_plane is not None:
                # on the exception path too, nothing may stay in
                # flight past run() — but don't mask the original
                # error with a side-task one
                self.side_plane.drain(raise_errors=False)
            # a COMPLETED (or cleanly crashed) run is not a hang: drop
            # the beat so only a truly wedged loop ages out on /healthz
            heartbeats.unregister(_hb_name)
            # run_count is incremented by Unit.process when nested; a bare
            # top-level run() tracks time only (no double counting)
            self._run_time += time.time() - t0
            _span_frame.attrs["steps"] = steps
            recorder.end(_span_frame)
            self.event("workflow.run", "end", workflow=self.name, steps=steps)

    def on_workflow_finished(self) -> None:
        """Called by EndPoint (reference: veles/workflow.py:377-401)."""
        if self.side_plane is not None:
            # drain barrier at EndPoint: offloaded plot/publish runs
            # finish before units are stopped (a forced Snapshotter
            # export on stop must queue AFTER everything it follows).
            # Raising here would wedge the stop sequence — errors are
            # stashed for run()'s final barrier instead.
            self._side_errors.extend(
                self.side_plane.drain(raise_errors=False))
        self.stopped <<= True
        for u in self._units:
            u.stop()

    def stop(self) -> None:
        self.stopped <<= True

    # -- results / stats / introspection ------------------------------------
    def gather_results(self) -> Dict[str, Any]:
        """Harvest metrics from units exposing ``get_metric_values``
        (reference: IResultProvider, veles/workflow.py:827-849)."""
        if self.side_plane is not None:
            # barrier: results (publisher paths, snapshot destinations)
            # must never be read while a side task is still writing them
            self.side_plane.drain(raise_errors=False)
        results: Dict[str, Any] = {}
        for u in self._units:
            getter = getattr(u, "get_metric_values", None)
            if callable(getter):
                results.update(getter())
        return results

    def print_stats(self, top: int = 10) -> List[tuple]:
        """Top-N unit run-time table (reference: veles/workflow.py:788-825)."""
        stats = sorted(((u.timers["run"], u.name, u.run_count)
                        for u in self._units), reverse=True)[:top]
        total = sum(s[0] for s in stats) or 1.0
        for t, name, n in stats:
            self.info("%6.2f%%  %-30s %8.3fs  ×%d", 100 * t / total, name,
                      t, n)
        return stats

    def checksum(self) -> str:
        """Stable identity of the workflow source (reference:
        veles/workflow.py:852-866, used for master/slave handshake; here it
        keys compilation/checkpoint compatibility)."""
        try:
            src = inspect.getsource(type(self))
        except (OSError, TypeError):
            src = repr(sorted(u.name for u in self._units))
        return hashlib.sha256(src.encode()).hexdigest()

    def generate_graph(self) -> str:
        """DOT text of the control graph (reference:
        veles/workflow.py:628-665)."""
        lines = ["digraph %s {" % self.name.replace(" ", "_")]
        for u in self._units:
            lines.append('  "%s";' % u.name)
            for v in u.links_to:
                lines.append('  "%s" -> "%s";' % (u.name, v.name))
        lines.append("}")
        return "\n".join(lines)
