"""AcceleratedUnit: base for every compute unit.

Equivalent of the reference's veles/accelerated_units.py:130-867, minus
everything XLA makes obsolete: there is no kernel source templating, no
build_program/nvcc, no binary cache tarballs — a compute unit declares pure
functions and ``jax.jit`` (with the persistent compilation cache) replaces
the whole kernel build/cache machinery (reference :298-673).

Preserved contract (SURVEY.md §4 "numpy is the oracle"):
- every accelerated unit implements ``numpy_run`` (host oracle) and an XLA
  path; ``--force-numpy`` (root.common.engine.force_numpy) switches, and the
  test harness asserts both agree (reference: @multi_device,
  veles/tests/accelerated_test.py:41-61);
- ``initialize(device=...)`` attaches the device; per-backend method dispatch
  (reference ocl_run/cuda_run/numpy_run binding, veles/backends.py:244-262)
  collapses to two: ``xla_run`` / ``numpy_run``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from .backends import Device, NumpyDevice, XLADevice
from .config import root
from .units import Unit
from .workflow import Workflow


def _abstract_shapes(args):
    """Pytree of ShapeDtypeStructs mirroring ``args`` (non-array leaves
    pass through — jit treats them as static-compatible values)."""
    import jax

    def leaf(a):
        if hasattr(a, "shape") and hasattr(a, "dtype"):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)
        return a
    return jax.tree_util.tree_map(leaf, args)


class AcceleratedUnit(Unit):
    """Compute unit with device dispatch (reference:
    veles/accelerated_units.py:130)."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.device: Optional[Device] = None
        self._jit_cache: Dict[str, Any] = {}
        #: raw fn + jit kwargs per key — program_cost() re-lowers from
        #: these (the jitted callable hides its Compiled objects)
        self._jit_fns: Dict[str, Any] = {}
        #: abstract arg shapes of the LAST dispatch per key (donated
        #: buffers die at dispatch, so cost analysis lowers on shapes)
        self._jit_arg_shapes: Dict[str, Any] = {}
        #: dispatches per jit key — lets cost accounting bill each
        #: program (train vs eval vs epoch_block) at its OWN cost
        self._dispatch_counts: Dict[str, int] = {}

    # -- lifecycle ----------------------------------------------------------
    def initialize(self, device: Optional[Device] = None, **kwargs):
        res = super().initialize(device=device, **kwargs)
        if res:
            return res
        self.device = device if device is not None else NumpyDevice()
        if isinstance(self.device, XLADevice):
            self.xla_init()
        else:
            self.numpy_init()
        return None

    def xla_init(self) -> None:
        """Backend-specific setup (reference ocl_init/cuda_init)."""

    def numpy_init(self) -> None:
        pass

    # -- dispatch -----------------------------------------------------------
    @property
    def accelerated(self) -> bool:
        return (isinstance(self.device, XLADevice)
                and not root.common.engine.force_numpy)

    def run(self) -> None:
        if self.accelerated:
            self.xla_run()
            if root.common.engine.sync_run:
                self.device.sync()
        else:
            self.numpy_run()

    def xla_run(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError("%s.xla_run" % type(self).__name__)

    def numpy_run(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError("%s.numpy_run" % type(self).__name__)

    # -- jit helper ---------------------------------------------------------
    def jit(self, key: str, fn: Callable, **jit_kwargs) -> Callable:
        """Cache a jitted callable per unit (the reference cached built
        kernels per device, veles/accelerated_units.py:605-673; XLA's own
        compilation cache does the heavy lifting — this only avoids
        re-tracing).

        The returned callable is telemetry-instrumented: every call
        counts one ``veles_dispatches_total``; a call that grows the
        jit's trace cache counts one ``veles_compiles_total`` (the
        counter the bench gate reads — recompiles are a deterministic
        regression signal the wall-clock medians cannot see); lookups
        served from the per-unit cache count
        ``veles_jit_cache_hits_total``."""
        cached = self._jit_cache.get(key)
        if cached is None:
            import jax
            from .telemetry.counters import inc
            jitted = jax.jit(fn, **jit_kwargs)
            self._jit_fns[key] = (fn, dict(jit_kwargs))
            unit = self

            def dispatch(*args, **kwargs):
                unit._dispatch_counts[key] = \
                    unit._dispatch_counts.get(key, 0) + 1
                try:
                    before = jitted._cache_size()
                except AttributeError:       # non-pjit backends
                    before = None
                out = jitted(*args, **kwargs)
                inc("veles_dispatches_total")
                if before is None:
                    # no cache introspection: capture shapes per call
                    unit._jit_arg_shapes[key] = _abstract_shapes(args)
                elif jitted._cache_size() > before:
                    inc("veles_compiles_total")
                    # shapes only change on retrace, and a retrace IS a
                    # cache growth — capturing here keeps the hot path
                    # free of the per-call pytree walk
                    unit._jit_arg_shapes[key] = _abstract_shapes(args)
                return out

            dispatch._jitted = jitted
            cached = self._jit_cache[key] = dispatch
        else:
            from .telemetry.counters import inc
            inc("veles_jit_cache_hits_total")
        return cached

    def program_cost(self, key: str):
        """FLOPs/bytes/peak-memory of the LAST program dispatched under
        ``key``, via ``Compiled.cost_analysis()`` on a re-lower at the
        recorded arg shapes (in-process, so XLA's compilation cache
        absorbs most of the cost). Returns a telemetry ``Cost`` or None
        when nothing has been dispatched under ``key``. On-demand only
        (bench sections, tests) — never on the hot path."""
        entry = self._jit_fns.get(key)
        shapes = self._jit_arg_shapes.get(key)
        if entry is None or shapes is None:
            return None
        import jax
        from .telemetry.cost import (collecting_kernel_costs,
                                     cost_of_compiled)
        fn, jit_kwargs = entry
        # donation changes buffer reuse, not the cost model; dropping it
        # lets the lowering accept abstract args without aliasing checks
        jit_kwargs = {k: v for k, v in jit_kwargs.items()
                      if k != "donate_argnums"}
        # the re-lower re-traces fn, so Pallas kernels (opaque to the
        # HLO cost model) note their analytic costs into the collector
        # — body-once, the same convention cost_analysis uses for
        # scan/while bodies
        with collecting_kernel_costs() as notes:
            compiled = jax.jit(fn, **jit_kwargs).lower(*shapes).compile()
        cost = cost_of_compiled(compiled)
        for kernel_cost in notes:
            cost = cost + kernel_cost
        return cost

    def __getstate__(self):
        d = dict(self.__dict__)
        d["_jit_cache"] = {}
        d["_jit_fns"] = {}
        d["_jit_arg_shapes"] = {}
        d["_dispatch_counts"] = {}
        d["device"] = None
        return d


class AcceleratedWorkflow(Workflow):
    """Workflow owning a device (reference:
    veles/accelerated_units.py:827-858)."""

    hide_from_registry = True

    def __init__(self, workflow=None, **kwargs):
        super().__init__(workflow, **kwargs)
        self.device: Optional[Device] = None

    def initialize(self, device: Optional[Device] = None, **kwargs):
        self.device = device if device is not None else NumpyDevice()
        return super().initialize(device=self.device, **kwargs)

    @property
    def computing_power(self) -> float:
        """GFLOP/s of the attached device; the reference reported this to
        the master for load balancing (veles/accelerated_units.py:843-858);
        kept as telemetry."""
        if isinstance(self.device, XLADevice):
            return self.device.compute_power()
        return 0.0
