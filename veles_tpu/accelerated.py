"""AcceleratedUnit: base for every compute unit.

Equivalent of the reference's veles/accelerated_units.py:130-867, minus
everything XLA makes obsolete: there is no kernel source templating, no
build_program/nvcc, no binary cache tarballs — a compute unit declares pure
functions and ``jax.jit`` (with the persistent compilation cache) replaces
the whole kernel build/cache machinery (reference :298-673).

Preserved contract (SURVEY.md §4 "numpy is the oracle"):
- every accelerated unit implements ``numpy_run`` (host oracle) and an XLA
  path; ``--force-numpy`` (root.common.engine.force_numpy) switches, and the
  test harness asserts both agree (reference: @multi_device,
  veles/tests/accelerated_test.py:41-61);
- ``initialize(device=...)`` attaches the device; per-backend method dispatch
  (reference ocl_run/cuda_run/numpy_run binding, veles/backends.py:244-262)
  collapses to two: ``xla_run`` / ``numpy_run``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from .backends import Device, NumpyDevice, XLADevice
from .config import root
from .units import Unit
from .workflow import Workflow


class AcceleratedUnit(Unit):
    """Compute unit with device dispatch (reference:
    veles/accelerated_units.py:130)."""

    hide_from_registry = True

    def __init__(self, workflow, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.device: Optional[Device] = None
        self._jit_cache: Dict[str, Any] = {}

    # -- lifecycle ----------------------------------------------------------
    def initialize(self, device: Optional[Device] = None, **kwargs):
        res = super().initialize(device=device, **kwargs)
        if res:
            return res
        self.device = device if device is not None else NumpyDevice()
        if isinstance(self.device, XLADevice):
            self.xla_init()
        else:
            self.numpy_init()
        return None

    def xla_init(self) -> None:
        """Backend-specific setup (reference ocl_init/cuda_init)."""

    def numpy_init(self) -> None:
        pass

    # -- dispatch -----------------------------------------------------------
    @property
    def accelerated(self) -> bool:
        return (isinstance(self.device, XLADevice)
                and not root.common.engine.force_numpy)

    def run(self) -> None:
        if self.accelerated:
            self.xla_run()
            if root.common.engine.sync_run:
                self.device.sync()
        else:
            self.numpy_run()

    def xla_run(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError("%s.xla_run" % type(self).__name__)

    def numpy_run(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError("%s.numpy_run" % type(self).__name__)

    # -- jit helper ---------------------------------------------------------
    def jit(self, key: str, fn: Callable, **jit_kwargs) -> Callable:
        """Cache a jitted callable per unit (the reference cached built
        kernels per device, veles/accelerated_units.py:605-673; XLA's own
        compilation cache does the heavy lifting — this only avoids
        re-tracing)."""
        cached = self._jit_cache.get(key)
        if cached is None:
            import jax
            cached = self._jit_cache[key] = jax.jit(fn, **jit_kwargs)
        return cached

    def __getstate__(self):
        d = dict(self.__dict__)
        d["_jit_cache"] = {}
        d["device"] = None
        return d


class AcceleratedWorkflow(Workflow):
    """Workflow owning a device (reference:
    veles/accelerated_units.py:827-858)."""

    hide_from_registry = True

    def __init__(self, workflow=None, **kwargs):
        super().__init__(workflow, **kwargs)
        self.device: Optional[Device] = None

    def initialize(self, device: Optional[Device] = None, **kwargs):
        self.device = device if device is not None else NumpyDevice()
        return super().initialize(device=self.device, **kwargs)

    @property
    def computing_power(self) -> float:
        """GFLOP/s of the attached device; the reference reported this to
        the master for load balancing (veles/accelerated_units.py:843-858);
        kept as telemetry."""
        if isinstance(self.device, XLADevice):
            return self.device.compute_power()
        return 0.0
