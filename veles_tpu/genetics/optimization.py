"""GeneticsOptimizer: GA over the config tree, fitness = training result.

Rebuild of the reference's veles/genetics/optimization_workflow.py:70-406
(--optimize N[:G], veles/__main__.py:334-345,724-726): each chromosome
evaluation is one full training run of the user model with the chromosome
written into the config tree. Two evaluation modes:

- inline (default): build_workflow() in-process, one jitted run per
  candidate — recompiles only when a tuneable changes a traced shape.
- subprocess: each candidate runs ``python -m veles_tpu MODEL --result-file
  ...`` with root.x.y=value overrides, isolating device state (the
  reference ran candidates as slave jobs / subprocesses).

Fitness is read from the run's gathered results: ``-results[minimize]``
(default minimize="best_err") or ``+results[maximize]``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
from typing import Callable, Optional

from ..config import root
from ..logger import Logger
from .config import find_tuneables, fix_config, restore_markers
from .core import Population


class GeneticsOptimizer(Logger):
    def __init__(self, build_workflow: Optional[Callable] = None,
                 model_path: Optional[str] = None,
                 config_node=None, size: int = 10, generations: int = 5,
                 minimize: str = "best_err", maximize: Optional[str] = None,
                 device=None, subprocess_mode: bool = False,
                 crossover: str = "uniform", selection: str = "roulette",
                 n_workers: int = 1, trial_timeout: Optional[float] = None,
                 placement=None,
                 extra_argv: Optional[list] = None) -> None:
        super().__init__()
        self.build_workflow = build_workflow
        self.model_path = model_path
        self.config_node = config_node if config_node is not None else root
        self.minimize = minimize
        self.maximize = maximize
        self.device = device
        # concurrent candidates need process isolation — n_workers > 1
        # implies the subprocess path (the reference's job-farm analog)
        self.n_workers = int(n_workers)
        self.subprocess_mode = subprocess_mode or self.n_workers > 1
        self.trial_timeout = trial_timeout
        self.placement = placement
        self.extra_argv = list(extra_argv or [])
        self.generations = int(generations)
        self.tuneables = find_tuneables(self.config_node)
        if not self.tuneables:
            raise ValueError(
                "no Range/Tuneable markers found in the config tree; "
                "set e.g. root.model.lr = Range(0.03, 0.001, 0.1)")
        self.population = Population(
            mins=[t[3].min for t in self.tuneables],
            maxs=[t[3].max for t in self.tuneables],
            ints=[t[3].is_int for t in self.tuneables],
            size=size, crossover=crossover, selection=selection)
        self.evaluations = 0
        self.history = []   # (values, fitness) of every evaluation

    # -- fitness --------------------------------------------------------------
    def _fitness_from_results(self, results: dict) -> float:
        if self.maximize:
            return float(results[self.maximize])
        return -float(results[self.minimize])

    def _evaluate_inline(self, values) -> float:
        fix_config(self.tuneables, values)
        try:
            workflow = self.build_workflow()
            workflow.initialize(device=self.device)
            workflow.run()
            return self._fitness_from_results(workflow.gather_results())
        except Exception as exc:
            # one pathological candidate (divergent lr, OOM shape, missing
            # metric) must not abort the whole search — roulette gives
            # -inf zero weight (core.py _roulette_pick)
            self.warning("candidate %s failed: %s", values, exc)
            return -float("inf")

    def _candidate_cmd(self, values, result_file) -> list:
        from ..cmdline import split_child_argv
        overrides = ["%s=%s" % (path, json.dumps(v)) for
                     (path, _, _, _), v in zip(self.tuneables, values)]
        # overrides are re-applied by the child AFTER it imports the
        # model module, so they win over import-time Range markers.
        # All positionals grouped right after the model path: argparse
        # rejects a second positional group after flags like --backend
        positionals, flags = split_child_argv(self.extra_argv)
        return ([sys.executable, "-m", "veles_tpu", self.model_path]
                + positionals + overrides
                + ["--result-file", result_file] + flags)

    def _fitness_from_file(self, values, result_file) -> float:
        try:
            with open(result_file) as fin:
                return self._fitness_from_results(json.load(fin))
        except (KeyError, ValueError, OSError) as exc:
            # same contract as inline mode: a candidate whose results
            # lack the metric scores -inf, it must not kill the search
            self.warning("candidate %s produced unusable results: %s",
                         values, exc)
            return -float("inf")

    def _evaluate_subprocess(self, values) -> float:
        fd, result_file = tempfile.mkstemp(suffix=".json")
        os.close(fd)
        try:
            proc = subprocess.run(self._candidate_cmd(values, result_file),
                                  capture_output=True, text=True)
            if proc.returncode != 0:
                self.warning("candidate failed (%s): %s",
                             values, proc.stderr[-500:])
                return -float("inf")
            return self._fitness_from_file(values, result_file)
        finally:
            os.unlink(result_file)

    def _evaluate_batch(self, chromosomes) -> list:
        """One GENERATION of candidates through the trial scheduler —
        the reference farmed exactly this unit to its slaves
        (veles/genetics/optimization_workflow.py:70)."""
        from ..parallel.trials import run_json_trials
        outcomes = run_json_trials(
            lambda i, rf: self._candidate_cmd(chromosomes[i].values(), rf),
            len(chromosomes), self.n_workers, placement=self.placement,
            timeout=self.trial_timeout,
            tags=[tuple(c.values()) for c in chromosomes])
        fits = []
        for chromo, (res, doc) in zip(chromosomes, outcomes):
            values = chromo.values()
            if doc is None:
                self.warning("candidate failed (%s): rc=%s%s %s",
                             values, res.returncode,
                             ", no result file" if res.ok else "",
                             res.stderr_tail[-500:])
                fit = -float("inf")
            else:
                try:
                    fit = self._fitness_from_results(doc)
                except (KeyError, ValueError, TypeError) as exc:
                    self.warning("candidate %s produced unusable "
                                 "results: %s", values, exc)
                    fit = -float("inf")
            self.evaluations += 1
            self.history.append((values, fit))
            self.info("eval %d: %s → fitness %.6g", self.evaluations,
                      dict(zip((t[0] for t in self.tuneables),
                               values)), fit)
            fits.append(fit)
        if fits and all(f == -float("inf") for f in fits):
            # a whole generation failing is a config/placement error
            # (e.g. a chip slice past the host's last chip), not N
            # independent divergences — degrading the search silently
            # would report a "successful" GA that explored nothing
            from ..error import VelesError
            raise VelesError(
                "every candidate in the generation failed — check "
                "worker placement (--trial-devices × workers vs the "
                "host's chips) and the first failure above")
        return fits

    def _evaluate(self, chromo, index) -> float:
        values = chromo.values()
        if self.subprocess_mode:
            fit = self._evaluate_subprocess(values)
        else:
            fit = self._evaluate_inline(values)
        self.evaluations += 1
        self.history.append((values, fit))
        self.info("eval %d: %s → fitness %.6g", self.evaluations,
                  dict(zip((t[0] for t in self.tuneables), values)), fit)
        return fit

    # -- driver ---------------------------------------------------------------
    def run(self) -> dict:
        """Evolve; returns {'best_config': {path: value}, 'best_fitness': f,
        'evaluations': n, 'generations': g}."""
        if self.subprocess_mode and not self.model_path:
            raise ValueError("subprocess mode needs model_path")
        if not self.subprocess_mode and self.build_workflow is None:
            raise ValueError("inline mode needs build_workflow")
        try:
            for _ in range(self.generations):
                if self.n_workers > 1:
                    self.population.evolve(
                        batch_evaluator=self._evaluate_batch)
                else:
                    self.population.evolve(self._evaluate)
            best = self.population.best
            best_cfg = dict(zip((t[0] for t in self.tuneables),
                                best.values()))
            self.info("optimize done: best %s fitness %.6g",
                      best_cfg, best.fitness)
            return {"best_config": best_cfg,
                    "best_fitness": best.fitness,
                    "evaluations": self.evaluations,
                    "generations": self.population.generation}
        finally:
            restore_markers(self.tuneables)
