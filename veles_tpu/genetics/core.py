"""GA engine: chromosomes over bounded numeric genes.

Rebuild of the reference's veles/genetics/core.py:58-830 capabilities:
gray-coded binary genomes (helpers :58-121), Chromosome (:133) with
binary-flip and gaussian "altering" mutations (:257), Population (:371)
with uniform / arithmetic / geometric / pointed crossover (:428-429,
633-659), roulette selection and elitism. The numeric representation here
is a flat numpy vector per chromosome instead of the reference's
per-gene python lists — the GA itself is host-side and tiny; device time
is spent only inside the fitness evaluations (full training runs).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy

from .. import prng
from ..logger import Logger

#: bits used for the gray-coded integer image of each gene
GRAY_BITS = 16


def gray_encode(n: int, bits: int = GRAY_BITS) -> int:
    return n ^ (n >> 1)


def gray_decode(g: int, bits: int = GRAY_BITS) -> int:
    n = 0
    while g:
        n ^= g
        g >>= 1
    return n


def _to_units(value: float, vmin: float, vmax: float,
              bits: int = GRAY_BITS) -> int:
    """Quantize value∈[vmin,vmax] onto a 2^bits grid."""
    span = vmax - vmin
    if span <= 0:
        return 0
    q = int(round((value - vmin) / span * ((1 << bits) - 1)))
    return max(0, min((1 << bits) - 1, q))


def _from_units(q: int, vmin: float, vmax: float,
                bits: int = GRAY_BITS) -> float:
    return vmin + (vmax - vmin) * q / float((1 << bits) - 1)


class Chromosome:
    """One candidate: a vector of genes, each bounded by [mins, maxs].

    ``binary`` mutation operates on the gray-code image of each gene so a
    single bit flip moves the value a (usually) small, occasionally large
    step — the reference's mutation_binary_point behavior
    (veles/genetics/core.py:257+).
    """

    def __init__(self, genes: numpy.ndarray, mins: numpy.ndarray,
                 maxs: numpy.ndarray, ints: Sequence[bool]) -> None:
        self.genes = numpy.asarray(genes, dtype=numpy.float64).copy()
        self.mins = mins
        self.maxs = maxs
        self.ints = list(ints)
        self.fitness: Optional[float] = None
        self._snap()

    def _snap(self) -> None:
        numpy.clip(self.genes, self.mins, self.maxs, out=self.genes)
        for i, isint in enumerate(self.ints):
            if isint:
                self.genes[i] = round(self.genes[i])

    def values(self) -> list:
        return [int(g) if isint else float(g)
                for g, isint in zip(self.genes, self.ints)]

    # -- mutations -----------------------------------------------------------
    def mutate_binary(self, points: int, rand) -> None:
        """Flip ``points`` random bits in the gray image of random genes."""
        for _ in range(points):
            i = int(rand.randint(0, len(self.genes)))
            q = _to_units(self.genes[i], self.mins[i], self.maxs[i])
            bit = int(rand.randint(0, GRAY_BITS))
            q = gray_encode(q) ^ (1 << bit)
            self.genes[i] = _from_units(gray_decode(q),
                                        self.mins[i], self.maxs[i])
        self._snap()

    def mutate_gaussian(self, points: int, scale: float, rand) -> None:
        """Add gaussian noise scaled to the gene's range (reference:
        mutation_gaussian, veles/genetics/core.py:310)."""
        for _ in range(points):
            i = int(rand.randint(0, len(self.genes)))
            span = self.maxs[i] - self.mins[i]
            self.genes[i] += rand.normal(0.0, scale * max(span, 1e-12))
        self._snap()

    def mutate_uniform(self, points: int, rand) -> None:
        """Replace a gene with a fresh uniform draw from its range
        (reference: mutation_uniform, veles/genetics/core.py:346)."""
        for _ in range(points):
            i = int(rand.randint(0, len(self.genes)))
            # mins + span*rand(): the project RandomGenerator exposes
            # rand/randint/normal but no uniform()
            self.genes[i] = self.mins[i] + \
                (self.maxs[i] - self.mins[i]) * float(rand.rand())
        self._snap()

    def mutate_altering(self, points: int, rand) -> None:
        """Swap the values of two gene positions (reference:
        mutation_altering, veles/genetics/core.py:277). The swapped
        values are re-snapped to each TARGET position's own bounds —
        gene ranges differ, unlike the reference's homogeneous-range
        chromosomes. No-op on single-gene chromosomes."""
        if len(self.genes) < 2:
            return
        for _ in range(points):
            i = int(rand.randint(0, len(self.genes)))
            j = int(rand.randint(0, len(self.genes)))
            self.genes[i], self.genes[j] = self.genes[j], self.genes[i]
        self._snap()


class Population(Logger):
    """Fixed-size population with elitism.

    evaluator(chromosome, index) -> float fitness (HIGHER is better);
    assigned to chromosome.fitness by ``evolve``.
    """

    #: mutation operator census (reference veles/genetics/core.py:205-211:
    #: binary_point / gaussian / uniform / altering)
    MUTATIONS = ("binary", "gaussian", "uniform", "altering")
    #: selection procedures (reference :573-616: roulette / random /
    #: tournament)
    SELECTIONS = ("roulette", "random", "tournament")

    def __init__(self, mins: Sequence[float], maxs: Sequence[float],
                 ints: Optional[Sequence[bool]] = None, size: int = 20,
                 crossover: str = "uniform", elite_fraction: float = 0.15,
                 mutation_rate: float = 0.25, rand=None,
                 selection: str = "roulette",
                 tournament_size: int = 3) -> None:
        super().__init__()
        self.mins = numpy.asarray(mins, dtype=numpy.float64)
        self.maxs = numpy.asarray(maxs, dtype=numpy.float64)
        if self.mins.shape != self.maxs.shape or self.mins.ndim != 1:
            raise ValueError("mins/maxs must be equal-length 1-D")
        self.ints = list(ints) if ints is not None else [False] * len(mins)
        self.size = int(size)
        self.crossover = crossover
        if selection not in self.SELECTIONS:
            raise ValueError("unknown selection %r (have: %s)"
                             % (selection, self.SELECTIONS))
        self.selection = selection
        self.tournament_size = int(tournament_size)
        self.elite_fraction = float(elite_fraction)
        self.mutation_rate = float(mutation_rate)
        self.rand = rand or prng.get("genetics")
        self.generation = 0
        self.chromosomes: List[Chromosome] = [
            self._random_chromosome() for _ in range(self.size)]

    def _random_chromosome(self) -> Chromosome:
        genes = self.mins + (self.maxs - self.mins) * self.rand.rand(
            len(self.mins))
        return Chromosome(genes, self.mins, self.maxs, self.ints)

    @property
    def best(self) -> Chromosome:
        scored = [c for c in self.chromosomes if c.fitness is not None]
        return max(scored, key=lambda c: c.fitness)

    # -- selection -----------------------------------------------------------
    def _pick(self) -> Chromosome:
        """One parent by the configured procedure (reference
        select_roulette/select_random/select_tournament,
        veles/genetics/core.py:578-616)."""
        if self.selection == "roulette":
            return self._roulette_pick()
        if self.selection == "random":
            return self.chromosomes[
                int(self.rand.randint(0, len(self.chromosomes)))]
        # tournament: best of a small uniform sample
        k = max(2, min(self.tournament_size, len(self.chromosomes)))
        idx = [int(self.rand.randint(0, len(self.chromosomes)))
               for _ in range(k)]
        pool = [self.chromosomes[i] for i in idx]
        fit = [c.fitness if (c.fitness is not None and
                             numpy.isfinite(c.fitness))
               else -numpy.inf for c in pool]
        return pool[int(numpy.argmax(fit))]

    def _roulette_pick(self) -> Chromosome:
        fits = numpy.array([c.fitness for c in self.chromosomes])
        # failed evaluations report -inf; give them zero selection weight
        # without poisoning the arithmetic below
        finite = numpy.isfinite(fits)
        if not finite.any():
            return self.chromosomes[int(self.rand.randint(0, len(fits)))]
        fits = numpy.where(finite, fits, fits[finite].min())
        fits = fits - fits.min() + 1e-9
        fits[~finite] = 0.0
        probs = fits / fits.sum()
        i = int(numpy.searchsorted(numpy.cumsum(probs), self.rand.rand()))
        return self.chromosomes[min(i, len(self.chromosomes) - 1)]

    # -- crossover family (reference veles/genetics/core.py:428-429,633-659) --
    def _cross(self, a: Chromosome, b: Chromosome) -> Chromosome:
        kind = self.crossover
        if kind == "uniform":
            mask = self.rand.rand(len(a.genes)) < 0.5
            genes = numpy.where(mask, a.genes, b.genes)
        elif kind == "arithmetic":
            t = self.rand.rand(len(a.genes))
            genes = t * a.genes + (1.0 - t) * b.genes
        elif kind == "geometric":
            # geometric mean in range-normalized space keeps bounds
            na = (a.genes - self.mins) / numpy.maximum(
                self.maxs - self.mins, 1e-12)
            nb = (b.genes - self.mins) / numpy.maximum(
                self.maxs - self.mins, 1e-12)
            g = numpy.sqrt(numpy.maximum(na, 1e-12) *
                           numpy.maximum(nb, 1e-12))
            genes = self.mins + g * (self.maxs - self.mins)
        elif kind == "pointed":
            # n-point crossover on the flat gene vector
            n = max(1, len(a.genes) // 2)
            points = sorted(set(
                int(self.rand.randint(1, max(2, len(a.genes))))
                for _ in range(n)))
            genes = a.genes.copy()
            src_b = False
            prev = 0
            for pt in points + [len(a.genes)]:
                if src_b:
                    genes[prev:pt] = b.genes[prev:pt]
                src_b = not src_b
                prev = pt
        else:
            raise ValueError("unknown crossover %r" % kind)
        return Chromosome(genes, self.mins, self.maxs, self.ints)

    def _mutate_child(self, child: Chromosome) -> None:
        """One operator drawn uniformly from the census (the reference
        applied every configured mutation with per-operator
        probabilities, core.py:549-566; one-draw keeps the per-child
        mutation pressure at ``mutation_rate`` exactly)."""
        op = self.MUTATIONS[int(self.rand.randint(0, len(self.MUTATIONS)))]
        if op == "binary":
            child.mutate_binary(1, self.rand)
        elif op == "gaussian":
            child.mutate_gaussian(1, 0.1, self.rand)
        elif op == "uniform":
            child.mutate_uniform(1, self.rand)
        else:
            child.mutate_altering(1, self.rand)

    # -- generation step ------------------------------------------------------
    def evolve(self, evaluator: Optional[
            Callable[[Chromosome, int], float]] = None,
            batch_evaluator: Optional[
                Callable[[List[Chromosome]], Sequence[float]]] = None
            ) -> None:
        """Evaluate all unscored chromosomes, then breed the next
        generation (elite carried over unchanged).

        ``batch_evaluator(chromosomes) -> fitnesses`` scores every
        unscored candidate in ONE call — the hook the parallel trial
        scheduler plugs into (the generation is the natural fan-out
        unit: its members are independent by construction)."""
        pending = [(i, c) for i, c in enumerate(self.chromosomes)
                   if c.fitness is None]
        if batch_evaluator is not None:
            fits = list(batch_evaluator([c for _, c in pending]))
            if len(fits) != len(pending):
                raise ValueError("batch evaluator returned %d scores for "
                                 "%d candidates" % (len(fits), len(pending)))
            for (_, chromo), fit in zip(pending, fits):
                chromo.fitness = float(fit)
        else:
            if evaluator is None:
                raise ValueError("evolve needs evaluator or batch_evaluator")
            for i, chromo in pending:
                chromo.fitness = float(evaluator(chromo, i))
        self.chromosomes.sort(key=lambda c: -c.fitness)
        n_elite = max(1, int(round(self.size * self.elite_fraction)))
        next_gen = self.chromosomes[:n_elite]
        while len(next_gen) < self.size:
            child = self._cross(self._pick(), self._pick())
            if self.rand.rand() < self.mutation_rate:
                self._mutate_child(child)
            next_gen.append(child)
        self.chromosomes = next_gen
        self.generation += 1
        self.info("generation %d: best fitness %.6g",
                  self.generation, self.chromosomes[0].fitness)
