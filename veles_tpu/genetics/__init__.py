"""Genetic hyper-parameter optimization (meta-learning).

TPU-era rebuild of the reference's veles/genetics/ package (SURVEY.md §2.6):
- core.py        — Chromosome / Population GA engine (gray coding, four
                   crossover families, binary + gaussian mutation,
                   roulette selection with elitism).
- config.py      — Range/Tuneable markers placed inside the config tree
                   and the chromosome ⇄ config mapping.
- optimization.py— GeneticsOptimizer: evaluates each chromosome by
                   building + running the user workflow, fitness from its
                   gathered results.
"""

from .core import Chromosome, Population  # noqa: F401
from .config import (Range, Tuneable, find_tuneables,  # noqa: F401
                     fix_config, materialize_defaults)
from .optimization import GeneticsOptimizer  # noqa: F401
