"""Range/Tuneable markers inside the config tree.

Rebuild of the reference's veles/genetics/config.py:45-223: a user writes

    root.my_model.lr = Range(0.03, 0.001, 0.1)
    root.my_model.layers = Range(2, 1, 5)

and the optimizer walks the tree, collects the markers (chromosome ⇄
config mapping), and ``fix_config`` materializes one chromosome's values
back into the tree before each evaluation (reference ``fix_config``
:164).
"""

from __future__ import annotations

from typing import Any, List, Tuple

from ..config import Config


class Tuneable:
    """Base marker for values the optimizer may change. In a plain
    (non ``--optimize``) run, ``materialize_defaults`` collapses every
    marker to its default before the workflow is built."""

    def __init__(self, default: Any) -> None:
        self.default = default

    def __repr__(self) -> str:
        return "%s(%r)" % (type(self).__name__, self.default)


class Range(Tuneable):
    """Numeric gene: default value plus inclusive [min, max] bounds.
    Integer-ness is inferred from the default's type (reference
    veles/genetics/config.py:45-130)."""

    def __init__(self, default, vmin, vmax) -> None:
        super().__init__(default)
        if not vmin <= default <= vmax:
            raise ValueError("Range default %r outside [%r, %r]"
                             % (default, vmin, vmax))
        self.min = vmin
        self.max = vmax
        self.is_int = isinstance(default, int) and not isinstance(
            default, bool)

    def __repr__(self) -> str:
        return "Range(%r, %r, %r)" % (self.default, self.min, self.max)


def resolve(value: Any) -> Any:
    """Config value or, for a yet-uncollapsed marker (direct script
    import, no CLI to call materialize_defaults), its default — the
    one resolver every optimize-ready model shares."""
    return value.default if isinstance(value, Tuneable) else value


def find_tuneables(node: Config, path: str = None) -> List[
        Tuple[str, Config, str, Range]]:
    """DFS the config tree for Tuneable leaves.

    Returns [(dotted_path, parent_node, attr_name, marker), ...] in
    deterministic (insertion) order — gene order must be stable across
    processes for distributed evaluation.
    """
    if path is None:
        path = node._path_
    found = []
    for key, value in node.items():
        sub = "%s.%s" % (path, key)
        if isinstance(value, Config):
            found.extend(find_tuneables(value, sub))
        elif isinstance(value, Tuneable):
            found.append((sub, node, key, value))
    return found


def fix_config(tuneables, values) -> None:
    """Write one chromosome's values into the tree in marker order."""
    if len(tuneables) != len(values):
        raise ValueError("%d tuneables vs %d values"
                         % (len(tuneables), len(values)))
    for (path, node, key, marker), value in zip(tuneables, values):
        setattr(node, key, int(value) if getattr(marker, "is_int", False)
                else value)


def materialize_defaults(node: Config) -> int:
    """Collapse every Tuneable marker to its default value — called for
    normal (non-optimizing) runs so a config written for ``--optimize``
    still works as-is. Returns how many markers were replaced."""
    replaced = 0
    for path, parent, key, marker in find_tuneables(node):
        setattr(parent, key, marker.default)
        replaced += 1
    return replaced


def restore_markers(tuneables) -> None:
    """Put the markers back (so repeated optimize runs see them)."""
    for path, node, key, marker in tuneables:
        setattr(node, key, marker)
