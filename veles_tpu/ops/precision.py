"""MXU precision policy.

The reference exposed OpenCL summation precision levels (simple / Kahan /
multipartial, veles/config.py:245-248 — +9 % and +90 % cost). The TPU
equivalent is the matmul/conv precision knob: ``bfloat16`` compute maps to
``lax.Precision.DEFAULT`` (one MXU pass over bf16-rounded operands),
``float32`` to ``Precision.HIGHEST`` (3-pass bf16 expansion). Keeping
arrays f32 and steering precision through this knob — instead of casting
operands — keeps autodiff dtype-consistent (mixed-dtype conv transposes
are rejected by lax) and lets the same code run full-precision on CPU.
"""

from __future__ import annotations

from ..config import root


def matmul_precision():
    """lax.Precision for dots/convs under the current engine config."""
    import jax.lax as lax
    cdt = str(root.common.engine.compute_dtype)
    if cdt in ("bfloat16", "bf16"):
        return lax.Precision.DEFAULT
    return lax.Precision.HIGHEST


def promote_operands(x, w):
    """Cast both MXU operands to their promoted common dtype so lax conv/
    dot never sees a mixed-dtype pair (f32 activations × bf16 params is
    legal config, illegal lax input)."""
    import jax.numpy as jnp
    ct = jnp.promote_types(x.dtype, w.dtype)
    return x.astype(ct), w.astype(ct), ct
