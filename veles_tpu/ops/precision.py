"""MXU precision policy.

The reference exposed OpenCL summation precision levels (simple / Kahan /
multipartial, veles/config.py:245-248 — +9 % and +90 % cost). The TPU
equivalent is the matmul/conv precision knob: ``bfloat16`` compute maps to
``lax.Precision.DEFAULT`` (one MXU pass over bf16-rounded operands),
``float32`` to ``Precision.HIGHEST`` (3-pass bf16 expansion). Keeping
arrays f32 and steering precision through this knob — instead of casting
operands — keeps autodiff dtype-consistent (mixed-dtype conv transposes
are rejected by lax) and lets the same code run full-precision on CPU.
"""

from __future__ import annotations

from ..config import root


def matmul_precision():
    """lax.Precision for dots/convs under the current engine config."""
    import jax.lax as lax
    cdt = str(root.common.engine.compute_dtype)
    if cdt in ("bfloat16", "bf16"):
        return lax.Precision.DEFAULT
    return lax.Precision.HIGHEST


def promote_operands(x, w):
    """Cast both MXU operands to their promoted common dtype so lax conv/
    dot never sees a mixed-dtype pair (f32 activations × bf16 params is
    legal config, illegal lax input)."""
    import jax.numpy as jnp
    ct = jnp.promote_types(x.dtype, w.dtype)
    return x.astype(ct), w.astype(ct), ct


# -- int8 quantization primitives (veles_tpu/quant/) -----------------------
#
# Symmetric linear quantization: q = round(x / s) clipped to [-127, 127],
# x̂ = q · s, with s = max|x| / 127 over the reduction group. "per_channel"
# keeps one scale per OUTPUT column of a 2-D weight (axis -1 — the
# granularity that survives a matmul: column j of W only ever multiplies
# into output j, so its scale factors out exactly); "per_tensor" keeps one
# scalar. The same functions trace under jit (dequant-on-read in the
# serving decode programs) and run eagerly on host arrays (the offline
# ``veles-tpu quantize`` CLI) — numpy inputs round-trip through jax on
# CPU, so the two paths cannot disagree on rounding.

#: symmetric int8 clip bound (−128 is unused so +x and −x quantize
#: symmetrically — the standard inference-quantization convention)
INT8_QMAX = 127.0


def quantize_int8(arr, axis=None):
    """``arr`` (float) → ``(q int8, scale f32)``. ``axis=None`` = one
    scalar scale (per-tensor); ``axis=k`` = per-channel scales along
    that axis (scale keeps ``arr``'s rank with size-1 reduced dims, so
    ``q * scale`` broadcasts back without bookkeeping). All-zero groups
    get scale 1 so dequantization never divides by or multiplies with
    junk."""
    import jax.numpy as jnp
    arr = jnp.asarray(arr)
    if axis is None:
        red = None
    else:
        axis = axis % arr.ndim
        red = tuple(i for i in range(arr.ndim) if i != axis)
    amax = jnp.max(jnp.abs(arr.astype(jnp.float32)), axis=red,
                   keepdims=axis is not None)
    scale = jnp.where(amax > 0, amax / INT8_QMAX, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(arr.astype(jnp.float32) / scale),
                 -INT8_QMAX, INT8_QMAX).astype(jnp.int8)
    return q, scale


def dequantize_int8(q, scale, dtype=None):
    """``q · scale`` back to float (``dtype`` defaults to the scale's
    float32). Trace-safe: this is THE dequant-on-read the serving decode
    programs inline in front of their matmuls — XLA fuses it into the
    consumer, so int8 is the *storage* format while the MXU still sees
    its usual float operands."""
    import jax.numpy as jnp
    out = jnp.asarray(q).astype(jnp.float32) * jnp.asarray(scale)
    return out if dtype is None else out.astype(dtype)


def quantize_rows_int8(x):
    """Per-row symmetric int8 for KV-cache tensors: ``x``
    (..., T, H, Dh) → ``(q int8, scales (..., T) f32)`` — one scale per
    cached position, amax-reduced over the row's (H, Dh) block. The
    row is the natural KV group: a decode step writes exactly one new
    position, so its scale is computed once and never revised, and
    re-quantizing an untouched row with its own unchanged scale is
    bit-exact (round(q·s/s) == q)."""
    import jax.numpy as jnp
    x = jnp.asarray(x)
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=(-2, -1))
    scale = jnp.where(amax > 0, amax / INT8_QMAX, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(x.astype(jnp.float32)
                           / scale[..., None, None]),
                 -INT8_QMAX, INT8_QMAX).astype(jnp.int8)
    return q, scale


def dequantize_rows_int8(q, scale, dtype=None):
    """Inverse of :func:`quantize_rows_int8` (scales broadcast back
    over each position's (H, Dh) block)."""
    import jax.numpy as jnp
    out = (jnp.asarray(q).astype(jnp.float32)
           * jnp.asarray(scale)[..., None, None])
    return out if dtype is None else out.astype(dtype)
