"""Low-level op helpers shared by compute units."""

from .precision import matmul_precision  # noqa: F401


def compiler_params(pltpu):
    """Mosaic compiler-params dataclass across jax versions:
    ``pltpu.CompilerParams`` (new) was ``pltpu.TPUCompilerParams`` on
    jax 0.4.x — same fields, renamed class. ONE copy for every Pallas
    kernel in this package (the shard_map analogue lives in
    parallel/compat.py)."""
    return (getattr(pltpu, "CompilerParams", None)
            or pltpu.TPUCompilerParams)
