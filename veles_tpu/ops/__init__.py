"""Low-level op helpers shared by compute units."""

from .precision import (matmul_precision, quantize_int8,  # noqa: F401
                        dequantize_int8, quantize_rows_int8,
                        dequantize_rows_int8)


def compiler_params(pltpu):
    """Mosaic compiler-params dataclass across jax versions:
    ``pltpu.CompilerParams`` (new) was ``pltpu.TPUCompilerParams`` on
    jax 0.4.x — same fields, renamed class. ONE copy for every Pallas
    kernel in this package (the shard_map analogue lives in
    parallel/compat.py)."""
    return (getattr(pltpu, "CompilerParams", None)
            or pltpu.TPUCompilerParams)
