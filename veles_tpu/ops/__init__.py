"""Low-level op helpers shared by compute units."""

from .precision import matmul_precision  # noqa: F401
