"""Whole-epoch fused FC training kernel (Pallas).

The MNIST-784 headline config (784 → hidden tanh → softmax, plain SGD,
reference topology `manualrst_veles_algorithms.rst:31`) is sequential-
SGD-bound, not FLOP-bound: `docs/perf.md` measures the per-step cost at
~36 µs — the TPU `lax.scan` step floor for these shapes, dominated by
per-step weight round trips through HBM and loop overhead, with the MXU
under 1 % busy. This kernel runs an ENTIRE epoch of SGD steps as ONE
Pallas grid with the weights resident in VMEM scratch for all K steps:
no HBM weight traffic between steps, no scan-step machinery — the only
per-step HBM reads are the minibatch block (pipelined by Mosaic's
double buffering) while forward, backward and update run back-to-back
on the same core-resident parameters.

Scope (checked by ``fused_fc_eligible``): exactly two dense layers
(tanh hidden, softmax + cross-entropy head), plain SGD, whole
minibatches. The TPU-first point is the *shape* of the solution — the
reference could never fuse its per-unit OpenCL dispatch chain
(`veles/znicz/all2all.py` + `gd.py` kernels) into one residency-
preserving program; on TPU one kernel IS the epoch.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

LANE = 128
SUB = 8
NEG = -1e30


def _pad_to(x, axis, mult):
    size = x.shape[axis]
    want = ((size + mult - 1) // mult) * mult
    if want == size:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, want - size)
    return jnp.pad(x, pads)


def _kernel(lr_ref, x_ref, y_ref, w1_ref, b1_ref, w2_ref, b2_ref,
            w1o_ref, b1o_ref, w2o_ref, b2o_ref, acc_ref,
            w1_s, b1_s, w2_s, b2_s, acc_s, *,
            mb: int, nout: int, steps: int,
            act_a: float = 1.0, act_b: float = 1.0):
    """One grid step = one SGD minibatch step, weights in VMEM scratch.

    acc layout: [0, 0] = summed CE loss, [0, 1] = error count — both
    over the REAL (unpadded) rows of the epoch.
    """
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _load():
        w1_s[:] = w1_ref[:]
        b1_s[:] = b1_ref[:]
        w2_s[:] = w2_ref[:]
        b2_s[:] = b2_ref[:]
        acc_s[:] = jnp.zeros_like(acc_s)

    x = x_ref[0]                       # (mb_p, fin_p) f32
    y = y_ref[0]                       # (mb_p, nout_p) one-hot, pad=0
    mb_p, _ = x.shape
    nout_p = y.shape[1]
    lr = lr_ref[0, 0]

    # masks for the zero-padded rows (minibatch → sublane multiple) and
    # class lanes (nout → lane multiple): pad rows must not contribute
    # gradients, pad lanes must not receive softmax mass
    row = jax.lax.broadcasted_iota(jnp.int32, (mb_p, 1), 0)
    rmask = (row < mb).astype(jnp.float32)                 # (mb_p, 1)
    lane = jax.lax.broadcasted_iota(jnp.int32, (mb_p, nout_p), 1)
    lane_bias = jnp.where(lane < nout, 0.0, NEG)

    h_pre = jax.lax.dot_general(
        x, w1_s[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) + b1_s[:1, :]
    # Znicz LeCun-scaled tanh: y = A*tanh(B*a) (all2all.py A, B);
    # A = B = 1 degrades to the plain tanh
    h = act_a * jnp.tanh(act_b * h_pre)                    # (mb_p, hid_p)
    logits = jax.lax.dot_general(
        h, w2_s[:], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) + b2_s[:1, :] + lane_bias

    m = logits.max(axis=1, keepdims=True)
    e = jnp.exp(logits - m)
    s = e.sum(axis=1, keepdims=True)
    p = e / s
    logp = logits - m - jnp.log(s)

    # metrics over real rows (y is all-zero on pad rows already).
    # Error rule must MATCH EvaluatorSoftmax exactly: strict argmax
    # with ties resolved to the LOWEST class index (jnp.argmax) — a
    # probability-tolerance rule would disagree on tied logits.
    loss = -(y * logp).sum()
    is_max = logits >= logits.max(axis=1, keepdims=True)
    big = jnp.int32(nout_p)
    pred = jnp.where(is_max, lane, big).min(axis=1, keepdims=True)
    label_idx = (y * lane.astype(jnp.float32)).sum(
        axis=1, keepdims=True).astype(jnp.int32)
    correct = pred == label_idx
    err = (rmask * (1.0 - correct.astype(jnp.float32))).sum()
    r0 = jax.lax.broadcasted_iota(jnp.int32, acc_s.shape, 0)
    c0 = jax.lax.broadcasted_iota(jnp.int32, acc_s.shape, 1)
    acc_s[:] = acc_s[:] + jnp.where(
        (r0 == 0) & (c0 == 0), loss,
        jnp.where((r0 == 0) & (c0 == 1), err, 0.0))

    # backward (mean CE over the real minibatch) + in-place SGD
    dlog = (p - y) * rmask / mb                            # (mb_p, nout_p)
    dw2 = jax.lax.dot_general(
        h, dlog, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                # (hid_p, nout_p)
    db2 = dlog.sum(axis=0, keepdims=True)
    dh = jax.lax.dot_general(
        dlog, w2_s[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                # (mb_p, hid_p)
    # dh/da of A*tanh(B*a) expressed in h: A*B - (B/A)*h^2
    dpre = dh * (act_a * act_b - (act_b / act_a) * h * h)
    dw1 = jax.lax.dot_general(
        x, dpre, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                # (fin_p, hid_p)
    db1 = dpre.sum(axis=0, keepdims=True)

    w1_s[:] = w1_s[:] - lr * dw1
    w2_s[:] = w2_s[:] - lr * dw2
    b1_s[:] = b1_s[:] - lr * jnp.broadcast_to(db1, b1_s.shape)
    b2_s[:] = b2_s[:] - lr * jnp.broadcast_to(db2, b2_s.shape)

    @pl.when(i == steps - 1)
    def _store():
        w1o_ref[:] = w1_s[:]
        b1o_ref[:] = b1_s[:]
        w2o_ref[:] = w2_s[:]
        b2o_ref[:] = b2_s[:]
        acc_ref[:] = acc_s[:]


def fused_fc_sgd_epoch(w1, b1, w2, b2, dataset, labels, plan, lr,
                       n_classes: Optional[int] = None,
                       act_a: float = 1.0, act_b: float = 1.0,
                       interpret: Optional[bool] = None):
    """One SGD epoch of ``x→tanh(x·W1+b1)→softmax(h·W2+b2)`` with CE
    loss, executed as a single Pallas program with VMEM-resident
    weights.

    - w1 (fin, hid), b1 (hid,), w2 (hid, nout), b2 (nout,) — f32
    - dataset (N, fin) f32, labels (N,) int32
    - plan (K, mb) int32: the epoch's shuffled minibatch indices (same
      contract as TrainStep's plan serving — trajectory parity with the
      per-step path needs the same plan)
    - lr: scalar learning rate

    Returns ``(w1', b1', w2', b2', loss_sum, err_count)`` — loss summed
    and errors counted over the whole epoch (the caller derives means).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    k_steps, mb = plan.shape
    fin, hid = w1.shape
    nout = w2.shape[1] if n_classes is None else int(n_classes)

    f32 = jnp.float32
    # epoch-sized gather+pad: ~2× the minibatch-stream HBM traffic and
    # a (K, mb_p, fin_p) intermediate. Measured against the headline:
    # ~224 MB write + re-read per epoch ≈ 0.6 ms at HBM speed vs a
    # ~20 ms epoch — the contiguous input stream it buys Mosaic's
    # pipeline is worth far more than a scalar-prefetch redesign
    xg = dataset.astype(f32)[plan]                  # (K, mb, fin)
    yg = jax.nn.one_hot(labels[plan], nout, dtype=f32)
    xg = _pad_to(_pad_to(xg, 1, SUB), 2, LANE)      # (K, mb_p, fin_p)
    yg = _pad_to(_pad_to(yg, 1, SUB), 2, LANE)
    mb_p, fin_p = xg.shape[1], xg.shape[2]
    nout_p = yg.shape[2]

    w1p = _pad_to(_pad_to(w1.astype(f32), 0, LANE), 1, LANE)
    w2p = _pad_to(_pad_to(w2.astype(f32), 0, LANE), 1, LANE)
    hid_p = w1p.shape[1]
    b1p = jnp.broadcast_to(_pad_to(b1.astype(f32)[None, :], 1, LANE),
                           (SUB, hid_p))
    b2p = jnp.broadcast_to(_pad_to(b2.astype(f32)[None, :], 1, LANE),
                           (SUB, nout_p))
    lr2 = jnp.full((1, 1), lr, f32)

    kernel = functools.partial(_kernel, mb=mb, nout=nout,
                               steps=k_steps, act_a=float(act_a),
                               act_b=float(act_b))
    vm = pltpu.VMEM
    fix = lambda *shape: pl.BlockSpec(shape, lambda i: (0,) * len(shape),  # noqa: E731
                                      memory_space=vm)
    w1o, b1o, w2o, b2o, acc = pl.pallas_call(
        kernel,
        grid=(k_steps,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, mb_p, fin_p), lambda i: (i, 0, 0),
                         memory_space=vm),
            pl.BlockSpec((1, mb_p, nout_p), lambda i: (i, 0, 0),
                         memory_space=vm),
            fix(fin_p, hid_p), fix(SUB, hid_p),
            fix(hid_p, nout_p), fix(SUB, nout_p),
        ],
        out_specs=[fix(fin_p, hid_p), fix(SUB, hid_p),
                   fix(hid_p, nout_p), fix(SUB, nout_p),
                   fix(SUB, LANE)],
        out_shape=[
            jax.ShapeDtypeStruct((fin_p, hid_p), f32),
            jax.ShapeDtypeStruct((SUB, hid_p), f32),
            jax.ShapeDtypeStruct((hid_p, nout_p), f32),
            jax.ShapeDtypeStruct((SUB, nout_p), f32),
            jax.ShapeDtypeStruct((SUB, LANE), f32),
        ],
        scratch_shapes=[
            pltpu.VMEM((fin_p, hid_p), f32),
            pltpu.VMEM((SUB, hid_p), f32),
            pltpu.VMEM((hid_p, nout_p), f32),
            pltpu.VMEM((SUB, nout_p), f32),
            pltpu.VMEM((SUB, LANE), f32),
        ],
        # one sequential dimension: every step reads+writes the same
        # VMEM-resident weights
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(lr2, xg, yg, w1p, b1p, w2p, b2p)

    return (w1o[:fin, :hid], b1o[0, :hid], w2o[:hid, :nout],
            b2o[0, :nout], acc[0, 0], acc[0, 1])


def fused_fc_oracle(w1, b1, w2, b2, dataset, labels, plan, lr,
                    n_classes: Optional[int] = None,
                    act_a: float = 1.0, act_b: float = 1.0):
    """jnp reference (lax.scan of per-step SGD) — the equivalence
    oracle for the kernel; same plan, same math, per-step HBM weights."""
    nout = w2.shape[1] if n_classes is None else int(n_classes)
    mb = plan.shape[1]
    f32 = jnp.float32

    def step(carry, idx):
        w1, b1, w2, b2, loss, err = carry
        x = dataset.astype(f32)[idx]
        y = jax.nn.one_hot(labels[idx], nout, dtype=f32)
        h = act_a * jnp.tanh(act_b * (x @ w1 + b1))
        logits = h @ w2 + b2
        logp = jax.nn.log_softmax(logits)
        p = jnp.exp(logp)
        loss = loss - (y * logp).sum()
        err = err + (jnp.argmax(logits, 1) != labels[idx]).sum()
        dlog = (p - y) / mb
        dw2 = h.T @ dlog
        db2 = dlog.sum(0)
        dh = dlog @ w2.T
        dpre = dh * (act_a * act_b - (act_b / act_a) * h * h)
        dw1 = x.T @ dpre
        db1 = dpre.sum(0)
        return (w1 - lr * dw1, b1 - lr * db1,
                w2 - lr * dw2, b2 - lr * db2, loss, err), None

    init = (w1.astype(f32), b1.astype(f32), w2.astype(f32),
            b2.astype(f32), jnp.float32(0.0), jnp.int32(0))
    (w1, b1, w2, b2, loss, err), _ = jax.lax.scan(step, init, plan)
    return w1, b1, w2, b2, loss, err.astype(f32)
