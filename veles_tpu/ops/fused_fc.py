"""Whole-epoch fused FC training kernel (Pallas).

The MNIST-784 headline config (784 → hidden tanh → softmax, plain SGD,
reference topology `manualrst_veles_algorithms.rst:31`) is sequential-
SGD-bound, not FLOP-bound: `docs/perf.md` measures the per-step cost at
~36 µs — the TPU `lax.scan` step floor for these shapes, dominated by
per-step weight round trips through HBM and loop overhead, with the MXU
under 1 % busy. This kernel runs an ENTIRE epoch of SGD steps as ONE
Pallas grid with the weights (and momentum state) resident in VMEM
scratch for all K steps: no HBM weight traffic between steps, no
scan-step machinery — the only per-step HBM reads are the minibatch
block (pipelined by Mosaic's double buffering) while forward, backward
and update run back-to-back on the same core-resident parameters.

Scope (checked by ``TrainStep._setup_fused_fc``): a chain of dense
tanh layers ending in a softmax + cross-entropy head, Znicz SGD with
momentum and coupled L2 weight decay, whole minibatches. The TPU-first
point is the *shape* of the solution — the reference could never fuse
its per-unit OpenCL dispatch chain (`veles/znicz/all2all.py` +
`gd.py` kernels) into one residency-preserving program; on TPU one
kernel IS the epoch.

Update rule, exactly the general path's (nn_units.py GradientDescent):
``delta = lr·(g + wd·p) + mu·delta_prev; p -= delta`` — the delta
recurrence (with lr folded in, like the scan path's opt_state) rides
in VMEM and is returned, so resuming or switching engines mid-training
continues the identical trajectory.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

LANE = 128
SUB = 8
NEG = -1e30


from . import compiler_params as _compiler_params


def _pad_to(x, axis, mult):
    size = x.shape[axis]
    want = ((size + mult - 1) // mult) * mult
    if want == size:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, want - size)
    return jnp.pad(x, pads)


def _kernel(refs, *, n_layers: int, mb: int, nout: int, steps: int,
            act_a: float, act_b: float, lr_bias_ratio: float,
            wd: float, wd_bias: float, momentum: float,
            precision=None):
    """One grid step = one SGD minibatch step, all state in VMEM.

    refs layout (built by fused_fc_sgd_epoch):
      [lr, x, y,
       w_0..w_{L-1}, b_0.., vw_0.., vb_0..,          (inputs)
       wo_0.., bo_0.., vwo_0.., vbo_0.., acc,        (outputs)
       ws_0.., bs_0.., vws_0.., vbs_0.., acc_s]      (scratch)
    acc[0, 0] = summed CE loss, acc[0, 1] = error count — over the
    REAL (unpadded) rows of the epoch.
    """
    from jax.experimental import pallas as pl

    L = n_layers
    it = iter(refs)
    lr_ref, x_ref, y_ref = next(it), next(it), next(it)
    w_in = [next(it) for _ in range(L)]
    b_in = [next(it) for _ in range(L)]
    vw_in = [next(it) for _ in range(L)]
    vb_in = [next(it) for _ in range(L)]
    w_out = [next(it) for _ in range(L)]
    b_out = [next(it) for _ in range(L)]
    vw_out = [next(it) for _ in range(L)]
    vb_out = [next(it) for _ in range(L)]
    acc_ref = next(it)
    w_s = [next(it) for _ in range(L)]
    b_s = [next(it) for _ in range(L)]
    vw_s = [next(it) for _ in range(L)]
    vb_s = [next(it) for _ in range(L)]
    acc_s = next(it)

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _load():
        for dst, src in zip(w_s + b_s + vw_s + vb_s,
                            w_in + b_in + vw_in + vb_in):
            dst[:] = src[:]
        acc_s[:] = jnp.zeros_like(acc_s)

    x = x_ref[0]                       # (mb_p, fin_p) f32
    y = y_ref[0]                       # (mb_p, nout_p) one-hot, pad=0
    mb_p = x.shape[0]
    nout_p = y.shape[1]
    lr = lr_ref[0, 0]

    # masks for the zero-padded rows (minibatch → sublane multiple) and
    # class lanes (nout → lane multiple): pad rows must not contribute
    # gradients, pad lanes must not receive softmax mass
    row = jax.lax.broadcasted_iota(jnp.int32, (mb_p, 1), 0)
    rmask = (row < mb).astype(jnp.float32)                 # (mb_p, 1)
    lane = jax.lax.broadcasted_iota(jnp.int32, (mb_p, nout_p), 1)
    lane_bias = jnp.where(lane < nout, 0.0, NEG)

    def dot(a, bmat):
        return jax.lax.dot_general(
            a, bmat, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=precision)

    # forward: tanh chain, logits head; acts[li] is layer li's INPUT
    # (so acts[li] for li >= 1 is also layer li-1's tanh output — the
    # backward reads both roles from the one list)
    acts = [x]
    h = x
    for li in range(L - 1):
        pre = dot(h, w_s[li][:]) + b_s[li][:1, :]
        # Znicz LeCun-scaled tanh: y = A*tanh(B*a) (all2all.py A, B)
        h = act_a * jnp.tanh(act_b * pre)
        acts.append(h)
    logits = dot(h, w_s[L - 1][:]) + b_s[L - 1][:1, :] + lane_bias

    m = logits.max(axis=1, keepdims=True)
    e = jnp.exp(logits - m)
    s = e.sum(axis=1, keepdims=True)
    p = e / s
    logp = logits - m - jnp.log(s)

    # metrics over real rows (y is all-zero on pad rows already).
    # Error rule MATCHES EvaluatorSoftmax: strict argmax, ties to the
    # LOWEST class index (jnp.argmax).
    loss = -(y * logp).sum()
    is_max = logits >= logits.max(axis=1, keepdims=True)
    big = jnp.int32(nout_p)
    pred = jnp.where(is_max, lane, big).min(axis=1, keepdims=True)
    label_idx = (y * lane.astype(jnp.float32)).sum(
        axis=1, keepdims=True).astype(jnp.int32)
    correct = pred == label_idx
    err = (rmask * (1.0 - correct.astype(jnp.float32))).sum()
    r0 = jax.lax.broadcasted_iota(jnp.int32, acc_s.shape, 0)
    c0 = jax.lax.broadcasted_iota(jnp.int32, acc_s.shape, 1)
    acc_s[:] = acc_s[:] + jnp.where(
        (r0 == 0) & (c0 == 0), loss,
        jnp.where((r0 == 0) & (c0 == 1), err, 0.0))

    # backward (mean CE over the real minibatch), then the Znicz SGD
    # delta recurrence, all in-place on the VMEM state
    d_out = (p - y) * rmask / mb                  # d loss / d logits

    def tdot(a, bmat, contract_rows):
        # contract_rows: a^T @ b (rows) vs a @ b^T (cols)
        dims = (((0,), (0,)), ((), ())) if contract_rows \
            else (((1,), (1,)), ((), ()))
        return jax.lax.dot_general(a, bmat, dims,
                                   preferred_element_type=jnp.float32,
                                   precision=precision)

    for li in range(L - 1, -1, -1):
        a_in = acts[li]
        dw = tdot(a_in, d_out, True)              # (in_p, out_p)
        db = d_out.sum(axis=0, keepdims=True)
        if li > 0:
            d_h = tdot(d_out, w_s[li][:], False)  # (mb_p, in_p)
            hh = acts[li]                         # layer li-1's tanh out
            # dh/da of A*tanh(B*a) expressed in h: A*B - (B/A)*h^2
            d_out = d_h * (act_a * act_b - (act_b / act_a) * hh * hh)
        dlt_w = lr * (dw + wd * w_s[li][:]) + momentum * vw_s[li][:]
        dlt_b = (lr * lr_bias_ratio
                 * (jnp.broadcast_to(db, b_s[li].shape)
                    + wd_bias * b_s[li][:])
                 + momentum * vb_s[li][:])
        w_s[li][:] = w_s[li][:] - dlt_w
        b_s[li][:] = b_s[li][:] - dlt_b
        vw_s[li][:] = dlt_w
        vb_s[li][:] = dlt_b

    @pl.when(i == steps - 1)
    def _store():
        for dst, src in zip(w_out + b_out + vw_out + vb_out,
                            w_s + b_s + vw_s + vb_s):
            dst[:] = src[:]
        acc_ref[:] = acc_s[:]


def analytic_cost(layer_shapes: Sequence, mb: int, steps: int):
    """Telemetry fallback cost of ONE fused epoch
    (veles_tpu/telemetry/cost.py): the Pallas custom call is opaque to
    XLA's HLO cost model, so the kernel's owner publishes the analytic
    model. ``layer_shapes``: (n_in, n_out) per dense layer. FLOPs per
    SGD step: forward 2·mb·Σ(in·out), backward 2× forward (dW and dx
    matmuls), plus the delta-recurrence update (~4 per parameter).
    Bytes: the minibatch stream is the only per-step HBM traffic (the
    residency-preserving point of the kernel); weights+momentum cross
    HBM exactly twice per epoch (load, store)."""
    from ..telemetry.cost import Cost
    mm = sum(int(i) * int(o) for i, o in layer_shapes)
    params = mm + sum(int(o) for _, o in layer_shapes)
    flops = steps * (3 * 2 * mb * mm + 4 * params)
    d0 = int(layer_shapes[0][0])
    stream = steps * mb * (d0 + 1) * 4            # f32 batch + labels
    bytes_accessed = stream + 2 * 2 * params * 4  # w+momentum, in+out

    def padded(n, m=LANE):
        return ((n + m - 1) // m) * m
    state = sum(2 * 4 * (padded(i) * padded(o) + SUB * padded(o))
                for i, o in layer_shapes)
    x_bytes = 4 * padded(mb, SUB) * padded(d0)
    return Cost(flops, bytes_accessed, state + 3 * x_bytes,
                source="analytic")


def fused_fc_sgd_epoch(weights: Sequence, biases: Sequence,
                       vel_w: Sequence, vel_b: Sequence,
                       dataset, labels, plan, lr,
                       n_classes: Optional[int] = None,
                       act_a: float = 1.0, act_b: float = 1.0,
                       lr_bias_ratio: float = 1.0,
                       wd: float = 0.0, wd_bias: float = 0.0,
                       momentum: float = 0.0,
                       interpret: Optional[bool] = None,
                       precision: Optional[str] = None):
    """One SGD epoch of an L-layer tanh chain + softmax-CE head as a
    single Pallas program with VMEM-resident weights AND momentum
    state.

    - weights[i] (d_i, d_{i+1}), biases[i] (d_{i+1},) — f32
    - vel_w/vel_b: the delta recurrence state (same shapes; the scan
      path's SGD opt_state). Pass zeros for a fresh run.
    - dataset (N, d_0) f32, labels (N,) int32
    - plan (K, mb) int32: the epoch's shuffled minibatch indices (same
      contract as TrainStep's plan serving)
    - lr: scalar learning rate for weights (traced OK — per-epoch
      schedules); the bias lr is ``lr * lr_bias_ratio`` (static
      ratio, so schedules scale both together like the scan path)
    - precision: dot precision for every matmul in the kernel. None
      (default) = the backend default — single-pass bf16 multiplies on
      the MXU, matching the scan path's own default-precision dots.
      'highest' = exact f32 multiplies; used by the chip parity gate to
      compare the kernel against an equally-exact oracle so algorithm
      bugs aren't hidden under (or mistaken for) bf16 rounding
      (measured on TPU v5 lite: default-vs-f32 drift is ~1.2e-3 after
      one step, ~2.6e-3 after a 12-step momentum epoch —
      docs/fused_fc_precision_probe.json)

    Returns ``(weights', biases', vel_w', vel_b', loss_sum,
    err_count)``.

    Note: the epoch-sized gather+pad below costs ~2× the minibatch-
    stream HBM traffic (~224 MB ≈ 0.6 ms/epoch at HBM speed for the
    MNIST headline vs a ~20 ms epoch) — the contiguous input stream it
    buys Mosaic's pipeline is worth far more than a scalar-prefetch
    redesign.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    L = len(weights)
    assert len(biases) == len(vel_w) == len(vel_b) == L and L >= 1
    k_steps, mb = plan.shape
    nout = weights[-1].shape[1] if n_classes is None else int(n_classes)

    f32 = jnp.float32
    xg = dataset.astype(f32)[plan]                  # (K, mb, d0)
    yg = jax.nn.one_hot(labels[plan], nout, dtype=f32)
    xg = _pad_to(_pad_to(xg, 1, SUB), 2, LANE)      # (K, mb_p, d0_p)
    yg = _pad_to(_pad_to(yg, 1, SUB), 2, LANE)
    mb_p, fin_p = xg.shape[1], xg.shape[2]
    nout_p = yg.shape[2]

    wp = [_pad_to(_pad_to(w.astype(f32), 0, LANE), 1, LANE)
          for w in weights]
    vwp = [_pad_to(_pad_to(v.astype(f32), 0, LANE), 1, LANE)
           for v in vel_w]
    bp, vbp = [], []
    for b, v in zip(biases, vel_b):
        row = _pad_to(b.astype(f32)[None, :], 1, LANE)
        bp.append(jnp.broadcast_to(row, (SUB, row.shape[1])))
        vrow = _pad_to(v.astype(f32)[None, :], 1, LANE)
        vbp.append(jnp.broadcast_to(vrow, (SUB, vrow.shape[1])))
    lr2 = jnp.full((1, 1), lr, f32)

    def kernel(*refs):
        _kernel(refs, n_layers=L, mb=mb, nout=nout, steps=k_steps,
                act_a=float(act_a), act_b=float(act_b),
                lr_bias_ratio=float(lr_bias_ratio), wd=float(wd),
                wd_bias=float(wd_bias), momentum=float(momentum),
                precision=precision)

    vm = pltpu.VMEM

    def fix(shape):
        return pl.BlockSpec(shape, lambda i: (0,) * len(shape),
                            memory_space=vm)

    mat_specs = [fix(w.shape) for w in wp]
    bias_specs = [fix(b.shape) for b in bp]
    in_specs = ([pl.BlockSpec((1, 1), lambda i: (0, 0),
                              memory_space=pltpu.SMEM),
                 pl.BlockSpec((1, mb_p, fin_p), lambda i: (i, 0, 0),
                              memory_space=vm),
                 pl.BlockSpec((1, mb_p, nout_p), lambda i: (i, 0, 0),
                              memory_space=vm)]
                + mat_specs + bias_specs + mat_specs + bias_specs)
    out_specs = (mat_specs + bias_specs + mat_specs + bias_specs
                 + [fix((SUB, LANE))])
    out_shape = ([jax.ShapeDtypeStruct(w.shape, f32) for w in wp]
                 + [jax.ShapeDtypeStruct(b.shape, f32) for b in bp]
                 + [jax.ShapeDtypeStruct(w.shape, f32) for w in wp]
                 + [jax.ShapeDtypeStruct(b.shape, f32) for b in bp]
                 + [jax.ShapeDtypeStruct((SUB, LANE), f32)])
    scratch = ([pltpu.VMEM(w.shape, f32) for w in wp]
               + [pltpu.VMEM(b.shape, f32) for b in bp]
               + [pltpu.VMEM(w.shape, f32) for w in wp]
               + [pltpu.VMEM(b.shape, f32) for b in bp]
               + [pltpu.VMEM((SUB, LANE), f32)])
    outs = pl.pallas_call(
        kernel,
        grid=(k_steps,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        # one sequential dimension: every step reads+writes the same
        # VMEM-resident weights
        compiler_params=_compiler_params(pltpu)(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(lr2, xg, yg, *wp, *bp, *vwp, *vbp)

    w_o = outs[:L]
    b_o = outs[L:2 * L]
    vw_o = outs[2 * L:3 * L]
    vb_o = outs[3 * L:4 * L]
    acc = outs[4 * L]
    dims = [w.shape for w in weights]
    w_f = [w_o[i][:dims[i][0], :dims[i][1]] for i in range(L)]
    b_f = [b_o[i][0, :dims[i][1]] for i in range(L)]
    vw_f = [vw_o[i][:dims[i][0], :dims[i][1]] for i in range(L)]
    vb_f = [vb_o[i][0, :dims[i][1]] for i in range(L)]
    return w_f, b_f, vw_f, vb_f, acc[0, 0], acc[0, 1]


# -- fused scale-bias-activation epilogues -----------------------------------
#
# The Znicz layer vocabulary allows standalone elementwise units after a
# matmul-bearing forward (``activation_tanh``/``activation_str``/
# ``activation_mul`` … — the cifar sample's topology). Inside the fused
# train step XLA fuses them for free, but on the standalone forward
# path (inference graphs, ``extract_forward_workflow``) every unit is
# its OWN jitted program: a [conv, activation] pair costs two device
# dispatches per minibatch where one consumer-fused program suffices.
# The epilogue plan folds each run of eligible elementwise tail units
# into the preceding matmul producer's program — the tail units then
# skip their dispatch entirely (removed, not renamed: the dispatch
# counter lock in tests/test_devtime.py). Opt-in via
# ``root.common.engine.fused_epilogue``; OFF is bit-identical to a
# build without the feature, ON applies the same ops in the same order
# inside one program. Composes with TensorMonitor taps: the taps read
# the post-epilogue head output, so monitoring never forces the
# unfused path (test-locked).


def epilogue_eligible(unit) -> bool:
    """True for forward units whose whole work is an rng-free,
    shape-preserving elementwise map — the scale (``activation_mul``)
    / activation vocabulary. Only these may fold into the producing
    matmul's program without changing semantics."""
    from ..nn.activation import ActivationForward
    return isinstance(unit, ActivationForward)


def plan_epilogues(forwards):
    """``[(producer, [tail units…]), …]`` — each maximal run of
    eligible elementwise units directly following a parameterized
    (matmul-bearing) forward, in chain order. Pure planning: no unit
    state is touched (the train step consumes the plan per trace;
    :func:`install_epilogues` materializes it for standalone runs)."""
    plan = []
    producer = None
    for f in forwards:
        if producer is not None and epilogue_eligible(f):
            if not plan or plan[-1][0] is not producer:
                plan.append((producer, []))
            plan[-1][1].append(f)
            continue
        producer = f if getattr(f, "PARAMETERIZED", False) else None
    return plan


def apply_epilogue(y, tails, train: bool = False):
    """Fold the elementwise tail into the matmul consumer: apply each
    planned tail unit's pure map to ``y`` inside the SAME traced
    program, in chain order — exactly the ops the unfused path runs,
    so on/off is bit-identical while the tail units' separate
    dispatches disappear."""
    for t in tails:
        y = t.apply({}, y, train=train, rng=None)
    return y


def install_epilogues(forwards, force: bool = False):
    """Materialize the epilogue plan on a standalone forward chain:
    producers get ``_epilogue_tails`` (their ``xla_run`` dispatches
    ONE program computing matmul + every tail, assigning EVERY
    stage's output array), tails get ``_epilogue_folded`` (their
    ``xla_run`` becomes a no-op — the removed dispatches). Gated on
    ``root.common.engine.fused_epilogue`` unless ``force``; returns
    the installed plan ``{producer name: [tail names]}`` (empty =
    nothing folded). Idempotent AND reversible: any previous plan on
    these units clears first — including each producer's cached
    ``apply_epilogue`` jitted closure, which would otherwise keep
    serving a stale tails list — so re-calling with the knob off
    restores the exact unfused dispatch layout. The numpy oracle path
    is untouched — tails still run there, keeping the oracle
    equivalence checks unfused."""
    from ..config import root
    for f in forwards:
        if getattr(f, "_epilogue_tails", None) is not None \
                or getattr(f, "_epilogue_folded", False):
            f._epilogue_tails = None
            f._epilogue_folded = False
            f._jit_cache.pop("apply_epilogue", None)
            f._jit_fns.pop("apply_epilogue", None)
    if not force and not root.common.engine.get("fused_epilogue",
                                                False):
        return {}
    installed = {}
    for producer, tails in plan_epilogues(forwards):
        producer._epilogue_tails = list(tails)
        for t in tails:
            t._epilogue_folded = True
        installed[producer.name] = [t.name for t in tails]
    return installed


def fused_fc_oracle(weights, biases, vel_w, vel_b, dataset, labels,
                    plan, lr, n_classes: Optional[int] = None,
                    act_a: float = 1.0, act_b: float = 1.0,
                    lr_bias_ratio: float = 1.0, wd: float = 0.0,
                    wd_bias: float = 0.0, momentum: float = 0.0):
    """jnp reference (lax.scan of per-step SGD) — the equivalence
    oracle for the kernel; same plan, same math, per-step HBM
    weights."""
    L = len(weights)
    nout = weights[-1].shape[1] if n_classes is None else int(n_classes)
    mb = plan.shape[1]
    lr_bias = lr * lr_bias_ratio
    f32 = jnp.float32

    def step(carry, idx):
        ws, bs, vws, vbs, loss, err = carry
        x = dataset.astype(f32)[idx]
        y = jax.nn.one_hot(labels[idx], nout, dtype=f32)
        acts = [x]
        h = x
        for li in range(L - 1):
            h = act_a * jnp.tanh(act_b * (h @ ws[li] + bs[li]))
            acts.append(h)
        logits = h @ ws[L - 1] + bs[L - 1]
        logp = jax.nn.log_softmax(logits)
        p = jnp.exp(logp)
        loss = loss - (y * logp).sum()
        err = err + (jnp.argmax(logits, 1) != labels[idx]).sum()
        d_out = (p - y) / mb
        n_ws, n_bs, n_vws, n_vbs = list(ws), list(bs), list(vws), \
            list(vbs)
        for li in range(L - 1, -1, -1):
            dw = acts[li].T @ d_out
            db = d_out.sum(0)
            if li > 0:
                d_h = d_out @ ws[li].T
                hh = acts[li]
                d_out = d_h * (act_a * act_b
                               - (act_b / act_a) * hh * hh)
            dlt_w = lr * (dw + wd * ws[li]) + momentum * vws[li]
            dlt_b = lr_bias * (db + wd_bias * bs[li]) \
                + momentum * vbs[li]
            n_ws[li] = ws[li] - dlt_w
            n_bs[li] = bs[li] - dlt_b
            n_vws[li] = dlt_w
            n_vbs[li] = dlt_b
        return (tuple(n_ws), tuple(n_bs), tuple(n_vws), tuple(n_vbs),
                loss, err), None

    init = (tuple(w.astype(f32) for w in weights),
            tuple(b.astype(f32) for b in biases),
            tuple(v.astype(f32) for v in vel_w),
            tuple(v.astype(f32) for v in vel_b),
            jnp.float32(0.0), jnp.int32(0))
    (ws, bs, vws, vbs, loss, err), _ = jax.lax.scan(step, init, plan)
    return (list(ws), list(bs), list(vws), list(vbs), loss,
            err.astype(f32))
