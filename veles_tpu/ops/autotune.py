"""Per-device kernel block-shape database — measure → persist → reuse.

Reference parity: the reference benchmarks GEMM block sizes per device
on first use and persists them keyed by device name
(`veles/backends.py:623-731` ``_find_optimal_bs_vo`` →
``devices/device_infos.json``), so every later run starts tuned. Here
XLA owns GEMM tuning, but the build's OWN Pallas kernel —
``ops/flash_attention.py`` — has ``block_q``/``block_k`` knobs the
compiler does not pick. This module ports the measure-and-persist
capability to it:

- first use of a (device_kind, shape-class) with no recorded entry runs
  a BOUNDED forward-timing sweep over divisor-compatible block pairs,
  persists the winner, and returns it;
- every later use (any process, any day) is a dict lookup.

Two DB layers, user overriding shipped (mirroring the reference's
in-repo ``device_infos.json`` + user cache):

- shipped: ``veles_tpu/devices/kernel_tuning.json`` (committed; the
  chip measurement batch seeds it — ``scripts/chip_experiments.py``),
- user:    ``root.common.dirs.cache / kernel_tuning.json`` (atomic
  writes; where first-use sweeps land).

``fused_fc`` deliberately has no entry here: its only tunable is
epochs-per-dispatch ``h`` (whole minibatches ARE its blocks), measured
by the chip batch's h-sweep, not a per-call shape knob.

Config: ``root.common.engine.kernel_autotune`` —
``"auto"`` (default: lookup, sweep on miss when a real TPU backend is
up), ``"reuse"`` (lookup only), ``False`` (hard-coded defaults).
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Optional, Sequence, Tuple

DEFAULT_BLOCKS = (128, 128)
#: bounded candidate census (the reference swept a fixed census too,
#: veles/backends.py:692); filtered per call to divisors of T. The
#: 1024-wide pairs exist because 512×512 won every r5 sweep length —
#: the knee hadn't been reached; ``sweep_flash``'s backward-compile
#: check rejects them wherever the bwd working set overflows VMEM
CANDIDATES = ((128, 128), (256, 128), (512, 128), (256, 256),
              (512, 512), (1024, 512), (1024, 1024))
SHIPPED = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "devices", "kernel_tuning.json")

#: per-process memo: key → blocks (or None after a failed sweep so a
#: bad environment costs one attempt, not one per trace)
_memo: dict = {}

#: (device_kind, key) pairs whose staleness was already warned about —
#: one log line per entry per process, however many traces look it up
_stale_warned: set = set()


def _jax_version() -> str:
    try:
        import jax
        return str(jax.__version__)
    except Exception:            # noqa: BLE001 — backend-less tooling
        return "unknown"


def _check_stale(key: str, kind: str, entry: dict) -> None:
    """Provenance check on a DB hit: an entry measured under a
    different jax (or none recorded — the pre-stamp DB format) may
    rank block shapes the current Mosaic lowers differently, so the
    hit is USED but flagged — warned once per (kind, key) and counted
    ``veles_autotune_stale_total`` every lookup, the signal a
    re-sweep (or chip measurement batch) clears."""
    stamped = entry.get("jax")
    current = _jax_version()
    if stamped == current:
        return
    from ..telemetry.counters import inc
    inc("veles_autotune_stale_total")
    if (kind, key) in _stale_warned:
        return
    _stale_warned.add((kind, key))
    import logging
    logging.getLogger("veles_tpu.ops.autotune").warning(
        "kernel_tuning entry %s (%s) was measured under jax %s, "
        "running %s — reusing it, but the ranking may be stale; "
        "re-sweep to refresh", key, kind,
        stamped if stamped is not None else "an unstamped build",
        current)


def _user_path() -> str:
    from ..config import root
    return os.path.join(root.common.dirs.cache, "kernel_tuning.json")


def _read(path: str) -> dict:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _device_db(device_kind: str) -> dict:
    """Merged view for one device_kind, user layer winning."""
    merged = dict(_read(SHIPPED).get(device_kind, {}))
    merged.update(_read(_user_path()).get(device_kind, {}))
    return merged


def current_device_kind() -> str:
    import jax
    try:
        return str(jax.devices()[0].device_kind)
    except Exception:            # noqa: BLE001 — backend init failure
        return "unknown"


def flash_key(t: int, d: int, causal: bool, window: int = 0) -> str:
    mode = "causal" if causal else "full"
    if window:
        mode += "_win"
    return "flash_t%d_d%d_%s" % (t, d, mode)


def lookup(key: str, device_kind: Optional[str] = None) -> Optional[dict]:
    kind = device_kind or current_device_kind()
    hit = _device_db(kind).get(key)
    if hit is not None:
        _check_stale(key, kind, hit)
    return hit


def record(key: str, entry: dict, device_kind: Optional[str] = None,
           shipped: bool = False) -> None:
    """Persist ``entry`` under (device_kind, key). ``shipped=True``
    additionally updates the committed in-repo DB — chip measurement
    batches only, so the repo ships what was actually measured.
    The read→merge→write is serialized through an flock'd sidecar so
    concurrent sweeps in processes sharing one cache dir cannot drop
    each other's entries."""
    import fcntl
    kind = device_kind or current_device_kind()
    # provenance stamp: which toolchain + chip measured this entry —
    # lookup() flags (veles_autotune_stale_total) hits whose jax
    # differs from the running one
    entry = dict(entry, ts=time.strftime("%Y-%m-%d %H:%M:%S"),
                 jax=_jax_version(), device_kind=kind)
    for path in ([_user_path(), SHIPPED] if shipped else [_user_path()]):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path + ".lock", "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            db = _read(path)
            db.setdefault(kind, {})[key] = entry
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(db, f, indent=1, sort_keys=True)
                f.write("\n")       # POSIX text file: end with newline
            os.replace(tmp, path)
    if "block_q" in entry:
        _memo[(kind, key)] = (entry["block_q"], entry["block_k"])
    elif "min_t" in entry:          # refresh the crossover memo too
        _memo[(kind, key, "min_t")] = int(entry["min_t"])


#: sentinel "flash never won a swept length on this device" — keeps
#: the fused-XLA reference in charge without disabling the config knob
NEVER = 1 << 30


def min_t_key(d: int) -> str:
    return "flash_min_t_d%d" % d


def flash_min_t(d: int, device_kind: Optional[str] = None,
                default: int = 4096) -> int:
    """The measured flash-vs-fused crossover length for this
    device_kind (seeded by the chip attn sweep — the reference
    persisted measured per-device decisions the same way,
    `veles/backends.py:623-731`); ``default`` (the v5e-measured 4096,
    docs/perf.md) until a sweep has run here. Memoized (this runs per
    attention layer per trace), and under multi-host it reads ONLY the
    shipped layer — same invariant as ``flash_blocks``: every SPMD
    process must resolve the same gate or traced programs diverge."""
    kind = device_kind or current_device_kind()
    key = min_t_key(d)
    memo_key = (kind, key, "min_t")
    if memo_key in _memo:
        return _memo[memo_key]
    import jax
    if jax.process_count() > 1:
        hit = _read(SHIPPED).get(kind, {}).get(key)
    else:
        hit = lookup(key, kind)
    val = default if hit is None else int(hit["min_t"])
    _memo[memo_key] = val
    return val


def resolved_min_t(d: int, device_kind: Optional[str] = None) -> int:
    """The ONE resolution of ``engine.flash_attention_min_t`` shared by
    the production gate (``choose_flash``) and the bench gate
    (scripts/bench_attention.py): ``"auto"``/None → the measured
    per-device crossover, an int pins it."""
    from ..config import root
    cfg = root.common.engine.get("flash_attention_min_t", "auto")
    if cfg in (None, "auto"):
        return flash_min_t(d, device_kind)
    return int(cfg or 0)


def candidates_for(t: int, d: int) -> Tuple[Tuple[int, int], ...]:
    from .flash_attention import supported
    out = tuple((bq, bk) for bq, bk in CANDIDATES
                if supported(t, d, bq, bk))
    return out or ((min(t, 128), min(t, 128)),)


def _time_flash(t: int, d: int, causal: bool,
                blocks: Tuple[int, int]) -> float:
    """Forward-mode timing probe on synthetic bf16 operands (b=1, h=1 —
    the grid repeats per head/batch, so the per-block ranking
    transfers); returns seconds per call."""
    import jax
    import jax.numpy as jnp
    import numpy
    from .flash_attention import flash_attention
    rng = numpy.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(1, t, 1, d), jnp.bfloat16)
               for _ in range(3))
    fn = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, causal=causal, block_q=blocks[0], block_k=blocks[1],
        interpret=False))
    jax.block_until_ready(fn(q, k, v))          # compile
    t0 = time.time()
    for _ in range(4):
        out = fn(q, k, v)
    jax.block_until_ready(out)
    return (time.time() - t0) / 4


def _bwd_compiles(t: int, d: int, causal: bool,
                  blocks: Tuple[int, int]) -> bool:
    """Whether the custom-VJP backward pair LOWERS at these blocks.
    The sweep times only the forward, but _prepare feeds its winner to
    the backward kernels too — whose VMEM working set is larger (q/do/
    k/v blocks + dk/dv accumulators resident), so a forward-fine
    (512, 512) can be a backward Mosaic OOM. Compile-only: no timing."""
    import jax
    import jax.numpy as jnp
    import numpy
    from .flash_attention import flash_attention
    rng = numpy.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(1, t, 1, d), jnp.bfloat16)
               for _ in range(3))
    try:
        jax.jit(jax.grad(
            lambda q, k, v: flash_attention(
                q, k, v, causal=causal, block_q=blocks[0],
                block_k=blocks[1],
                interpret=False).astype(jnp.float32).sum(),
            argnums=(0, 1, 2))).lower(q, k, v).compile()
        return True
    except Exception:            # noqa: BLE001 — lowering/VMEM failure
        return False


def sweep_flash(t: int, d: int, causal: bool = True,
                device_kind: Optional[str] = None,
                measure: Optional[Callable] = None,
                cands: Optional[Sequence[Tuple[int, int]]] = None,
                persist: bool = True,
                check_bwd: Optional[Callable] = None) -> Tuple[int, int]:
    """Bounded block sweep for one shape class; persists and returns the
    winner — the fastest forward whose BACKWARD also compiles
    (``_bwd_compiles``). ``measure(t, d, causal, blocks) -> seconds``
    and ``check_bwd(t, d, causal, blocks) -> bool`` are injectable
    (tests use a fake device_kind + fakes to prove persist/reuse
    without a chip)."""
    measure = measure or _time_flash
    check_bwd = check_bwd or _bwd_compiles
    rows = {}
    timed = []
    for blocks in (cands or candidates_for(t, d)):
        try:
            dt = measure(t, d, causal, blocks)
        except Exception:        # noqa: BLE001 — candidate didn't lower
            continue
        rows["%dx%d" % blocks] = round(dt * 1e3, 3)
        timed.append((dt, blocks))
    best = best_dt = None
    for dt, blocks in sorted(timed):
        if blocks == DEFAULT_BLOCKS or check_bwd(t, d, causal, blocks):
            best, best_dt = blocks, dt
            break
        rows["%dx%d" % blocks] = "bwd_compile_failed"
    if best is None:
        raise RuntimeError("flash autotune: no candidate ran for "
                           "t=%d d=%d" % (t, d))
    if persist:
        record(flash_key(t, d, causal),
               {"block_q": best[0], "block_k": best[1],
                "ms": round(best_dt * 1e3, 3), "sweep_ms": rows,
                "mode": "fwd_inline_sweep"},
               device_kind=device_kind)
    return best


def _nearest_blocks(t: int, d: int, causal: bool, kind: str,
                    shipped_only: bool) -> Optional[Tuple[int, int]]:
    """Measured winner from the nearest tuned length of the same
    (d, mode) class whose blocks divide this ``t``. Rationale
    (measured, docs/perf.md attn sweep): the per-device block
    preference is set by MXU-pipeline fill, which transfers across
    lengths — the committed v5e winners are 1024×1024 at BOTH 2048
    and 8192 (devices/kernel_tuning.json, round-5 extended census;
    512×512 is the runner-up throughout), while the 128×128
    DEFAULT_BLOCKS lost to fused XLA at 2048. Without this, an
    untuned T between swept lengths would pair the measured
    ``flash_min_t`` gate with the unmeasured default blocks — the
    exact combination the sweep showed regressing."""
    db = (_read(SHIPPED).get(kind, {}) if shipped_only
          else _device_db(kind))
    pref = "flash_t"
    suf = "_d%d_%s" % (d, "causal" if causal else "full")
    from .flash_attention import supported
    best = None
    for key, entry in db.items():
        if not (key.startswith(pref) and key.endswith(suf)):
            continue
        try:
            t_entry = int(key[len(pref):-len(suf)])
        except ValueError:
            continue
        try:
            bq, bk = int(entry["block_q"]), int(entry["block_k"])
        except (KeyError, TypeError, ValueError):
            continue
        if not supported(t, d, bq, bk):
            continue
        dist = abs(t_entry - t)
        if best is None or dist < best[0]:
            best = (dist, (bq, bk))
    return best[1] if best else None


def _check_inherited(t: int, d: int, causal: bool,
                     blocks: Tuple[int, int], kind: str
                     ) -> Tuple[int, int]:
    """First use of a length-INHERITED winner at this ``t``: confirm
    the custom-VJP pair actually LOWERS (mirroring ``sweep_flash``'s
    ``_bwd_compiles`` gate, which only ran at the swept lengths) and
    fall back to DEFAULT_BLOCKS instead of erroring inside the model's
    jitted step. TPU-only: off-TPU the kernel runs in interpret mode
    where there is no Mosaic lowering to fail (and tests drive
    inheritance with fake device kinds). The verdict is memoized per
    (kind, t, blocks) so the compile probe costs once, not per trace."""
    if blocks == DEFAULT_BLOCKS:
        return blocks
    import jax
    if jax.default_backend() != "tpu":
        return blocks
    memo_key = (kind, "inherit_ok", t, d, causal, blocks)
    ok = _memo.get(memo_key)
    if ok is None:
        ok = _memo[memo_key] = _bwd_compiles(t, d, causal, blocks)
    return blocks if ok else DEFAULT_BLOCKS


def flash_blocks(t: int, d: int, causal: bool = True, window: int = 0,
                 device_kind: Optional[str] = None) -> Tuple[int, int]:
    """THE policy lookup ``flash_attention`` resolves its default
    blocks through. Lookup is a memoized dict read (safe at trace
    time); a first-use sweep only fires in ``"auto"`` mode on a real
    TPU backend — its timing probes are independent eager programs, so
    running them while an outer jit traces is legal."""
    from ..config import root
    mode = root.common.engine.get("kernel_autotune", "auto")
    if not mode:
        return DEFAULT_BLOCKS
    kind = device_kind or current_device_kind()
    key = flash_key(t, d, causal, window)
    memo_key = (kind, key)
    if memo_key in _memo:
        return _memo[memo_key] or DEFAULT_BLOCKS
    import jax
    multihost = jax.process_count() > 1
    if multihost:
        # every process of an SPMD program must trace IDENTICAL block
        # shapes or the jobs' executables diverge and hang at the first
        # collective — so multi-host reads ONLY the shipped (committed,
        # host-identical) DB layer and never sweeps: per-host sweeps
        # could pick different near-tied winners, and per-host user DBs
        # can differ
        hit = _read(SHIPPED).get(kind, {}).get(key)
        if hit is not None:
            blocks = (int(hit["block_q"]), int(hit["block_k"]))
        else:
            # shipped-layer nearest-length fallback: deterministic and
            # host-identical, so SPMD processes still trace the same
            # shapes (the compile probe is host-identical too — same
            # kernel code on the same device kind)
            inherited = _nearest_blocks(t, d, causal, kind,
                                        shipped_only=True)
            blocks = (_check_inherited(t, d, causal, inherited, kind)
                      if inherited else DEFAULT_BLOCKS)
        _memo[memo_key] = blocks
        return blocks
    hit = lookup(key, kind)
    if hit is not None:
        blocks = (int(hit["block_q"]), int(hit["block_k"]))
        _memo[memo_key] = blocks
        return blocks
    if mode != "auto" or jax.default_backend() != "tpu" or window:
        # windowed shapes reuse the causal entry's ranking if present,
        # else defaults — no dedicated sweep for every window size.
        # Misses are deliberately NOT memoized here: a later record()
        # or a mode switch back to "auto" must be able to change the
        # answer within the process.
        if window:
            base = lookup(flash_key(t, d, causal), kind)
            if base is not None:
                blocks = (int(base["block_q"]), int(base["block_k"]))
                _memo[memo_key] = blocks
                return blocks
        # NOT memoized, same as the DEFAULT_BLOCKS miss below: a later
        # record() of a nearer length or a switch back to "auto" must
        # be able to change the answer within the process
        inherited = _nearest_blocks(t, d, causal, kind,
                                    shipped_only=False)
        if inherited is None:
            return DEFAULT_BLOCKS
        return _check_inherited(t, d, causal, inherited, kind)
    try:
        blocks = sweep_flash(t, d, causal, device_kind=kind)
    except Exception:            # noqa: BLE001 — never fail the model;
        # a failed sweep IS memoized (retrying it every trace would
        # re-pay the compile storm each time) — but as the nearest
        # tuned length's measured winner when one exists (compile-
        # checked at THIS t), not the unmeasured defaults
        fallback = _nearest_blocks(t, d, causal, kind,
                                   shipped_only=False)
        if fallback is not None:
            fallback = _check_inherited(t, d, causal, fallback, kind)
            if fallback == DEFAULT_BLOCKS:
                fallback = None  # store the miss, not a fake winner
        _memo[memo_key] = fallback   # None → DEFAULT_BLOCKS on re-read
        return fallback or DEFAULT_BLOCKS
    _memo[memo_key] = blocks
    return blocks


def clear_memo() -> None:
    _memo.clear()
    _stale_warned.clear()
