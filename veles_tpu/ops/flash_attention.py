"""Flash attention: Pallas TPU kernel for the attention core.

The one place in the op set where XLA fusion is genuinely insufficient
(SURVEY.md §7 "Pallas only where XLA fusion is insufficient"): naive
attention materializes the (B, H, T, T) score matrix in HBM, so for long
sequences the op is HBM-bandwidth-bound. This kernel streams K/V blocks
through VMEM with an online softmax (running max/sum rescaling), keeping
the working set at (block_q × block_k) — the standard flash-attention
recipe expressed in Pallas (guide: /opt/skills/guides/pallas_guide.md;
same tiling discipline as the public jax.experimental.pallas TPU ops).

The backward pass recomputes scores blockwise from the saved
log-sum-exp (``lse``) under ``jax.custom_vjp`` — O(T·block) memory, no
(T, T) materialization. Two Pallas kernels (dk/dv accumulating over Q
blocks; dq accumulating over K/V blocks) keep the recompute working set
VMEM-resident like the forward; ``_bwd_blockwise`` (plain jnp) is kept
as the oracle and the fallback
(``root.common.engine.flash_attention_pallas_bwd = False``).

Layout contract: (B, T, H, D) like the rest of the attention stack; heads
are folded into the grid's leading dimension. D is zero-padded to the
128-lane width (zero features change neither scores nor outputs).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

LANE = 128
NEG_INF = -1e30


from . import compiler_params as _compiler_params


def _mask_scores(s, q_start, k_start, block_q: int, block_k: int,
                 causal: bool, window: int):
    """The one copy of the score mask all three kernels share:
    causal (k <= q) and, when ``window`` > 0, sliding-window
    (q - k < window: each query attends to itself plus window-1
    predecessors — the Mistral convention)."""
    if not causal and not window:
        return s
    q_pos = q_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = k_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    keep = None
    if causal:
        keep = q_pos >= k_pos
    if window:
        in_win = q_pos - k_pos < window
        keep = in_win if keep is None else keep & in_win
    return jnp.where(keep, s, NEG_INF)


def _block_live(q_start, k_start, block_q: int, block_k: int,
                causal: bool, window: int):
    """Whether a (q-block, k-block) pair holds ANY unmasked score —
    the block-skip predicate paired with _mask_scores. Causal kills
    blocks strictly above the diagonal; a window kills blocks entirely
    behind every query row's horizon."""
    live = True
    if causal:
        live = k_start <= q_start + block_q - 1
    if window:
        live = jnp.logical_and(
            live, k_start + block_k - 1 >
            q_start - window)  # newest k in block within oldest q's win
    return live


def _kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_scr, l_scr, *,
            scale: float, causal: bool, block_q: int, block_k: int,
            window: int):
    from jax.experimental import pallas as pl

    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)

    q_start = qi * block_q
    k_start = ki * block_k

    def _step():
        q = q_ref[0]                    # (bq, D)
        k = k_ref[0]                    # (bk, D)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (bq, bk)
        s = _mask_scores(s, q_start, k_start, block_q, block_k,
                         causal, window)
        m_prev = m_scr[:, :1]                       # (bq, 1)
        m_cur = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)             # (bq, 1)
        p = jnp.exp(s - m_cur)                      # (bq, bk)
        l_scr[:] = jnp.broadcast_to(
            l_scr[:, :1] * alpha + p.sum(axis=1, keepdims=True),
            l_scr.shape)
        m_scr[:] = jnp.broadcast_to(m_cur, m_scr.shape)
        acc[:] = acc[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal or window:
        # skip K/V blocks with no unmasked scores (above the causal
        # diagonal / behind the window horizon)
        pl.when(_block_live(q_start, k_start, block_q, block_k,
                            causal, window))(_step)
    else:
        _step()

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finish():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0] = (acc[:] / l).astype(o_ref.dtype)
        # (8, bq) sublane-padded: TPU block shapes need ≥(8, 128) tiles
        lse_ref[0] = jnp.broadcast_to(
            (m_scr[:, :1] + jnp.log(l))[:, 0][None, :], lse_ref.shape[1:])


def _kv_fold_of(h: int, kv: int):
    """Map a folded (batch*h) q-grid index to the folded (batch*kv)
    K/V row its query head reads — the GQA head-group mapping expressed
    as a BlockSpec index transform, so grouped K/V are NEVER expanded
    in the kernel operands (query head qh reads kv head qh // (h//kv))."""
    group = h // kv

    def kv_of(g):
        return (g // h) * kv + (g % h) // group
    return kv_of


def _fwd_pallas(q, k, v, causal: bool, scale: float, block_q: int,
                block_k: int, interpret: bool, window: int = 0,
                h: int = 1, kv: int = 1):
    """q: (B*h, T, D); k/v: (B*kv, T, D) with D == LANE (kv == h is
    MHA) → (o (B*h, T, D), lse (B*h, 8, T) sublane-padded — callers
    use ``lse[:, 0, :]``)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    g, t, d = q.shape
    kv_of = _kv_fold_of(h, kv)
    grid = (g, t // block_q, t // block_k)
    kernel = functools.partial(_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k,
                               window=window)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d),
                         lambda b, i, j: (kv_of(b), j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d),
                         lambda b, i, j: (kv_of(b), j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((g, t, d), q.dtype),
            jax.ShapeDtypeStruct((g, 8, t), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, LANE), jnp.float32),
            pltpu.VMEM((block_q, LANE), jnp.float32),
        ],
        # scheduling hint, not semantics: head and Q-block grid dims
        # carry no state between steps, so Mosaic may parallelize /
        # pipeline them; only the K/V dim accumulates in scratch and
        # must stay sequential ("arbitrary")
        compiler_params=_compiler_params(pltpu)(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)


def _bwd_blockwise(causal, scale, block_k, window, res, do):
    """Blockwise recompute backward (no (T, T) materialization).
    Grouped (GQA) k/v with fewer rows than q are expanded per block for
    the recompute and the dk/dv contributions summed back per group."""
    q, k, v, o, lse = res
    g, t, d = q.shape
    gk = k.shape[0]
    if gk != g:
        group = g // gk
        kx = jnp.broadcast_to(k[:, None], (gk, group, t, d)
                              ).reshape(g, t, d)
        vx = jnp.broadcast_to(v[:, None], (gk, group, t, d)
                              ).reshape(g, t, d)
        dq, dk, dv = _bwd_blockwise(causal, scale, block_k, window,
                                    (q, kx, vx, o, lse), do)
        dk = dk.reshape(gk, group, t, d).sum(1).astype(k.dtype)
        dv = dv.reshape(gk, group, t, d).sum(1).astype(v.dtype)
        return dq, dk, dv
    nk = t // block_k
    delta = (do.astype(jnp.float32) * o.astype(jnp.float32)
             ).sum(-1)                                      # (G, T)
    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    q_pos = jnp.arange(t)

    def body(dq, j):
        ks = jax.lax.dynamic_slice_in_dim(k, j * block_k, block_k, 1)
        vs = jax.lax.dynamic_slice_in_dim(v, j * block_k, block_k, 1)
        ksf = ks.astype(jnp.float32)
        s = jnp.einsum("gqd,gkd->gqk", qf, ksf) * scale
        if causal or window:
            k_pos = j * block_k + jnp.arange(block_k)
            rel = q_pos[None, :, None] - k_pos[None, None, :]
            keep = rel >= 0 if causal else True
            if window:
                in_win = rel < window
                keep = in_win if keep is True else keep & in_win
            s = jnp.where(keep, s, NEG_INF)
        p = jnp.exp(s - lse[..., None])                     # (G, T, bk)
        dv = jnp.einsum("gqk,gqd->gkd", p, dof)
        dp = jnp.einsum("gqd,gkd->gqk", dof, vs.astype(jnp.float32))
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("gqk,gkd->gqd", ds, ksf)
        dk = jnp.einsum("gqk,gqd->gkd", ds, qf)
        return dq, (dk, dv)

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dq, (dks, dvs) = jax.lax.scan(body, dq0, jnp.arange(nk))
    dk = jnp.moveaxis(dks, 0, 1).reshape(g, t, d)
    dv = jnp.moveaxis(dvs, 0, 1).reshape(g, t, d)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


def _bwd_dkv_kernel(q_ref, do_ref, k_ref, v_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *,
                    scale: float, causal: bool, block_q: int,
                    block_k: int, window: int, n_q_blocks: int = 0):
    from jax.experimental import pallas as pl

    ki, j = pl.program_id(1), pl.program_id(2)
    # grouped (GQA) grids fold (query-head-in-group, q-block) into the
    # sequential dim: j = qh * n_q_blocks + qi. n_q_blocks=0 → MHA (j
    # IS the q-block index).
    qi = j % n_q_blocks if n_q_blocks else j

    @pl.when(j == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = qi * block_q
    k_start = ki * block_k

    def _step():
        q = q_ref[0]                       # (bq, D)
        do = do_ref[0]                     # (bq, D)
        k = k_ref[0]                       # (bk, D)
        v = v_ref[0]
        lse = lse_ref[0][:1].T             # (bq, 1) from (8, bq) row 0
        delta = delta_ref[0][:1].T         # (bq, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (bq, bk)
        s = _mask_scores(s, q_start, k_start, block_q, block_k,
                         causal, window)
        p = jnp.exp(s - lse)               # (bq, bk) f32
        # dv_j += p^T do_i    (contract the bq axis)
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # (bq, bk)
        ds = p * (dp - delta) * scale
        # dk_j += ds^T q_i
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal or window:
        # same liveness predicate as the forward, from the k block's
        # perspective (q/k roles swap in the grid, the set of live
        # (q, k) pairs does not)
        pl.when(_block_live(q_start, k_start, block_q, block_k,
                            causal, window))(_step)
    else:
        _step()

    @pl.when(j == pl.num_programs(2) - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, do_ref, k_ref, v_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc, *, scale: float, causal: bool,
                   block_q: int, block_k: int, window: int):
    from jax.experimental import pallas as pl

    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start = qi * block_q
    k_start = ki * block_k

    def _step():
        q = q_ref[0]
        do = do_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        lse = lse_ref[0][:1].T
        delta = delta_ref[0][:1].T
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = _mask_scores(s, q_start, k_start, block_q, block_k,
                         causal, window)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        # dq_i += ds k_j
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal or window:
        pl.when(_block_live(q_start, k_start, block_q, block_k,
                            causal, window))(_step)
    else:
        _step()

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_pallas(q, k, v, o, lse, do, causal: bool, scale: float,
                block_q: int, block_k: int, interpret: bool,
                window: int = 0, h: int = 1, kv: int = 1):
    """Pallas twin of ``_bwd_blockwise``: same math, VMEM-resident
    blockwise recompute. delta = rowsum(do*o) is O(T·D) and computed
    outside the kernels."""
    delta = (do.astype(jnp.float32) * o.astype(jnp.float32)).sum(-1)
    return _bwd_pallas_core(q, k, v, lse, delta, do, causal, scale,
                            block_q, block_k, interpret, window, h, kv)


def _bwd_pallas_core(q, k, v, lse, delta, do, causal: bool,
                     scale: float, block_q: int, block_k: int,
                     interpret: bool, window: int = 0, h: int = 1,
                     kv: int = 1, out_dtype=None):
    """The kernel pair behind the backward, against a CALLER-SUPPLIED
    normalizer: ``p = exp(s − lse)`` with ``lse``/``delta`` (G, T)
    computed over whatever attention the caller ran (the full T here;
    the GLOBAL ring softmax in parallel/ring_attention.py — that is
    what makes these kernels reusable per ring step). lse/delta ride
    in the forward's (G, 8, T) sublane-padded layout. GQA (kv < h):
    k/v stay grouped (B*kv rows); the dq grid remaps K/V reads per
    query head, and the dk/dv grid runs over the GROUPED rows with
    (query-head-in-group, q-block) folded into its sequential
    dimension — each kv head's gradient accumulates the contributions
    of all h/kv query heads with no expanded operands and no racy
    parallel writes."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    g, t, d = q.shape
    gk = k.shape[0]
    group = h // kv
    nq, nk = t // block_q, t // block_k
    kv_of = _kv_fold_of(h, kv)

    def q_of(b, j):
        # dkv grid: b indexes grouped K/V rows; j = qh * nq + qi
        return (b // kv) * h + (b % kv) * group + j // nq

    pad8 = jnp.broadcast_to(delta[:, None, :], (g, 8, t))
    lse8 = jnp.broadcast_to(lse[:, None, :], (g, 8, t))
    common = dict(scale=scale, causal=causal, block_q=block_q,
                  block_k=block_k, window=window)
    qspec = pl.BlockSpec((1, block_q, d),
                         lambda b, i, j: (q_of(b, j), j % nq, 0),
                         memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM)
    row_q = pl.BlockSpec((1, 8, block_q),
                         lambda b, i, j: (q_of(b, j), 0, j % nq),
                         memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, n_q_blocks=nq, **common),
        grid=(gk, nk, nq * group),
        in_specs=[qspec, qspec, kspec, kspec, row_q, row_q],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((gk, t, d), out_dtype or k.dtype),
            jax.ShapeDtypeStruct((gk, t, d), out_dtype or v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_compiler_params(pltpu)(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, do, k, v, lse8, pad8)
    dq, = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(g, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d),
                         lambda b, i, j: (kv_of(b), j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d),
                         lambda b, i, j: (kv_of(b), j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[jax.ShapeDtypeStruct((g, t, d), out_dtype or q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(pltpu)(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, do, k, v, lse8, pad8)
    return dq, dk, dv


def _use_pallas_bwd() -> bool:
    from ..config import root
    return bool(root.common.engine.get("flash_attention_pallas_bwd",
                                       True))


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret,
           window, h, kv, d_logical):
    o, _ = _fwd_pallas(q, k, v, causal, scale, block_q, block_k,
                       interpret, window, h, kv)
    return o


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret,
               window, h, kv, d_logical):
    o, lse = _fwd_pallas(q, k, v, causal, scale, block_q, block_k,
                         interpret, window, h, kv)
    # residuals keep the GROUPED k/v — the GQA memory saving holds
    # through the backward
    return o, (q, k, v, o, lse[:, 0, :])


def _flash_bwd(causal, scale, block_q, block_k, interpret, window,
               h, kv, d_logical, res, do):
    q = res[0]
    # trace-time analytic note for the backward pair (standard 2.5×
    # the forward: blockwise recompute + 4 gradient matmuls), billed
    # at the LOGICAL head dim (``d_logical`` rides the nondiff args:
    # the folded residual is lane-padded, and model FLOPs count the
    # useful dim, matching the forward note)
    from ..telemetry.cost import note_kernel_cost
    note_kernel_cost(analytic_cost(
        q.shape[0] // h, q.shape[1], h, d_logical, causal,
        window).scaled(2.5))
    if _use_pallas_bwd():
        q, k, v, o, lse = res
        return _bwd_pallas(q, k, v, o, lse, do, causal, scale,
                           block_q, block_k, interpret, window, h, kv)
    return _bwd_blockwise(causal, scale, block_k, window, res, do)


_flash.defvjp(_flash_fwd, _flash_bwd)


#: VMEM budget bound: the kernel keeps ~5 (block, D_padded) f32 tiles
#: resident; 512 lanes ≈ 1.3 MiB — comfortably inside the ~16 MiB VMEM
MAX_D = 512


def supported(t: int, d: int, block_q: int = 128,
              block_k: int = 128) -> bool:
    """Head dims beyond one lane group run with D zero-padded to the next
    128 multiple (zero features change neither scores nor outputs);
    above MAX_D the padded working set would pressure VMEM — callers
    fall back to the fused XLA reference."""
    return t % block_q == 0 and t % block_k == 0 and d <= MAX_D


def choose_flash(t: int, d: int) -> bool:
    """THE policy predicate for picking this kernel over the fused XLA
    reference — one definition shared by every call site
    (nn/attention.attention_core, parallel/ulysses) so the crossover
    cannot silently diverge between paths. True when the config enables
    flash, the shapes qualify, and T is past the measured crossover
    (engine.flash_attention_min_t, docs/perf.md); "force" overrides the
    backend/length gates (pallas interpret mode — tests only)."""
    import jax
    from ..config import root
    cfg = root.common.engine.flash_attention
    if not cfg:
        return False
    if not supported(t, d):
        return False
    if cfg == "force":
        return True
    if jax.default_backend() != "tpu":
        return False          # before any DB read — off-TPU never flash
    # per-device measured crossover (seeded by the chip attn sweep;
    # v5e-measured 4096 until then); one resolver shared with the
    # bench gate
    from .autotune import resolved_min_t
    return t >= resolved_min_t(d)


def analytic_cost(b: int, t: int, h: int, d: int, causal: bool = False,
                  window: int = 0, train: bool = False,
                  dtype_bytes: int = 2):
    """Telemetry fallback cost of one flash-attention call
    (veles_tpu/telemetry/cost.py): the Pallas custom call is opaque to
    XLA's HLO cost model, so the kernel's owner publishes the standard
    analytic model instead. FLOPs: 2·T·T_ctx·D per head for QK^T plus
    the same for PV (T_ctx = T/2 causal, min(T, W) windowed); training
    adds the blockwise backward at the standard 2.5× forward
    (recompute + 4 gradient matmuls). Bytes: the HBM traffic floor —
    q/k/v read + o written (+lse), ×3 round trips under training."""
    from ..telemetry.cost import Cost
    t_ctx = float(t)
    if window:
        t_ctx = min(t_ctx, float(window))
    elif causal:
        t_ctx = t / 2.0
    fwd = 4.0 * b * h * t * t_ctx * d
    flops = fwd * 3.5 if train else fwd
    io = b * h * t * d * dtype_bytes
    lse = b * h * t * 4
    bytes_accessed = (4 * io + lse) * (3 if train else 1)
    # VMEM working set: ~5 f32 (block, D_padded) tiles per grid step
    d_pad = ((d + LANE - 1) // LANE) * LANE
    peak = 5.0 * 128 * d_pad * 4
    return Cost(flops, bytes_accessed, peak, source="analytic")


def _prepare(q, k, v, scale, block_q, block_k, interpret, caller,
             causal=False, window=0):
    """Shared prologue for the public entry points: validation, scale
    default, interpret default, block resolution (``None`` blocks go
    through the per-device autotune DB — ``ops/autotune.py``, the
    build's port of the reference's measured-per-device block sizes,
    `veles/backends.py:623-731`), and the head-fold + lane-pad of the
    operands. Returns (q3, k3, v3, scale, interpret, b, t, h, kv, d,
    block_q, block_k)."""
    b, t, h, d = q.shape
    kv = k.shape[2]
    if block_q is None or block_k is None:
        from .autotune import flash_blocks
        abq, abk = flash_blocks(t, d, causal=causal, window=window)
        block_q = abq if block_q is None else block_q
        block_k = abk if block_k is None else block_k
    if v.shape[2] != kv or h % kv:
        raise ValueError(
            "k/v head counts must match and divide q heads: q has %d, "
            "k %d, v %d" % (h, kv, v.shape[2]))
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if not supported(t, d, block_q, block_k):
        raise ValueError("%s: T=%d D=%d not supported with blocks "
                         "(%d, %d)" % (caller, t, d, block_q, block_k))
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    d_pad = ((d + LANE - 1) // LANE) * LANE

    def fold(x):
        heads = x.shape[2]
        xt = jnp.moveaxis(x, 2, 1).reshape(b * heads, t, d)
        if d < d_pad:
            xt = jnp.pad(xt, ((0, 0), (0, 0), (0, d_pad - d)))
        return xt

    return (fold(q), fold(k), fold(v), float(scale), interpret,
            b, t, h, kv, d, block_q, block_k)


def flash_attention_fwd_lse(q, k, v, causal: bool = False,
                            scale: Optional[float] = None,
                            block_q: Optional[int] = None,
                            block_k: Optional[int] = None,
                            interpret: Optional[bool] = None):
    """FORWARD-ONLY flash returning ``(o, lse)`` with lse ``(B, T, H)``
    (log-sum-exp of the scaled scores per query row). No custom VJP —
    the caller owns differentiation: ring attention merges per-block
    partials by lse and defines the blockwise ring backward itself
    (parallel/ring_attention.py). Same folding/padding/support rules
    as :func:`flash_attention`."""
    q3, k3, v3, scale, interpret, b, t, h, kv, d, block_q, block_k = \
        _prepare(q, k, v, scale, block_q, block_k, interpret,
                 "flash_attention_fwd_lse", causal=causal)
    o, lse = _fwd_pallas(q3, k3, v3, causal, scale, block_q, block_k,
                         interpret, 0, h, kv)
    o = jnp.moveaxis(o[..., :d].reshape(b, h, t, d), 1, 2)
    lse = jnp.moveaxis(lse[:, 0, :].reshape(b, h, t), 1, 2)  # (B,T,H)
    return o, lse


def flash_attention_bwd_lse(q, k, v, lse, delta, do,
                            causal: bool = False,
                            scale: Optional[float] = None,
                            block_q: Optional[int] = None,
                            block_k: Optional[int] = None,
                            interpret: Optional[bool] = None):
    """Blockwise flash BACKWARD against an external (global) softmax
    normalizer: ``(dq, dk, dv)`` contributions of this K/V block set,
    with ``p = exp(s − lse)``. ``lse``/``delta = rowsum(do·o)`` are
    (B, T, H), computed by the caller over the FULL attention — ring
    attention's per-step backward engine (the global lse makes each
    block's probabilities exact regardless of which blocks this call
    sees). VMEM-resident kernels; no (T, T) materialization."""
    q3, k3, v3, scale, interpret, b, t, h, kv, d, block_q, block_k = \
        _prepare(q, k, v, scale, block_q, block_k, interpret,
                 "flash_attention_bwd_lse", causal=causal)

    def fold_g(x):      # (B, T, H) → (B*H, T)
        return jnp.moveaxis(x, -1, 1).reshape(b * h, t)

    d_pad = q3.shape[-1]
    do3 = jnp.moveaxis(do, 2, 1).reshape(b * h, t, d)
    if d < d_pad:
        do3 = jnp.pad(do3, ((0, 0), (0, 0), (0, d_pad - d)))
    # f32 outputs: these are PARTIAL contributions the ring sums across
    # steps — rounding each partial to bf16 before the f32 accumulation
    # would grow error O(ring size) over the einsum engine
    dq, dk, dv = _bwd_pallas_core(
        q3, k3, v3, fold_g(lse).astype(jnp.float32),
        fold_g(delta).astype(jnp.float32), do3, causal, scale,
        block_q, block_k, interpret, 0, h, kv, out_dtype=jnp.float32)

    def unfold(x, heads):
        return jnp.moveaxis(x[..., :d].reshape(b, heads, t, d), 1, 2)

    return unfold(dq, h), unfold(dk, kv), unfold(dv, kv)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    window: Optional[int] = None):
    """(B, T, H, D) × 3 → (B, T, H, D), differentiable.

    Falls back is the caller's job — check ``supported(T, D)`` first.
    ``interpret`` defaults to True off-TPU so tests exercise the same
    kernel on the CPU backend. ``window=W`` restricts each query to
    itself plus W-1 predecessors (sliding-window / Mistral convention;
    requires ``causal``): compute AND the blockwise backward drop the
    dead blocks, so long-T cost scales O(T·W) instead of O(T²).
    """
    window = int(window or 0)
    if window < 0:
        raise ValueError("window must be >= 1 (or None)")
    if window and not causal:
        raise ValueError("sliding-window attention requires causal=True")
    if window >= q.shape[1]:
        window = 0          # a window covering everything is no window
    q3, k3, v3, scale, interpret, b, t, h, kv, d, block_q, block_k = \
        _prepare(q, k, v, scale, block_q, block_k, interpret,
                 "flash_attention", causal=causal, window=window)
    # trace-time events (run once per trace, not per execution): the
    # counter records that a program containing this kernel was
    # (re)built — recompile churn shows up here first — and the
    # analytic forward cost lands in any active kernel-cost collector
    # (AcceleratedUnit.program_cost: the custom call is opaque to
    # XLA's cost model, so the kernel reports itself)
    from ..telemetry.counters import inc
    from ..telemetry.cost import note_kernel_cost
    inc("veles_flash_attention_traces_total")
    note_kernel_cost(analytic_cost(b, t, h, d, causal, window))
    o = _flash(q3, k3, v3, causal, scale,
               block_q, block_k, interpret, window, h, kv, d)
    o = o[..., :d].reshape(b, h, t, d)
    return jnp.moveaxis(o, 1, 2)
