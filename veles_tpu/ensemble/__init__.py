"""Ensemble meta-learning: train/test N model instances, aggregate.

Rebuild of the reference's veles/ensemble/ (SURVEY.md §2.6:
EnsembleModelManagerBase veles/ensemble/base_workflow.py:59,
EnsembleModelWorkflow model_workflow.py:137, EnsembleTestWorkflow
test_workflow.py:102; CLI --ensemble-train N[:r] / --ensemble-test,
veles/__main__.py:347-361,727-732).

- EnsembleTrainer: trains ``n_models`` instances of one workflow, each
  with a distinct master seed and (optionally) a random ``train_ratio``
  subset of the train set; writes per-model snapshot + results into one
  ensemble JSON manifest.
- EnsembleTester: rebuilds each instance, resumes its snapshot, runs the
  forward chain over the validation set, and soft-votes (mean class
  probability) into aggregate metrics.

The reference evaluated members as master–slave jobs
(veles/ensemble/model_workflow.py:137); here ``n_workers > 1`` farms
members through ``parallel.trials.TrialScheduler`` — each member is one
subprocess running the normal CLI with ``--ensemble-member i``, placed
on its own device slice by the scheduler's placement hook (private
XLA:CPU by default; mesh_slice_placement on multi-chip hosts). On the
single exclusive chip members run sequentially (n_workers=1), which is
also the default.
"""

from __future__ import annotations

import gzip
import json
import os
import pickle
import time
from typing import Callable, Optional

import numpy

from .. import prng
from ..config import root
from ..error import VelesError
from ..logger import Logger
from ..loader.base import VALID
from ..snapshotter import collect_state, load_snapshot, apply_state


class EnsembleTrainer(Logger):
    def __init__(self, build_workflow: Callable, n_models: int = 3,
                 train_ratio: float = 1.0, device=None,
                 out_file: str = "ensemble.json", base_seed: Optional[int]
                 = None, directory: Optional[str] = None,
                 prefix: str = "ensemble", n_workers: int = 1,
                 model_path: Optional[str] = None,
                 extra_argv: Optional[list] = None,
                 trial_timeout: Optional[float] = None,
                 placement=None) -> None:
        super().__init__()
        self.build_workflow = build_workflow
        self.n_models = int(n_models)
        self.train_ratio = float(train_ratio)
        self.device = device
        self.out_file = out_file
        self.base_seed = (int(base_seed) if base_seed is not None
                          else int(root.common.random_seed))
        self.directory = directory or root.common.dirs.snapshots
        self.prefix = prefix
        self.n_workers = int(n_workers)
        self.model_path = model_path
        self.extra_argv = list(extra_argv or [])
        self.trial_timeout = trial_timeout
        self.placement = placement
        if self.n_workers > 1 and not self.model_path:
            raise VelesError(
                "parallel ensemble training (n_workers > 1) farms "
                "members out as CLI subprocesses and needs model_path")

    def _train_one(self, index: int) -> dict:
        seed = self.base_seed + index
        prng.seed_all(seed)
        workflow = self.build_workflow()
        if self.train_ratio < 1.0 and workflow.loader is not None:
            workflow.loader.train_ratio = self.train_ratio
        workflow.initialize(device=self.device)
        t0 = time.time()
        workflow.run()
        results = workflow.gather_results()
        os.makedirs(self.directory, exist_ok=True)
        snap_path = os.path.join(
            self.directory, "%s_%d.pickle.gz" % (self.prefix, index))
        with gzip.open(snap_path, "wb") as fout:
            pickle.dump(collect_state(workflow), fout,
                        protocol=pickle.HIGHEST_PROTOCOL)
        self.info("member %d/%d: seed %d, %.1fs, results %s",
                  index + 1, self.n_models, seed, time.time() - t0,
                  {k: v for k, v in results.items()
                   if not isinstance(v, dict)})
        return {"id": index, "seed": seed, "snapshot": snap_path,
                "results": {k: v for k, v in results.items()
                            if isinstance(v, (int, float, str, bool))
                            or v is None}}

    def train_member(self, index: int) -> dict:
        """Train ONE member and return its manifest entry — the unit a
        ``--ensemble-member`` CLI child executes when members are
        farmed out by the trial scheduler."""
        return self._train_one(index)

    def _run_parallel(self) -> dict:
        import sys
        from ..cmdline import split_child_argv
        from ..parallel.trials import run_json_trials
        positionals, flags = split_child_argv(self.extra_argv)

        def member_argv(i, rf):
            return ([sys.executable, "-m", "veles_tpu",
                     self.model_path] + positionals +
                    ["--ensemble-member", str(i),
                     "--ensemble-train",
                     "%d:%s" % (self.n_models, self.train_ratio),
                     "--random-seed", str(self.base_seed),
                     "--snapshot-dir", self.directory,
                     "--result-file", rf] + flags)

        manifest = {"n_models": self.n_models,
                    "train_ratio": self.train_ratio,
                    "base_seed": self.base_seed,
                    "models": []}
        failed = []
        for i, (res, doc) in enumerate(run_json_trials(
                member_argv, self.n_models, self.n_workers,
                placement=self.placement, timeout=self.trial_timeout)):
            if doc is None:
                # the reference's job farm survived slave death
                # (veles/server.py:315-338); a failed member — dead
                # process OR unusable result file — is recorded and
                # the rest of the ensemble stands
                self.warning("member %d failed (rc=%s%s): %s", i,
                             res.returncode,
                             ", no result file" if res.ok else "",
                             res.stderr_tail[-500:])
                failed.append(i)
                continue
            manifest["models"].append(doc)
        if not manifest["models"]:
            raise VelesError(
                "all %d ensemble members failed" % self.n_models)
        if failed:
            manifest["failed_members"] = failed
        with open(self.out_file, "w") as fout:
            json.dump(manifest, fout, indent=2)
        self.info("ensemble manifest → %s (%d workers)", self.out_file,
                  self.n_workers)
        return manifest

    def run(self) -> dict:
        if self.n_workers > 1:
            return self._run_parallel()
        manifest = {"n_models": self.n_models,
                    "train_ratio": self.train_ratio,
                    "base_seed": self.base_seed,
                    "models": []}
        for i in range(self.n_models):
            manifest["models"].append(self._train_one(i))
            # incremental write: a member crash must not discard the
            # record of the members already trained
            with open(self.out_file, "w") as fout:
                json.dump(manifest, fout, indent=2)
        self.info("ensemble manifest → %s", self.out_file)
        return manifest


class EnsembleTester(Logger):
    """Soft-voting evaluation of a trained ensemble over VALIDATION."""

    def __init__(self, build_workflow: Callable, manifest: str | dict,
                 device=None, save_outputs: Optional[str] = None) -> None:
        super().__init__()
        self.build_workflow = build_workflow
        if isinstance(manifest, str):
            with open(manifest) as fin:
                manifest = json.load(fin)
        self.manifest = manifest
        self.device = device
        #: directory to dump per-member probability .npy files + an
        #: outputs manifest consumable by loader.EnsembleLoader (stacking)
        self.save_outputs = save_outputs

    def _member_probs(self, entry: dict):
        """(probs over VALID set, labels) for one member, via the trained
        forward chain on host numpy (oracle path — identical math to the
        jitted chain, veles_tpu/nn tests assert that)."""
        prng.seed_all(entry["seed"])
        workflow = self.build_workflow()
        workflow.initialize(device=self.device)
        apply_state(workflow, load_snapshot(entry["snapshot"]))
        workflow.train_step.sync_params_to_arrays()
        loader = workflow.loader
        start = loader.class_end_offsets[VALID] - loader.class_lengths[VALID]
        end = loader.class_end_offsets[VALID]
        idx = numpy.arange(start, end)
        if len(idx) == 0:
            raise VelesError(
                "EnsembleTester needs a validation set; loader %s has "
                "none (set validation_ratio or provide VALID samples)"
                % loader.name)
        x = loader.original_data.mem[idx]
        if not loader.original_labels:
            raise VelesError(
                "EnsembleTester soft-voting needs integer labels; loader "
                "%s has none (MSE/autoencoder ensembles are aggregated "
                "from their results manifests instead)" % loader.name)
        y = loader.original_labels.mem[idx]
        for f in workflow.forwards:
            x = f.numpy_apply(f.params_np(), x)
        return x, y

    def run(self) -> dict:
        probs_sum, labels = None, None
        member_errs, output_files = [], []
        for entry in self.manifest["models"]:
            probs, labels = self._member_probs(entry)
            errs = float((probs.argmax(1) != labels).mean())
            member_errs.append(errs)
            probs_sum = probs if probs_sum is None else probs_sum + probs
            self.info("member %d: validation error %.4f", entry["id"], errs)
            if self.save_outputs:
                os.makedirs(self.save_outputs, exist_ok=True)
                path = os.path.join(self.save_outputs,
                                    "member_%d.npy" % entry["id"])
                numpy.save(path, probs)
                output_files.append(path)
        ens_err = float((probs_sum.argmax(1) != labels).mean())
        out = {"ensemble_err": ens_err, "member_errs": member_errs,
               "n_models": len(self.manifest["models"])}
        if self.save_outputs:
            labels_path = os.path.join(self.save_outputs, "labels.npy")
            numpy.save(labels_path, labels)
            man_path = os.path.join(self.save_outputs, "outputs.json")
            with open(man_path, "w") as fout:
                json.dump({"outputs": output_files,
                           "labels": labels_path}, fout, indent=2)
            out["outputs_manifest"] = man_path
        self.info("ensemble soft-vote validation error: %.4f "
                  "(best member %.4f)", ens_err, min(member_errs))
        return out
