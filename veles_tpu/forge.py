"""Forge: the model hub — publish, list and fetch workflow packages.

Equivalent of the reference's veles/forge/forge_client.py:91 +
veles/forge/forge_server.py:462 (tornado service exchanging
manifest.json + tarball packages, token-authenticated uploads) and
veles/forge_common.py (package/manifest validation). Stdlib http.server
replaces tornado; the e-mail/registration machinery of the reference is
out of scope (tokens are provisioned by the operator instead).

A forge package is a ``.tar.gz`` whose root holds ``manifest.json``::

    {"name": ..., "version": ..., "author": ..., "description": ...,
     "workflow": <entry file or exported package member>}

plus the payload — typically a veles_tpu ``package_export`` directory
(contents.json + .npy weights + optional StableHLO) and/or the model's
.py source.

Endpoints (mirroring the reference's service/fetch/upload URL surface):
    GET  /service?query=list                → JSON manifest summaries
    GET  /service?query=details&name=N      → full manifest
    GET  /fetch?name=N[&version=V]          → package tarball
    POST /upload?token=T                    → body is the tarball
"""

from __future__ import annotations

import io
import json
import os
import re
import shutil
import tarfile
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler
from typing import Any, Dict, List, Optional, Tuple

from ._http import HTTPService, bytes_reply, json_reply
from .error import VelesError
from .logger import Logger

MANIFEST = "manifest.json"
REQUIRED_KEYS = ("name", "version", "author", "description")
_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]*$")


def version_key(version: str) -> Tuple:
    """Order versions numerically where possible: 1.10 > 1.9, 10.0 > 2.0
    (plain lexicographic sort gets these wrong)."""
    parts = []
    for piece in re.split(r"[._-]", str(version)):
        parts.append((0, int(piece)) if piece.isdigit() else (1, piece))
    return tuple(parts)


# ---------------------------------------------------------------------------
# package helpers
# ---------------------------------------------------------------------------

def validate_manifest(manifest: Dict[str, Any]) -> None:
    missing = [k for k in REQUIRED_KEYS if not manifest.get(k)]
    if missing:
        raise VelesError("manifest lacks %s" % ", ".join(missing))
    for key in ("name", "version"):
        if not _NAME_RE.match(str(manifest[key])):
            raise VelesError("manifest %s %r must match %s" %
                             (key, manifest[key], _NAME_RE.pattern))


def make_package(src_dir: str, manifest: Dict[str, Any],
                 out_path: Optional[str] = None) -> str:
    """Bundle ``src_dir`` + manifest into ``<name>-<version>.tar.gz``."""
    validate_manifest(manifest)
    out_path = out_path or "%s-%s.tar.gz" % (manifest["name"],
                                             manifest["version"])
    with tarfile.open(out_path, "w:gz") as tar:
        data = json.dumps(manifest, indent=2).encode()
        info = tarfile.TarInfo(MANIFEST)
        info.size = len(data)
        tar.addfile(info, io.BytesIO(data))
        for fname in sorted(os.listdir(src_dir)):
            if fname == MANIFEST:
                continue
            tar.add(os.path.join(src_dir, fname), arcname=fname)
    return out_path


def read_package_manifest(path: str) -> Dict[str, Any]:
    with tarfile.open(path, "r:gz") as tar:
        try:
            member = tar.extractfile(MANIFEST)
        except KeyError:        # missing member raises, not returns None
            member = None
        if member is None:
            raise VelesError("%s: no %s" % (path, MANIFEST))
        manifest = json.load(member)
    validate_manifest(manifest)
    return manifest


def extract_package(path: str, dest_dir: str) -> Dict[str, Any]:
    manifest = read_package_manifest(path)
    os.makedirs(dest_dir, exist_ok=True)
    with tarfile.open(path, "r:gz") as tar:
        tar.extractall(dest_dir, filter="data")   # refuses path escapes
    return manifest


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class ForgeServer(Logger):
    """Package registry (reference: veles/forge/forge_server.py:462).

    Storage layout: ``<store>/<name>/<version>/package.tar.gz`` +
    extracted ``manifest.json`` for cheap listing.
    """

    TOKENS_FILE = "_tokens.json"

    def __init__(self, store_dir: str, port: int = 0,
                 upload_tokens: Optional[List[str]] = None,
                 host: str = "127.0.0.1",
                 registration_open: bool = False) -> None:
        super().__init__()
        self.store_dir = store_dir
        os.makedirs(store_dir, exist_ok=True)
        self.upload_tokens = set(upload_tokens or ())
        #: POST /register issues author-bound tokens (the reference's
        #: email-verification loop, forge_server.py:462 — this image has
        #: no egress, so the token returns in the response instead of a
        #: confirmation mail; the author/ownership semantics are kept)
        self.registration_open = registration_open
        import threading
        #: guards _tokens and the ownership check-then-write in store()
        #: (handlers run on ThreadingHTTPServer threads)
        self._auth_lock = threading.Lock()
        self._tokens: Dict[str, Dict[str, str]] = self._load_tokens()
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                server.debug("http: " + fmt, *args)

            def do_GET(self):
                url = urllib.parse.urlparse(self.path)
                query = urllib.parse.parse_qs(url.query)
                if url.path == "/service":
                    self._service(query)
                elif url.path == "/fetch":
                    self._fetch(query)
                else:
                    self.send_error(404)

            def _service(self, query):
                kind = query.get("query", [""])[0]
                if kind == "list":
                    json_reply(self, 200, server.list_packages())
                elif kind == "details":
                    name = query.get("name", [""])[0]
                    try:
                        json_reply(self, 200, server.details(name))
                    except KeyError:
                        json_reply(self, 404,
                                   {"error": "unknown %r" % name})
                else:
                    json_reply(self, 400, {"error": "bad query %r" % kind})

            def _fetch(self, query):
                name = query.get("name", [""])[0]
                version = query.get("version", [None])[0]
                try:
                    path = server.package_path(name, version)
                except KeyError as e:
                    json_reply(self, 404, {"error": str(e)})
                    return
                with open(path, "rb") as fin:
                    data = fin.read()
                bytes_reply(self, 200, data, "application/gzip")

            def do_POST(self):
                url = urllib.parse.urlparse(self.path)
                if url.path == "/register":
                    self._register()
                    return
                if url.path != "/upload":
                    self.send_error(404)
                    return
                query = urllib.parse.parse_qs(url.query)
                token = query.get("token", [""])[0]
                author = server.authorize(token)
                if author is None:
                    json_reply(self, 403, {"error": "bad token"})
                    return
                length = int(self.headers.get("Content-Length", 0))
                blob = self.rfile.read(length)
                try:
                    manifest = server.store(blob, author=author)
                except PermissionError as e:
                    json_reply(self, 403, {"error": str(e)})
                    return
                except VelesError as e:
                    json_reply(self, 400, {"error": str(e)})
                    return
                json_reply(self, 200, {"ok": True,
                                       "name": manifest["name"],
                                       "version": manifest["version"]})

            def _register(self):
                if not server.registration_open:
                    json_reply(self, 403,
                               {"error": "registration closed; ask the "
                                         "operator for a token"})
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                    author = str(body["author"])
                    email = str(body.get("email", ""))
                except (ValueError, KeyError):
                    json_reply(self, 400,
                               {"error": "body must be JSON with "
                                         "'author' (+optional 'email')"})
                    return
                if not _NAME_RE.match(author):
                    # '' would alias the operator/admin sentinel in
                    # authorize() — ownership bypass for anyone
                    json_reply(self, 400,
                               {"error": "author must match %s"
                                         % _NAME_RE.pattern})
                    return
                token = server.register(author, email)
                json_reply(self, 200, {"ok": True, "token": token,
                                       "author": author})

        self._service = HTTPService(Handler, port, "forge", host=host)
        self.port = self._service.port

    # -- auth ----------------------------------------------------------------
    def _tokens_path(self) -> str:
        return os.path.join(self.store_dir, self.TOKENS_FILE)

    def _load_tokens(self) -> Dict[str, Dict[str, str]]:
        try:
            with open(self._tokens_path()) as fin:
                return json.load(fin)
        except (OSError, ValueError):
            return {}

    def _save_tokens(self) -> None:
        tmp = self._tokens_path() + ".tmp"
        with open(tmp, "w") as fout:
            json.dump(self._tokens, fout, indent=2)
        os.replace(tmp, self._tokens_path())

    def register(self, author: str, email: str = "") -> str:
        """Issue an author-bound token (persisted across restarts)."""
        import secrets
        import time as _time
        if not _NAME_RE.match(author or ""):
            raise VelesError("author must match %s" % _NAME_RE.pattern)
        token = secrets.token_urlsafe(24)
        with self._auth_lock:
            self._tokens[token] = {"author": author, "email": email,
                                   "created": _time.time()}
            self._save_tokens()
        self.info("registered author %r", author)
        return token

    def authorize(self, token: str) -> Optional[str]:
        """token → author name; '' when auth is disabled entirely; None
        when rejected. Operator tokens (--token) act as admin ('')."""
        if token in self.upload_tokens:
            return ""
        with self._auth_lock:
            entry = self._tokens.get(token)
            no_auth = (not self.upload_tokens and not self._tokens
                       and not self.registration_open)
        if entry is not None:
            return entry["author"] or ""
        if no_auth:
            return ""        # open hub (loopback/dev): no auth configured
        return None

    # -- storage ------------------------------------------------------------
    def list_packages(self) -> List[Dict[str, Any]]:
        out = []
        for name in sorted(os.listdir(self.store_dir)):
            if not os.path.isdir(os.path.join(self.store_dir, name)):
                continue        # stray files must not break the registry
            versions = sorted(
                (v for v in os.listdir(os.path.join(self.store_dir, name))
                 if os.path.isdir(os.path.join(self.store_dir, name, v))),
                key=version_key)
            if not versions:
                continue
            with open(os.path.join(self.store_dir, name, versions[-1],
                                   MANIFEST)) as fin:
                manifest = json.load(fin)
            manifest["versions"] = versions
            out.append(manifest)
        return out

    def details(self, name: str) -> Dict[str, Any]:
        for entry in self.list_packages():
            if entry["name"] == name:
                return entry
        raise KeyError(name)

    def package_path(self, name: str, version: Optional[str]) -> str:
        if not _NAME_RE.match(name or ""):
            raise KeyError("bad name %r" % name)
        base = os.path.join(self.store_dir, name)
        if not os.path.isdir(base):
            raise KeyError("unknown package %r" % name)
        if version is None:
            version = sorted(
                (v for v in os.listdir(base)
                 if os.path.isdir(os.path.join(base, v))),
                key=version_key)[-1]
        elif not _NAME_RE.match(version):
            raise KeyError("bad version %r" % version)
        path = os.path.join(base, version, "package.tar.gz")
        if not os.path.exists(path):
            raise KeyError("no %s version %s" % (name, version))
        return path

    def store(self, blob: bytes, author: str = "") -> Dict[str, Any]:
        import tempfile
        with tempfile.NamedTemporaryFile(suffix=".tar.gz") as tmp:
            tmp.write(blob)
            tmp.flush()
            try:
                manifest = read_package_manifest(tmp.name)
            except (tarfile.TarError, ValueError) as e:
                raise VelesError("bad package: %s" % e)
            base = os.path.join(self.store_dir, manifest["name"])
            owner_file = os.path.join(base, "_owner")
            with self._auth_lock:
                if os.path.exists(owner_file):
                    with open(owner_file) as fin:
                        owner = fin.read().strip()
                    # author '' = admin/operator token: may publish over
                    # anyone; a non-admin may never publish over a
                    # package they don't own — including operator-owned
                    # packages (owner '')
                    if author != "" and owner != author:
                        raise PermissionError(
                            "package %r is owned by %r" %
                            (manifest["name"], owner or "<operator>"))
                dest = os.path.join(base, str(manifest["version"]))
                os.makedirs(dest, exist_ok=True)
                if not os.path.exists(owner_file):
                    # operator-published packages record '' so a later
                    # registered author cannot claim them
                    with open(owner_file, "w") as fout:
                        fout.write(author)
                shutil.copy(tmp.name,
                            os.path.join(dest, "package.tar.gz"))
                with open(os.path.join(dest, MANIFEST), "w") as fout:
                    json.dump(manifest, fout, indent=2)
        self.info("stored %s %s%s", manifest["name"], manifest["version"],
                  (" (author %s)" % author) if author else "")
        return manifest

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ForgeServer":
        self._service.start_serving()
        self.info("forge on http://127.0.0.1:%d/", self.port)
        return self

    def stop(self) -> None:
        self._service.stop_serving()


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class ForgeClient(Logger):
    """Talks to a ForgeServer (reference: veles/forge/forge_client.py:91).
    Every HTTP call runs under a RetryPolicy — the hub is a remote
    service; timeouts/resets/5xx back off and retry, 4xx (the caller's
    mistake) fail immediately."""

    def __init__(self, base_url: str, retry=None) -> None:
        super().__init__()
        self.base_url = base_url.rstrip("/")
        from .resilience.retry import RetryPolicy
        import urllib.error
        self.retry = retry or RetryPolicy(
            name="forge.client", max_attempts=3, base_delay=0.5,
            retry_if=lambda e: not (isinstance(e, urllib.error.HTTPError)
                                    and e.code < 500))

    def _get_json(self, path: str) -> Any:
        def get():
            with urllib.request.urlopen(self.base_url + path,
                                        timeout=30) as resp:
                return json.loads(resp.read())
        return self.retry.call(get)

    def list(self) -> List[Dict[str, Any]]:
        return self._get_json("/service?query=list")

    def details(self, name: str) -> Dict[str, Any]:
        return self._get_json("/service?query=details&name=" +
                              urllib.parse.quote(name))

    def fetch(self, name: str, dest_dir: str,
              version: Optional[str] = None) -> Dict[str, Any]:
        """Download and extract; returns the manifest."""
        url = self.base_url + "/fetch?name=" + urllib.parse.quote(name)
        if version:
            url += "&version=" + urllib.parse.quote(version)
        os.makedirs(dest_dir, exist_ok=True)
        tar_path = os.path.join(dest_dir, name + ".tar.gz")

        def download():
            # "wb" every attempt: a retried transfer restarts clean
            # instead of appending to a truncated body
            with urllib.request.urlopen(url, timeout=60) as resp, \
                    open(tar_path, "wb") as fout:
                shutil.copyfileobj(resp, fout)
        self.retry.call(download)
        manifest = extract_package(tar_path, dest_dir)
        os.unlink(tar_path)
        self.info("fetched %s %s → %s", manifest["name"],
                  manifest["version"], dest_dir)
        return manifest

    def register(self, author: str, email: str = "") -> str:
        """Self-register and return an author-bound upload token
        (reference: forge registration, minus the confirmation mail)."""
        req = urllib.request.Request(
            self.base_url + "/register",
            data=json.dumps({"author": author,
                             "email": email}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            def post():
                with urllib.request.urlopen(req, timeout=30) as resp:
                    return json.loads(resp.read())["token"]
            return self.retry.call(post)
        except urllib.error.HTTPError as e:
            raise VelesError("registration rejected (%d): %s" %
                             (e.code, e.read().decode(errors="replace")))

    def upload(self, package_path: str, token: str = "") -> Dict[str, Any]:
        read_package_manifest(package_path)      # validate before sending
        with open(package_path, "rb") as fin:
            blob = fin.read()
        req = urllib.request.Request(
            self.base_url + "/upload?token=" +
            urllib.parse.quote(token), data=blob,
            headers={"Content-Type": "application/gzip"})
        try:
            def post():
                with urllib.request.urlopen(req, timeout=60) as resp:
                    return json.loads(resp.read())
            return self.retry.call(post)
        except urllib.error.HTTPError as e:
            raise VelesError("upload rejected (%d): %s" %
                             (e.code, e.read().decode(errors="replace")))


def main(argv=None) -> int:
    """``python -m veles_tpu.forge {serve,list,details,fetch,upload,pack}``
    (reference CLI: velescli forge / veles/scripts/update_forge.py)."""
    import argparse
    parser = argparse.ArgumentParser(description=main.__doc__)
    sub = parser.add_subparsers(dest="cmd", required=True)
    ps = sub.add_parser("serve")
    ps.add_argument("store_dir")
    ps.add_argument("--port", type=int, default=8070)
    ps.add_argument("--host", default="0.0.0.0",
                    help="bind address (hub serves remote clients)")
    ps.add_argument("--token", action="append", default=[])
    ps.add_argument("--open-registration", action="store_true",
                    help="allow POST /register to self-issue "
                         "author-bound upload tokens")
    pr = sub.add_parser("register")
    pr.add_argument("--server", required=True)
    pr.add_argument("--author", required=True)
    pr.add_argument("--email", default="")
    for name in ("list", "details", "fetch", "upload"):
        p = sub.add_parser(name)
        p.add_argument("--server", required=True)
        if name in ("details", "fetch"):
            p.add_argument("name")
        if name == "fetch":
            p.add_argument("--dest", default=".")
            p.add_argument("--version", default=None)
        if name == "upload":
            p.add_argument("package")
            p.add_argument("--token", default="")
    pp = sub.add_parser("pack")
    pp.add_argument("src_dir")
    for key in REQUIRED_KEYS:
        pp.add_argument("--" + key, required=True)
    args = parser.parse_args(argv)
    if args.cmd == "serve":
        if args.host not in ("127.0.0.1", "localhost", "::1") and \
                not args.token and not args.open_registration:
            parser.error("serving on %s requires --token or "
                         "--open-registration (anonymous upload on a "
                         "non-loopback bind would let any host publish "
                         "executable model code)" % args.host)
        server = ForgeServer(args.store_dir, port=args.port,
                             host=args.host, upload_tokens=args.token,
                             registration_open=args.open_registration
                             ).start()
        import time
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            server.stop()
        return 0
    if args.cmd == "pack":
        manifest = {k: getattr(args, k) for k in REQUIRED_KEYS}
        print(make_package(args.src_dir, manifest))
        return 0
    client = ForgeClient(args.server)
    if args.cmd == "register":
        print(client.register(args.author, args.email))
        return 0
    if args.cmd == "list":
        print(json.dumps(client.list(), indent=2))
    elif args.cmd == "details":
        print(json.dumps(client.details(args.name), indent=2))
    elif args.cmd == "fetch":
        client.fetch(args.name, args.dest, args.version)
    elif args.cmd == "upload":
        print(json.dumps(client.upload(args.package, args.token)))
    return 0


if __name__ == "__main__":      # pragma: no cover
    import sys
    sys.exit(main())
