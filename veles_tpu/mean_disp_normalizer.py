"""MeanDispNormalizer: y = (x - mean) * rdisp, elementwise over samples.

Equivalent of the reference's veles/mean_disp_normalizer.py:50 with its
ocl/cuda kernels (mean_disp_normalizer.cl/.cu) — BASELINE config #2. The
kernel body collapses to a fused XLA expression; the reduction that builds
``rdisp`` from dispersion is the matrix_reduce.cl equivalent (a jnp
reduction XLA tiles itself)."""

from __future__ import annotations

from typing import Optional

import numpy

from .accelerated import AcceleratedUnit
from .config import root
from .memory import Array


class MeanDispNormalizer(AcceleratedUnit):
    """input (B, ...), mean (...), rdisp (...) → output (B, ...) float."""

    MAPPING = "mean_disp_normalizer"
    hide_from_registry = False

    def __init__(self, workflow, **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "WORKER"
        self.input: Optional[Array] = None
        self.mean: Optional[Array] = None
        self.rdisp: Optional[Array] = None
        self.output = Array(name=self.name + ".output")
        self.demand("input", "mean", "rdisp")

    def initialize(self, device=None, **kwargs):
        res = super().initialize(device=device, **kwargs)
        if res:
            return res
        dtype = root.common.engine.precision_type
        if (self.output.mem is None
                or self.output.shape != self.input.shape):
            self.output.reset(numpy.zeros(self.input.shape, dtype=dtype))
        return None

    @staticmethod
    def compute_mean_rdisp(data: numpy.ndarray):
        """Build (mean, rdisp) from a dataset — single definition shared
        with the host-side normalizer registry."""
        from .normalization import MeanDispNormalizerHost
        host = MeanDispNormalizerHost()
        host.analyze(data)
        host._finish()
        return host.mean, host.rdisp

    def apply(self, x, mean, rdisp):
        return (x - mean) * rdisp

    def xla_run(self) -> None:
        fn = self.jit("norm", self.apply)
        self.output.assign_devmem(fn(
            self.input.device_view(), self.mean.device_view(),
            self.rdisp.device_view()))

    def numpy_run(self) -> None:
        x = self.input.map_read().astype(numpy.float32)
        self.output.reset(
            (x - self.mean.map_read()) * self.rdisp.map_read())
