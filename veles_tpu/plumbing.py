"""Graph-skeleton units (reference: veles/plumbing.py:17-112)."""

from __future__ import annotations

from .units import Unit


class StartPoint(Unit):
    """Workflow entry node (reference: veles/plumbing.py:44)."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "Start")
        super().__init__(workflow, **kwargs)


class EndPoint(Unit):
    """Workflow exit node: running it finishes the workflow
    (reference: veles/plumbing.py:60-88)."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "End")
        super().__init__(workflow, **kwargs)

    def run(self) -> None:
        self.workflow.on_workflow_finished()


class Repeater(Unit):
    """Loop head: ignores its gate so the cycle back-edge can re-fire it
    (reference: veles/plumbing.py:17-41)."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "Repeater")
        kwargs.setdefault("ignores_gate", True)
        super().__init__(workflow, **kwargs)


class FireStarter(Unit):
    """Resets the ``stopped`` flag of attached units so a finished subgraph
    can run again (reference: veles/plumbing.py:92-112)."""

    def __init__(self, workflow, units=(), **kwargs):
        kwargs.setdefault("name", "FireStarter")
        super().__init__(workflow, **kwargs)
        self.units = list(units)

    def run(self) -> None:
        for u in self.units:
            u.stopped <<= False
