"""Interaction: drop into a live shell inside a running workflow.

Equivalent of the reference's veles/interaction.py:49 (``Shell`` unit: an
embedded IPython console). Differences: the reference bound it
to the 'i' hot-key through its thread-pool/manhole machinery; here
activation is explicit — programmatic ``activate()``, a trigger file
(``touch <path>`` from another terminal — the moral equivalent of the
hot-key for a headless TPU job), or every N runs — because the scheduler
is deterministic and single-threaded between steps, which is exactly when
inspecting live state is safe.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from .units import Unit


class Shell(Unit):
    """Interactive inspection point.

    Place anywhere in the loop (typically after the decision). When
    triggered, opens IPython (if installed) or a stdlib ``code`` console
    whose namespace holds the workflow, its units by name, and numpy.
    """

    MAPPING = "shell"
    hide_from_registry = False

    def __init__(self, workflow, trigger_file: Optional[str] = None,
                 every: int = 0, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.trigger_file = trigger_file
        self.every = int(every)
        self._armed = False
        self.sessions = 0

    def activate(self) -> None:
        """Arm the shell: the next ``run()`` opens it."""
        self._armed = True

    def _should_open(self) -> bool:
        if self._armed:
            return True
        if self.every and self.run_count and \
                self.run_count % self.every == 0:
            return True
        if self.trigger_file and os.path.exists(self.trigger_file):
            try:
                os.unlink(self.trigger_file)    # one shot per touch
            except OSError:
                pass
            return True
        return False

    def namespace(self) -> Dict[str, Any]:
        import numpy
        ns: Dict[str, Any] = {"workflow": self.workflow, "numpy": numpy,
                              "np": numpy}
        for u in getattr(self.workflow, "units", ()):
            key = u.name.replace(" ", "_").replace("-", "_")
            if key.isidentifier() and key not in ns:
                ns[key] = u
        return ns

    def run(self) -> None:
        if not self._should_open():
            return
        self._armed = False
        self.sessions += 1
        ns = self.namespace()
        banner = ("veles_tpu shell — workflow %r; names: %s\n"
                  "Ctrl-D resumes training." %
                  (getattr(self.workflow, "name", "?"),
                   ", ".join(sorted(ns))))
        self.open_console(ns, banner)

    # separated for testability (overridden / monkeypatched in tests)
    def open_console(self, ns: Dict[str, Any], banner: str) -> None:
        try:
            from IPython import embed
            embed(user_ns=ns, banner1=banner)
        except ImportError:
            import code
            code.interact(banner=banner, local=ns)
