"""Sharding rules: parameter pytrees → NamedShardings.

The reference classified per-unit state as master-only / replicated /
aggregated in its generate/apply protocol (veles/distributable.py:222 —
the IDistributable 4-method plane). The TPU equivalent is a *rule table*
mapping parameter names+shapes to PartitionSpecs over the mesh:

- 'tensor' in mesh → All2All/Conv kernels column-split over their output
  axis (Megatron-style; XLA inserts the activation collectives);
- 'fsdp' in mesh → remaining large params sharded over their biggest
  divisible axis, all-gathered at use (ZeRO-3, free via XLA SPMD);
- otherwise replicated.
"""

from __future__ import annotations

from typing import Any, Dict


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(mesh, P())


def batch_sharding(mesh, ndim: int = 1, plan: bool = False):
    """Minibatch arrays: shard the sample axis over 'data'
    (plan=True for (K, mb) scan plans: sample axis is axis 1)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    if "data" not in mesh.axis_names:
        return replicated(mesh)
    spec = [None] * ndim
    spec[1 if plan else 0] = "data"
    return NamedSharding(mesh, P(*spec))


def _spec_for(name: str, shape, mesh) -> tuple:
    """PartitionSpec elements for one parameter (by name AND shape)."""
    sizes = dict(mesh.shape)
    tp = sizes.get("tensor", 1)
    fsdp = sizes.get("fsdp", 1)
    ep = sizes.get("expert", 1)
    spec = [None] * len(shape)
    if name in ("bias",):
        # small vectors: replicating is cheaper than the gather traffic
        return tuple(spec)
    if ep > 1 and name in ("w1", "b1", "w2", "b2") and \
            shape[0] % ep == 0:
        # expert-leading MoE params shard over the expert axis; GSPMD
        # partitions the expert einsum, no hand-written dispatch
        spec[0] = "expert"
        return tuple(spec)
    if tp > 1 and len(shape) >= 2 and shape[-1] % tp == 0:
        # column parallel: split the output-features axis
        spec[-1] = "tensor"
    if fsdp > 1:
        # shard the largest remaining divisible axis over fsdp
        order = sorted(range(len(shape)), key=lambda i: -shape[i])
        for i in order:
            if spec[i] is None and shape[i] % fsdp == 0:
                spec[i] = "fsdp"
                break
    return tuple(spec)


#: TrainStep's stacked pipeline-block entry (train_step.py): leaves carry
#: a leading n_layers axis that shards over 'pipeline'
PP_BLOCK = "__pp_block__"


def _pp_block_spec(name: str, shape, mesh) -> tuple:
    """Stacked pipeline block: leading layer axis over 'pipeline', output
    features over 'tensor' when divisible (biases replicate per stage),
    and the largest remaining divisible axis over 'fsdp' — ZeRO-3
    composes with the stage stacking exactly like with flat params."""
    sizes = dict(mesh.shape)
    spec = [None] * len(shape)
    spec[0] = "pipeline"
    tp = sizes.get("tensor", 1)
    if name not in ("bias",) and tp > 1 and len(shape) >= 3 \
            and shape[-1] % tp == 0:
        spec[-1] = "tensor"
    fsdp = sizes.get("fsdp", 1)
    if name not in ("bias",) and fsdp > 1:
        order = sorted(range(1, len(shape)), key=lambda i: -shape[i])
        for i in order:
            if spec[i] is None and shape[i] % fsdp == 0:
                spec[i] = "fsdp"
                break
    return tuple(spec)


def state_shardings(opt_state: Dict[str, Any],
                    params: Dict[str, Dict[str, Any]],
                    pspec: Dict[str, Dict[str, Any]], mesh):
    """Shardings for optimizer-state pytrees of ANY structure (SGD's
    flat {param: buf}, Adam's {m, v, t}, rprop's nested trees): every
    state leaf whose shape matches a parameter of the same layer
    inherits that parameter's sharding; anything else (step counters,
    odd-shaped accumulators) replicates."""
    import jax
    repl = replicated(mesh)
    out: Dict[str, Any] = {}
    for layer, st in opt_state.items():
        layer_params = params.get(layer, {})
        layer_spec = pspec.get(layer, {})
        by_shape = {}
        for k, arr in layer_params.items():
            by_shape.setdefault(tuple(arr.shape), layer_spec[k])

        def sh_for(path, leaf, _shapes=by_shape, _p=layer_params,
                   _s=layer_spec):
            # exact match first: the innermost dict key naming a param
            # (SGD's {param: buf}, Adam's {m: {param: buf}}) — shape
            # lookup alone mis-binds when two params share a shape
            for entry in reversed(path):
                key = getattr(entry, "key", None)
                if key in _p:
                    return _s[key]
            return _shapes.get(tuple(getattr(leaf, "shape", ())), repl)

        out[layer] = jax.tree_util.tree_map_with_path(sh_for, st)
    return out


def param_shardings(params: Dict[str, Dict[str, Any]], mesh):
    """NamedSharding pytree matching a {layer: {param: array}} tree."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    out: Dict[str, Dict[str, Any]] = {}
    for layer, tree in params.items():
        out[layer] = {}
        for pname, arr in tree.items():
            if layer == PP_BLOCK and "pipeline" in mesh.axis_names:
                spec = _pp_block_spec(pname, arr.shape, mesh)
            else:
                spec = _spec_for(pname, arr.shape, mesh)
            out[layer][pname] = NamedSharding(mesh, P(*spec))
    return out
