"""Parallel trial scheduler: the job farm under GA / ensemble search.

The reference farmed chromosome evaluations and ensemble members out as
master–slave jobs over its ZeroMQ server (veles/genetics/
optimization_workflow.py:70, veles/ensemble/model_workflow.py:137,
veles/server.py job protocol). TPU-first redesign (SURVEY.md §2.4
"ensemble/GA parallelism → trial scheduler over TPU slices"): a trial
is one OS subprocess running the normal CLI; a fixed pool of worker
SLOTS runs up to ``n_workers`` trials concurrently; a *placement hook*
maps each slot to the environment that pins its device resources:

- ``cpu_placement`` (default): every slot gets its own single-device
  XLA:CPU platform — correctness fan-out on any host, including CI.
- ``mesh_slice_placement(...)``: slots map onto disjoint accelerator
  slices via env (TPU_VISIBLE_CHIPS on multi-chip hosts). On this rig
  the tunnelled chip is exclusive-single, so slice placement degrades
  to ``n_workers=1`` — the scheduler is still the single code path.

Trials never share a process with the scheduler (device state isolation
— the reference's exact reason for slave processes), and an overrunning
or crashing trial is killed by process group and reported, never
propagated.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import subprocess
import threading
import time
from queue import Queue
from typing import Callable, Dict, List, Optional, Sequence

from ..logger import Logger


def cpu_placement(slot: int) -> Dict[str, str]:
    """One private XLA:CPU device per worker slot. Strips any forced
    host-device-count (the test harness exports 8) so concurrent trials
    don't each spin up 8 virtual devices' worth of threads."""
    flags = os.environ.get("XLA_FLAGS", "")
    flags = " ".join(t for t in flags.split()
                     if "xla_force_host_platform_device_count" not in t)
    return {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": flags,
            # slots must not fight over host cores via intra-op pools
            "XLA_CPU_MULTI_THREAD_EIGEN": "false"}


def mesh_slice_placement(devices_per_trial: int = 1,
                         total_devices: Optional[int] = None
                         ) -> Callable[[int], Dict[str, str]]:
    """Placement hook for real multi-chip hosts: slot *i* sees chips
    ``[i*d, (i+1)*d)`` via TPU_VISIBLE_CHIPS, so trials train on
    disjoint slices of one host's chips concurrently (the TPU analog of
    the reference's one-job-per-slave placement)."""
    def place(slot: int) -> Dict[str, str]:
        d = int(devices_per_trial)
        chips = range(slot * d, (slot + 1) * d)
        if total_devices is not None and chips[-1] >= total_devices:
            raise ValueError(
                "slot %d needs chips %s but only %d exist"
                % (slot, list(chips), total_devices))
        return {"TPU_VISIBLE_CHIPS": ",".join(map(str, chips)),
                # bounds must cover the d visible chips (flat topology);
                # a 1,1,1 bound would contradict a multi-chip slice
                "TPU_CHIPS_PER_PROCESS_BOUNDS": "%d,1,1" % d}
    return place


def run_json_trials(make_argv, n: int, n_workers: int,
                    placement: Optional[Callable[[int],
                                                 Dict[str, str]]] = None,
                    timeout: Optional[float] = None,
                    tags: Optional[Sequence[object]] = None):
    """Run ``n`` CLI trials that each write a JSON result file; returns
    ``[(TrialResult, parsed_json_or_None), ...]`` in submission order.

    ``make_argv(i, result_file) -> argv``. Owns the whole result-file
    lifecycle (mkstemp, guarded parse, unlink) so every caller — GA
    generations, ensemble members — shares one failure contract: a
    trial whose process failed OR whose result file is unreadable
    yields ``doc=None`` and never raises."""
    import json
    import tempfile
    result_files, trials = [], []
    for i in range(n):
        fd, rf = tempfile.mkstemp(suffix=".json")
        os.close(fd)
        result_files.append(rf)
        trials.append(Trial(argv=make_argv(i, rf),
                            tag=tags[i] if tags else i, timeout=timeout))
    sched = TrialScheduler(n_workers=n_workers,
                           placement=placement or cpu_placement)
    try:
        out = []
        for res, rf in zip(sched.run(trials), result_files):
            doc = None
            if res.ok:
                try:
                    with open(rf) as fin:
                        doc = json.load(fin)
                except (ValueError, OSError):
                    doc = None      # rc=0 but no usable result: caller
                    # treats it exactly like a failed trial
            out.append((res, doc))
        return out
    finally:
        for rf in result_files:
            try:
                os.unlink(rf)
            except OSError:
                pass


@dataclasses.dataclass
class Trial:
    """One unit of farmed work: an argv command plus per-trial env."""
    argv: Sequence[str]
    tag: object = None
    env: Optional[Dict[str, str]] = None
    timeout: Optional[float] = None


@dataclasses.dataclass
class TrialResult:
    tag: object
    returncode: int
    stderr_tail: str
    elapsed: float
    slot: int
    timed_out: bool = False

    @property
    def ok(self) -> bool:
        return self.returncode == 0 and not self.timed_out


class TrialScheduler(Logger):
    """Run trials with bounded concurrency and per-slot placement.

    ``run`` preserves submission order in its result list; a failed or
    overrunning trial yields a TrialResult with ``ok == False`` (killed
    by process group) and never raises — one divergent candidate must
    not take down a whole generation (same contract the reference's
    job farm kept, veles/server.py:315-338 slave-death handling).
    """

    def __init__(self, n_workers: Optional[int] = None,
                 placement: Callable[[int], Dict[str, str]] = cpu_placement,
                 timeout: Optional[float] = None) -> None:
        super().__init__()
        if n_workers is None:
            n_workers = min(4, os.cpu_count() or 1)
        self.n_workers = int(n_workers)
        if self.n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        self.placement = placement
        self.timeout = timeout

    def _run_one(self, trial: Trial, slot: int) -> TrialResult:
        env = dict(os.environ)
        env.update(self.placement(slot))
        if trial.env:
            env.update(trial.env)
        t0 = time.time()
        timeout = trial.timeout or self.timeout
        timed_out = False
        proc = subprocess.Popen(
            list(trial.argv), env=env, text=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
            start_new_session=True)     # killable with its children
        try:
            _, err = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            timed_out = True
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                proc.kill()
            _, err = proc.communicate()
        return TrialResult(tag=trial.tag, returncode=proc.returncode,
                           stderr_tail=(err or "")[-2000:],
                           elapsed=time.time() - t0, slot=slot,
                           timed_out=timed_out)

    def run(self, trials: Sequence[Trial]) -> List[TrialResult]:
        trials = list(trials)
        # placement misconfiguration (e.g. a slice past the host's last
        # chip) is a caller error and must raise BEFORE any trial runs,
        # not surface as N per-trial "failures"; only slots that can
        # ever be taken are validated (returned slots re-enter at the
        # queue tail, so indices ≥ the worker count never circulate)
        for s in range(min(self.n_workers, len(trials))):
            self.placement(s)
        results: List[Optional[TrialResult]] = [None] * len(trials)
        slots: Queue = Queue()
        for s in range(self.n_workers):
            slots.put(s)
        pending: Queue = Queue()
        for i, t in enumerate(trials):
            pending.put((i, t))

        def worker() -> None:
            while True:
                try:
                    i, trial = pending.get_nowait()
                except Exception:
                    return
                slot = slots.get()
                try:
                    res = self._run_one(trial, slot)
                except Exception as exc:   # spawn failure: report, go on
                    res = TrialResult(tag=trial.tag, returncode=-1,
                                      stderr_tail=str(exc), elapsed=0.0,
                                      slot=slot)
                finally:
                    slots.put(slot)
                if not res.ok:
                    self.warning(
                        "trial %r failed (rc=%s%s): %s", trial.tag,
                        res.returncode,
                        ", timed out" if res.timed_out else "",
                        res.stderr_tail[-500:])
                results[i] = res

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(min(self.n_workers, len(trials)))]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        for i, r in enumerate(results):
            if r is None:      # worker thread died outside _run_one
                results[i] = TrialResult(
                    tag=trials[i].tag, returncode=-1,
                    stderr_tail="worker thread died", elapsed=0.0,
                    slot=-1)
        return results  # type: ignore[return-value]
