"""jax API compatibility shims for the parallel subsystem.

One function for now: ``shard_map`` moved twice across jax releases —
``jax.experimental.shard_map.shard_map`` (0.4.x, replication checking
via ``check_rep=``) became top-level ``jax.shard_map`` (varying-
manual-axes checking via ``check_vma=``). Every call site in this
package wants the check OFF (the schedules mix replicated and
per-device values by construction), so the shim resolves both the
import location and the kwarg spelling in one place.
"""

from __future__ import annotations


def shard_map_compat(fn, mesh, in_specs, out_specs):
    """``shard_map(fn, ...)`` with replication/VMA checking disabled,
    on whichever jax API this environment ships."""
    try:
        from jax import shard_map            # jax >= 0.6: check_vma
    except ImportError:
        from jax.experimental.shard_map import shard_map  # 0.4.x
        return shard_map(fn, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_rep=False)
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_vma=False)
