"""Pipeline parallelism: GPipe-style microbatched stage pipeline.

New capability vs the reference (SURVEY.md §2.4 row "Model-parallel /
pipeline — absent; new capability"). The standard TPU formulation (the
scaling-book recipe): each device on the ``pipeline`` mesh axis holds one
stage's parameters; microbatches ripple through, activations hopping
stage-to-stage with ``ppermute`` inside ``shard_map``; the schedule runs
``M + n_stages - 1`` ticks (fill + drain). Differentiable end to end —
``jax.grad`` through the scan/ppermute yields the reverse schedule
automatically, so the fused train step can wrap a pipelined forward like
any other pure function.

This implementation handles the uniform-stage case (every stage maps an
activation of shape S to shape S — e.g. a stack of residual blocks),
which is the shape pipeline parallelism is actually used in.
``plan_pipeline`` below stage-groups a workflow's forward chain into that
form so ``{"pipeline": N}`` is a StandardWorkflow/TrainStep capability,
not a standalone demo.
"""

from __future__ import annotations

from typing import Any, Callable, List, Tuple


def gpipe(fn: Callable[[Any, Any], Any], stage_params: Any, xs: Any,
          mesh, axis: str = "pipeline", batch_spec=None):
    """Run ``y_m = fn_{n-1}(…fn_0(x_m))`` for M microbatches.

    - ``fn(params_slice, x)`` — one stage; same activation shape in/out.
    - ``stage_params`` — pytree whose leaves have a leading ``n_stages``
      axis (sharded over ``axis``; each device sees its slice with the
      leading axis of size 1).
    - ``xs`` — (M, mb, …) microbatches; ``batch_spec`` is their
      PartitionSpec (e.g. ``P(None, "data")`` when the minibatch axis is
      data-sharded in the surrounding SPMD program; default replicated).

    Returns (M, mb, …) outputs, sharded like ``batch_spec``.
    """
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    if batch_spec is None:
        batch_spec = P()
    n = mesh.shape[axis]
    m = xs.shape[0]
    ticks = m + n - 1
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.ndim == 0 or leaf.shape[0] != n:
            raise ValueError(
                "stage_params leaves need a leading axis of exactly %d "
                "pipeline stages, got shape %s (a multiple would shard "
                "silently and drop stages)" % (n, leaf.shape))

    def local(params, x_all):
        # params leaves: (1, …) — this stage's slice
        my_params = jax.tree_util.tree_map(lambda p: p[0], params)
        idx = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % n) for i in range(n)]
        zero = jnp.zeros_like(x_all[0])

        def tick(carry, t):
            buf, outputs = carry
            # stage 0 injects microbatch t (garbage after the fill phase —
            # those lanes never reach a collected slot)
            inject = x_all[jnp.clip(t, 0, m - 1)]
            inp = jnp.where(idx == 0, inject, buf)
            y = fn(my_params, inp)
            # the LAST stage emits microbatch (t - (n-1)) at tick t
            out_slot = t - (n - 1)
            collect = jnp.logical_and(idx == n - 1, out_slot >= 0)
            outputs = jax.lax.cond(
                collect,
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, y, jnp.maximum(out_slot, 0), 0),
                lambda o: o, outputs)
            buf = jax.lax.ppermute(y, axis, perm)
            return (buf, outputs), None

        outputs0 = jnp.zeros((m,) + x_all.shape[1:], x_all.dtype)
        (_, outputs), _ = jax.lax.scan(tick, (zero, outputs0),
                                       jnp.arange(ticks))
        # only the last stage holds real outputs; psum replicates them
        # (all other stages contribute zeros)
        outputs = jnp.where(idx == n - 1, outputs, 0.0)
        return jax.lax.psum(outputs, axis)

    params_spec = jax.tree_util.tree_map(
        lambda _: P(axis), stage_params)
    fn_sharded = shard_map(
        local, mesh=mesh,
        in_specs=(params_spec, batch_spec), out_specs=batch_spec,
        check_vma=False)
    return fn_sharded(stage_params, xs)


def plan_pipeline(forwards: List[Any], n_stages: int
                  ) -> Tuple[List[Any], List[Any], List[Any]]:
    """Stage-group a forward chain for ``{"pipeline": N}``.

    Returns ``(pre, block, post)``: the longest contiguous run of
    *identical, shape-preserving, parameterized* forwards (same class,
    same parameter signature, same GD hyper-parameters, activation shape
    in == out), trimmed to a multiple of ``n_stages``; everything before/
    after runs replicated outside the pipelined region. Raises ValueError
    when no viable run exists — pipelining a heterogeneous chain would
    silently serialize, which is worse than failing loudly.
    """
    def signature(f):
        if not getattr(f, "PARAMETERIZED", False):
            return None
        if f.input is None or not f.input or not f.output:
            return None
        if tuple(f.input.shape) != tuple(f.output.shape):
            return None  # stages must be shape-preserving
        params = tuple(sorted(
            (k, tuple(v.shape), str(v.dtype))
            for k, v in f.param_arrays().items()))
        gd = tuple(sorted(getattr(f, "gd_config", {}).items()))
        # semantic config must match too: the grouped block runs every
        # layer through block[0].apply, so e.g. rope=True/False or
        # causal differences would silently apply block 0's setting to
        # all stages. The export key list IS the inference-defining
        # config inventory — reuse it.
        from ..export.package import _EXPORT_KEYS
        cfg = tuple((k, repr(getattr(f, k))) for k in _EXPORT_KEYS
                    if hasattr(f, k))
        return (type(f).__name__, params, gd, cfg)

    sigs = [signature(f) for f in forwards]
    best = (0, 0)  # (length, start)
    i = 0
    while i < len(sigs):
        if sigs[i] is None:
            i += 1
            continue
        j = i
        while j < len(sigs) and sigs[j] == sigs[i]:
            j += 1
        if j - i > best[0]:
            best = (j - i, i)
        i = j
    length, start = best
    usable = (length // n_stages) * n_stages
    if usable < n_stages or usable == 0:
        raise ValueError(
            "pipeline axis of size %d needs >= %d contiguous identical "
            "shape-preserving parameterized layers; longest run is %d. "
            "Stack repeated blocks (e.g. N x all2all_tanh of equal width) "
            "or drop the 'pipeline' mesh axis." % (n_stages, n_stages,
                                                   length))
    block = list(forwards[start:start + usable])
    pre = list(forwards[:start])
    post = list(forwards[start + usable:])
    return pre, block, post


def microbatch(x, n_micro: int):
    """(B, …) → (M, B/M, …); B must divide."""
    b = x.shape[0]
    if b % n_micro:
        raise ValueError("batch %d not divisible into %d microbatches"
                         % (b, n_micro))
    return x.reshape((n_micro, b // n_micro) + x.shape[1:])


def unmicrobatch(y):
    return y.reshape((-1,) + y.shape[2:])
