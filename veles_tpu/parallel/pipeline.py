"""Pipeline parallelism: GPipe-style microbatched stage pipeline.

New capability vs the reference (SURVEY.md §2.4 row "Model-parallel /
pipeline — absent; new capability"). The standard TPU formulation (the
scaling-book recipe): each device on the ``pipeline`` mesh axis holds one
stage's parameters; microbatches ripple through, activations hopping
stage-to-stage with ``ppermute`` inside ``shard_map``; the schedule runs
``M + n_stages - 1`` ticks (fill + drain). Differentiable end to end —
``jax.grad`` through the scan/ppermute yields the reverse schedule
automatically, so the fused train step can wrap a pipelined forward like
any other pure function.

Two schedules live here. :func:`gpipe` handles the uniform-stage case
(every stage maps an activation of shape S to shape S — e.g. a stack of
residual blocks), the memory-scaling formulation: stacked stage params
are *sharded* over the axis. :func:`gpipe_hetero` handles
shape-changing chains (conv → pool → dense) with per-stage
``lax.switch`` and a padded flat wire — compute overlap without the
memory scaling (params replicated; see its docstring for the trade).
``plan_pipeline`` / ``plan_pipeline_hetero`` stage-group a workflow's
forward chain so ``{"pipeline": N}`` is a StandardWorkflow/TrainStep
capability, not a standalone demo.
"""

from __future__ import annotations

from typing import Any, Callable, List, Tuple


def _ring_schedule(step_of, x_all, m, n, axis, wire0, out_of_wire,
                   out_shape):
    """The one copy of the GPipe tick loop both schedules share.

    ``m + n - 1`` ticks (fill + drain). Each tick: stage 0 injects
    microbatch t (garbage after the fill phase — those lanes never
    reach a collected slot), every device applies its stage via
    ``step_of(idx, buf, inject) -> y`` (wire-shaped), the LAST stage
    decodes and collects microbatch ``t - (n-1)`` via
    ``out_of_wire(y)``, and the wire hops the ``ppermute`` ring.
    Returns (m, *out_shape) outputs — only the last stage holds real
    values; the closing psum replicates them (other stages contribute
    zeros)."""
    import jax
    import jax.numpy as jnp

    idx = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def tick(carry, t):
        buf, outputs = carry
        inject = x_all[jnp.clip(t, 0, m - 1)]
        y = step_of(idx, buf, inject)
        out_slot = t - (n - 1)
        collect = jnp.logical_and(idx == n - 1, out_slot >= 0)
        outputs = jax.lax.cond(
            collect,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, out_of_wire(y), jnp.maximum(out_slot, 0), 0),
            lambda o: o, outputs)
        buf = jax.lax.ppermute(y, axis, perm)
        return (buf, outputs), None

    outputs0 = jnp.zeros((m,) + out_shape, x_all.dtype)
    (_, outputs), _ = jax.lax.scan(tick, (wire0, outputs0),
                                   jnp.arange(m + n - 1))
    outputs = jnp.where(idx == n - 1, outputs, 0.0)
    return jax.lax.psum(outputs, axis)


def gpipe(fn: Callable[[Any, Any], Any], stage_params: Any, xs: Any,
          mesh, axis: str = "pipeline", batch_spec=None):
    """Run ``y_m = fn_{n-1}(…fn_0(x_m))`` for M microbatches.

    - ``fn(params_slice, x)`` — one stage; same activation shape in/out.
    - ``stage_params`` — pytree whose leaves have a leading ``n_stages``
      axis (sharded over ``axis``; each device sees its slice with the
      leading axis of size 1).
    - ``xs`` — (M, mb, …) microbatches; ``batch_spec`` is their
      PartitionSpec (e.g. ``P(None, "data")`` when the minibatch axis is
      data-sharded in the surrounding SPMD program; default replicated).

    Returns (M, mb, …) outputs, sharded like ``batch_spec``.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from .compat import shard_map_compat

    if batch_spec is None:
        batch_spec = P()
    n = mesh.shape[axis]
    m = xs.shape[0]
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.ndim == 0 or leaf.shape[0] != n:
            raise ValueError(
                "stage_params leaves need a leading axis of exactly %d "
                "pipeline stages, got shape %s (a multiple would shard "
                "silently and drop stages)" % (n, leaf.shape))

    def local(params, x_all):
        # params leaves: (1, …) — this stage's slice; the wire carries
        # the (unpadded) activation itself: every hop has the same shape
        my_params = jax.tree_util.tree_map(lambda p: p[0], params)

        def step_of(idx, buf, inject):
            return fn(my_params, jnp.where(idx == 0, inject, buf))

        return _ring_schedule(step_of, x_all, m, n, axis,
                              jnp.zeros_like(x_all[0]), lambda y: y,
                              x_all.shape[1:])

    params_spec = jax.tree_util.tree_map(
        lambda _: P(axis), stage_params)
    fn_sharded = shard_map_compat(
        local, mesh=mesh,
        in_specs=(params_spec, batch_spec), out_specs=batch_spec)
    return fn_sharded(stage_params, xs)


def gpipe_hetero(stage_fns: List[Callable[[Any, Any], Any]],
                 stage_params: List[Any], xs: Any, mesh,
                 axis: str = "pipeline", batch_spec=None):
    """GPipe schedule over *heterogeneous* stages (shape-changing chain).

    Where :func:`gpipe` demands identical shape-preserving stages (and
    in return shards the stacked parameters over the axis — the
    memory-scaling formulation), this variant accepts one arbitrary
    ``fn_i(params_i, x) -> y`` per stage: each device selects its own
    stage with ``lax.switch`` on ``axis_index``, and the inter-stage
    activations — whose shapes differ per hop — ride the ``ppermute``
    ring as a flat buffer padded to the widest hop. That makes
    AlexNet/ImagenetAE-shaped chains (conv → pool → … → dense)
    pipelineable, which the uniform planner refuses.

    The trade, stated plainly: ``stage_params`` is a *list of per-stage
    pytrees replicated on every device* (SPMD cannot scatter
    differently-shaped arrays along one mesh axis), so heterogeneous
    pipelining buys compute overlap, not parameter-memory scaling. For
    the conv-era nets this targets, parameters are tiny next to
    activations, which is why the trade is acceptable. The backward
    ride comes free: ``lax.switch`` transposes to the executed branch
    only, so each device contributes exactly its stage's parameter
    cotangents, and shard_map's replicated-input transpose psums them.

    - ``xs`` — (M, mb, *in_shape) microbatches; ``batch_spec`` as in
      :func:`gpipe` (dim 1 may be data-sharded).
    - every stage must preserve dtype (checked at trace time); AMP
      casts happen outside.
    Returns (M, mb, *out_shape) outputs from the final stage.
    """
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from .compat import shard_map_compat

    if batch_spec is None:
        batch_spec = P()
    n = mesh.shape[axis]
    if len(stage_fns) != n or len(stage_params) != n:
        raise ValueError("need exactly %d stage fns/params, got %d/%d"
                         % (n, len(stage_fns), len(stage_params)))
    m = xs.shape[0]

    def local(all_params, x_all):
        # trace the shape chain on the LOCAL microbatch shape (dim 1 may
        # be data-sharded, so shapes must be derived inside shard_map)
        shapes = [x_all.shape[1:]]
        for fn, p in zip(stage_fns, all_params):
            out = jax.eval_shape(
                fn, p, jax.ShapeDtypeStruct(shapes[-1], x_all.dtype))
            if out.dtype != x_all.dtype:
                raise ValueError(
                    "pipeline stages must preserve dtype: stage yields "
                    "%s from %s input" % (out.dtype, x_all.dtype))
            shapes.append(out.shape)
        sizes = [int(np.prod(s)) for s in shapes]
        wire = max(sizes)

        def make_branch(i):
            def branch(buf, inject):
                x = (inject if i == 0
                     else buf[:sizes[i]].reshape(shapes[i]))
                y = stage_fns[i](all_params[i], x)
                y = y.reshape(-1)
                return jnp.pad(y, (0, wire - y.size))
            return branch

        branches = [make_branch(i) for i in range(n)]

        def step_of(idx, buf, inject):
            return jax.lax.switch(idx, branches, buf, inject)

        return _ring_schedule(
            step_of, x_all, m, n, axis,
            jnp.zeros((wire,), x_all.dtype),
            lambda y: y[:sizes[n]].reshape(shapes[n]), shapes[n])

    params_spec = jax.tree_util.tree_map(lambda _: P(), stage_params)
    fn_sharded = shard_map_compat(
        local, mesh=mesh,
        in_specs=(params_spec, batch_spec), out_specs=batch_spec)
    return fn_sharded(stage_params, xs)


def stage_cost(f) -> float:
    """Rough per-sample FLOP proxy for stage balancing: 2 × weight
    elements × output spatial positions for conv-likes (input positions
    for deconv), 2 × weight elements for dense, output size for
    unparameterized plumbing (pool/activation — bandwidth, not FLOPs,
    but enough to keep them from looking free)."""
    import numpy as np
    kind = type(f).__name__
    w = None
    if getattr(f, "PARAMETERIZED", False):
        w = f.param_arrays().get("weights")
    out_size = (int(np.prod(f.output.shape[1:]))
                if getattr(f, "output", None) else 1)
    if w is None:
        return float(out_size)
    if "Deconv" in kind and getattr(f, "input", None):
        _, ih, iw = f.input.shape[:3]
        return 2.0 * ih * iw * w.mem.size
    if "Conv" in kind and getattr(f, "output", None):
        _, oh, ow = f.output.shape[:3]
        return 2.0 * oh * ow * w.mem.size
    return 2.0 * float(w.mem.size)


def plan_pipeline_hetero(forwards: List[Any], n_stages: int
                         ) -> List[List[Any]]:
    """Split a heterogeneous forward chain into ``n_stages`` contiguous
    groups minimizing the max per-stage cost (classic linear-partition
    DP over :func:`stage_cost`) — the balance decides the pipeline's
    steady-state tick time. Every stage gets >= 1 unit; raises when the
    chain is shorter than the axis."""
    if len(forwards) < n_stages:
        raise ValueError(
            "pipeline axis of size %d needs >= %d forward units to "
            "stage; chain has %d. Drop the 'pipeline' mesh axis or "
            "shrink it." % (n_stages, n_stages, len(forwards)))
    costs = [stage_cost(f) for f in forwards]
    k = len(costs)
    prefix = [0.0]
    for c in costs:
        prefix.append(prefix[-1] + c)

    def span(i, j):           # cost of units [i, j)
        return prefix[j] - prefix[i]

    INF = float("inf")
    # best[s][j] = minimal max-stage-cost splitting first j units into s
    best = [[INF] * (k + 1) for _ in range(n_stages + 1)]
    cut = [[0] * (k + 1) for _ in range(n_stages + 1)]
    best[0][0] = 0.0
    for s in range(1, n_stages + 1):
        for j in range(s, k + 1):
            for i in range(s - 1, j):
                v = max(best[s - 1][i], span(i, j))
                if v < best[s][j]:
                    best[s][j] = v
                    cut[s][j] = i
    bounds = [k]
    for s in range(n_stages, 0, -1):
        bounds.append(cut[s][bounds[-1]])
    bounds.reverse()
    return [list(forwards[bounds[s]:bounds[s + 1]])
            for s in range(n_stages)]


def plan_pipeline(forwards: List[Any], n_stages: int
                  ) -> Tuple[List[Any], List[Any], List[Any]]:
    """Stage-group a forward chain for ``{"pipeline": N}``.

    Returns ``(pre, block, post)``: the longest contiguous run of
    *identical, shape-preserving, parameterized* forwards (same class,
    same parameter signature, same GD hyper-parameters, activation shape
    in == out), trimmed to a multiple of ``n_stages``; everything before/
    after runs replicated outside the pipelined region. Raises ValueError
    when no viable run exists — pipelining a heterogeneous chain would
    silently serialize, which is worse than failing loudly.
    """
    def signature(f):
        if not getattr(f, "PARAMETERIZED", False):
            return None
        if f.input is None or not f.input or not f.output:
            return None
        if tuple(f.input.shape) != tuple(f.output.shape):
            return None  # stages must be shape-preserving
        params = tuple(sorted(
            (k, tuple(v.shape), str(v.dtype))
            for k, v in f.param_arrays().items()))
        gd = tuple(sorted(getattr(f, "gd_config", {}).items()))
        # semantic config must match too: the grouped block runs every
        # layer through block[0].apply, so e.g. rope=True/False or
        # causal differences would silently apply block 0's setting to
        # all stages. The export key list IS the inference-defining
        # config inventory — reuse it.
        from ..export.package import _EXPORT_KEYS
        cfg = tuple((k, repr(getattr(f, k))) for k in _EXPORT_KEYS
                    if hasattr(f, k))
        return (type(f).__name__, params, gd, cfg)

    sigs = [signature(f) for f in forwards]
    best = (0, 0)  # (length, start)
    i = 0
    while i < len(sigs):
        if sigs[i] is None:
            i += 1
            continue
        j = i
        while j < len(sigs) and sigs[j] == sigs[i]:
            j += 1
        if j - i > best[0]:
            best = (j - i, i)
        i = j
    length, start = best
    usable = (length // n_stages) * n_stages
    if usable < n_stages or usable == 0:
        raise ValueError(
            "pipeline axis of size %d needs >= %d contiguous identical "
            "shape-preserving parameterized layers; longest run is %d. "
            "Stack repeated blocks (e.g. N x all2all_tanh of equal width) "
            "or drop the 'pipeline' mesh axis." % (n_stages, n_stages,
                                                   length))
    block = list(forwards[start:start + usable])
    pre = list(forwards[:start])
    post = list(forwards[start + usable:])
    return pre, block, post


def microbatch(x, n_micro: int):
    """(B, …) → (M, B/M, …); B must divide."""
    b = x.shape[0]
    if b % n_micro:
        raise ValueError("batch %d not divisible into %d microbatches"
                         % (b, n_micro))
    return x.reshape((n_micro, b // n_micro) + x.shape[1:])


def unmicrobatch(y):
    return y.reshape((-1,) + y.shape[2:])
