"""Pipeline parallelism: GPipe-style microbatched stage pipeline.

New capability vs the reference (SURVEY.md §2.4 row "Model-parallel /
pipeline — absent; new capability"). The standard TPU formulation (the
scaling-book recipe): each device on the ``pipeline`` mesh axis holds one
stage's parameters; microbatches ripple through, activations hopping
stage-to-stage with ``ppermute`` inside ``shard_map``; the schedule runs
``M + n_stages - 1`` ticks (fill + drain). Differentiable end to end —
``jax.grad`` through the scan/ppermute yields the reverse schedule
automatically, so the fused train step can wrap a pipelined forward like
any other pure function.

This implementation handles the uniform-stage case (every stage maps an
activation of shape S to shape S — e.g. a stack of residual blocks),
which is the shape pipeline parallelism is actually used in.
"""

from __future__ import annotations

from typing import Any, Callable


def gpipe(fn: Callable[[Any, Any], Any], stage_params: Any, xs: Any,
          mesh, axis: str = "pipeline"):
    """Run ``y_m = fn_{n-1}(…fn_0(x_m))`` for M microbatches.

    - ``fn(params_slice, x)`` — one stage; same activation shape in/out.
    - ``stage_params`` — pytree whose leaves have a leading ``n_stages``
      axis (sharded over ``axis``; each device sees its slice with the
      leading axis of size 1).
    - ``xs`` — (M, mb, …) microbatches, replicated.

    Returns (M, mb, …) outputs, replicated.
    """
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    n = mesh.shape[axis]
    m = xs.shape[0]
    ticks = m + n - 1
    for leaf in jax.tree_util.tree_leaves(stage_params):
        if leaf.ndim == 0 or leaf.shape[0] != n:
            raise ValueError(
                "stage_params leaves need a leading axis of exactly %d "
                "pipeline stages, got shape %s (a multiple would shard "
                "silently and drop stages)" % (n, leaf.shape))

    def local(params, x_all):
        # params leaves: (1, …) — this stage's slice
        my_params = jax.tree_util.tree_map(lambda p: p[0], params)
        idx = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % n) for i in range(n)]
        zero = jnp.zeros_like(x_all[0])

        def tick(carry, t):
            buf, outputs = carry
            # stage 0 injects microbatch t (garbage after the fill phase —
            # those lanes never reach a collected slot)
            inject = x_all[jnp.clip(t, 0, m - 1)]
            inp = jnp.where(idx == 0, inject, buf)
            y = fn(my_params, inp)
            # the LAST stage emits microbatch (t - (n-1)) at tick t
            out_slot = t - (n - 1)
            collect = jnp.logical_and(idx == n - 1, out_slot >= 0)
            outputs = jax.lax.cond(
                collect,
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, y, jnp.maximum(out_slot, 0), 0),
                lambda o: o, outputs)
            buf = jax.lax.ppermute(y, axis, perm)
            return (buf, outputs), None

        outputs0 = jnp.zeros((m,) + x_all.shape[1:], x_all.dtype)
        (_, outputs), _ = jax.lax.scan(tick, (zero, outputs0),
                                       jnp.arange(ticks))
        # only the last stage holds real outputs; psum replicates them
        # (all other stages contribute zeros)
        outputs = jnp.where(idx == n - 1, outputs, 0.0)
        return jax.lax.psum(outputs, axis)

    params_spec = jax.tree_util.tree_map(
        lambda _: P(axis), stage_params)
    fn_sharded = shard_map(
        local, mesh=mesh,
        in_specs=(params_spec, P()), out_specs=P(),
        check_vma=False)
    return fn_sharded(stage_params, xs)


def microbatch(x, n_micro: int):
    """(B, …) → (M, B/M, …); B must divide."""
    b = x.shape[0]
    if b % n_micro:
        raise ValueError("batch %d not divisible into %d microbatches"
                         % (b, n_micro))
    return x.reshape((n_micro, b // n_micro) + x.shape[1:])


def unmicrobatch(y):
    return y.reshape((-1,) + y.shape[2:])
