"""Ring attention: exact attention over a sequence-sharded axis.

Long-context capability (green-field vs the reference, SURVEY.md §5.7):
sequences sharded over the mesh 'sequence' axis, each device holding a
T/n block of Q, K, V. K/V blocks rotate around the ring via
``lax.ppermute`` over ICI while each device accumulates its Q block's
attention with the online-softmax (running max / denominator) recurrence —
memory O(T/n) per device, compute overlapped with neighbor transfers by
XLA. This is the blockwise ring attention construction (Liu et al.) built
from shard_map + XLA collectives rather than custom kernels.
"""

from __future__ import annotations

from functools import partial
from typing import Optional


def ring_attention(q, k, v, mesh, axis: str = "sequence",
                   causal: bool = False, scale: Optional[float] = None,
                   window: Optional[int] = None):
    """q, k, v: (B, T, H, D) GLOBAL arrays (or already sharded); returns
    (B, T, H, D) attention output, sequence axis sharded over ``axis``.

    ``window=W`` (causal only): each query sees itself plus W-1
    predecessors. Beyond the mask, the ring itself shortens — a device
    only ever needs K/V blocks reaching W-1 positions behind its
    oldest query, so the rotation scan runs ``min(n, ceil((W-1+Tl)/Tl))``
    steps instead of ``n``: fewer ppermutes over ICI and fewer masked
    einsums, the point of windowed attention at ring scale."""
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    window = int(window or 0)
    if window < 0:
        raise ValueError("window must be >= 1 (or None)")
    if window and not causal:
        raise ValueError("sliding-window attention requires causal=True")
    n = mesh.shape[axis]
    # carry the batch sharding through: without 'data' in the specs a
    # dp x sp mesh would all-gather the batch and compute it redundantly
    batch_axis = "data" if "data" in mesh.axis_names else None

    def local(q_blk, k_blk, v_blk):
        # q_blk: (B, Tl, H, D)
        my = jax.lax.axis_index(axis)
        tl = q_blk.shape[1]
        q_pos = my * tl + jnp.arange(tl)
        # uniform across devices (SPMD): the step count bound comes
        # from the worst case (oldest query row of a block)
        steps = n if not window else min(n, (window + tl - 2) // tl + 1)

        def body(carry, i):
            o, m, l, kb, vb = carry
            src = (my - i) % n          # who produced this K/V block
            s = jnp.einsum("bqhd,bkhd->bhqk", q_blk, kb) * scale
            s = s.astype(jnp.float32)
            if causal:
                k_pos = src * tl + jnp.arange(tl)
                rel = q_pos[:, None] - k_pos[None, :]
                mask = rel >= 0
                if window:
                    mask = mask & (rel < window)
                s = jnp.where(mask[None, None], s, -1e30)
            m_new = jnp.maximum(m, s.max(axis=-1))
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new[..., None])
            l_new = l * alpha + p.sum(axis=-1)
            pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(q_blk.dtype), vb)
            o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv
            # rotate K/V to the next device on the ring
            perm = [(j, (j + 1) % n) for j in range(n)]
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
            return (o_new, m_new, l_new, kb, vb), None

        b, tl_, h, d = q_blk.shape
        o0 = jnp.zeros((b, tl_, h, d), dtype=q_blk.dtype)
        m0 = jnp.full((b, h, tl_), -jnp.inf, dtype=jnp.float32)
        l0 = jnp.zeros((b, h, tl_), dtype=jnp.float32)
        (o, m, l, _, _), _ = jax.lax.scan(
            body, (o0, m0, l0, k_blk, v_blk), jnp.arange(steps))
        denom = l.transpose(0, 2, 1)[..., None]
        return (o / jnp.maximum(denom, 1e-30)).astype(q_blk.dtype)

    spec = P(batch_axis, axis, None, None)
    fn = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=False)
    return fn(q, k, v)


def attention_reference(q, k, v, causal: bool = False,
                        scale: Optional[float] = None,
                        window: Optional[int] = None):
    """Single-device exact attention — the oracle for ring_attention
    and the flash kernel. ``window=W``: each query sees itself plus
    W-1 predecessors (requires causal)."""
    import jax.numpy as jnp
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if window is not None and int(window) < 0:
        raise ValueError("window must be >= 1 (or None)")
    if window and not causal:
        raise ValueError("sliding-window attention requires causal=True")
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        rel = jnp.arange(tq)[:, None] - jnp.arange(tk)[None, :]
        mask = rel >= 0
        if window:
            mask = mask & (rel < window)
        s = jnp.where(mask[None, None], s, -1e30)
    p = jnp.exp(s - s.max(axis=-1, keepdims=True))
    p = p / p.sum(axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v)
