"""Ring attention: exact attention over a sequence-sharded axis.

Long-context capability (green-field vs the reference, SURVEY.md §5.7):
sequences sharded over the mesh 'sequence' axis, each device holding a
T/n block of Q, K, V. K/V blocks rotate around the ring via
``lax.ppermute`` over ICI while each device accumulates its Q block's
attention with the online-softmax (running max / denominator) recurrence —
memory O(T/n) per device, compute overlapped with neighbor transfers by
XLA. This is the blockwise ring attention construction (Liu et al.) built
from shard_map + XLA collectives.

Two inner engines for the per-step block attention:

- **flash** (Pallas, ``ops/flash_attention.py``): when the local block
  qualifies (``choose_flash``; causal/full only — no window) each ring
  step runs the VMEM-resident kernel: the diagonal step (own K/V)
  causally masked, every later step unmasked — a block strictly behind
  the queries needs no mask, a wrapped future block is killed by
  weighting its contribution with ``exp(-inf)`` in the lse merge. The
  per-step partials ``(o_i, lse_i)`` fold into the running softmax by
  log-sum-exp.
- **einsum** (fused XLA): the reference engine, and the only one for
  sliding-window rings (the in-block window cut needs element masks at
  traced block offsets, which the kernel does not take).

Differentiation is a hand-written blockwise ring backward under
``jax.custom_vjp`` — NOT autodiff through the forward scan: the
backward recomputes each block's probabilities from the saved global
``lse`` (flash-attention style) while dk/dv accumulators rotate with
their K/V blocks, so residual memory stays O(T/n · D) per device
instead of the O(steps · Tl²) score blocks autodiff-of-scan would save.
"""

from __future__ import annotations

from functools import partial
from typing import Optional


def _ring_perm(n):
    return [(j, (j + 1) % n) for j in range(n)]


def ring_attention(q, k, v, mesh, axis: str = "sequence",
                   causal: bool = False, scale: Optional[float] = None,
                   window: Optional[int] = None,
                   use_flash: Optional[bool] = None):
    """q, k, v: (B, T, H, D) GLOBAL arrays (or already sharded); returns
    (B, T, H, D) attention output, sequence axis sharded over ``axis``.

    ``window=W`` (causal only): each query sees itself plus W-1
    predecessors. Beyond the mask, the ring itself shortens — a device
    only ever needs K/V blocks reaching W-1 positions behind its
    oldest query, so the rotation scan runs ``min(n, ceil((W-1+Tl)/Tl))``
    steps instead of ``n``: fewer ppermutes over ICI and fewer masked
    einsums, the point of windowed attention at ring scale.

    ``use_flash``: None = auto (``ops.flash_attention.choose_flash`` on
    the LOCAL block length, windowless, equal q/kv heads); True forces
    the Pallas engine (tests: pallas interpret off-TPU), False forces
    the einsum engine."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from .compat import shard_map_compat

    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    window = int(window or 0)
    if window < 0:
        raise ValueError("window must be >= 1 (or None)")
    if window and not causal:
        raise ValueError("sliding-window attention requires causal=True")
    n = mesh.shape[axis]
    tl = q.shape[1] // n
    d = q.shape[-1]
    if use_flash is None:
        from ..ops.flash_attention import choose_flash
        use_flash = (not window and q.shape[2] == k.shape[2]
                     and choose_flash(tl, d))
    if use_flash and window:
        raise ValueError("use_flash composes with causal/full rings "
                         "only; window rings use the einsum engine")
    if use_flash:
        if q.shape[2] != k.shape[2]:
            # the flash FORWARD would accept grouped k/v, but the ring
            # backward's einsums assume equal head counts — refuse at
            # the API instead of exploding inside the custom VJP
            raise ValueError(
                "use_flash ring requires equal q/kv head counts "
                "(expand grouped K/V first — nn/attention.expand_kv)")
        from ..ops.flash_attention import supported
        if not supported(tl, d):
            raise ValueError(
                "use_flash: local block T/n=%d D=%d not kernel-"
                "compatible" % (tl, d))
    # carry the batch sharding through: without 'data' in the specs a
    # dp x sp mesh would all-gather the batch and compute it redundantly
    batch_axis = "data" if "data" in mesh.axis_names else None

    local = partial(_ring_local, axis=axis, n=n, causal=causal,
                    scale=float(scale), window=window,
                    use_flash=bool(use_flash))
    spec = P(batch_axis, axis, None, None)
    fn = shard_map_compat(local, mesh=mesh,
                          in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def _steps_for(n: int, window: int, tl: int) -> int:
    """Rotation count: full ring, or window-shortened (uniform across
    devices — the bound comes from each block's oldest query row)."""
    return n if not window else min(n, (window + tl - 2) // tl + 1)


def _ring_local(q_blk, k_blk, v_blk, *, axis, n, causal, scale,
                window, use_flash):
    """Per-shard ring attention with a custom blockwise backward."""
    import jax

    @jax.custom_vjp
    def ring(q_blk, k_blk, v_blk):
        o, _ = _ring_fwd_impl(q_blk, k_blk, v_blk, axis=axis, n=n,
                              causal=causal, scale=scale, window=window,
                              use_flash=use_flash)
        return o

    def fwd(q_blk, k_blk, v_blk):
        o, lse = _ring_fwd_impl(q_blk, k_blk, v_blk, axis=axis, n=n,
                                causal=causal, scale=scale,
                                window=window, use_flash=use_flash)
        return o, (q_blk, k_blk, v_blk, o, lse)

    def bwd(res, do):
        return _ring_bwd_impl(res, do, axis=axis, n=n, causal=causal,
                              scale=scale, window=window,
                              use_flash=use_flash)

    ring.defvjp(fwd, bwd)
    return ring(q_blk, k_blk, v_blk)


def _ring_fwd_impl(q_blk, k_blk, v_blk, *, axis, n, causal, scale,
                   window, use_flash):
    """Returns (o (B,Tl,H,D), lse (B,H,Tl) — global log-sum-exp of the
    scaled, masked scores per query row: the backward's residual)."""
    import jax
    import jax.numpy as jnp

    my = jax.lax.axis_index(axis)
    b, tl, h, d = q_blk.shape
    q_pos = my * tl + jnp.arange(tl)
    steps = _steps_for(n, window, tl)
    perm = _ring_perm(n)

    if use_flash:
        from ..ops.flash_attention import flash_attention_fwd_lse

        # diagonal step peeled out of the scan: it is the only one
        # whose mask (causal within the block) is static
        o0, lse0 = flash_attention_fwd_lse(q_blk, k_blk, v_blk,
                                           causal=causal, scale=scale)
        o_acc = o0.astype(jnp.float32)
        m = jnp.moveaxis(lse0, -1, 1)              # (B, H, Tl)
        l = jnp.ones_like(m)
        kb = jax.lax.ppermute(k_blk, axis, perm)
        vb = jax.lax.ppermute(v_blk, axis, perm)

        def body(carry, i):
            o_acc, m, l, kb, vb = carry
            src = (my - i) % n
            # a block strictly behind every query needs no mask; a
            # wrapped "future" block (src > my under causal) is dead —
            # its whole contribution is annulled in the merge weight
            oi, lsei = flash_attention_fwd_lse(q_blk, kb, vb,
                                               causal=False, scale=scale)
            mi = jnp.moveaxis(lsei, -1, 1)         # (B, H, Tl)
            if causal:
                live = src < my                    # traced scalar bool
                mi = jnp.where(live, mi, -jnp.inf)
            m_new = jnp.maximum(m, mi)
            # guard the all-dead row: exp(-inf - -inf) would be NaN
            m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
            alpha = jnp.exp(m - m_safe)            # (B, H, Tl)
            beta = jnp.exp(mi - m_safe)
            w_a = alpha.transpose(0, 2, 1)[..., None]
            w_b = beta.transpose(0, 2, 1)[..., None]
            o_new = o_acc * w_a + oi.astype(jnp.float32) * w_b
            l_new = l * alpha + beta
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
            return (o_new, m_new, l_new, kb, vb), None

        if steps > 1:
            (o_acc, m, l, _, _), _ = jax.lax.scan(
                body, (o_acc, m, l, kb, vb), jnp.arange(1, steps))
        denom = l.transpose(0, 2, 1)[..., None]
        o = (o_acc / jnp.maximum(denom, 1e-30)).astype(q_blk.dtype)
        lse = m + jnp.log(jnp.maximum(l, 1e-30))
        return o, lse

    def body(carry, i):
        o, m, l, kb, vb = carry
        src = (my - i) % n          # who produced this K/V block
        s = jnp.einsum("bqhd,bkhd->bhqk", q_blk, kb) * scale
        s = s.astype(jnp.float32)
        if causal:
            k_pos = src * tl + jnp.arange(tl)
            rel = q_pos[:, None] - k_pos[None, :]
            mask = rel >= 0
            if window:
                mask = mask & (rel < window)
            s = jnp.where(mask[None, None], s, -1e30)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + p.sum(axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(q_blk.dtype), vb)
        o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv
        # rotate K/V to the next device on the ring
        kb = jax.lax.ppermute(kb, axis, perm)
        vb = jax.lax.ppermute(vb, axis, perm)
        return (o_new, m_new, l_new, kb, vb), None

    o0 = jnp.zeros((b, tl, h, d), dtype=jnp.float32)
    m0 = jnp.full((b, h, tl), -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, tl), dtype=jnp.float32)
    (o, m, l, _, _), _ = jax.lax.scan(
        body, (o0, m0, l0, k_blk, v_blk), jnp.arange(steps))
    denom = l.transpose(0, 2, 1)[..., None]
    out = (o / jnp.maximum(denom, 1e-30)).astype(q_blk.dtype)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return out, lse


def _ring_bwd_impl(res, do, *, axis, n, causal, scale, window,
                   use_flash=False):
    """Blockwise ring backward (flash-attention bwd math at ring
    scale): p recomputed per step from the global lse; dq accumulates
    locally; dk/dv accumulators rotate WITH their K/V blocks and are
    fast-forwarded home after the (possibly window-shortened) scan.
    ``use_flash`` runs each step's recompute through the Pallas bwd
    kernel pair (``flash_attention_bwd_lse`` — VMEM-resident, no
    (Tl, Tl) score materialization), same peeled-diagonal structure as
    the forward."""
    import jax
    import jax.numpy as jnp

    q_blk, k_blk, v_blk, o, lse = res     # lse (B, H, Tl) global
    my = jax.lax.axis_index(axis)
    b, tl, h, d = q_blk.shape
    q_pos = my * tl + jnp.arange(tl)
    steps = _steps_for(n, window, tl)
    perm = _ring_perm(n)

    qf = q_blk.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = (dof * o.astype(jnp.float32)).sum(-1)        # (B, Tl, H)
    delta_bh = delta.transpose(0, 2, 1)                  # (B, H, Tl)

    if use_flash:
        from ..ops.flash_attention import flash_attention_bwd_lse
        lse_bth = jnp.moveaxis(lse, 1, -1)               # (B, Tl, H)

        def step_grads(kb, vb, diag, src):
            # diagonal step: static causal mask in the kernel; behind
            # blocks unmasked; a wrapped future block's contribution
            # is zeroed by the liveness weight (like the forward). The
            # kernels emit f32 partials — see flash_attention_bwd_lse.
            dqi, dki, dvi = flash_attention_bwd_lse(
                q_blk, kb, vb, lse_bth, delta, do,
                causal=bool(causal) and diag, scale=scale)
            if causal and not diag:
                live = src < my
                dqi = jnp.where(live, dqi, 0)
                dki = jnp.where(live, dki, 0)
                dvi = jnp.where(live, dvi, 0)
            return dqi, dki, dvi
    else:
        def step_grads(kb, vb, diag, src):
            s = jnp.einsum("bqhd,bkhd->bhqk", qf,
                           kb.astype(jnp.float32)) * scale
            if causal:
                k_pos = src * tl + jnp.arange(tl)
                rel = q_pos[:, None] - k_pos[None, :]
                mask = rel >= 0
                if window:
                    mask = mask & (rel < window)
                s = jnp.where(mask[None, None], s, -jnp.inf)
            # probabilities against the GLOBAL normalizer; fully masked
            # rows/blocks (incl. wrapped future ones) give exp(-inf)=0
            p = jnp.exp(s - lse[..., :, None])
            dvi = jnp.einsum("bhqk,bqhd->bkhd", p, dof)
            dp = jnp.einsum("bqhd,bkhd->bhqk", dof,
                            vb.astype(jnp.float32))
            ds = p * (dp - delta_bh[..., None]) * scale
            dqi = jnp.einsum("bhqk,bkhd->bqhd", ds,
                             kb.astype(jnp.float32))
            dki = jnp.einsum("bhqk,bqhd->bkhd", ds, qf)
            return dqi, dki, dvi

    def rotate(*xs):
        return tuple(jax.lax.ppermute(x, axis, perm) for x in xs)

    # step 0 peeled (the flash engine needs its causal mask static);
    # accumulators then rotate WITH their K/V blocks each step
    dq, dkb, dvb = step_grads(k_blk, v_blk, True, my)
    kb, vb, dkb, dvb = rotate(k_blk, v_blk, dkb, dvb)

    def body(carry, i):
        dq, kb, vb, dkb, dvb = carry
        src = (my - i) % n
        dqi, dki, dvi = step_grads(kb, vb, False, src)
        dq, dkb, dvb = dq + dqi, dkb + dki, dvb + dvi
        kb, vb, dkb, dvb = rotate(kb, vb, dkb, dvb)
        return (dq, kb, vb, dkb, dvb), None

    if steps > 1:
        (dq, _, _, dkb, dvb), _ = jax.lax.scan(
            body, (dq, kb, vb, dkb, dvb), jnp.arange(1, steps))
    # after `steps` hops the accumulators sit `steps` devices ahead of
    # home; one shifted ppermute completes the (window-shortened) ring
    # in a single collective (dead far blocks contributed exact zeros)
    home = (n - steps) % n
    if home:
        shift = [(j, (j + home) % n) for j in range(n)]
        dkb = jax.lax.ppermute(dkb, axis, shift)
        dvb = jax.lax.ppermute(dvb, axis, shift)
    return (dq.astype(q_blk.dtype), dkb.astype(k_blk.dtype),
            dvb.astype(v_blk.dtype))


def attention_reference(q, k, v, causal: bool = False,
                        scale: Optional[float] = None,
                        window: Optional[int] = None):
    """Single-device exact attention — the oracle for ring_attention
    and the flash kernel. ``window=W``: each query sees itself plus
    W-1 predecessors (requires causal)."""
    import jax.numpy as jnp
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if window is not None and int(window) < 0:
        raise ValueError("window must be >= 1 (or None)")
    if window and not causal:
        raise ValueError("sliding-window attention requires causal=True")
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        rel = jnp.arange(tq)[:, None] - jnp.arange(tk)[None, :]
        mask = rel >= 0
        if window:
            mask = mask & (rel < window)
        s = jnp.where(mask[None, None], s, -1e30)
    p = jnp.exp(s - s.max(axis=-1, keepdims=True))
    p = p / p.sum(axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v)
