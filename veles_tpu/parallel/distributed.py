"""Multi-host runtime: coordinator init, membership, fault handling.

Replaces the reference's control plane (Twisted TCP JSON handshake +
ZeroMQ data plane + SSH slave spawning, veles/server.py / veles/client.py /
veles/launcher.py:808-842) with the JAX distributed runtime: one GRPC
coordinator, N processes, global device mesh over ICI/DCN.

Capability mapping (SURVEY.md §5.3):
- slave join/handshake+checksum   → jax.distributed.initialize barrier
  (+ workflow checksum verification helper)
- slave death / job re-serving    → SPMD has no per-slave jobs; recovery is
  checkpoint restart (restore_latest) — the reference itself called
  snapshots the disaster-recovery story
- hang detection (mean+3σ timeout)→ step_watchdog context manager
  (trips counted in veles_watchdog_trips_total)
- --slave-death-probability       → fault_injection() preserved as a
  testing flag that kills the process with the same semantics, now
  routed through the resilience fault plane (veles_tpu/resilience/
  faults.py, which generalizes it to named injection points)
"""

from __future__ import annotations

import os
import time
from contextlib import contextmanager
from typing import Optional

from ..config import root
from ..error import DistributedCommunicationError
from ..logger import Logger

_initialized = False

#: elastic training generation this process participates in (0 =
#: non-elastic run). The elastic controller sets it at every
#: generation declaration — seeded from VELES_ELASTIC_GENERATION in
#: respawned workers, corrected to the coordinator's agreed index by
#: survivor_barrier — and Snapshotter._cursor stamps it into every
#: manifest. Topology changes themselves travel through process
#: respawn (exit 43 → Supervisor), never an in-process
#: jax.distributed re-join.
_generation = 0


def generation() -> int:
    return _generation


def set_generation(value: int) -> None:
    global _generation
    _generation = int(value)


def survivor_barrier(generation: int) -> int:
    """All surviving processes agree on the coordinator's generation
    index — the elastic plane's synchronization point before anyone
    touches the checkpoint chain. A dead peer surfaces here first (the
    collective raises or times out); the elastic controller converts
    that into a counted barrier timeout. Pure: returns the agreed
    index, mutates nothing — adoption of a disagreeing view is the
    controller's job. No-op (returns ``generation``) on a single
    process."""
    import jax
    if jax.process_count() == 1:
        return int(generation)
    import numpy
    from jax.experimental import multihost_utils
    return int(multihost_utils.broadcast_one_to_all(
        numpy.int64(int(generation))))


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> None:
    """Join the multi-host job. No-op on single host. Arguments default to
    the standard env vars the TPU runtime provides; explicit values mirror
    the reference's -m/--master-address & node-index flags. The
    coordinator join is retried with backoff — process 0's GRPC server
    races the other processes' dial on every real pod launch."""
    global _initialized
    if _initialized:
        return
    import jax
    if coordinator_address is None and num_processes is None \
            and "JAX_COORDINATOR_ADDRESS" not in os.environ \
            and "COORDINATOR_ADDRESS" not in os.environ:
        return  # single host
    # CPU processes talk gloo (the multi-host CI/loopback path — the
    # reference's in-process master+slave tests, SURVEY.md §4); TPU pods
    # use the native runtime and ignore this setting
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    from ..resilience.retry import RetryPolicy

    def join() -> None:
        from ..resilience.faults import fire as fire_fault
        fire_fault("distributed.init")   # inside the retried callable:
        # an injected raise exercises exactly the path a slow
        # coordinator does
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)

    try:
        RetryPolicy(name="distributed.init", base_delay=1.0,
                    max_delay=10.0, retryable=(Exception,)).call(join)
        _initialized = True
    except Exception as e:
        raise DistributedCommunicationError(
            "multi-host init failed: %s" % e) from e


def process_count() -> int:
    import jax
    return jax.process_count()


def is_coordinator() -> bool:
    import jax
    return jax.process_index() == 0


_lockstep_depth = 0


@contextmanager
def lockstep():
    """Marks a region every process is guaranteed to enter in the same
    order (the snapshot plane: collection runs on EVERY rank, only the
    coordinator writes). fetch_global only all-gathers inside such a
    region — a rank-local caller would otherwise block forever in the
    collective while the other ranks are elsewhere."""
    global _lockstep_depth
    _lockstep_depth += 1
    try:
        yield
    finally:
        _lockstep_depth -= 1


def fetch_global(tree):
    """Host (numpy) copy of a pytree that may contain cross-process
    sharded ``jax.Array``s (fsdp/tensor params on a multi-host mesh).

    Fully-addressable or fully-replicated leaves take the plain
    ``device_get`` path; anything else all-gathers its shards. The
    gather is a COLLECTIVE — it is only legal inside a lockstep()
    region (the reference made the same all-participate/master-writes
    split in its generate/apply protocol, veles/distributable.py:222);
    a coordinator-only caller (pickling, package export) gets the old
    loud RuntimeError instead of a silent distributed hang."""
    import jax

    def one(x):
        if not isinstance(x, jax.Array) or x.is_fully_addressable \
                or x.sharding.is_fully_replicated:
            return jax.device_get(x)
        if not _lockstep_depth:
            raise RuntimeError(
                "fetching a cross-process sharded array outside a "
                "lockstep region would deadlock the all-gather: every "
                "rank must participate. Route through the snapshot "
                "plane (Snapshotter/collect_state) or wrap the call in "
                "parallel.distributed.lockstep() on ALL ranks.")
        from jax.experimental import multihost_utils
        return multihost_utils.process_allgather(x, tiled=True)
    return jax.tree_util.tree_map(one, tree)


def agree(want: bool) -> bool:
    """Coordinator-agreed boolean: every process returns rank 0's value.
    Used for nondeterministic snapshot gates (wall-clock intervals) so
    the collectives inside state collection fire in lockstep; no-op on
    a single process."""
    import jax
    if jax.process_count() == 1:
        return bool(want)
    import numpy
    from jax.experimental import multihost_utils
    return bool(multihost_utils.broadcast_one_to_all(
        numpy.int32(bool(want))))


def verify_checksums(workflow) -> None:
    """All hosts must run the same workflow code — the reference refused
    mismatched slaves at handshake (veles/server.py:478-529). Gathers the
    workflow checksum from every process and raises on mismatch."""
    import jax
    import jax.numpy as jnp
    import numpy
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils
    digest = numpy.frombuffer(
        bytes.fromhex(workflow.checksum()[:16]), dtype=numpy.uint8)
    all_digests = multihost_utils.process_allgather(digest)
    if not (all_digests == all_digests[0]).all():
        raise DistributedCommunicationError(
            "workflow checksum mismatch across hosts")


@contextmanager
def step_watchdog(name: str = "step", timeout: float = 0.0,
                  history: Optional[list] = None):
    """Detect hung steps: warn when a step exceeds max(mean+3σ of its own
    history, timeout) — the reference's job-timeout dropper semantics
    (veles/server.py:619-635) as a local watchdog."""
    t0 = time.time()
    yield
    dt = time.time() - t0
    if history is not None:
        # threshold from PRIOR history only: including the current sample
        # would inflate its own baseline (no sample can exceed
        # mean+sqrt(n-1)·std of a set containing it)
        if len(history) >= 8:
            import numpy
            mean, std = numpy.mean(history), numpy.std(history)
            threshold = max(mean + 3 * std, timeout)
            if dt > threshold:
                from ..telemetry.counters import inc
                inc("veles_watchdog_trips_total")
                Logger().warning(
                    "watchdog trip on span %r: %.2fs (mean %.2fs + "
                    "3σ %.2fs) — possible hang", name, dt, mean, 3 * std)
                # a trip is a pre-crash signal: capture the flight
                # recorder's last-seconds window while the process is
                # still alive (autodump-gated, never raises)
                from ..telemetry.recorder import flight
                flight.note("watchdog.trip", span=name,
                            seconds=round(dt, 3),
                            threshold=round(float(threshold), 3))
                flight.crash_dump("watchdog trip on %r (%.2fs)"
                                  % (name, dt))
        history.append(dt)


def fault_injection(probability: Optional[float] = None) -> None:
    """Randomly kill this process — the reference's
    --slave-death-probability fault-injection flag
    (veles/client.py:303-307,438-442) for testing recovery paths.
    Subsumed by the resilience fault plane (a ``dispatch:crash:p=...``
    spec is the general form); kept as the CLI-flag fast path with
    identical die-roll semantics."""
    from .. import prng
    p = probability if probability is not None else float(
        root.common.get("slave_death_probability", 0.0) or 0.0)
    if p > 0 and prng.get("fault_injection", ephemeral=True).rand() < p:
        from ..resilience.faults import inject_crash
        inject_crash("slave_death_probability=%g" % p)


def restore_latest(workflow, directory: str, prefix: str = "wf") -> bool:
    """Elastic recovery: resume from the newest VALID snapshot if one
    exists (preemption/restart path) — the chain walk verifies
    checksums and quarantines corrupt files on the way
    (resilience/checkpoint_chain.py). Returns True if restored."""
    from ..resilience.checkpoint_chain import restore_latest as walk
    return walk(workflow, directory, prefix) is not None
