"""Parallelism: mesh axes, sharding rules, collectives, multi-host.

The TPU-native replacement for the reference's entire distributed runtime
(SURVEY.md §2.4/§5.8): where VELES shipped a ZeroMQ master–slave parameter
server (veles/server.py, veles/client.py, txzmq/) carrying pickled per-unit
job/update payloads, this package expresses every parallelism as shardings
over a named ``jax.sharding.Mesh`` and lets XLA insert the collectives over
ICI/DCN:

- **data**      minibatch axis (psum of grads ≡ the master's update-apply)
- **fsdp**      parameter shards, all-gathered at use (ZeRO-3 style)
- **tensor**    intra-layer model parallelism (column/row splits)
- **sequence**  long-context axis: ring attention via shard_map+ppermute
- **expert**    MoE expert axis (reserved)
- **pipeline**  inter-layer pipelining (reserved)

The reference's parallelism inventory maps as: sync DP → 'data'; async DP
→ superseded (documented non-goal); ensemble/GA population parallelism →
veles_tpu.ensemble / veles_tpu.genetics; everything else (fsdp/tensor/
sequence) is new capability the reference never had (SURVEY.md §5.7).
"""

from .sharding import (param_shardings, batch_sharding,
                       replicated)                        # noqa: F401
from .distributed import (initialize_multihost, is_coordinator,
                          process_count)                  # noqa: F401
from .ring_attention import ring_attention                # noqa: F401
from .ulysses import ulysses_attention                    # noqa: F401
