"""Ulysses-style all-to-all sequence parallelism.

The second of the two standard long-context schemes (new capability vs
the reference — SURVEY.md §5.7 names this the green-field requirement;
the public DeepSpeed-Ulysses recipe is the pattern): instead of rotating
K/V blocks around a ring (parallel/ring_attention.py), ONE all-to-all
re-shards the activations from sequence-sharded to **head-sharded**, the
exact attention runs locally per head group over the full sequence, and
a second all-to-all restores sequence sharding.

Trade-off vs ring: 2 collectives total instead of n-1 permutes (better
for moderate T and enough heads), but requires ``heads % n == 0`` and
holds full-T activations per head group (memory grows with T). Ring
stays memory-flat in T. `nn.MultiHeadAttention` picks via
``root.common.engine.sequence_parallel`` ("ring" | "ulysses"), falling
back to ring when the head count does not divide.
"""

from __future__ import annotations

from typing import Optional


def ulysses_attention(q, k, v, mesh, axis: str = "sequence",
                      causal: bool = False,
                      scale: Optional[float] = None,
                      window: Optional[int] = None):
    """q, k, v: (B, T, H, D) global arrays; returns (B, T, H, D) with the
    sequence axis sharded over ``axis``."""
    import jax
    from jax.sharding import PartitionSpec as P

    from .compat import shard_map_compat
    from .ring_attention import attention_reference

    n = mesh.shape[axis]
    heads = q.shape[2]
    if heads % n:
        raise ValueError("ulysses needs heads %% devices == 0 "
                         "(%d heads over %d devices)" % (heads, n))
    batch_axis = "data" if "data" in mesh.axis_names else None

    def local(q_blk, k_blk, v_blk):
        # (B, T/n, H, D) → all-to-all → (B, T, H/n, D)
        def spread(x):
            return jax.lax.all_to_all(x, axis, split_axis=2,
                                      concat_axis=1, tiled=True)

        qh, kh, vh = spread(q_blk), spread(k_blk), spread(v_blk)
        # after the re-shard each device holds the FULL sequence for
        # its head group — exactly the single-chip attention problem,
        # so the per-shape chooser applies: the Pallas flash kernel
        # takes the long-T regime Ulysses exists for, the fused XLA
        # reference the short one (same crossover as attention_core)
        t, hd = qh.shape[1], qh.shape[-1]
        from ..ops import flash_attention as fa
        if fa.choose_flash(t, hd):
            o = fa.flash_attention(qh, kh, vh, causal=causal,
                                   scale=scale, window=window)
        else:
            o = attention_reference(qh, kh, vh, causal=causal,
                                    scale=scale, window=window)
        # (B, T, H/n, D) → all-to-all back → (B, T/n, H, D)
        return jax.lax.all_to_all(o, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    spec = P(batch_axis, axis, None, None)
    fn = shard_map_compat(local, mesh=mesh,
                          in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
