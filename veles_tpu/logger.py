"""Class-scoped logging + event spans.

TPU-era equivalent of the reference's veles/logger.py:59-332: every framework
object mixes in :class:`Logger` and gets a logger named after its class; event
spans (``begin``/``end``/``single``) record timestamped intervals for
observability. Where the reference duplicated records to MongoDB, this build
appends JSON lines to a trace file (and keeps an in-memory ring) — the same
data model, no external service. The span stream is also the hook point for
``jax.profiler`` trace annotation.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from typing import Any, Deque, Dict, Optional

_event_lock = threading.Lock()
_event_ring: Deque[Dict[str, Any]] = collections.deque(maxlen=65536)
_event_file = None
_event_path: Optional[str] = None
_file_handler: Optional[logging.FileHandler] = None

#: event observers installed by the flight recorder
#: (telemetry/recorder.py): called with each record AFTER the event
#: lock is released; exceptions swallowed.
_event_hooks = []


def add_event_hook(fn) -> None:
    if fn not in _event_hooks:
        _event_hooks.append(fn)


def setup_logging(level: int = logging.INFO, logfile: Optional[str] = None,
                  tracefile: Optional[str] = None) -> None:
    """Configure root logging (reference: Logger.setup_logging,
    veles/logger.py:107-151) and optionally an event-trace JSONL sink
    (reference duplicated events to Mongo, veles/logger.py:210-213)."""
    global _event_file, _event_path, _file_handler
    fmt = "%(asctime)s %(levelname).1s %(name)s: %(message)s"
    logging.basicConfig(level=level, format=fmt)
    if logfile:
        if _file_handler is not None:
            logging.getLogger().removeHandler(_file_handler)
            _file_handler.close()
        _file_handler = logging.FileHandler(logfile)
        _file_handler.setFormatter(logging.Formatter(fmt))
        logging.getLogger().addHandler(_file_handler)
    if tracefile and tracefile != _event_path:
        os.makedirs(os.path.dirname(tracefile) or ".", exist_ok=True)
        with _event_lock:
            if _event_file is not None:
                _event_file.close()
            _event_file = open(tracefile, "a")
            _event_path = tracefile


def events(name: Optional[str] = None):
    """Snapshot of recorded event spans (newest last)."""
    with _event_lock:
        evs = list(_event_ring)
    if name is not None:
        evs = [e for e in evs if e["name"] == name]
    return evs


def clear_events() -> None:
    with _event_lock:
        _event_ring.clear()


def enable_debug(names) -> None:
    """Per-class debug enable: ``--debug ClassA,ClassB`` sets just those
    loggers to DEBUG (reference: veles/__main__.py:834-835); the name
    ``all`` raises the root logger."""
    import logging
    if isinstance(names, str):
        names = [n.strip() for n in names.split(",") if n.strip()]
    for name in names:
        target = logging.getLogger() if name == "all" \
            else logging.getLogger(name)
        target.setLevel(logging.DEBUG)


class Logger:
    """Mixin granting ``self.logger`` plus debug/info/... helpers and
    :meth:`event` span recording (reference: veles/logger.py:59,264-289)."""

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)

    @property
    def logger(self) -> logging.Logger:
        return logging.getLogger(type(self).__name__)

    def debug(self, msg: str, *args: Any) -> None:
        self.logger.debug(msg, *args)

    def info(self, msg: str, *args: Any) -> None:
        self.logger.info(msg, *args)

    def warning(self, msg: str, *args: Any) -> None:
        self.logger.warning(msg, *args)

    def error(self, msg: str, *args: Any) -> None:
        self.logger.error(msg, *args)

    def exception(self, msg: str = "Error", *args: Any) -> None:
        self.logger.exception(msg, *args)

    def event(self, name: str, etype: str = "single", **info: Any) -> None:
        """Record a span edge: etype in {begin, end, single}
        (reference: Logger.event, veles/logger.py:264-289)."""
        assert etype in ("begin", "end", "single"), etype
        rec = {"name": name, "type": etype, "time": time.time(),
               "who": type(self).__name__}
        rec.update(info)
        with _event_lock:
            _event_ring.append(rec)
            if _event_file is not None:
                _event_file.write(json.dumps(rec, default=str) + "\n")
                _event_file.flush()
        for hook in _event_hooks:
            try:
                hook(rec)
            except Exception:       # noqa: BLE001 — observers only
                pass


class SpanTimer:
    """``with SpanTimer(obj, "step"):`` → begin/end event pair + elapsed."""

    def __init__(self, owner: Logger, name: str, **info: Any) -> None:
        self.owner, self.name, self.info = owner, name, info
        self.elapsed = 0.0

    def __enter__(self) -> "SpanTimer":
        self._t0 = time.time()
        self.owner.event(self.name, "begin", **self.info)
        return self

    def __exit__(self, *exc: Any) -> None:
        self.elapsed = time.time() - self._t0
        self.owner.event(self.name, "end", elapsed=self.elapsed, **self.info)
