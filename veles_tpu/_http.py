"""Shared scaffolding for the stdlib-HTTP services (web status, REST
serving, forge). One place for the JSON reply helper and the
daemon-thread serve/shutdown lifecycle."""

from __future__ import annotations

import json
import threading
from http.server import ThreadingHTTPServer
from typing import Any, Dict, Optional


def json_reply(handler, code: int, payload: Any,
               headers: Optional[Dict[str, str]] = None) -> None:
    data = json.dumps(payload).encode()
    bytes_reply(handler, code, data, "application/json",
                headers=headers)


def bytes_reply(handler, code: int, data: bytes, ctype: str,
                headers: Optional[Dict[str, str]] = None) -> None:
    handler.send_response(code)
    handler.send_header("Content-Type", ctype)
    handler.send_header("Content-Length", str(len(data)))
    for name, value in (headers or {}).items():
        handler.send_header(name, value)
    handler.end_headers()
    handler.wfile.write(data)


def handle_trace_spans(handler, path: str, name: str = "") -> bool:
    """Serve ``GET /trace/spans[?since=CURSOR]`` — the span-ring pull
    every request-plane HTTP surface exposes (router, GenerationAPI,
    RESTfulAPI), so ``veles-tpu trace fleet`` assembles a cross-
    process timeline without any replica needing ``--trace-file``.
    Returns True when the path was handled (mirrors
    ``health.handle_health``). The body is JSONL (header line + one
    line per span) so a torn read salvages per record."""
    if path.split("?", 1)[0] != "/trace/spans":
        return False
    since = 0
    if "?" in path:
        from urllib.parse import parse_qs
        try:
            since = int(parse_qs(path.split("?", 1)[1]
                                 ).get("since", ["0"])[0])
        except (TypeError, ValueError):
            json_reply(handler, 400,
                       {"error": "since must be an integer cursor"})
            return True
    from .telemetry.spans import pull_payload
    bytes_reply(handler, 200, pull_payload(since, name=name).encode(),
                "application/x-ndjson")
    return True


def handle_metrics_history(handler, path: str, name: str = "") -> bool:
    """Serve ``GET /metrics/history[?since=CURSOR]`` — the watchtower
    SeriesStore pull every request-plane HTTP surface exposes
    (router, GenerationAPI, RESTfulAPI, web status), same contract as
    :func:`handle_trace_spans`: JSONL body (header line + one line per
    ring record) so a torn read salvages per record. With the
    watchtower off the reply is the header alone (``enabled: false``)
    and no ``veles_watch_*`` counter moves."""
    if path.split("?", 1)[0] != "/metrics/history":
        return False
    since = 0
    if "?" in path:
        from urllib.parse import parse_qs
        try:
            since = int(parse_qs(path.split("?", 1)[1]
                                 ).get("since", ["0"])[0])
        except (TypeError, ValueError):
            json_reply(handler, 400,
                       {"error": "since must be an integer cursor"})
            return True
    from .telemetry import timeseries
    bytes_reply(handler, 200,
                timeseries.pull_payload(since, name=name).encode(),
                "application/x-ndjson")
    return True


def handle_alerts(handler, path: str) -> bool:
    """Serve ``GET /alerts`` — the watchtower rule states as JSON
    (``veles-tpu alerts`` and loadgen ``--abort-on-alert`` poll
    this). Off → ``{"enabled": false, "rules": []}``."""
    if path.split("?", 1)[0] != "/alerts":
        return False
    from .telemetry import timeseries
    json_reply(handler, 200, timeseries.alerts_payload())
    return True


def sse_headers(handler) -> None:
    """Commit a 200 ``text/event-stream`` response (token streaming —
    the GenerationAPI's stream reply and the FleetRouter's stream
    proxy share this framing, so the wire protocol cannot drift
    between them)."""
    handler.send_response(200)
    handler.send_header("Content-Type", "text/event-stream")
    handler.send_header("Cache-Control", "no-store")
    handler.end_headers()
    handler.close_connection = True


def sse_event(handler, payload: Any) -> None:
    """Write one ``data: <json>`` SSE event and flush. Write errors
    (the CLIENT went away) propagate — callers distinguish them from
    upstream failures."""
    handler.wfile.write(b"data: " + json.dumps(payload).encode()
                        + b"\n\n")
    handler.wfile.flush()


def read_json_object(handler) -> Dict[str, Any]:
    """Parse the request body as a JSON *object*; raises ValueError on
    malformed JSON and on valid-JSON non-objects (lists, strings, …) so
    one `except ValueError` covers every bad body."""
    length = int(handler.headers.get("Content-Length", 0))
    body = json.loads(handler.rfile.read(length) or b"{}")
    if not isinstance(body, dict):
        raise ValueError("JSON object expected, got %s" %
                         type(body).__name__)
    return body


class HTTPService:
    """Owns a ThreadingHTTPServer + daemon thread (start/stop lifecycle
    shared by WebStatusServer / ForgeServer / RESTfulAPI)."""

    def __init__(self, handler_cls, port: int = 0,
                 thread_name: str = "http",
                 host: str = "127.0.0.1") -> None:
        self._httpd = ThreadingHTTPServer((host, port), handler_cls)
        self.port = self._httpd.server_port
        self._thread: Optional[threading.Thread] = None
        self._thread_name = thread_name

    def start_serving(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name=self._thread_name)
        self._thread.start()

    def stop_serving(self) -> None:
        if self._httpd is not None:
            if self._thread is not None:
                # shutdown() waits on an event only serve_forever() sets —
                # calling it on a never-started server deadlocks
                self._httpd.shutdown()
            self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
