"""Deterministic fault-injection plane.

The reference shipped chaos testing as a first-class flag
(``--slave-death-probability``, veles/client.py:303-307: each slave
rolls a die after every job and kills itself) because its recovery
story — job re-serving, checkpoint restart — was only trusted once it
was exercised. This build generalizes that one kill switch into a
plane of **named injection points** that any spec can arm:

    point:action[:key=value[,key=value...]][;next clause...]

e.g. ``VELES_FAULTS="snapshot.write:crash:after=1,times=1;download:raise:p=0.5"``

Actions:
- ``raise``   — raise :class:`FaultInjected` at the point;
- ``crash``   — ``os._exit(42)`` (the reference's slave-death exit code);
- ``delay``   — sleep ``delay`` seconds (default 0.05) and continue;
- ``corrupt`` — return the :class:`Fault` so the call site damages its
  payload via :meth:`Fault.corrupt` (only points that write/read bytes
  honor it; others treat it as a no-op).

Params: ``p`` (fire probability, default 1 — the die is rolled on the
PRNG-seeded ``faults`` stream, so a seeded run injects the same faults
every time), ``after`` (skip the first N hits), ``times`` (fire at
most N times), ``delay`` (seconds, for action=delay), ``window=T0:T1``
(armed only between the T0-th and T1-th trigger: the clause skips the
first T0 hits and disarms after the T1-th — a timed chaos STORM as a
plain spec, e.g. ``serve.page_alloc:raise:window=50:80`` fails page
allocations 51..80 and then heals; the loadgen harness arms its storms
this way).

The spec comes from the ``VELES_FAULTS`` env var (wins) or
``root.common.resilience.faults``. With neither set, every
:func:`fire` is a no-op and the fault counters stay at zero — asserted
by ``python bench.py gate``'s resilience section. Every fired fault
increments ``veles_faults_injected_total``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..config import root
from ..error import VelesError
from ..logger import Logger
from ..telemetry.counters import inc


class FaultInjected(VelesError):
    """Raised by an armed injection point (action=raise)."""


#: exit code of action=crash — the reference's fault-injection death
#: code (veles/client.py:438-442), kept so recovery tests recognize it
CRASH_EXIT_CODE = 42

ACTIONS = ("raise", "crash", "delay", "corrupt")

#: name → description of every registered injection point
#: (``veles_tpu faults list`` prints this table)
POINTS: Dict[str, str] = {}


def register_point(name: str, description: str) -> None:
    """Declare an injection point so specs can reference it (typos in a
    spec fail at parse, not silently never fire)."""
    POINTS[name] = description


def list_points() -> Dict[str, str]:
    return dict(POINTS)


for _name, _desc in (
    ("snapshot.write", "Snapshotter.export, before the state file is "
                       "committed (corrupt: damage the written bytes)"),
    ("snapshot.load", "load_snapshot, before a snapshot file is read"),
    ("loader.batch", "Loader.run, before a minibatch is served"),
    ("dispatch", "the launcher-armed train-step dispatch"),
    ("download", "Downloader fetch, before each HTTP attempt"),
    ("serve.request", "REST/generation request intake (raise is shed "
                      "as 503 + Retry-After, never a crash)"),
    ("serve.decode_step", "continuous-batching engine, before each "
                          "pooled decode step (raise sheds the "
                          "in-flight rows 503 + Retry-After; the "
                          "slot pool stays consistent)"),
    ("serve.page_alloc", "paged KV-cache allocator, at every page "
                         "allocation (raise = simulated exhaustion: "
                         "admission sheds the head request, decode-"
                         "time growth sheds the growing row — 503 + "
                         "Retry-After either way; the page ledger "
                         "stays consistent)"),
    ("distributed.init", "initialize_multihost, inside the retried "
                         "coordinator join"),
    # elastic training plane (resilience/elastic.py): chaos for the
    # generation lifecycle — a raised host_loss simulates a preempted
    # peer (the survivor declares a new generation), a crash IS the
    # preemption (the respawn Supervisor rebuilds the job); an armed
    # generation_barrier exercises the survivor-barrier failure path
    ("distributed.host_loss", "elastic host-loss probe, per armed "
                              "train-step dispatch (raise = a peer "
                              "was preempted -> new generation; "
                              "crash = this host IS preempted)"),
    ("distributed.generation_barrier", "elastic survivor barrier, "
                                       "before the generation's "
                                       "collective agreement (raise "
                                       "counts a barrier timeout and "
                                       "ends the generation)"),
    # overlap subsystem (veles_tpu/overlap/): chaos for the async
    # side-plane — crash/delay a lane worker or the prefetch producer
    # and prove drain barriers + checkpoint-lane ordering survive
    ("sideplane.task", "side-plane lane worker, before each offloaded "
                       "task executes (overlap/executor.py)"),
    ("prefetch.batch", "prefetch producer, before each staged batch "
                       "(overlap/prefetch.py)"),
    # model-health observability (telemetry/recorder.py): chaos for
    # the crash black box itself — raise/crash while dumping, or
    # corrupt the written blackbox-*.jsonl bytes
    ("recorder.dump", "FlightRecorder.dump, before the black-box "
                      "file is written (corrupt: damage the dump "
                      "bytes)"),
    # quantization subsystem (veles_tpu/quant/): chaos for the AOT/
    # int8 serving plane — a failed artifact load or calibration must
    # degrade to live-jit / float serving, never crash the API
    ("artifact.load", "serving engine, before an AOT serve-artifact "
                      "is deserialized (raise falls back to live jit "
                      "with a counted warning)"),
    ("quant.calibrate", "weight quantization scale calibration "
                        "(quantize_params/quantize_state), before "
                        "the amax scan"),
    # serving fleet (serving/router.py + restful_api.GenerationAPI):
    # chaos for the multi-replica topology — the router must open the
    # breaker, fail the request over to a survivor, and answer it
    # exactly once while the Supervisor plane respawns the hole
    ("router.replica_request", "fleet router, before each proxied "
                               "replica attempt (raise = the attempt "
                               "fails like a dead replica: counted, "
                               "the breaker advances, the request "
                               "fails over to another replica)"),
    ("serve.replica_death", "serving replica death mid-decode: fired "
                            "in the GenerationAPI request path after "
                            "admission AND per engine decode tick "
                            "(raise = this replica tears down its "
                            "HTTP front and aborts in-flight work "
                            "with a dying-gasp 503 carrying each "
                            "ticket's resume progress; crash = the "
                            "replica process actually exits %d)"
                            % CRASH_EXIT_CODE),
    # lossless request plane (serving/journal.py + token-level resume):
    # chaos for the durability story — a corrupted journal record must
    # be quarantined with a counted warning at replay (never refuse to
    # start), and a failed progress snapshot mid-drain must degrade
    # that one ticket to a plain 503 (no resume), never block the drain
    ("router.journal", "durable request journal, at every record "
                       "append and every replay read (corrupt: "
                       "damage the record bytes — replay salvages "
                       "the torn entry with a counted warning; "
                       "raise at append: the admission is shed "
                       "rather than accepted un-journaled)"),
    ("serve.prefix_match", "prefix-cache radix walk at admission "
                           "(raise = injected index loss, corrupt = "
                           "injected index rot: both degrade to a "
                           "shorter/empty match and a full prefill — "
                           "token equality is the match authority, "
                           "so answers are never wrong)"),
    ("serve.prefill_chunk", "chunked prefill, before each chunk "
                            "dispatch (raise = that admission is "
                            "shed 503 + Retry-After with a resume "
                            "payload while co-tenant decodes keep "
                            "running)"),
    ("serve.handoff", "drain-by-handoff progress snapshot, per "
                      "in-flight ticket at a draining replica "
                      "(raise = that ticket's handoff degrades to a "
                      "plain 503 shed without resume progress; the "
                      "drain itself always completes)"),
    # O(1)-state serving lane (serving/recurrent.py): chaos for the
    # state-checkpoint prefix cache — a lost/rotten checkpoint must
    # cost a re-scan, never a wrong state
    ("serve.state_restore", "O(1)-state checkpoint lookup at "
                            "admission (raise = injected checkpoint "
                            "loss: degrades to a full re-scan from "
                            "zeros, counted; corrupt = injected "
                            "index rot: degrades to a shorter/empty "
                            "match — token equality is the match "
                            "authority, so adopted state is never "
                            "wrong)"),
    ("serve.state_checkpoint", "O(1)-state block-boundary snapshot "
                               "insert after prefill (raise = the "
                               "scanned prompt is NOT cached with a "
                               "counted warning — the request is "
                               "already answered from live state, so "
                               "only future same-prefix admissions "
                               "pay a re-scan)"),
    ("linalg.block_op", "blocked linear-algebra block dispatch "
                        "(linalg/blocked.py k-panel dots, potrf/trsm "
                        "panels, SUMMA launches; raise = abort the "
                        "solve, corrupt = flip bytes in the "
                        "dispatched block — verify_residual's "
                        "trusted dense check must then FAIL the "
                        "solve loudly, never return a silently-"
                        "wrong x)"),
):
    register_point(_name, _desc)


class Fault:
    """One armed clause of a fault spec."""

    def __init__(self, point: str, action: str, p: float = 1.0,
                 after: int = 0, times: Optional[int] = None,
                 delay: float = 0.05,
                 window: Optional[Tuple[int, int]] = None) -> None:
        if point not in POINTS:
            raise VelesError(
                "unknown fault injection point %r (registered: %s)"
                % (point, ", ".join(sorted(POINTS))))
        if action not in ACTIONS:
            raise VelesError("unknown fault action %r (one of %s)"
                             % (action, "/".join(ACTIONS)))
        if not 0.0 <= p <= 1.0:
            raise VelesError("fault probability p=%r outside [0, 1]" % p)
        if window is not None:
            lo, hi = int(window[0]), int(window[1])
            if lo < 0 or hi <= lo:
                raise VelesError(
                    "fault window=%d:%d needs 0 <= T0 < T1" % (lo, hi))
            window = (lo, hi)
        self.point = point
        self.action = action
        self.p = float(p)
        self.after = int(after)
        self.times = None if times is None else int(times)
        self.delay = float(delay)
        self.window = window
        self.hits = 0
        self.fired = 0

    def consider(self) -> bool:
        """Roll this clause once; True when it fires now."""
        self.hits += 1
        if self.hits <= self.after:
            return False
        if self.window is not None and not (
                self.window[0] < self.hits <= self.window[1]):
            # a timed storm: armed only between the T0-th and T1-th
            # trigger, then the point heals
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        if self.p < 1.0:
            from .. import prng
            if prng.get("faults", ephemeral=True).rand() >= self.p:
                return False
        self.fired += 1
        return True

    @staticmethod
    def corrupt(data: bytes) -> bytes:
        """Deterministically damage a payload: flip the middle byte —
        enough to break any checksum/codec without changing length."""
        if not data:
            return b"\x00"
        i = len(data) // 2
        return data[:i] + bytes([data[i] ^ 0xFF]) + data[i + 1:]

    def __repr__(self) -> str:
        win = ("" if self.window is None
               else " window=%d:%d" % self.window)
        return ("<Fault %s:%s p=%g after=%d times=%s%s fired=%d/%d>"
                % (self.point, self.action, self.p, self.after,
                   self.times, win, self.fired, self.hits))


def parse_spec(text: str) -> List[Fault]:
    """Parse a fault spec string into armed clauses (see module doc for
    the grammar). Empty/whitespace text parses to no faults."""
    faults: List[Fault] = []
    for clause in filter(None, (c.strip() for c in (text or "").split(";"))):
        # maxsplit=2: the param field may itself contain ":"
        # (window=T0:T1) — only the first two colons structure the
        # clause
        parts = clause.split(":", 2)
        if len(parts) < 2:
            raise VelesError(
                "fault clause %r is not point:action[:k=v,...]" % clause)
        kwargs: Dict[str, object] = {}
        if len(parts) > 2 and parts[2].strip():
            for kv in parts[2].split(","):
                key, sep, val = kv.partition("=")
                key = key.strip()
                if not sep or key not in ("p", "after", "times",
                                          "delay", "window"):
                    raise VelesError(
                        "fault param %r in %r is not one of "
                        "p/after/times/delay/window=value"
                        % (kv, clause))
                try:
                    if key == "window":
                        lo, sep2, hi = val.partition(":")
                        if not sep2:
                            raise ValueError("want window=T0:T1")
                        kwargs[key] = (int(lo), int(hi))
                    else:
                        kwargs[key] = (float(val)
                                       if key in ("p", "delay")
                                       else int(val))
                except ValueError as e:
                    raise VelesError("bad fault param %r: %s" % (kv, e))
        faults.append(Fault(parts[0].strip(), parts[1].strip(), **kwargs))
    return faults


class FaultPlane(Logger):
    """The process-global injection plane: resolves the active spec
    (env > config), keeps per-clause counters, and runs every armed
    clause when an instrumented call site hits :meth:`fire`."""

    def __init__(self) -> None:
        super().__init__()
        self._lock = threading.Lock()
        self._spec_text: Optional[str] = None
        self._faults: Dict[str, List[Fault]] = {}

    def current_spec(self) -> str:
        """The spec string that would be active right now."""
        env = os.environ.get("VELES_FAULTS")
        if env is not None:
            return env
        return str(root.common.resilience.get("faults", "") or "")

    def configure(self, spec: Optional[str] = None) -> None:
        """(Re)arm from ``spec`` (or the env/config resolution). Clause
        counters reset — tests and chaos drivers call this directly."""
        text = self.current_spec() if spec is None else spec
        with self._lock:
            self._spec_text = text
            self._faults = {}
            for fault in parse_spec(text):
                self._faults.setdefault(fault.point, []).append(fault)

    def _refresh(self) -> None:
        # env/config may change between fires (tests monkeypatch
        # VELES_FAULTS); a changed spec re-arms, an unchanged one is a
        # string compare
        if self.current_spec() != self._spec_text:
            self.configure()

    def active(self) -> bool:
        self._refresh()
        return bool(self._faults)

    def fire(self, point: str, **ctx) -> Optional[Fault]:
        """Run the injection point. Raises/exits/sleeps per the armed
        clauses; returns the :class:`Fault` when an armed clause says
        ``corrupt`` (the call site applies :meth:`Fault.corrupt`), else
        None. With no spec set this is a dict miss — cheap enough for
        per-batch call sites."""
        self._refresh()
        clauses = self._faults.get(point)
        if not clauses:
            return None
        corrupting = None
        for fault in clauses:
            with self._lock:
                fires = fault.consider()
            if not fires:
                continue
            inc("veles_faults_injected_total")
            self.warning("fault injected at %s: %s (hit %d)%s", point,
                         fault.action, fault.hits,
                         (" %s" % (ctx,)) if ctx else "")
            if fault.action == "raise":
                raise FaultInjected("injected fault at %s" % point)
            if fault.action == "crash":
                os._exit(CRASH_EXIT_CODE)
            if fault.action == "delay":
                time.sleep(fault.delay)
            elif fault.action == "corrupt":
                corrupting = fault
        return corrupting


#: THE process-global plane every instrumented call site uses
plane = FaultPlane()
fire = plane.fire


def inject_crash(reason: str) -> None:
    """The legacy ``--slave-death-probability`` kill switch routed
    through the plane: counted like any fired fault, same exit code
    (reference: veles/client.py:438-442)."""
    inc("veles_faults_injected_total")
    Logger().warning("fault injection: terminating process (%s)", reason)
    os._exit(CRASH_EXIT_CODE)
