"""Resilience subsystem: failures injected, retried, survived, counted.

The reference VELES treated recovery as a first-class feature — slave
death re-served jobs or restarted from a checkpoint, and
``--slave-death-probability`` existed precisely to prove it. This
package is that story rebuilt for the SPMD runtime (docs/resilience.md
is the operator guide):

- :mod:`faults` — deterministic, PRNG-seeded fault-injection plane:
  named points (``snapshot.write``, ``loader.batch``, ``dispatch``,
  ``download``, ``serve.request``, ``distributed.init``, …) armed by a
  ``VELES_FAULTS`` / ``root.common.resilience.faults`` spec;
- :mod:`retry` — :class:`~veles_tpu.resilience.retry.RetryPolicy`
  (exponential backoff + full jitter, attempt cap, deadline,
  retryable predicates) applied to downloads, the multi-host join,
  forge client calls and snapshot DB export;
- :mod:`checkpoint_chain` — crash-safe snapshots: fsync'd commits,
  SHA-256 sidecar manifests, verification at load, newest-valid
  restore past quarantined ``*.corrupt`` files, ``keep_last`` pruning;
- :mod:`health` — heartbeat registry + readiness marks behind the
  ``/healthz`` / ``/readyz`` endpoints, and 503 + ``Retry-After`` load
  shedding for the bounded serving queues.

Everything observable lands in the PR-1 telemetry counters
(:data:`RESILIENCE_COUNTERS`); ``python bench.py gate`` asserts they
exist and read zero in clean (no-spec) runs.
"""

from __future__ import annotations

from .faults import (FaultInjected, FaultPlane, fire,     # noqa: F401
                     list_points, parse_spec, plane, register_point)
from .retry import RetryPolicy, TransientError            # noqa: F401
from .checkpoint_chain import (SnapshotCorruptError,      # noqa: F401
                               chain, cursor_of, latest_cursor,
                               load_latest, prune, quarantine,
                               restore_latest, verify)
from .health import (heartbeats, mark_draining,           # noqa: F401
                     mark_ready, mark_unready, shed)
from .elastic import (ELASTIC_COUNTERS,                   # noqa: F401
                      ElasticController, GENERATION_EXIT_CODE,
                      HostLostError, Supervisor, generation_barrier,
                      predict_step_time, psum_bytes_per_step)

#: every counter this subsystem increments — registered with HELP
#: strings in telemetry.counters.DESCRIPTIONS and asserted zero in
#: clean runs by ``python bench.py gate``'s resilience section (the
#: elastic generation counters have their own tuple + gate section:
#: resilience.elastic.ELASTIC_COUNTERS)
RESILIENCE_COUNTERS = (
    "veles_faults_injected_total",
    "veles_retries_total",
    "veles_shed_requests_total",
    "veles_watchdog_trips_total",
    "veles_snapshots_quarantined_total",
    "veles_manifest_cursor_defaults_total",
)
