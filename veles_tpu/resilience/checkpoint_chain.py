"""Crash-safe checkpoint chain: fsync'd commits, SHA-256 manifests,
quarantine-and-fall-back restore, bounded retention.

The reference called snapshots its disaster-recovery story, but wrote
them as unchecksummed pickles: a crash mid-write or silent bitrot left
a file that LOOKED like a snapshot and exploded (or worse, half-
applied) at resume. This module makes the chain trustworthy:

- **commit**: tmp write → ``fsync(tmp)`` → ``os.replace`` →
  ``fsync(dir)`` — after :func:`commit_file` returns, the snapshot is
  durably on disk under its final name or not at all;
- **manifest**: every snapshot gets a ``<file>.manifest.json`` sidecar
  carrying its SHA-256 (plus size/metadata), written with the same
  atomic commit;
- **verify**: :func:`verify` recomputes the digest;
  ``snapshotter.load_snapshot`` refuses a mismatching file with
  :class:`SnapshotCorruptError` instead of feeding pickle garbage;
- **restore**: :func:`restore_latest` walks the chain newest→oldest,
  quarantining corrupt files (renamed ``*.corrupt``, counted in
  ``veles_snapshots_quarantined_total``) until it finds the newest
  snapshot that both verifies and deserializes;
- **retention**: :func:`prune` keeps the newest ``keep_last`` and
  deletes the rest (with their sidecars) — quarantined files are
  evidence and are never pruned.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
from typing import Any, Dict, List, Optional, Tuple

from ..error import VelesError
from ..logger import Logger
from ..telemetry.counters import inc


class SnapshotCorruptError(VelesError):
    """A snapshot file failed its manifest SHA-256 or could not be
    deserialized (truncated / torn write / bitrot)."""


MANIFEST_SUFFIX = ".manifest.json"
CORRUPT_SUFFIX = ".corrupt"


def file_sha256(path: str, chunk: int = 1 << 20) -> str:
    digest = hashlib.sha256()
    with open(path, "rb") as fin:
        while True:
            block = fin.read(chunk)
            if not block:
                break
            digest.update(block)
    return digest.hexdigest()


def commit_file(tmp: str, path: str) -> None:
    """Durably move ``tmp`` to ``path``: fsync the data, rename, fsync
    the directory entry. A crash at any instant leaves either the old
    state or the complete new file — never a torn ``path``."""
    with open(tmp, "rb") as fin:
        os.fsync(fin.fileno())
    os.replace(tmp, path)
    dir_fd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                     os.O_RDONLY)
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)


def manifest_path(path: str) -> str:
    return path + MANIFEST_SUFFIX


def write_manifest(path: str, **meta: Any) -> str:
    """Write the sidecar manifest for ``path`` (atomic commit). The
    SHA-256 defaults to the file's current digest; callers that
    corrupt-inject pass the pristine digest explicitly."""
    meta.setdefault("sha256", file_sha256(path))
    meta.setdefault("bytes", os.path.getsize(path))
    mpath = manifest_path(path)
    tmp = mpath + ".tmp"
    with open(tmp, "w") as fout:
        json.dump(meta, fout, indent=1, sort_keys=True)
        fout.write("\n")
        fout.flush()
        os.fsync(fout.fileno())
    os.replace(tmp, mpath)
    return mpath


def read_manifest(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(manifest_path(path)) as fin:
            man = json.load(fin)
        return man if isinstance(man, dict) else None
    except (OSError, ValueError):
        return None


def verify(path: str) -> Optional[bool]:
    """True = digest matches the manifest, False = mismatch (corrupt),
    None = no manifest (pre-manifest snapshot: unverifiable but
    loadable)."""
    man = read_manifest(path)
    if not man or "sha256" not in man:
        return None
    try:
        return file_sha256(path) == man["sha256"]
    except OSError:
        return False


def quarantine(path: str) -> str:
    """Rename a corrupt snapshot (and its sidecar) to ``*.corrupt`` so
    the chain walk never reconsiders it while the evidence survives.
    Any ``<prefix>_current`` symlink that pointed at the quarantined
    file is repointed to the next-newest valid-named snapshot (or
    removed when none is left) — an elastic rerun that resumes via the
    link must skip straight to the older valid entry, never trip over
    a dangling link to evidence."""
    dest = path + CORRUPT_SUFFIX
    os.replace(path, dest)
    man = manifest_path(path)
    if os.path.exists(man):
        os.replace(man, dest + MANIFEST_SUFFIX)
    inc("veles_snapshots_quarantined_total")
    Logger().warning("quarantined corrupt snapshot %s -> %s", path, dest)
    _repair_current_links(os.path.dirname(os.path.abspath(path)))
    return dest


def _repair_current_links(directory: str) -> None:
    """Repoint every dangling ``*_current.pickle*`` symlink in
    ``directory`` at the newest surviving snapshot of its prefix
    (atomic: temp symlink + ``os.replace``), or remove it when the
    chain is empty. Idempotent — healthy links are untouched."""
    for link in glob.glob(os.path.join(directory, "*_current.pickle*")):
        if link.endswith(".tmp"):
            # a crash between symlink() and os.replace() in
            # _update_current_link leaves a *_current.pickle*.tmp —
            # debris, not a current link; repairing it would mint a
            # second never-cleaned pseudo-current link
            continue
        if not os.path.islink(link) or os.path.exists(link):
            continue                       # healthy (or not a link)
        prefix = os.path.basename(link).split("_current.pickle")[0]
        survivors = chain(directory, prefix)
        try:
            if not survivors:
                os.unlink(link)
                Logger().warning(
                    "removed dangling snapshot link %s (chain empty)",
                    link)
                continue
            tmp_link = link + ".tmp"
            try:
                os.unlink(tmp_link)
            except OSError:
                pass
            os.symlink(os.path.basename(survivors[0]), tmp_link)
            os.replace(tmp_link, link)
            Logger().warning("repointed snapshot link %s -> %s", link,
                             os.path.basename(survivors[0]))
        except OSError:
            # link repair is best-effort: the chain walk never follows
            # links, so restore still works either way
            pass


def chain(directory: str, prefix: str = "wf") -> List[str]:
    """Snapshot files for ``prefix`` in ``directory``, newest first.
    The ``_current`` symlink, sidecars, temp files and quarantined
    files are excluded."""
    out = []
    for path in glob.glob(os.path.join(directory, prefix + "*.pickle*")):
        if (path.endswith(CORRUPT_SUFFIX)
                or path.endswith(MANIFEST_SUFFIX)
                or path.endswith(".tmp") or os.path.islink(path)):
            continue
        out.append(path)
    return sorted(out, key=lambda p: (os.path.getmtime(p), p),
                  reverse=True)


def load_latest(directory: str, prefix: str = "wf"
                ) -> Optional[Tuple[str, Dict[str, Any]]]:
    """Walk the chain newest→oldest to the newest snapshot that both
    verifies and deserializes; corrupt files met on the way are
    quarantined. Returns (path, state tree) or None. (load_snapshot
    runs the SHA-256 verification itself — one hash per candidate.)"""
    from ..snapshotter import load_snapshot
    for path in chain(directory, prefix):
        try:
            return path, load_snapshot(path)
        except SnapshotCorruptError as e:
            Logger().warning("snapshot %s unreadable (%s)", path, e)
            quarantine(path)
    return None


def restore_latest(workflow, directory: str,
                   prefix: str = "wf") -> Optional[str]:
    """Apply the newest valid snapshot in the chain to an initialized
    workflow; returns the path restored from, or None when the chain
    holds no valid snapshot."""
    found = load_latest(directory, prefix)
    if found is None:
        return None
    path, state = found
    from ..snapshotter import apply_state
    apply_state(workflow, state)
    workflow.restored_from_snapshot = True
    return path


#: cursor defaults for manifests written before the elastic plane
#: (docs/resilience.md "Elastic training"): epoch/step 0, one host
CURSOR_DEFAULT = {"epoch": 0, "step": 0, "world_size": 1}


def cursor_of(path: str) -> Dict[str, int]:
    """The snapshot's ``{epoch, step, world_size}`` training cursor
    from its sidecar manifest — where an elastic generation resumes.
    Legacy manifests (and missing/partial cursors) default the missing
    fields with a counted warning
    (``veles_manifest_cursor_defaults_total``), never a crash."""
    man = read_manifest(path) or {}
    raw = man.get("cursor")
    out = dict(CURSOR_DEFAULT)
    defaulted = []
    if not isinstance(raw, dict):
        raw = {}
    for key in out:
        try:
            out[key] = int(raw[key])
        except (KeyError, TypeError, ValueError):
            defaulted.append(key)
    if defaulted:
        inc("veles_manifest_cursor_defaults_total")
        Logger().warning(
            "snapshot %s manifest carries no %s cursor — defaulting "
            "to %s (pre-elastic manifest, or a torn sidecar)", path,
            "/".join(defaulted),
            {k: out[k] for k in defaulted})
    return out


def latest_cursor(directory: str, prefix: str = "wf"):
    """(path, cursor) of the newest chain entry, or None on an empty
    chain. Reads only the sidecar — no unpickle, so it is cheap enough
    for the elastic controller to log at every generation handoff."""
    for path in chain(directory, prefix):
        return path, cursor_of(path)
    return None


def prune(directory: str, prefix: str = "wf",
          keep_last: int = 0) -> List[str]:
    """Bounded retention: delete all but the newest ``keep_last``
    snapshots (and their sidecars). 0/None keeps everything. The
    ``_current`` symlink always points at the newest snapshot, so its
    target survives any ``keep_last >= 1``."""
    if not keep_last or keep_last <= 0:
        return []
    removed = []
    for path in chain(directory, prefix)[keep_last:]:
        for victim in (path, manifest_path(path)):
            try:
                os.unlink(victim)
                removed.append(victim)
            except OSError:
                pass
    return removed
