"""Health plane: heartbeat registry, readiness marks, load shedding.

Generalizes ``parallel/distributed.py``'s ``step_watchdog`` (one
context manager around one dispatch) into a process-wide registry that
every long-running loop reports into — ``Workflow.run`` beats per
scheduler step, the serving worker loops beat per wakeup, the launcher
beats around the run. ``/healthz`` (liveness: every registered
heartbeat younger than its timeout) and ``/readyz`` (readiness marks
flipped by service initialize/stop) are served by ``web_status`` and
both serving APIs via :func:`handle_health`.

Load shedding: bounded serving queues reply **503 + Retry-After**
through :func:`shed` instead of growing unboundedly — every shed is
counted in ``veles_shed_requests_total``. The reference's tornado/
twisted services simply queued until memory ran out; under the
north-star's traffic that is an outage, not a queue.
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ..config import root
from ..telemetry.counters import inc


def _default_timeout() -> float:
    return float(root.common.resilience.get("heartbeat_timeout", 300.0)
                 or 300.0)


class HeartbeatRegistry:
    """Thread-safe name → last-beat map with per-entry timeouts."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._beats: Dict[str, Dict[str, Any]] = {}

    def beat(self, name: str, timeout: Optional[float] = None) -> None:
        now = time.time()
        with self._lock:
            entry = self._beats.get(name)
            if entry is None:
                entry = self._beats[name] = {
                    "first": now, "beats": 0,
                    "timeout": _default_timeout()}
            entry["last"] = now
            entry["beats"] += 1
            if timeout is not None:
                entry["timeout"] = float(timeout)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._beats.pop(name, None)

    def status(self) -> Dict[str, Dict[str, Any]]:
        now = time.time()
        with self._lock:
            out = {}
            for name, entry in self._beats.items():
                age = now - entry["last"]
                out[name] = {
                    "age_sec": round(age, 3),
                    "timeout_sec": entry["timeout"],
                    "beats": entry["beats"],
                    "healthy": age < entry["timeout"],
                }
            return out

    def healthy(self) -> bool:
        return all(v["healthy"] for v in self.status().values())

    def stale(self, prefix: str = "") -> list:
        """Names of entries (matching ``prefix``) whose beat aged past
        its timeout — the cheap probe hot paths use instead of
        materializing the full :meth:`status` dict per call."""
        now = time.time()
        with self._lock:
            return [name for name, entry in self._beats.items()
                    if name.startswith(prefix)
                    and now - entry["last"] >= entry["timeout"]]

    def clear(self) -> None:
        """Tests only — production registries live with the process."""
        with self._lock:
            self._beats.clear()


#: THE process-global registry (one process = one liveness surface)
heartbeats = HeartbeatRegistry()

_ready_lock = threading.Lock()
_ready: Dict[str, bool] = {}
_draining: set = set()
#: free-form info keys merged into the /readyz payload — the roster
#: discovery surface for facts that are NOT health (e.g. a TP engine
#: publishes {"tp": {"devices": N, "axis": "model"}} so a router
#: learns replica = mesh slice without a second probe endpoint)
_info: Dict[str, Any] = {}


def set_info(key: str, value: Any = None) -> None:
    """Publish (or, with ``value=None``, retract) one info key on the
    /readyz payload. Values must be JSON-serializable."""
    with _ready_lock:
        if value is None:
            _info.pop(key, None)
        else:
            _info[key] = value


def info() -> Dict[str, Any]:
    with _ready_lock:
        return dict(_info)


def mark_ready(name: str) -> None:
    with _ready_lock:
        _ready[name] = True
        _draining.discard(name)


def mark_unready(name: str) -> None:
    with _ready_lock:
        _ready[name] = False
        _draining.discard(name)


def mark_draining(name: str) -> None:
    """Graceful-drain readiness: ``/readyz`` flips 503 (a router/LB
    stops sending NEW work here) while ``/healthz`` stays green — the
    process is alive and finishing its in-flight tickets, which is
    exactly the state the payload's ``"draining"`` status names for
    the operator watching the drain."""
    with _ready_lock:
        _ready[name] = False
        _draining.add(name)


def forget(name: str) -> None:
    """Deliberate shutdown: drop the readiness mark AND the heartbeat —
    a service stopped on purpose must not pin /readyz at 503 or age
    into an /healthz failure."""
    with _ready_lock:
        _ready.pop(name, None)
        _draining.discard(name)
    heartbeats.unregister(name)


def readiness() -> Dict[str, bool]:
    with _ready_lock:
        return dict(_ready)


def draining() -> set:
    """Names currently draining (subset of the not-ready marks)."""
    with _ready_lock:
        return set(_draining)


def healthz() -> Tuple[int, Dict[str, Any]]:
    """(status code, payload) for a liveness probe: 200 while every
    registered heartbeat is younger than its timeout (a process with no
    registered heartbeats is alive by definition — it answered)."""
    status = heartbeats.status()
    ok = all(v["healthy"] for v in status.values())
    return (200 if ok else 503), {
        "status": "ok" if ok else "unhealthy", "heartbeats": status}


def readyz() -> Tuple[int, Dict[str, Any]]:
    """(status code, payload) for a readiness probe: 200 once every
    component that declared itself is marked ready. A component in
    graceful drain reports ``"draining"`` in the components map (and
    flips the page status to ``"draining"`` when every not-ready
    component is one) — a fleet router distinguishes "spill away and
    come back" from "never was ready"."""
    marks = readiness()
    drains = draining()
    ok = all(marks.values()) if marks else True
    status = "ok"
    if not ok:
        not_ready = {n for n, v in marks.items() if not v}
        status = ("draining" if not_ready and not_ready <= drains
                  else "not ready")
    payload: Dict[str, Any] = {
        "status": status,
        "components": {n: ("draining" if n in drains else v)
                       for n, v in marks.items()}}
    # info keys ride the same payload (never affect the code): a
    # router's probe learns e.g. the mesh-slice shape for free —
    # setdefault so no info key can shadow status/components
    for k, v in info().items():
        payload.setdefault(k, v)
    return (200 if ok else 503), payload


def handle_health(handler, path: str) -> bool:
    """Route ``/healthz`` + ``/readyz`` on a stdlib HTTP handler; True
    when the path was one of them (reply already sent)."""
    if path == "/healthz":
        code, payload = healthz()
    elif path == "/readyz":
        code, payload = readyz()
    else:
        return False
    from .._http import json_reply
    json_reply(handler, code, payload)
    return True


def shed(handler, retry_after: float = 1.0,
         reason: str = "overloaded",
         request_id: Optional[str] = None) -> None:
    """Reply 503 with a ``Retry-After`` header — the load-shedding
    answer a bounded queue gives instead of growing. Counted. A
    ``request_id`` (the ticket's, or the router-supplied one) rides
    the body so a fleet router can correlate the shed with the
    attempt it belongs to — success bodies already carry the id via
    ``Ticket.succeed``. With QoS on, the stamped hint scales with the
    live queue pressure (serving/overload.py) so storming clients back
    off proportionally; with it off the hint passes through
    unchanged."""
    inc("veles_shed_requests_total")
    try:
        from ..serving.overload import dynamic_retry_after
        retry_after = dynamic_retry_after(retry_after) or retry_after
    except Exception:       # noqa: BLE001 — shedding must never fail
        pass
    body = {"error": reason, "retry_after": retry_after}
    if request_id is not None:
        body["request_id"] = request_id
    data = json.dumps(body).encode()
    handler.send_response(503)
    handler.send_header("Retry-After",
                        str(max(1, int(math.ceil(retry_after)))))
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(data)))
    handler.end_headers()
    handler.wfile.write(data)
