"""Elastic, preemption-tolerant training: generations, survivor
barriers, respawn supervision, and a falsifiable scaling model.

The reference master survived slave loss with a blacklist/respawn
plane (veles/server.py:384-394, 637-655): a dead slave's jobs were
re-served and the node was either respawned over SSH or blacklisted.
The SPMD equivalent has no per-slave jobs to re-serve — the modern
answer is **generations**: a run is a sequence of generations, each
executing under the current world size. On detected host loss
(heartbeat lapse, coordinator-join failure, or an injected
``distributed.host_loss`` fault) or host gain, the coordinator
declares a new generation, the survivors reach a barrier,
``jax.distributed`` reinitializes with the new topology, and state
resumes from the newest valid checkpoint in the chain
(:func:`~veles_tpu.resilience.checkpoint_chain.restore_latest`) with
params/optimizer state resharded onto the new mesh.

Resharding is free by construction: the snapshot layout contract is
**device-count-agnostic** — ``collect_state`` all-gathers every
cross-process shard to host numpy (unsharded logical trees), and
``apply_state`` device_puts them back through each unit's own sharding
on whatever mesh the new generation built. A snapshot taken at N=4
restores at N=2 or N=8 with identical forward logits
(tests/test_elastic.py locks this).

Data order stays deterministic per generation: the chain manifest
carries an ``{epoch, step, world_size}`` cursor
(:func:`~veles_tpu.resilience.checkpoint_chain.cursor_of`), and the
loader's shuffle indices re-derive from the restored PRNG streams +
epoch cursor — so a run interrupted mid-epoch resumes at the last
epoch boundary and converges to the same state tree as an
uninterrupted run (the psum-DP equivalence proven 1→64 in
SCALING.json makes this hold across world-size changes too).

Two halves:

- :class:`ElasticController` — the in-process generation loop a
  launcher runs under ``--elastic`` /
  ``root.common.resilience.elastic.enabled``;
- :class:`Supervisor` — the respawn plane for multi-process jobs: it
  watches the worker processes of a generation, and when one dies
  (preemption, injected crash) it reaps the survivors (wedged in
  collectives), shrinks — or regrows — the world, and respawns the
  next generation. This is the reference's blacklist/respawn loop
  with checkpoint-restart instead of job re-serving.

The **falsifiable scaling model** (:func:`predict_step_time`) predicts
data-parallel step time at any world size N from two stated inputs:
the gradient psum bytes a step moves (ring all-reduce wire cost,
``2·(N-1)/N · grad_bytes`` per chip) and the assumed per-chip ICI
bandwidth (:data:`~veles_tpu.telemetry.cost.ICI_BW_BYTES`).
``scripts/scaling_sweep.py`` stamps predicted-vs-measured step time
per workflow into SCALING.json so any future chip allocation confirms
or refutes the model in one run.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..config import root
from ..error import DistributedCommunicationError, VelesError
from ..logger import Logger
from ..telemetry.counters import inc
from .faults import FaultInjected, fire
from .health import heartbeats

#: exit code a survivor uses to hand control back to the respawn plane
#: (distinct from faults.CRASH_EXIT_CODE=42, the slave-death code: a
#: 43 means "I am healthy but the generation is over — respawn me")
GENERATION_EXIT_CODE = 43

#: heartbeat-name prefix the elastic plane watches (one entry per
#: participating host process, beaten by the armed train step)
HOST_BEAT_PREFIX = "host:"

#: env var the respawn plane exports so a respawned worker's
#: generation numbering (gauges, manifest cursor, logs) continues from
#: the job's true generation instead of restarting at 1 — the
#: Supervisor sets it before every spawn; schedulers doing their own
#: respawn should too
GENERATION_ENV = "VELES_ELASTIC_GENERATION"


def base_generation() -> int:
    """The generation this process's controller starts counting from:
    :data:`GENERATION_ENV` when the respawn plane exported it, else 1."""
    try:
        return max(1, int(os.environ.get(GENERATION_ENV, "1")))
    except ValueError:
        return 1

#: every counter this module increments — registered with HELP strings
#: in telemetry.counters.DESCRIPTIONS; ``bench.py gate``'s elastic
#: section asserts zero leakage in non-elastic runs
ELASTIC_COUNTERS = (
    "veles_elastic_generations_total",
    "veles_elastic_preemptions_total",
    "veles_elastic_reshard_seconds_total",
    "veles_elastic_barrier_timeouts_total",
)


class HostLostError(VelesError):
    """A participating host was declared lost (heartbeat lapse,
    coordinator-join failure, or an injected ``distributed.host_loss``
    fault) — the current generation is over."""


# -- gauge state (both /metrics surfaces render it) ----------------------

_lock = threading.Lock()
_state: Dict[str, Any] = {
    "enabled": False, "generation": 0, "world_size": 0,
    "last_reshard_s": 0.0, "min_hosts": 1,
}


def _set_state(**kv: Any) -> None:
    with _lock:
        _state.update(kv)


def state() -> Dict[str, Any]:
    with _lock:
        return dict(_state)


def gauges() -> Dict[str, Any]:
    """Elastic gauges for the /metrics surfaces (web_status and the
    GenerationAPI port). No rows at all until the elastic plane was
    enabled — non-elastic processes keep a clean scrape page."""
    st = state()
    if not st["enabled"]:
        return {}
    return {
        "veles_elastic_generation":
            (st["generation"], "Current elastic training generation"),
        "veles_elastic_world_size":
            (st["world_size"],
             "Host processes participating in the current generation"),
        "veles_elastic_last_reshard_seconds":
            (round(st["last_reshard_s"], 6),
             "Restore+reshard time of the latest generation handoff"),
        "veles_elastic_min_hosts":
            (st["min_hosts"],
             "Floor below which the elastic run refuses to continue"),
    }


def config() -> Dict[str, Any]:
    """The elastic knob block ``root.common.resilience.elastic.*``
    (CLI: ``--elastic`` flips ``enabled``)."""
    node = root.common.resilience.elastic
    return {
        "enabled": bool(node.get("enabled", False)),
        "min_hosts": int(node.get("min_hosts", 1) or 1),
        "generation_timeout": float(
            node.get("generation_timeout", 60.0) or 60.0),
        "max_generations": int(node.get("max_generations", 8) or 8),
    }


def enabled() -> bool:
    return config()["enabled"]


# -- detection -----------------------------------------------------------

def check_hosts(registry=heartbeats) -> None:
    """One host-loss probe: fires the ``distributed.host_loss``
    injection point (an armed ``raise`` simulates a preempted peer,
    ``crash`` kills this process like a real preemption) and checks
    every ``host:*`` heartbeat for lapse. Raises :class:`HostLostError`
    on either signal; the armed train step calls this per dispatch when
    the elastic plane is on.

    The lapse check covers **locally registered** host beats only (the
    registry is process-local): this process's own participants, or
    peer liveness a sidecar feeds in via
    ``health.heartbeats.beat("host:<n>", timeout=...)``. Remote-peer
    death with no such feed surfaces through the other two signals —
    the collective failure a dead peer causes mid-step, and the
    respawn plane's process watch (:class:`Supervisor`)."""
    try:
        fire("distributed.host_loss")
    except FaultInjected as e:
        raise HostLostError(
            "injected host loss (distributed.host_loss)") from e
    # prefix-filtered age probe — this runs per train-step dispatch,
    # so it must not materialize the whole registry status each call
    stale = registry.stale(HOST_BEAT_PREFIX)
    if stale:
        # the loss is hereby DECLARED: drop the lapsed entries so the
        # next generation starts clean instead of instantly re-raising
        # on the same stale beat — a host that comes back re-registers
        # itself with its first fresh beat
        for name in stale:
            registry.unregister(name)
        raise HostLostError(
            "host heartbeat(s) lapsed: %s" % ", ".join(sorted(stale)))


def generation_barrier(generation: int,
                       timeout: Optional[float] = None) -> int:
    """All survivors agree on the coordinator's generation index before
    any of them touches the checkpoint chain. Fires the
    ``distributed.generation_barrier`` injection point; a barrier that
    raises (injected, or a real collective failure — a dead peer shows
    up here first) OR overruns ``timeout`` (the collective itself has
    none: a dead peer simply never arrives, so the wait runs on a
    watchdog thread that is abandoned on overrun — the process hands
    off to the respawn plane right after) is counted in
    ``veles_elastic_barrier_timeouts_total`` and raised as
    :class:`HostLostError`. Returns the agreed generation index."""
    from ..parallel import distributed

    def _barrier() -> int:
        fire("distributed.generation_barrier")
        return distributed.survivor_barrier(generation)

    try:
        if not timeout or timeout <= 0:
            return _barrier()
        outcome: Dict[str, Any] = {}

        def _run() -> None:
            try:
                outcome["value"] = _barrier()
            except BaseException as e:   # noqa: BLE001 — re-raised below
                outcome["error"] = e

        worker = threading.Thread(target=_run, daemon=True,
                                  name="elastic-generation-barrier")
        worker.start()
        worker.join(timeout)
        if worker.is_alive():
            inc("veles_elastic_barrier_timeouts_total")
            raise HostLostError(
                "generation %d barrier timed out after %.0fs — a dead "
                "peer never arrives at the collective" % (generation,
                                                          timeout))
        if "error" in outcome:
            raise outcome["error"]
        return outcome["value"]
    except HostLostError:
        raise                           # timeout above: already counted
    except (FaultInjected, DistributedCommunicationError,
            RuntimeError) as e:
        inc("veles_elastic_barrier_timeouts_total")
        raise HostLostError(
            "generation %d barrier failed%s: %s"
            % (generation,
               "" if timeout is None else " (timeout %.0fs)" % timeout,
               e)) from e


# -- the in-process generation loop --------------------------------------

class ElasticController(Logger):
    """Wraps a launcher's run in generations.

    Each generation: survivors reach the barrier, the newest valid
    checkpoint is restored (resharded onto the current mesh by
    ``apply_state``'s ordinary device_put path), and training runs
    until it completes or a host is lost. Host loss in a
    single-process job (virtual mesh, injected faults) continues
    in-process; in a multi-process job the controller exits with
    :data:`GENERATION_EXIT_CODE` so the respawn plane
    (:class:`Supervisor`, or the pod scheduler) rebuilds the job with
    the surviving topology — a process cannot change its own
    ``jax.distributed`` world from inside a wedged collective.
    """

    def __init__(self, launcher) -> None:
        super().__init__()
        self._launcher = launcher
        cfg = config()
        self.min_hosts = cfg["min_hosts"]
        self.generation_timeout = cfg["generation_timeout"]
        self.max_generations = cfg["max_generations"]

    def run(self) -> Dict[str, Any]:
        from ..parallel import distributed
        world = distributed.process_count()
        _set_state(enabled=True, world_size=world,
                   min_hosts=self.min_hosts)
        if world < self.min_hosts:
            # refuse BEFORE training a generation the floor forbids
            raise HostLostError(
                "cannot start an elastic run at world size %d: "
                "min_hosts=%d" % (world, self.min_hosts))
        try:
            return self._generations(world)
        finally:
            # services (beacon, graphics, final redraws) are torn down
            # once per JOB, not per generation — see Launcher.run(
            # keep_services=True)
            finalize = getattr(self._launcher, "finalize_services",
                               None)
            if callable(finalize):
                finalize()

    def _generations(self, world: int) -> Dict[str, Any]:
        from ..parallel import distributed
        self._last_loss: Optional[BaseException] = None
        # a respawned worker continues the job's generation numbering
        # (the respawn plane exports GENERATION_ENV) — gauges, cursor
        # logs and the manifest all tell the operator the truth
        generation = base_generation()
        for _attempt in range(self.max_generations):
            distributed.set_generation(generation)
            _set_state(generation=generation, world_size=world)
            inc("veles_elastic_generations_total")
            try:
                # a failed barrier (injected, or survivors noticing a
                # peer died between spawn and agreement) ends the
                # generation like any other host loss — never the
                # whole run (generation_barrier converts collective
                # errors itself)
                agreed = generation_barrier(
                    generation, timeout=self.generation_timeout)
                if agreed != generation:
                    # this worker missed generation declarations (a
                    # scheduler respawned it without GENERATION_ENV):
                    # adopt the coordinator's numbering everywhere —
                    # gauges, cursor, logs
                    self.warning(
                        "adopting coordinator generation %d (local "
                        "view was %d)", agreed, generation)
                    generation = agreed
                    distributed.set_generation(agreed)
                    _set_state(generation=agreed)
            except HostLostError as e:
                generation = self._lost(generation, world, e)
                continue
            # EVERY generation restores from the chain — keyed on
            # checkpoint existence, not on the generation index: a
            # respawned worker resumes the job's newest state even if
            # the original argv carried --snapshot (an empty chain is
            # a cheap no-op). Sole exception: a genuinely FRESH job
            # (generation 1 by every signal) whose workflow the caller
            # already restored explicitly — that choice wins once.
            # Restore runs OUTSIDE the preemption handlers: a
            # deterministic restore failure (e.g. OOM resharding onto
            # a shrunken mesh) is a real error, not a host loss to
            # respawn max_generations times.
            fresh_job = generation == 1 and base_generation() == 1
            already = bool(getattr(self._launcher.workflow,
                                   "restored_from_snapshot", False))
            if not (fresh_job and already):
                self._restore(generation,
                              initial=generation == base_generation())
            try:
                results = self._launcher.run(keep_services=True)
                results["elastic_generations"] = generation
                return results
            except HostLostError as e:
                # single process: the survivor IS the job (world stays
                # 1, and the floor was enforced before generation 1) —
                # declare the next generation and keep training from
                # the newest valid checkpoint
                generation = self._lost(generation, world, e)
            except (DistributedCommunicationError, RuntimeError) as e:
                # a collective blew up mid-step: in a multi-process job
                # the likeliest cause is a dead peer (gloo surfaces it
                # as a runtime error on the survivors) — that IS a
                # preemption, hand off to the respawn plane. On a
                # single host a RuntimeError is a real bug: re-raise.
                if world <= 1:
                    raise
                self._last_loss = e
                inc("veles_elastic_preemptions_total")
                self.warning(
                    "generation %d collective failure (%s: %s) — "
                    "treating as host loss, handing off to the "
                    "respawn plane (exit %d)", generation,
                    type(e).__name__, e, GENERATION_EXIT_CODE)
                raise SystemExit(GENERATION_EXIT_CODE)
        raise HostLostError(
            "elastic run did not complete within %d generation(s); "
            "last loss: %s" % (self.max_generations, self._last_loss))

    def _lost(self, generation: int, world: int,
              e: HostLostError) -> int:
        """Account one host loss; returns the next generation to
        declare (single process) or hands off to the respawn plane
        (multi-process)."""
        self._last_loss = e
        inc("veles_elastic_preemptions_total")
        self.warning("generation %d lost a host: %s", generation, e)
        if world > 1:
            # multi-process: the respawn plane owns topology — exit
            # with the generation code so the Supervisor (or
            # scheduler) rebuilds the job at the surviving world size
            # from the newest valid checkpoint
            self.warning("handing off to the respawn plane (exit %d)",
                         GENERATION_EXIT_CODE)
            raise SystemExit(GENERATION_EXIT_CODE)
        return generation + 1

    def _restore(self, generation: int, initial: bool = False) -> None:
        """Generation handoff: newest valid checkpoint → current mesh.
        Timed into ``veles_elastic_reshard_seconds_total`` (the gate
        bounds it); the manifest cursor is logged so operators see
        exactly where the new generation resumes. ``initial`` marks
        the first generation this process declares — an empty chain is
        then a fresh start, not a lost checkpoint."""
        from .checkpoint_chain import latest_cursor
        t0 = time.time()
        restored = self._launcher.try_restore_latest()
        dt = time.time() - t0
        inc("veles_elastic_reshard_seconds_total", dt)
        _set_state(last_reshard_s=dt)
        if restored:
            directory = getattr(self._launcher, "_last_restore_dir",
                                None) or root.common.dirs.snapshots
            prefix = getattr(self._launcher, "_last_restore_prefix",
                             "wf")
            found = (latest_cursor(directory, prefix)
                     if directory else None)
            if found is not None:
                path, cur = found
                self.info(
                    "generation %d resumes from %s (epoch=%d step=%d, "
                    "snapshot world_size=%d) in %.2fs", generation,
                    path, cur["epoch"], cur["step"], cur["world_size"],
                    dt)
            else:
                self.info("generation %d resumed from newest valid "
                          "checkpoint in %.2fs", generation, dt)
        elif initial:
            self.debug("generation %d starts with an empty chain "
                       "(fresh job)", generation)
        else:
            self.warning(
                "generation %d found no valid checkpoint — continuing "
                "from live in-memory state (determinism vs an "
                "uninterrupted run is only guaranteed from a "
                "checkpoint)", generation)


# -- the respawn plane ---------------------------------------------------

class Supervisor(Logger):
    """Elastic respawn plane for multi-process jobs — the modern
    blacklist/respawn loop (reference veles/server.py:384-394,
    637-655): spawn a generation's worker processes, watch them, and
    when one dies reap the survivors (wedged in collectives), shrink
    or regrow the world, and respawn from the newest valid checkpoint.

    ``spawn(generation, world_size) -> [subprocess.Popen]`` builds one
    generation (the caller owns argv/env — coordinator port, process
    ids, snapshot dir). Worker exits are classified:

    - all zero → the job completed: done;
    - :data:`GENERATION_EXIT_CODE` → a healthy survivor handing
      control back: respawned, world unchanged (unless peers died);
    - anything else (crash code, SIGKILL) → a lost host: the world
      shrinks by the number of losses, or regrows to ``target_world``
      when ``regrow`` is set (a preempted host coming back is the
      "gain" leg of elasticity).
    """

    def __init__(self, spawn: Callable[[int, int], List[Any]],
                 world_size: int, min_hosts: Optional[int] = None,
                 max_generations: Optional[int] = None,
                 regrow: bool = False, poll_interval: float = 0.2,
                 reap_timeout: float = 30.0,
                 generation_deadline: float = 0.0) -> None:
        super().__init__()
        cfg = config()
        self._spawn = spawn
        self.target_world = int(world_size)
        self.min_hosts = int(cfg["min_hosts"] if min_hosts is None
                             else min_hosts)
        self.max_generations = int(
            cfg["max_generations"] if max_generations is None
            else max_generations)
        self.regrow = bool(regrow)
        self.poll_interval = float(poll_interval)
        self.reap_timeout = float(reap_timeout)
        #: wall-clock bound on ONE generation (0 = unbounded). The
        #: hang class this covers: a network-partitioned host whose
        #: process stays alive — no peer exits, so exit-code watching
        #: alone would block the respawn plane forever. Overrun reaps
        #: the wedged generation and respawns it (counted preemption).
        self.generation_deadline = float(generation_deadline or 0.0)
        self.generation = 0
        self.world = int(world_size)

    def run(self) -> int:
        saved = os.environ.get(GENERATION_ENV)
        try:
            return self._run()
        finally:
            if saved is None:
                os.environ.pop(GENERATION_ENV, None)
            else:
                os.environ[GENERATION_ENV] = saved

    def _run(self) -> int:
        _set_state(enabled=True, min_hosts=self.min_hosts)
        for generation in range(1, self.max_generations + 1):
            self.generation = generation
            _set_state(generation=generation, world_size=self.world)
            inc("veles_elastic_generations_total")
            self.info("generation %d: spawning %d host process(es)",
                      generation, self.world)
            # exported BEFORE spawn so workers inherit it (directly, or
            # through the dict(os.environ) copy spawn callbacks build):
            # their controllers then number generations from the job's
            # truth and the veles_elastic_generation gauge climbs with
            # real preemptions
            os.environ[GENERATION_ENV] = str(generation)
            procs = list(self._spawn(generation, self.world))
            lost, restart = self._watch(procs)
            if lost == 0 and restart == 0:
                self.info("elastic job completed in generation %d "
                          "(world %d)", generation, self.world)
                return generation
            inc("veles_elastic_preemptions_total")
            survivors = self.world - lost
            self.warning(
                "generation %d over: %d host(s) lost, %d survivor "
                "restart(s); world %d -> %d", generation, lost,
                restart, self.world,
                self.target_world if self.regrow else survivors)
            self.world = self.target_world if self.regrow else survivors
            if self.world < self.min_hosts:
                raise HostLostError(
                    "world shrank to %d host(s), below min_hosts=%d"
                    % (self.world, self.min_hosts))
        raise HostLostError(
            "elastic job did not complete within %d generation(s)"
            % self.max_generations)

    def _watch(self, procs: List[Any]):
        """Block until the generation resolves. Returns
        ``(lost, restart)``: hosts that died vs healthy survivors. The
        first non-clean exit ends the generation — the rest are reaped
        (a survivor of a dead peer is wedged in a collective and will
        never finish on its own). Classification: a process that died
        by itself with a code other than 0/:data:`GENERATION_EXIT_CODE`
        is a lost host; one that exited with the generation code OR
        that the supervisor had to kill is a healthy survivor — its
        host is fine, only the wedged process was reaped. When
        ``generation_deadline`` is set, a generation with NO exit
        signal at all (every process wedged — a partitioned peer whose
        process stays alive) is reaped at the deadline instead of
        blocking the respawn plane forever."""
        deadline = (time.time() + self.generation_deadline
                    if self.generation_deadline > 0 else None)
        while True:
            codes = [p.poll() for p in procs]
            if all(c == 0 for c in codes):
                return 0, 0
            overdue = deadline is not None and time.time() >= deadline
            if overdue and not any(c is not None and c != 0
                                   for c in codes):
                self.warning(
                    "generation deadline %.0fs exceeded with %d "
                    "process(es) still running and no exit signal — "
                    "reaping the wedged generation",
                    self.generation_deadline,
                    sum(1 for c in codes if c is None))
            if overdue or any(c is not None and c != 0 for c in codes):
                reaped = self._reap(procs)
                codes = [p.poll() for p in procs]
                lost = sum(
                    1 for i, c in enumerate(codes)
                    if c not in (0, GENERATION_EXIT_CODE)
                    and i not in reaped)
                restart = sum(
                    1 for i, c in enumerate(codes)
                    if c == GENERATION_EXIT_CODE or i in reaped)
                # everyone finished cleanly during the reap grace: the
                # generation actually completed
                return lost, restart
            time.sleep(self.poll_interval)

    def _reap(self, procs: List[Any]):
        """Give survivors a grace window to exit on their own
        (GENERATION_EXIT_CODE), then kill the rest. Returns the
        indices of processes the supervisor killed — reaped survivors,
        not lost hosts."""
        deadline = time.time() + self.reap_timeout
        while time.time() < deadline:
            if all(p.poll() is not None for p in procs):
                return set()
            time.sleep(self.poll_interval)
        killed = set()
        for i, p in enumerate(procs):
            if p.poll() is None:
                try:
                    p.kill()
                    killed.add(i)
                except OSError:
                    pass
        for p in procs:
            try:
                p.wait(timeout=self.reap_timeout)
            except Exception:       # noqa: BLE001 — already killed
                pass
        return killed


# -- the falsifiable scaling model ---------------------------------------

def psum_bytes_per_step(grad_bytes: float, n: int) -> float:
    """Per-chip wire bytes one data-parallel step moves through the
    gradient psum at world size ``n`` — the ring all-reduce cost
    ``2·(N-1)/N · grad_bytes`` (reduce-scatter + all-gather), the
    comms model of the TPU linear-algebra-at-scale literature
    (PAPERS.md). 0 at N=1: no psum is emitted."""
    if n <= 1:
        return 0.0
    return 2.0 * (n - 1) / n * float(grad_bytes)


def predict_step_time(t1_step_s: float, grad_bytes: float, n: int,
                      ici_bw: Optional[float] = None,
                      device_kind: Optional[str] = None
                      ) -> Dict[str, Any]:
    """Predicted data-parallel step time at world size ``n``:

        t_pred(N) = t1_compute / N  +  psum_bytes(N) / ici_bw

    with every input STATED in the returned record — the point is
    falsifiability: any future chip allocation measures one run and
    either confirms the prediction or refutes an input (the measured
    single-chip step time, the gradient bytes, or the assumed ICI
    bandwidth). ``ici_bw`` defaults to the chip's entry in
    :data:`~veles_tpu.telemetry.cost.ICI_BW_BYTES`."""
    from ..telemetry.cost import ici_bandwidth
    if ici_bw is None:
        ici_bw = ici_bandwidth(device_kind)
    psum = psum_bytes_per_step(grad_bytes, n)
    compute_s = float(t1_step_s) / max(1, int(n))
    comm_s = psum / float(ici_bw) if ici_bw else 0.0
    return {
        "n": int(n),
        "predicted_step_s": compute_s + comm_s,
        "compute_s": compute_s,
        "comm_s": comm_s,
        "inputs": {
            "t1_step_s": float(t1_step_s),
            "grad_bytes": float(grad_bytes),
            "psum_bytes_per_step": psum,
            "ici_bw_bytes_per_s": float(ici_bw),
        },
    }
