"""Retry policy engine: exponential backoff + full jitter.

The reference's I/O paths assumed a LAN (single ``urlopen``, no
timeout, Twisted reconnect loops hidden in the transport); production
multi-host runs retry instead. One policy object carries the whole
contract — attempt cap, backoff curve, deadline, retryable-exception
predicate — and is applied as a decorator, via :meth:`RetryPolicy.call`,
or as the context-manager loop :meth:`RetryPolicy.attempts`:

    policy = RetryPolicy(name="download", max_attempts=5)

    @policy
    def fetch(): ...

    policy.call(fetch)

    for attempt in policy.attempts():
        with attempt:
            fetch()

Backoff before retry ``n`` (1-based) is ``min(max_delay,
base_delay * 2**(n-1))``, scaled by full jitter — uniform in [0, raw)
drawn from the PRNG-seeded ``retry`` stream, so herds decorrelate but
seeded runs reproduce. Every performed retry increments
``veles_retries_total``; exhaustion re-raises the last exception
unchanged (callers keep their own error types).
"""

from __future__ import annotations

import functools
import time
from typing import Callable, Optional, Tuple, Type

from ..config import root
from ..error import VelesError
from ..logger import Logger
from ..telemetry.counters import inc


class TransientError(VelesError):
    """An error the raiser knows is safe to retry (e.g. a truncated
    download whose .part file was already deleted) — default policies
    treat it as retryable alongside OSError."""


DEFAULT_RETRYABLE: Tuple[Type[BaseException], ...] = (OSError,
                                                      TransientError)


class RetryPolicy(Logger):
    """See module doc. ``sleep``/``clock``/``rng`` are injectable for
    deterministic tests (fake clock, pinned jitter)."""

    def __init__(self, max_attempts: Optional[int] = None,
                 base_delay: Optional[float] = None,
                 max_delay: Optional[float] = None,
                 deadline: Optional[float] = None,
                 retryable: Tuple[Type[BaseException], ...]
                 = DEFAULT_RETRYABLE,
                 retry_if: Optional[Callable[[BaseException], bool]]
                 = None,
                 jitter: bool = True, name: str = "retry",
                 sleep: Optional[Callable[[float], None]] = None,
                 clock: Optional[Callable[[], float]] = None,
                 rng: Optional[Callable[[], float]] = None) -> None:
        super().__init__()
        cfg = root.common.resilience.get("retry")
        cfg = cfg.as_dict() if cfg is not None and hasattr(
            cfg, "as_dict") else (cfg or {})
        self.max_attempts = int(max_attempts if max_attempts is not None
                                else cfg.get("max_attempts", 4))
        self.base_delay = float(base_delay if base_delay is not None
                                else cfg.get("base_delay", 0.5))
        self.max_delay = float(max_delay if max_delay is not None
                               else cfg.get("max_delay", 30.0))
        #: wall-clock budget from the FIRST attempt; a retry whose
        #: backoff would overrun it re-raises instead of sleeping
        self.deadline = deadline
        self.retryable = tuple(retryable)
        self.retry_if = retry_if
        self.jitter = jitter
        self.name = name
        self._sleep = sleep if sleep is not None else time.sleep
        self._clock = clock if clock is not None else time.monotonic
        self._rng = rng

    # -- math ----------------------------------------------------------------
    def _random(self) -> float:
        if self._rng is not None:
            return float(self._rng())
        from .. import prng
        return float(prng.get("retry", ephemeral=True).rand())

    def backoff(self, attempt: int) -> float:
        """Delay before retry ``attempt`` (1-based)."""
        raw = min(self.max_delay, self.base_delay * (2.0 ** (attempt - 1)))
        return raw * self._random() if self.jitter else raw

    def is_retryable(self, exc: BaseException) -> bool:
        return isinstance(exc, self.retryable) and (
            self.retry_if is None or bool(self.retry_if(exc)))

    def _admit_retry(self, attempt: int, start: float,
                     exc: BaseException) -> bool:
        """Decide+perform the wait before retry ``attempt``; False means
        the caller must re-raise (budget exhausted / not retryable)."""
        if not self.is_retryable(exc):
            return False
        if attempt >= self.max_attempts:
            return False
        delay = self.backoff(attempt)
        if self.deadline is not None and \
                self._clock() - start + delay > self.deadline:
            return False
        inc("veles_retries_total")
        self.warning("%s: attempt %d/%d failed (%s: %s) — retrying in "
                     "%.2fs", self.name, attempt, self.max_attempts,
                     type(exc).__name__, exc, delay)
        self._sleep(delay)
        return True

    # -- application forms ---------------------------------------------------
    def call(self, fn: Callable, *args, **kwargs):
        start = self._clock()
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(*args, **kwargs)
            except Exception as exc:   # noqa: BLE001 — filtered below
                if not self._admit_retry(attempt, start, exc):
                    raise

    def __call__(self, fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, **kwargs)
        wrapped.retry_policy = self
        return wrapped

    def attempts(self):
        """Context-manager loop: each yielded attempt swallows a
        retryable exception (after the backoff sleep) until the budget
        runs out, then lets it propagate; a clean exit ends the loop."""
        start = self._clock()
        state = {"done": False}
        for number in range(1, self.max_attempts + 1):
            yield _Attempt(self, number, start, state)
            if state["done"]:
                return


class _Attempt:
    __slots__ = ("_policy", "number", "_start", "_state")

    def __init__(self, policy: RetryPolicy, number: int, start: float,
                 state: dict) -> None:
        self._policy = policy
        self.number = number
        self._start = start
        self._state = state

    def __enter__(self) -> "_Attempt":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is None:
            self._state["done"] = True
            return False
        return self._policy._admit_retry(self.number, self._start, exc)
