"""Array: host-numpy + device-jax pair with explicit coherence.

Equivalent of the reference's veles/memory.py:56-512 (``Array`` with the
map_read/map_write/map_invalidate/unmap protocol and the device-memory
``Watcher``). TPU-first redesign: ``jax.Array`` is immutable, so instead of
mapped pointers the coherence protocol tracks *which side is newer*:

- ``map_read()``  → make ``mem`` (numpy) current (device→host sync if needed);
- ``map_write()`` → same, then mark host as the newer side;
- ``assign_devmem(x)`` → a jitted step produced a new device array; device
  side becomes the newer one (zero-copy, no host sync until someone reads);
- ``device_view(sharding=None)`` → jax.Array for tracing/compute, pushing
  host→device if host is newer (sharded placement via ``jax.device_put``).

This preserves the reference's key property — snapshots and host-side units
always observe coherent data (veles/memory.py:284-292 synced device→host on
pickle) — while keeping steady-state training entirely on device.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

import numpy

from .error import Bug
from .logger import Logger


class Watcher:
    """Device-memory accounting (reference: veles/memory.py:56-107)."""

    lock = threading.Lock()
    total = 0
    peak = 0
    per_name: Dict[str, int] = {}

    @classmethod
    def add(cls, name: str, nbytes: int) -> None:
        with cls.lock:
            cls.total += nbytes
            cls.peak = max(cls.peak, cls.total)
            cls.per_name[name] = cls.per_name.get(name, 0) + nbytes

    @classmethod
    def sub(cls, name: str, nbytes: int) -> None:
        with cls.lock:
            cls.total -= nbytes
            cls.per_name[name] = cls.per_name.get(name, 0) - nbytes

    @classmethod
    def reset(cls) -> None:
        with cls.lock:
            cls.total = cls.peak = 0
            cls.per_name.clear()


class Array(Logger):
    """Host/device tensor pair (reference: veles/memory.py:110)."""

    def __init__(self, data: Any = None, shape: Tuple[int, ...] = None,
                 dtype: Any = numpy.float32, name: str = "") -> None:
        super().__init__()
        self.name = name
        self._lock = threading.RLock()
        self.mem: Optional[numpy.ndarray] = None
        self.devmem = None          # jax.Array | None
        self._host_newer = False
        self._dev_newer = False
        self._accounted = 0
        if data is not None:
            self.reset(numpy.asarray(data))
        elif shape is not None:
            self.reset(numpy.zeros(shape, dtype=dtype))

    # -- shape/dtype passthrough --------------------------------------------
    @property
    def shape(self):
        return self.mem.shape if self.mem is not None else None

    @property
    def dtype(self):
        return self.mem.dtype if self.mem is not None else None

    @property
    def nbytes(self) -> int:
        return self.mem.nbytes if self.mem is not None else 0

    def __bool__(self) -> bool:
        return self.mem is not None

    def __len__(self) -> int:
        return len(self.mem) if self.mem is not None else 0

    def __getitem__(self, idx):
        self.map_read()
        return self.mem[idx]

    def __setitem__(self, idx, value):
        self.map_write()
        self.mem[idx] = value

    # -- lifecycle ----------------------------------------------------------
    def reset(self, data: Optional[numpy.ndarray] = None) -> "Array":
        """(Re)bind host storage, dropping any device copy
        (reference: veles/memory.py:323-345)."""
        with self._lock:
            self._drop_devmem()
            self.mem = data
            self._host_newer = data is not None
            self._dev_newer = False
        return self

    def initialize(self, device=None) -> None:
        """Attach to a device; actual placement is lazy via device_view
        (reference eagerly created cl/cuda buffers, veles/memory.py:347)."""
        # retained for API parity with the reference unit contract

    # -- coherence protocol -------------------------------------------------
    def map_read(self) -> numpy.ndarray:
        with self._lock:
            if self._dev_newer:
                if getattr(self.devmem, "is_deleted", lambda: False)():
                    raise Bug(
                        "Array %s: device buffer was deleted (donated to a "
                        "jitted step?) before host sync" % self.name)
                host = numpy.asarray(self.devmem)  # may be a read-only view
                if self.mem is not None and host.dtype != self.mem.dtype:
                    host = host.astype(self.mem.dtype)
                self.mem = host
                self._dev_newer = False
            return self.mem

    def map_write(self) -> numpy.ndarray:
        mem = self.map_read()
        if mem is not None and not mem.flags.writeable:
            # device→host adoption yields read-only views; writers get a copy
            mem = self.mem = mem.copy()
        self._host_newer = True
        return mem

    def map_invalidate(self) -> numpy.ndarray:
        """Host will fully overwrite; skip device→host sync
        (reference: veles/memory.py:379)."""
        with self._lock:
            self._dev_newer = False
            self._host_newer = True
            return self.mem

    def unmap(self) -> None:
        """No-op kept for API parity (jax has no mapped pointers)."""

    def detach_devmem(self) -> None:
        """Forget the device copy, keeping the current host mirror as
        canonical. Used when another owner (e.g. a fused step's parameter
        pytree) takes over the device side and may donate those buffers."""
        with self._lock:
            if self._dev_newer:
                self.map_read()
            self._drop_devmem()
            self._host_newer = self.mem is not None

    def assign_devmem(self, devmem) -> None:
        """Adopt a device array produced by a jitted step (device becomes the
        newer side; no host transfer until map_read)."""
        with self._lock:
            self._account(devmem)
            self.devmem = devmem
            self._dev_newer = True
            self._host_newer = False

    def device_view(self, device=None, sharding=None, dtype=None):
        """The jax.Array for compute, pushing host data if it is newer (or
        cached under a different sharding)."""
        import jax
        with self._lock:
            stale = (
                self.devmem is not None
                and ((sharding is not None and getattr(
                    self.devmem, "sharding", None) != sharding)
                     or (dtype is not None
                         and self.devmem.dtype != numpy.dtype(dtype))))
            if stale and self._dev_newer:
                self.map_read()  # pull newest to host before re-placing
            if self.devmem is None or self._host_newer or stale:
                if self.mem is None:
                    raise Bug("Array %s: device_view before reset" %
                              self.name)
                src = self.mem if dtype is None else self.mem.astype(dtype)
                # ALWAYS copy the staging buffer: on host-backed platforms
                # jax.device_put can be zero-copy, and a later in-place
                # host mutation (e.g. the loader refilling minibatch
                # indices) would race with the async computation still
                # reading this memory
                if src is self.mem:
                    src = numpy.array(src)
                dev = (jax.device_put(src, sharding) if sharding is not None
                       else jax.device_put(src))
                self._account(dev)
                self.devmem = dev
                self._host_newer = False
            return self.devmem

    def __del__(self) -> None:
        try:
            self._drop_devmem()
        except Exception:
            pass

    def _drop_devmem(self) -> None:
        if self.devmem is not None and self._accounted:
            Watcher.sub(self.name or "anon", self._accounted)
            self._accounted = 0
        self.devmem = None

    def _account(self, dev) -> None:
        nbytes = getattr(dev, "nbytes", 0)
        if self._accounted:
            Watcher.sub(self.name or "anon", self._accounted)
        Watcher.add(self.name or "anon", nbytes)
        self._accounted = nbytes

    # -- pickling (reference: veles/memory.py:284-299) ----------------------
    def __getstate__(self):
        self.map_read()
        return {"name": self.name, "mem": self.mem}

    def __setstate__(self, state):
        Logger.__init__(self)
        self.name = state["name"]
        self._lock = threading.RLock()
        self.mem = state["mem"]
        self.devmem = None
        self._host_newer = self.mem is not None
        self._dev_newer = False
        self._accounted = 0

    def __repr__(self) -> str:
        return "<Array %r %s %s host_newer=%s dev_newer=%s>" % (
            self.name, self.shape, self.dtype, self._host_newer,
            self._dev_newer)
