"""int8 KV-cache storage for the serving slot pool.

The continuous engine's pool is ``max_slots × max_context`` rows per
block — HBM-resident for the life of the server, sized for the worst
case, mostly cold. Storing it int8 with per-slot, per-position scales
halves that residency vs bf16 (4× vs f32) at the same ``max_slots``:

    float block:  (S, T, H, Dh) ck + cv                 — dtype bytes
    int8  block:  (S, T, H, Dh) int8 ck + cv
                  + (S, T) f32 k/v scale sidecars       — ~1 byte + ε

One scale per cached POSITION is the lossless-bookkeeping choice for
an append-only cache: prefill fixes the scales of the prompt rows in
one pass, each decode step writes exactly one new row with its own
fresh scale, and no already-written row is ever re-scaled — so there
is no error accumulation across steps, only the one-time rounding of
each row at write time. Dequant-on-read happens inside the jitted
step (``ops.precision.dequantize_rows_int8``); XLA fuses it into the
attention reads, so the MXU math — and the masking, and the PRNG —
is byte-for-byte the float engine's.

The numeric core lives in ``ops/precision.py``; this module owns the
pool *shapes* so the engine and its tests agree on the layout.
"""

from __future__ import annotations

from typing import Tuple

from ..ops.precision import (dequantize_rows_int8,  # noqa: F401
                             quantize_rows_int8)


def block_pool(max_slots: int, max_context: int, n_kv: int, hd: int,
               dtype, quantized: bool) -> Tuple:
    """One transformer block's DENSE pool state (the pre-paged layout,
    kept for tests and offline tooling). Float: ``(ck, cv)``.
    Quantized: ``(ck_q, cv_q, k_scale, v_scale)`` — int8 payloads plus
    f32 per-(slot, position) scale sidecars. Zero-initialized
    throughout: scale 0 dequantizes untouched rows to exact 0.0, the
    same content the float pool starts with."""
    import jax.numpy as jnp
    shape = (max_slots, max_context, n_kv, hd)
    if not quantized:
        return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
    return (jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
            jnp.zeros((max_slots, max_context), jnp.float32),
            jnp.zeros((max_slots, max_context), jnp.float32))


def block_page_pool(pages: int, page_size: int, n_kv: int, hd: int,
                    dtype, quantized: bool) -> Tuple:
    """One transformer block's PAGED pool state (serving/pages.py):
    ``pages`` device rows of ``page_size`` positions each — row 0 is
    the allocator's sink page. Float: ``(kp, vp)`` shaped
    ``(pages, page_size, n_kv, hd)``. Quantized:
    ``(kp_q, vp_q, k_scale, v_scale)`` — int8 payloads plus f32
    per-page scale sidecars shaped ``(pages, page_size)`` (one scale
    per cached position, laid out page-wise so a page's payload and
    its scales travel together through the same gather/scatter
    indices). Zero-initialized: scale 0 dequantizes untouched rows to
    exact 0.0, the float pool's starting content."""
    import jax.numpy as jnp
    shape = (pages, page_size, n_kv, hd)
    if not quantized:
        return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
    return (jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
            jnp.zeros((pages, page_size), jnp.float32),
            jnp.zeros((pages, page_size), jnp.float32))


def pool_nbytes(caches) -> int:
    """Total bytes of a pool (all blocks, payloads + scale sidecars) —
    the number the HBM-halving claim is asserted on."""
    total = 0
    for block in caches or ():
        for arr in block:
            total += arr.size * arr.dtype.itemsize
    return int(total)
