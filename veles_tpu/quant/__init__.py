"""Quantization subsystem: int8 weights, int8 KV cache, AOT serving.

The production-loop closer named by ROADMAP item 3 (reference analog:
``Workflow.package_export`` → ``libVeles/src/workflow_loader.cc``).
Three planes, all OFF by default and bit-identical when off:

- **Weights** (:mod:`weights`): per-channel symmetric int8 for the
  decode matmul weights, dequantized on read inside the jitted serving
  programs (``root.common.quant.weights`` / ``--quant-weights``), plus
  the offline ``veles-tpu quantize <snapshot>`` CLI producing
  snapshots with ~4× smaller weight payloads any build can resume
  from.
- **KV cache** (:mod:`kv`): int8 slot-pool storage with per-slot,
  per-position scales — half the pool HBM at the same ``max_slots``
  (``root.common.quant.kv`` / ``--quant-kv``).
- **AOT artifacts** (``export/serve_artifact.py``): ``veles-tpu export
  serve-artifact`` serializes the engine's per-bucket prefill programs
  and its one fixed-shape decode step via ``jax.export`` into the
  package format; the engine loads them at initialize, so serving
  startup performs ZERO jit traces/compiles.

Numeric primitives live in ``ops/precision.py`` (the MXU precision
policy's home). Operator guide: docs/services.md "Quantized serving".
"""

from __future__ import annotations

from .weights import (dequantize_params, dequantize_state,  # noqa: F401
                      is_quantized_params, quantize_params,
                      quantize_params_spec, quantize_state,
                      quantize_tensor, GRANULARITIES)
from .kv import (block_page_pool, block_pool,                # noqa: F401
                 dequantize_rows_int8, pool_nbytes,
                 quantize_rows_int8)

#: every counter the quantization/artifact plane increments —
#: registered with HELP strings in telemetry/counters.py DESCRIPTIONS
#: and asserted zero in quant-off runs by ``python bench.py gate``'s
#: quant section
QUANT_COUNTERS = (
    "veles_quant_params_total",
    "veles_quant_bytes_saved_total",
    "veles_quant_calibrations_total",
    "veles_artifact_loads_total",
    "veles_artifact_load_failures_total",
)


def policy() -> dict:
    """The active quantization policy
    (``root.common.quant.{weights,kv,granularity}``) as plain values —
    what the engine, the bench section and the /metrics gauges read."""
    from ..config import root
    from .weights import granularity_from_config
    return {
        "weights": bool(root.common.quant.get("weights", False)),
        "kv": bool(root.common.quant.get("kv", False)),
        "granularity": granularity_from_config(),
    }
